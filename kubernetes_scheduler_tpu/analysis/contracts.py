"""Layer 2: trace-backed engine contracts, verified with jax.eval_shape.

The AST families (layer 1) prove properties of the SOURCE; this layer
proves the engine boundary's shape/dtype contract by actually TRACING
it — `jax.eval_shape` runs the full jaxpr abstraction on CPU (Pallas
kernels included — abstract eval never launches them) without
compiling or allocating, so `make lint` catches a contract drift
between the fused and unfused paths, or between a wire-schema field's
dtype and the engine's expectation, instead of a 4k-node bench round
discovering it.

Every entry point the host/bridge dispatch to is declared here with its
EXPECTED output spec as a function of the bucket shape, and checked
across a small grid of bucket shapes (two points per axis — enough to
catch a shape formula drifting with n or p, cheap enough for lint):

- `engine.schedule_batch` (greedy + auction, unfused) — ScheduleResult;
- the fused path drift check: `schedule_batch(fused=True)` must produce
  the IDENTICAL output spec as the unfused call it replaces;
- `engine.schedule_windows` — WindowsResult;
- `engine.apply_snapshot_delta` / `engine.apply_layout_delta` — donated
  folds must be spec-preserving leaf for leaf (the resident-state
  parity guarantee's static half);
- `engine.build_fused_layout` and the `ops/pallas_fused` wrappers
  (`fused_masked_score`, `fused_score_row_stats`, `fused_auction_bid`)
  — the kernel-layout padding formulas;
- the MESH-SHARDED engine surfaces (`parallel/engine.py`'s
  `make_sharded_schedule_fn` greedy/auction and
  `make_sharded_windows_fn` greedy/auction), traced THROUGH shard_map
  on a virtual multi-device CPU mesh: the sharded output spec must
  equal the dense spec it replaces LEAF FOR LEAF (sharded/dense drift
  fails lint exactly like fused/dense drift does), the declared
  node-axis divisibility formula (n % mesh.size == 0) must predict
  trace success AND failure, and the static collective count of each
  traced program (psum/pmax/pmin/all_gather/axis_index, walked out of
  the jaxpr) must match the checked-in COLLECTIVE_BUDGET.json — an
  accidental extra collective in the election scan body fails lint
  with a diff (pseudo-rule `collective-budget`) instead of surfacing
  as a bench regression three rounds later. Regenerate the budget
  after an intentional change with `make collective-baseline`.

Violations surface as pseudo-rule `engine-contract` (and
`collective-budget`) findings through the same CLI/baseline machinery
as layer 1. Fixture modules (the violating/clean drift pair in
tests/analysis_fixtures/) declare the same thing in miniature via a
CONTRACTS table checked by `check_fixture_module`.
"""

from __future__ import annotations

import os

from kubernetes_scheduler_tpu.analysis.core import Violation

RULE = "engine-contract"

# bucket-shape grid: (nodes, pods, resources, selectors, windows)
GRID = (
    dict(n=16, p=8, r=7, s=3, w=2),
    dict(n=64, p=32, r=7, s=3, w=2),
)

ENGINE_PATH = "kubernetes_scheduler_tpu/engine.py"
FUSED_PATH = "kubernetes_scheduler_tpu/ops/pallas_fused.py"
PARALLEL_PATH = "kubernetes_scheduler_tpu/parallel/engine.py"

# the files whose edits can move a declared contract — a changed-only
# lint run traces the layer only when its closure touches these (the
# sharded surfaces and the SPMD mutant harness included)
SURFACE = (
    ENGINE_PATH,
    "kubernetes_scheduler_tpu/ops/*.py",
    "kubernetes_scheduler_tpu/parallel/*.py",
    "kubernetes_scheduler_tpu/analysis/contracts.py",
    "kubernetes_scheduler_tpu/analysis/spmd.py",
    "kubernetes_scheduler_tpu/analysis/spmd_mutants.py",
    # the sharded-resident delta router lives host-side; its edits can
    # drift the stacked-delta layout the sharded appliers trace against
    "kubernetes_scheduler_tpu/host/snapshot.py",
)


def _spec_tree(tree):
    """Pytree of concrete arrays -> pytree of ShapeDtypeStruct."""
    import jax

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree
    )


def _make_inputs(g):
    """Concrete tiny snapshot/pods/delta/layout for one grid point —
    built through the SAME constructors the host uses, then abstracted
    to specs, so the contract tracks the real dispatch payload."""
    import numpy as np

    from kubernetes_scheduler_tpu import engine

    n, p, r, s = g["n"], g["p"], g["r"], g["s"]
    snap = engine.make_snapshot(
        np.ones((n, r), np.float32),
        np.zeros((n, r), np.float32),
        np.zeros(n, np.float32),
        np.zeros(n, np.float32),
        np.zeros(n, np.float32),
        domain_counts=np.zeros((n, s), np.float32),
    )
    pods = engine.make_pod_batch(
        np.zeros((p, r), np.float32),
        pod_matches=np.zeros((p, s), bool),
    )
    k = 2
    delta = engine.SnapshotDelta(
        req_rows=np.full(k, n, np.int32),
        req_vals=np.zeros((k, r), np.float32),
        util_rows=np.full(k, n, np.int32),
        util_vals=np.zeros((k, 5), np.float32),
        dom_rows=np.full(k, n, np.int32),
        dom_vals=np.zeros((k, s, 4), np.float32),
        node_mask=np.ones(n, bool),
    )
    return snap, pods, delta


def _leaf_mismatches(name, got, want, fields=None):
    """Human-readable diffs between two spec pytrees (NamedTuples or
    single specs), field names attached."""
    import jax

    got_leaves, got_def = jax.tree_util.tree_flatten(got)
    want_leaves, want_def = jax.tree_util.tree_flatten(want)
    if got_def != want_def:
        return [f"{name}: pytree structure {got_def} != declared {want_def}"]
    names = fields or [str(i) for i in range(len(got_leaves))]
    out = []
    for fname, a, b in zip(names, got_leaves, want_leaves):
        if tuple(a.shape) != tuple(b.shape) or a.dtype != b.dtype:
            out.append(
                f"{name}.{fname}: traced {tuple(a.shape)}/{a.dtype} != "
                f"declared {tuple(b.shape)}/{b.dtype}"
            )
    return out


def check_contracts() -> list[Violation]:
    """Trace every declared engine entry point across the bucket grid
    and diff against the declared specs. Returns [] when the engine
    honors its contracts."""
    import functools

    import jax
    import jax.numpy as jnp

    from kubernetes_scheduler_tpu import engine
    from kubernetes_scheduler_tpu.ops import pallas_fused

    out: list[Violation] = []

    def sds(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    for g in GRID:
        n, p, r, s, w = g["n"], g["p"], g["r"], g["s"], g["w"]
        snap_c, pods_c, delta_c = _make_inputs(g)
        snap, pods, delta = (
            _spec_tree(snap_c), _spec_tree(pods_c), _spec_tree(delta_c)
        )
        tag = f"[n={n} p={p} r={r} s={s}]"

        def expect(name, path, fn, args, want, fields=None, line=1):
            try:
                got = jax.eval_shape(fn, *args)
            except Exception as e:  # noqa: BLE001 — the trace failing IS the finding
                out.append(Violation(
                    RULE, path, line,
                    f"{name} {tag}: eval_shape trace failed: {e}",
                ))
                return None
            for msg in _leaf_mismatches(name, got, want, fields):
                out.append(Violation(RULE, path, line, f"{tag} {msg}"))
            return got

        sched_want = engine.ScheduleResult(
            node_idx=sds((p,), jnp.int32),
            scores=sds((p, n), jnp.float32),
            raw_scores=sds((p, n), jnp.float32),
            feasible=sds((p, n), jnp.bool_),
            free_after=sds((n, r), jnp.float32),
            n_assigned=sds((), jnp.int32),
        )
        unfused = expect(
            "schedule_batch", ENGINE_PATH,
            functools.partial(engine.schedule_batch, assigner="greedy"),
            (snap, pods), sched_want, engine.ScheduleResult._fields,
        )
        expect(
            "schedule_batch(auction)", ENGINE_PATH,
            functools.partial(
                engine.schedule_batch, assigner="auction", auction_rounds=4
            ),
            (snap, pods), sched_want, engine.ScheduleResult._fields,
        )
        # fused-vs-unfused drift: the fused megakernel path must present
        # the EXACT spec of the path it replaces
        if unfused is not None:
            expect(
                "schedule_batch(fused)", ENGINE_PATH,
                functools.partial(
                    engine.schedule_batch, assigner="greedy", fused=True
                ),
                (snap, pods), unfused, engine.ScheduleResult._fields,
            )
        pods_w = jax.tree_util.tree_map(
            lambda spec: sds((w,) + tuple(spec.shape), spec.dtype), pods
        )
        expect(
            "schedule_windows", ENGINE_PATH,
            engine.schedule_windows, (snap, pods_w),
            engine.WindowsResult(
                node_idx=sds((w, p), jnp.int32),
                free_after=sds((n, r), jnp.float32),
                n_assigned=sds((), jnp.int32),
            ),
            engine.WindowsResult._fields,
        )
        # donated folds are spec-preserving leaf for leaf
        expect(
            "apply_snapshot_delta", ENGINE_PATH,
            engine.apply_snapshot_delta, (snap, delta), snap,
            engine.SnapshotArrays._fields,
        )
        nn = -(-n // pallas_fused.TILE_N) * pallas_fused.TILE_N
        layout_want = engine.FusedLayout(
            node_ft=sds((3, nn), jnp.float32),
            alloc_t=sds((r, nn), jnp.float32),
            reqd_t=sds((r, nn), jnp.float32),
        )
        layout = expect(
            "build_fused_layout", ENGINE_PATH,
            engine.build_fused_layout, (snap,), layout_want,
            engine.FusedLayout._fields,
        )
        if layout is not None:
            expect(
                "apply_layout_delta", ENGINE_PATH,
                engine.apply_layout_delta, (layout, delta), layout_want,
                engine.FusedLayout._fields,
            )
        # ops/pallas_fused wrappers: kernel-layout padding formulas
        pp = -(-p // pallas_fused.TILE_P) * pallas_fused.TILE_P
        expect(
            "fused_masked_score", FUSED_PATH,
            pallas_fused.fused_masked_score,
            (
                sds((n,), jnp.float32), sds((n,), jnp.float32),
                sds((n,), jnp.bool_), sds((n, r), jnp.float32),
                sds((n, r), jnp.float32), sds((p,), jnp.float32),
                sds((p,), jnp.float32), sds((p, r), jnp.float32),
                sds((p,), jnp.bool_),
            ),
            sds((p, n), jnp.float32),
        )
        expect(
            "fused_score_row_stats", FUSED_PATH,
            pallas_fused.fused_score_row_stats,
            (sds((4, pp), jnp.float32), sds((3, nn), jnp.float32)),
            sds((2, pp), jnp.float32),
        )
        expect(
            "fused_auction_bid", FUSED_PATH,
            functools.partial(pallas_fused.fused_auction_bid, p=p),
            (
                sds((pp, nn), jnp.float32), sds((n,), jnp.float32),
                sds((p,), jnp.bool_), sds((r, pp), jnp.float32),
                sds((n, r), jnp.float32),
            ),
            (sds((p,), jnp.int32), sds((p,), jnp.bool_)),
        )
    return out


# the entry points the acceptance criteria pin — tests assert coverage
CONTRACT_NAMES = (
    "schedule_batch", "schedule_batch(auction)", "schedule_batch(fused)",
    "schedule_windows", "apply_snapshot_delta", "apply_layout_delta",
    "build_fused_layout", "fused_masked_score", "fused_score_row_stats",
    "fused_auction_bid",
)


# ---- sharded engine contracts + the collective budget ---------------------

BUDGET_RULE = "collective-budget"
COLLECTIVE_BUDGET_NAME = "COLLECTIVE_BUDGET.json"
# the collective kinds budgeted per surface, in report order
COLLECTIVE_KINDS = ("psum", "pmax", "pmin", "all_gather", "axis_index")

# the sharded entry points the acceptance criteria pin
SHARDED_CONTRACT_NAMES = (
    "sharded_schedule(greedy)", "sharded_schedule(auction)",
    "sharded_windows(greedy)", "sharded_windows(auction)",
    # the sharded-RESIDENT surfaces (parallel/engine.ShardedEngine's
    # production path): the fused megakernel step fed by retained
    # per-shard kernel-layout buffers, and the per-shard donated folds
    "sharded_schedule(fused)",
    "sharded_apply_delta",
    "sharded_build_layout",
    "sharded_apply_layout_delta",
)


def node_axis_divisor(mesh) -> int:
    """The declared node-axis divisibility formula: every sharded
    surface requires n % (product of the mesh's node axes) == 0 — the
    host pads the node bucket to it. Checked below by predicting both
    trace success AND failure."""
    return int(mesh.size)


def _virtual_mesh():
    """1-D mesh over every visible device. Lint runs force the CPU
    platform with a virtual 8-device topology (conftest / the Makefile
    lint targets); with fewer devices the layer still traces — the
    collective counts are device-count-independent static facts — and
    only the divisibility-failure prediction is skipped (D == 1 divides
    everything)."""
    from kubernetes_scheduler_tpu.parallel.mesh import make_mesh

    return make_mesh()


def sharded_surfaces(mesh) -> dict:
    """name -> built sharded schedule fn for every declared surface —
    the SAME factories the host dispatches through, at their build-time
    default knobs, so the budget tracks the production programs."""
    from kubernetes_scheduler_tpu.parallel.engine import (
        make_sharded_schedule_fn,
        make_sharded_windows_fn,
    )

    return {
        "sharded_schedule(greedy)": make_sharded_schedule_fn(
            mesh, assigner="greedy"
        ),
        "sharded_schedule(auction)": make_sharded_schedule_fn(
            mesh, assigner="auction"
        ),
        "sharded_windows(greedy)": make_sharded_windows_fn(
            mesh, assigner="greedy"
        ),
        "sharded_windows(auction)": make_sharded_windows_fn(
            mesh, assigner="auction"
        ),
    }


def sharded_resident_surfaces(mesh) -> dict:
    """name -> built sharded-RESIDENT surface: the programs
    parallel/engine.ShardedEngine dispatches per cycle — the fused
    megakernel step taking retained per-shard kernel-layout buffers
    (built at the production knobs: auction assigner, normalizer
    "none", the sharded fused contract), and the donated per-shard
    delta/layout folds plus the one-per-upload layout build."""
    from kubernetes_scheduler_tpu.parallel.engine import (
        make_sharded_apply_delta_fn,
        make_sharded_apply_layout_fn,
        make_sharded_build_layout_fn,
        make_sharded_schedule_fn,
    )

    return {
        "sharded_schedule(fused)": make_sharded_schedule_fn(
            mesh, assigner="auction", normalizer="none", fused=True,
            resident_layout=True,
        ),
        "sharded_apply_delta": make_sharded_apply_delta_fn(mesh),
        "sharded_build_layout": make_sharded_build_layout_fn(mesh),
        "sharded_apply_layout_delta": make_sharded_apply_layout_fn(mesh),
    }


def _stacked_delta_spec(g, d: int):
    """Spec of a stacked per-shard delta (parallel/engine.
    stack_shard_deltas): every dense-delta leaf with a leading [D]
    shard axis, rows in shard-local coordinates, node_mask reshaped
    [D, n_local]. k=8 is _rows_padded's floor bucket."""
    import jax
    import jax.numpy as jnp

    from kubernetes_scheduler_tpu import engine

    def sds(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    n, r, s, k = g["n"], g["r"], g["s"], 8
    return engine.SnapshotDelta(
        req_rows=sds((d, k), jnp.int32),
        req_vals=sds((d, k, r), jnp.float32),
        util_rows=sds((d, k), jnp.int32),
        util_vals=sds((d, k, 5), jnp.float32),
        dom_rows=sds((d, k), jnp.int32),
        dom_vals=sds((d, k, s, 4), jnp.float32),
        node_mask=sds((d, n // d), jnp.bool_),
    )


def sharded_layout_spec(g, d: int):
    """The declared sharded kernel-layout padding formula: each shard
    TILE-pads ITS n_local columns, so the global column axis is
    D * roundup(n/D, TILE_N) — NOT the dense roundup(n, TILE_N)."""
    import jax
    import jax.numpy as jnp

    from kubernetes_scheduler_tpu import engine
    from kubernetes_scheduler_tpu.ops import pallas_fused

    def sds(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    nn = d * (-(-(g["n"] // d) // pallas_fused.TILE_N) * pallas_fused.TILE_N)
    return engine.FusedLayout(
        node_ft=sds((3, nn), jnp.float32),
        alloc_t=sds((g["r"], nn), jnp.float32),
        reqd_t=sds((g["r"], nn), jnp.float32),
    )


def _resident_surface_args(name: str, mesh, g) -> tuple:
    """Trace-time argument specs for one sharded-resident surface."""
    d = int(mesh.size)
    snap, pods, _ = _sharded_inputs(g)
    if name == "sharded_schedule(fused)":
        return (snap, pods, sharded_layout_spec(g, d))
    if name == "sharded_apply_delta":
        return (snap, _stacked_delta_spec(g, d))
    if name == "sharded_build_layout":
        return (snap,)
    if name == "sharded_apply_layout_delta":
        return (sharded_layout_spec(g, d), _stacked_delta_spec(g, d))
    raise KeyError(name)


def collective_counts(fn, *args) -> dict:
    """Static per-kind collective counts of `fn`'s traced jaxpr —
    sub-jaxprs (shard_map bodies, scans, while loops, pjit calls)
    walked recursively. Counts are trace-time facts: a scan's body
    traces once however many steps run, so the budget pins the
    PER-ROUND collective structure, not a runtime tally."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args)
    counts = dict.fromkeys(COLLECTIVE_KINDS, 0)

    def walk(jx):
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name in counts:
                counts[name] += 1
            for v in eqn.params.values():
                vals = v if isinstance(v, (list, tuple)) else [v]
                for vv in vals:
                    if hasattr(vv, "eqns"):
                        walk(vv)
                    elif hasattr(vv, "jaxpr") and hasattr(
                        vv.jaxpr, "eqns"
                    ):
                        walk(vv.jaxpr)

    walk(jaxpr.jaxpr)
    return counts


def _sharded_inputs(g):
    """Spec pytrees for one grid point, windows variant included."""
    import jax

    snap_c, pods_c, _ = _make_inputs(g)
    snap, pods = _spec_tree(snap_c), _spec_tree(pods_c)
    pods_w = jax.tree_util.tree_map(
        lambda spec: jax.ShapeDtypeStruct(
            (g["w"],) + tuple(spec.shape), spec.dtype
        ),
        pods,
    )
    return snap, pods, pods_w


def traced_surface_counts(mesh=None) -> dict:
    """name -> collective counts for every declared sharded surface
    (what `make collective-baseline` writes and the gate re-traces)."""
    mesh = mesh or _virtual_mesh()
    g = GRID[0]
    snap, pods, pods_w = _sharded_inputs(g)
    out = {}
    for name, fn in sharded_surfaces(mesh).items():
        args = (snap, pods_w) if "windows" in name else (snap, pods)
        out[name] = collective_counts(fn, *args)
    for name, fn in sharded_resident_surfaces(mesh).items():
        out[name] = collective_counts(
            fn, *_resident_surface_args(name, mesh, g)
        )
    return out


def write_collective_budget(path: str | None = None) -> dict:
    """Regenerate COLLECTIVE_BUDGET.json from the traced jaxprs (the
    `make collective-baseline` entry point). Returns the document."""
    import json

    mesh = _virtual_mesh()
    doc = {
        "comment": (
            "Static collective counts of every declared sharded engine "
            "surface, walked out of the traced jaxpr. `make lint` "
            "re-traces and diffs; regenerate with `make "
            "collective-baseline` after an INTENTIONAL collective-"
            "structure change."
        ),
        "mesh_devices": int(mesh.size),
        "surfaces": traced_surface_counts(mesh),
    }
    path = path or os.path.join(_repo_root(), COLLECTIVE_BUDGET_NAME)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return doc


def _repo_root() -> str:
    from kubernetes_scheduler_tpu.analysis.core import _REPO_ROOT

    return _REPO_ROOT


def check_collective_budget(
    budget_path: str | None = None,
    traced: dict | None = None,
    failed: set | None = None,
) -> list[Violation]:
    """Diff the traced per-surface collective counts against the
    checked-in budget. Every failure mode is loud: a missing or
    unparseable budget file, a traced surface the file does not budget,
    a stale budgeted surface nothing traces anymore, and any per-kind
    count drift (the extra-collective class) each produce a finding.
    `failed` names surfaces whose TRACE failed this run: their budget
    entries are exempt from the staleness check — the trace failure is
    already its own finding, and advising `make collective-baseline`
    there would point the maintainer at dropping the pin instead of at
    the broken trace."""
    import json

    path = budget_path or os.path.join(
        _repo_root(), COLLECTIVE_BUDGET_NAME
    )
    rel = os.path.basename(path)
    if traced is None:
        try:
            traced = traced_surface_counts()
        except Exception as e:  # noqa: BLE001 — the trace failing IS the finding
            return [Violation(
                BUDGET_RULE, PARALLEL_PATH, 1,
                f"tracing the sharded surfaces for the collective "
                f"budget failed: {e}",
            )]
    if not os.path.exists(path):
        return [Violation(
            BUDGET_RULE, rel, 1,
            f"{rel} is missing — the sharded engine's collective "
            "budget is unpinned; run `make collective-baseline`",
        )]
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        budget = doc["surfaces"]
        if not isinstance(budget, dict) or not all(
            isinstance(v, dict) for v in budget.values()
        ):
            raise TypeError("'surfaces' must map names to count dicts")
    except Exception as e:  # noqa: BLE001
        return [Violation(
            BUDGET_RULE, rel, 1,
            f"{rel} does not parse as {{'surfaces': {{...}}}}: {e} — "
            "regenerate with `make collective-baseline`",
        )]
    out: list[Violation] = []
    for name, counts in sorted(traced.items()):
        want = budget.get(name)
        if want is None:
            out.append(Violation(
                BUDGET_RULE, rel, 1,
                f"sharded surface `{name}` has no budget entry — new "
                "surfaces must be consciously budgeted; run `make "
                "collective-baseline`",
            ))
            continue
        diffs = [
            f"{kind}: traced {counts.get(kind, 0)} != budgeted "
            f"{want.get(kind, 0)}"
            for kind in COLLECTIVE_KINDS
            if counts.get(kind, 0) != want.get(kind, 0)
        ]
        if diffs:
            out.append(Violation(
                BUDGET_RULE, PARALLEL_PATH, 1,
                f"`{name}` collective budget drift ({'; '.join(diffs)}) "
                "— an unbudgeted collective is a per-round latency tax "
                "on the election scan; fix the program, or regenerate "
                "with `make collective-baseline` if the change is "
                "intentional",
            ))
    for name in sorted(set(budget) - set(traced) - (failed or set())):
        out.append(Violation(
            BUDGET_RULE, rel, 1,
            f"budget entry `{name}` matches no declared sharded "
            "surface — stale; run `make collective-baseline`",
        ))
    return out


def check_sharded_contracts() -> list[Violation]:
    """Trace every sharded surface through shard_map on the virtual
    CPU mesh and pin (a) sharded output spec == dense output spec leaf
    for leaf, (b) the node-axis divisibility formula predicting both
    trace success and failure, (c) the collective budget. Returns []
    when the mesh-sharded engine honors its contracts."""
    import jax

    from kubernetes_scheduler_tpu import engine

    out: list[Violation] = []
    try:
        mesh = _virtual_mesh()
    except Exception as e:  # noqa: BLE001
        return [Violation(
            RULE, PARALLEL_PATH, 1,
            f"virtual mesh construction failed: {e}",
        )]
    divisor = node_axis_divisor(mesh)
    try:
        surfaces = sharded_surfaces(mesh)
        resident = sharded_resident_surfaces(mesh)
    except Exception as e:  # noqa: BLE001
        return [Violation(
            RULE, PARALLEL_PATH, 1,
            f"building the sharded surfaces failed: {e}",
        )]
    for g in GRID:
        if g["n"] % divisor:
            out.append(Violation(
                RULE, PARALLEL_PATH, 1,
                f"grid point n={g['n']} violates the declared "
                f"divisibility formula n % {divisor} == 0 — the "
                "sharded layer cannot be checked at it",
            ))
            continue
        snap, pods, pods_w = _sharded_inputs(g)
        tag = f"[n={g['n']} p={g['p']} D={divisor}]"
        dense = {
            "batch": jax.eval_shape(engine.schedule_batch, snap, pods),
            "windows": jax.eval_shape(
                engine.schedule_windows, snap, pods_w
            ),
        }
        fields = {
            "batch": engine.ScheduleResult._fields,
            "windows": engine.WindowsResult._fields,
        }
        for name, fn in surfaces.items():
            kind = "windows" if "windows" in name else "batch"
            args = (snap, pods_w) if kind == "windows" else (snap, pods)
            try:
                got = jax.eval_shape(fn, *args)
            except Exception as e:  # noqa: BLE001
                out.append(Violation(
                    RULE, PARALLEL_PATH, 1,
                    f"{name} {tag}: eval_shape through shard_map "
                    f"failed: {e}",
                ))
                continue
            for msg in _leaf_mismatches(
                name, got, dense[kind], fields[kind]
            ):
                out.append(Violation(
                    RULE, PARALLEL_PATH, 1,
                    f"{tag} sharded/dense drift: {msg.replace('declared', 'dense')}",
                ))
        # sharded-RESIDENT surfaces: the fused step must present the
        # dense ScheduleResult spec; the donated folds must be spec-
        # preserving leaf for leaf (like apply_snapshot_delta/
        # apply_layout_delta); the layout build must honor the declared
        # per-shard padding formula
        lay_want = sharded_layout_spec(g, divisor)
        resident_want = {
            "sharded_schedule(fused)": (
                dense["batch"], engine.ScheduleResult._fields,
            ),
            "sharded_apply_delta": (snap, engine.SnapshotArrays._fields),
            "sharded_build_layout": (lay_want, engine.FusedLayout._fields),
            "sharded_apply_layout_delta": (
                lay_want, engine.FusedLayout._fields,
            ),
        }
        for name, fn in resident.items():
            want, fnames = resident_want[name]
            try:
                got = jax.eval_shape(
                    fn, *_resident_surface_args(name, mesh, g)
                )
            except Exception as e:  # noqa: BLE001
                out.append(Violation(
                    RULE, PARALLEL_PATH, 1,
                    f"{name} {tag}: eval_shape through shard_map "
                    f"failed: {e}",
                ))
                continue
            for msg in _leaf_mismatches(name, got, want, fnames):
                out.append(Violation(
                    RULE, PARALLEL_PATH, 1,
                    f"{tag} sharded-resident drift: {msg}",
                ))
    # the divisibility formula must also predict FAILURE: a node count
    # the formula rejects must actually fail to trace (D == 1 divides
    # everything — nothing to predict)
    if divisor > 1:
        g = dict(GRID[0])
        g["n"] = divisor + 1  # never divisible by D > 1
        snap, pods, _ = _sharded_inputs(g)
        fn = surfaces["sharded_schedule(greedy)"]
        try:
            jax.eval_shape(fn, snap, pods)
        except Exception:  # noqa: BLE001 — expected: the formula holds
            pass
        else:
            out.append(Violation(
                RULE, PARALLEL_PATH, 1,
                f"n={g['n']} traces despite violating the declared "
                f"divisibility formula n % {divisor} == 0 — the "
                "formula drifted from shard_map's actual constraint",
            ))
    # the budget gate reuses the surfaces already built above (the
    # jaxpr walk is the only extra trace)
    g0 = GRID[0]
    snap, pods, pods_w = _sharded_inputs(g0)
    traced: dict = {}
    failed: set = set()
    for name, fn in surfaces.items():
        args = (snap, pods_w) if "windows" in name else (snap, pods)
        try:
            traced[name] = collective_counts(fn, *args)
        except Exception as e:  # noqa: BLE001
            failed.add(name)
            out.append(Violation(
                BUDGET_RULE, PARALLEL_PATH, 1,
                f"tracing `{name}` for the collective budget failed: {e}",
            ))
    for name, fn in resident.items():
        try:
            traced[name] = collective_counts(
                fn, *_resident_surface_args(name, mesh, g0)
            )
        except Exception as e:  # noqa: BLE001
            failed.add(name)
            out.append(Violation(
                BUDGET_RULE, PARALLEL_PATH, 1,
                f"tracing `{name}` for the collective budget failed: {e}",
            ))
    out.extend(check_collective_budget(traced=traced, failed=failed))
    return out


def check_fixture_module(path: str) -> list[Violation]:
    """The miniature declarative form for fixtures and one-off modules:
    the module's CONTRACTS table is a list of

        {"fn": "name",
         "args": [("float32", ("n", "r")), ...],
         "out":  ("float32", ("n", "r"))  # or a list for tuple returns
         "grid": [{"n": 8, "r": 4}, ...]}

    dims are grid keys or int literals; each entry is eval_shape-checked
    at every grid point."""
    import importlib.util

    import jax
    import numpy as np

    rel = os.path.basename(path)
    spec = importlib.util.spec_from_file_location(
        f"_contract_fixture_{abs(hash(path))}", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out: list[Violation] = []

    def resolve(shape, g):
        return tuple(d if isinstance(d, int) else g[d] for d in shape)

    def to_spec(entry, g):
        dtype, shape = entry
        return jax.ShapeDtypeStruct(resolve(shape, g), np.dtype(dtype))

    for decl in getattr(mod, "CONTRACTS", ()):
        fn = getattr(mod, decl["fn"])
        line = getattr(fn, "__code__", None)
        line = line.co_firstlineno if line else 1
        for g in decl["grid"]:
            args = [to_spec(a, g) for a in decl["args"]]
            want = decl["out"]
            want = (
                tuple(to_spec(o, g) for o in want)
                if isinstance(want, list)
                else to_spec(want, g)
            )
            try:
                got = jax.eval_shape(fn, *args)
            except Exception as e:  # noqa: BLE001
                out.append(Violation(
                    RULE, rel, line,
                    f"{decl['fn']} {g}: eval_shape trace failed: {e}",
                ))
                continue
            for msg in _leaf_mismatches(decl["fn"], got, want):
                out.append(Violation(RULE, rel, line, f"{g} {msg}"))
    return out
