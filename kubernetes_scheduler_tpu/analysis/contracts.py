"""Layer 2: trace-backed engine contracts, verified with jax.eval_shape.

The AST families (layer 1) prove properties of the SOURCE; this layer
proves the engine boundary's shape/dtype contract by actually TRACING
it — `jax.eval_shape` runs the full jaxpr abstraction on CPU (Pallas
kernels included — abstract eval never launches them) without
compiling or allocating, so `make lint` catches a contract drift
between the fused and unfused paths, or between a wire-schema field's
dtype and the engine's expectation, instead of a 4k-node bench round
discovering it.

Every entry point the host/bridge dispatch to is declared here with its
EXPECTED output spec as a function of the bucket shape, and checked
across a small grid of bucket shapes (two points per axis — enough to
catch a shape formula drifting with n or p, cheap enough for lint):

- `engine.schedule_batch` (greedy + auction, unfused) — ScheduleResult;
- the fused path drift check: `schedule_batch(fused=True)` must produce
  the IDENTICAL output spec as the unfused call it replaces;
- `engine.schedule_windows` — WindowsResult;
- `engine.apply_snapshot_delta` / `engine.apply_layout_delta` — donated
  folds must be spec-preserving leaf for leaf (the resident-state
  parity guarantee's static half);
- `engine.build_fused_layout` and the `ops/pallas_fused` wrappers
  (`fused_masked_score`, `fused_score_row_stats`, `fused_auction_bid`)
  — the kernel-layout padding formulas.

Violations surface as pseudo-rule `engine-contract` findings through
the same CLI/baseline machinery as layer 1. Fixture modules (the
violating/clean drift pair in tests/analysis_fixtures/) declare the
same thing in miniature via a CONTRACTS table checked by
`check_fixture_module`.
"""

from __future__ import annotations

import os

from kubernetes_scheduler_tpu.analysis.core import Violation

RULE = "engine-contract"

# bucket-shape grid: (nodes, pods, resources, selectors, windows)
GRID = (
    dict(n=16, p=8, r=7, s=3, w=2),
    dict(n=64, p=32, r=7, s=3, w=2),
)

ENGINE_PATH = "kubernetes_scheduler_tpu/engine.py"
FUSED_PATH = "kubernetes_scheduler_tpu/ops/pallas_fused.py"

# the files whose edits can move a declared contract — a changed-only
# lint run traces the layer only when its closure touches these
SURFACE = (
    ENGINE_PATH,
    "kubernetes_scheduler_tpu/ops/*.py",
    "kubernetes_scheduler_tpu/analysis/contracts.py",
)


def _spec_tree(tree):
    """Pytree of concrete arrays -> pytree of ShapeDtypeStruct."""
    import jax

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree
    )


def _make_inputs(g):
    """Concrete tiny snapshot/pods/delta/layout for one grid point —
    built through the SAME constructors the host uses, then abstracted
    to specs, so the contract tracks the real dispatch payload."""
    import numpy as np

    from kubernetes_scheduler_tpu import engine

    n, p, r, s = g["n"], g["p"], g["r"], g["s"]
    snap = engine.make_snapshot(
        np.ones((n, r), np.float32),
        np.zeros((n, r), np.float32),
        np.zeros(n, np.float32),
        np.zeros(n, np.float32),
        np.zeros(n, np.float32),
        domain_counts=np.zeros((n, s), np.float32),
    )
    pods = engine.make_pod_batch(
        np.zeros((p, r), np.float32),
        pod_matches=np.zeros((p, s), bool),
    )
    k = 2
    delta = engine.SnapshotDelta(
        req_rows=np.full(k, n, np.int32),
        req_vals=np.zeros((k, r), np.float32),
        util_rows=np.full(k, n, np.int32),
        util_vals=np.zeros((k, 5), np.float32),
        dom_rows=np.full(k, n, np.int32),
        dom_vals=np.zeros((k, s, 4), np.float32),
        node_mask=np.ones(n, bool),
    )
    return snap, pods, delta


def _leaf_mismatches(name, got, want, fields=None):
    """Human-readable diffs between two spec pytrees (NamedTuples or
    single specs), field names attached."""
    import jax

    got_leaves, got_def = jax.tree_util.tree_flatten(got)
    want_leaves, want_def = jax.tree_util.tree_flatten(want)
    if got_def != want_def:
        return [f"{name}: pytree structure {got_def} != declared {want_def}"]
    names = fields or [str(i) for i in range(len(got_leaves))]
    out = []
    for fname, a, b in zip(names, got_leaves, want_leaves):
        if tuple(a.shape) != tuple(b.shape) or a.dtype != b.dtype:
            out.append(
                f"{name}.{fname}: traced {tuple(a.shape)}/{a.dtype} != "
                f"declared {tuple(b.shape)}/{b.dtype}"
            )
    return out


def check_contracts() -> list[Violation]:
    """Trace every declared engine entry point across the bucket grid
    and diff against the declared specs. Returns [] when the engine
    honors its contracts."""
    import functools

    import jax
    import jax.numpy as jnp

    from kubernetes_scheduler_tpu import engine
    from kubernetes_scheduler_tpu.ops import pallas_fused

    out: list[Violation] = []

    def sds(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    for g in GRID:
        n, p, r, s, w = g["n"], g["p"], g["r"], g["s"], g["w"]
        snap_c, pods_c, delta_c = _make_inputs(g)
        snap, pods, delta = (
            _spec_tree(snap_c), _spec_tree(pods_c), _spec_tree(delta_c)
        )
        tag = f"[n={n} p={p} r={r} s={s}]"

        def expect(name, path, fn, args, want, fields=None, line=1):
            try:
                got = jax.eval_shape(fn, *args)
            except Exception as e:  # noqa: BLE001 — the trace failing IS the finding
                out.append(Violation(
                    RULE, path, line,
                    f"{name} {tag}: eval_shape trace failed: {e}",
                ))
                return None
            for msg in _leaf_mismatches(name, got, want, fields):
                out.append(Violation(RULE, path, line, f"{tag} {msg}"))
            return got

        sched_want = engine.ScheduleResult(
            node_idx=sds((p,), jnp.int32),
            scores=sds((p, n), jnp.float32),
            raw_scores=sds((p, n), jnp.float32),
            feasible=sds((p, n), jnp.bool_),
            free_after=sds((n, r), jnp.float32),
            n_assigned=sds((), jnp.int32),
        )
        unfused = expect(
            "schedule_batch", ENGINE_PATH,
            functools.partial(engine.schedule_batch, assigner="greedy"),
            (snap, pods), sched_want, engine.ScheduleResult._fields,
        )
        expect(
            "schedule_batch(auction)", ENGINE_PATH,
            functools.partial(
                engine.schedule_batch, assigner="auction", auction_rounds=4
            ),
            (snap, pods), sched_want, engine.ScheduleResult._fields,
        )
        # fused-vs-unfused drift: the fused megakernel path must present
        # the EXACT spec of the path it replaces
        if unfused is not None:
            expect(
                "schedule_batch(fused)", ENGINE_PATH,
                functools.partial(
                    engine.schedule_batch, assigner="greedy", fused=True
                ),
                (snap, pods), unfused, engine.ScheduleResult._fields,
            )
        pods_w = jax.tree_util.tree_map(
            lambda spec: sds((w,) + tuple(spec.shape), spec.dtype), pods
        )
        expect(
            "schedule_windows", ENGINE_PATH,
            engine.schedule_windows, (snap, pods_w),
            engine.WindowsResult(
                node_idx=sds((w, p), jnp.int32),
                free_after=sds((n, r), jnp.float32),
                n_assigned=sds((), jnp.int32),
            ),
            engine.WindowsResult._fields,
        )
        # donated folds are spec-preserving leaf for leaf
        expect(
            "apply_snapshot_delta", ENGINE_PATH,
            engine.apply_snapshot_delta, (snap, delta), snap,
            engine.SnapshotArrays._fields,
        )
        nn = -(-n // pallas_fused.TILE_N) * pallas_fused.TILE_N
        layout_want = engine.FusedLayout(
            node_ft=sds((3, nn), jnp.float32),
            alloc_t=sds((r, nn), jnp.float32),
            reqd_t=sds((r, nn), jnp.float32),
        )
        layout = expect(
            "build_fused_layout", ENGINE_PATH,
            engine.build_fused_layout, (snap,), layout_want,
            engine.FusedLayout._fields,
        )
        if layout is not None:
            expect(
                "apply_layout_delta", ENGINE_PATH,
                engine.apply_layout_delta, (layout, delta), layout_want,
                engine.FusedLayout._fields,
            )
        # ops/pallas_fused wrappers: kernel-layout padding formulas
        pp = -(-p // pallas_fused.TILE_P) * pallas_fused.TILE_P
        expect(
            "fused_masked_score", FUSED_PATH,
            pallas_fused.fused_masked_score,
            (
                sds((n,), jnp.float32), sds((n,), jnp.float32),
                sds((n,), jnp.bool_), sds((n, r), jnp.float32),
                sds((n, r), jnp.float32), sds((p,), jnp.float32),
                sds((p,), jnp.float32), sds((p, r), jnp.float32),
                sds((p,), jnp.bool_),
            ),
            sds((p, n), jnp.float32),
        )
        expect(
            "fused_score_row_stats", FUSED_PATH,
            pallas_fused.fused_score_row_stats,
            (sds((4, pp), jnp.float32), sds((3, nn), jnp.float32)),
            sds((2, pp), jnp.float32),
        )
        expect(
            "fused_auction_bid", FUSED_PATH,
            functools.partial(pallas_fused.fused_auction_bid, p=p),
            (
                sds((pp, nn), jnp.float32), sds((n,), jnp.float32),
                sds((p,), jnp.bool_), sds((r, pp), jnp.float32),
                sds((n, r), jnp.float32),
            ),
            (sds((p,), jnp.int32), sds((p,), jnp.bool_)),
        )
    return out


# the entry points the acceptance criteria pin — tests assert coverage
CONTRACT_NAMES = (
    "schedule_batch", "schedule_batch(auction)", "schedule_batch(fused)",
    "schedule_windows", "apply_snapshot_delta", "apply_layout_delta",
    "build_fused_layout", "fused_masked_score", "fused_score_row_stats",
    "fused_auction_bid",
)


def check_fixture_module(path: str) -> list[Violation]:
    """The miniature declarative form for fixtures and one-off modules:
    the module's CONTRACTS table is a list of

        {"fn": "name",
         "args": [("float32", ("n", "r")), ...],
         "out":  ("float32", ("n", "r"))  # or a list for tuple returns
         "grid": [{"n": 8, "r": 4}, ...]}

    dims are grid keys or int literals; each entry is eval_shape-checked
    at every grid point."""
    import importlib.util

    import jax
    import numpy as np

    rel = os.path.basename(path)
    spec = importlib.util.spec_from_file_location(
        f"_contract_fixture_{abs(hash(path))}", path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out: list[Violation] = []

    def resolve(shape, g):
        return tuple(d if isinstance(d, int) else g[d] for d in shape)

    def to_spec(entry, g):
        dtype, shape = entry
        return jax.ShapeDtypeStruct(resolve(shape, g), np.dtype(dtype))

    for decl in getattr(mod, "CONTRACTS", ()):
        fn = getattr(mod, decl["fn"])
        line = getattr(fn, "__code__", None)
        line = line.co_firstlineno if line else 1
        for g in decl["grid"]:
            args = [to_spec(a, g) for a in decl["args"]]
            want = decl["out"]
            want = (
                tuple(to_spec(o, g) for o in want)
                if isinstance(want, list)
                else to_spec(want, g)
            )
            try:
                got = jax.eval_shape(fn, *args)
            except Exception as e:  # noqa: BLE001
                out.append(Violation(
                    RULE, rel, line,
                    f"{decl['fn']} {g}: eval_shape trace failed: {e}",
                ))
                continue
            for msg in _leaf_mismatches(decl["fn"], got, want):
                out.append(Violation(RULE, rel, line, f"{g} {msg}"))
    return out
