"""Seeded SPMD mutants: the analyzer must catch each bug class.

The PR-10 lesson extended to the SPMD layer: an analyzer that has
never caught anything is an assertion, not a tool. Each mutant below
re-introduces one real SPMD bug class into a miniature mesh-sharded
scoring module (BASE below — psum statistics, a pmax/pmin bound pair,
an all_gather candidate election, a declared collective budget), and
the layer that owns that class MUST report it:

- `dropped-psum`: the global-mean psum deleted — the shard-local sum
  ships as if it were global. Caught by the AST rule (the value flows
  to a `P()` out_specs leaf still provably sharded) AND by the
  collective budget (psum count drifts down);
- `wrong-axis`: a collective moved onto an axis name no mesh declares
  — the deadlock/miscount class. Caught by the AST rule's unbound-axis
  check;
- `replicated-double-count`: a second psum wrapped around the already-
  replicated global sum — counts it D times. Caught by the AST rule's
  replicated-psum check (and the budget drifts too);
- `extra-gather-over-budget`: a gratuitous extra all_gather of a
  shard-local value — AST-silent by construction (gathering varying
  data is a legitimate shape), so ONLY the collective budget catches
  it: the per-round latency-tax class the budget exists for.

`check_spmd_mutants` runs on every full-repo lint (folded in next to
the contracts layer): the unmutated BASE must be clean on both layers,
and every mutant must be caught by EVERY layer it declares — a
survived mutant is itself a lint violation, the analyzer lost its
teeth for that class. tests/test_bench_smoke.py asserts the harness
one mutant at a time by name.
"""

from __future__ import annotations

import os
import tempfile

from kubernetes_scheduler_tpu.analysis.core import Violation

RULE = "spmd-mutant"

MUTANTS_PATH = "kubernetes_scheduler_tpu/analysis/spmd_mutants.py"

# the miniature sharded surface every mutant perturbs: one psum-based
# global statistic, a pmax bound, an axis_index/all_gather candidate
# election, and a two-leaf replicated output discharged the sanctioned
# way — with its own declared collective budget
BASE = '''\
"""SPMD mutant base: a miniature mesh-sharded scoring surface."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map

NODE_AXIS = "node"

# NOTE: psum(1, axis) of a literal constant-folds at trace time (the
# axis size is static), so only the data psum appears in the jaxpr
BUDGET = {"psum": 1, "pmax": 1, "pmin": 1, "all_gather": 1,
          "axis_index": 1}


def make_mesh():
    return Mesh(np.asarray(jax.devices()), (NODE_AXIS,))


def make_score_fn(mesh):
    def body(x, w):
        n_dev = jax.lax.psum(1, NODE_AXIS)
        total = jax.lax.psum(x.sum(), NODE_AXIS)
        mean = total / (n_dev * x.shape[0])
        hi = jax.lax.pmax(x.max(), NODE_AXIS)
        lo = jax.lax.pmin(x.min(), NODE_AXIS)
        scaled = (x - mean) * w.sum() / jnp.maximum(hi - lo, 1e-6)
        n_local = x.shape[0]
        offset = jax.lax.axis_index(NODE_AXIS).astype(jnp.int32) * n_local
        local_arg = jnp.argmax(scaled).astype(jnp.int32) + offset
        cand = jax.lax.all_gather(
            jnp.stack([scaled.max(), local_arg.astype(jnp.float32)]),
            NODE_AXIS,
        )
        best = cand[jnp.argmax(cand[:, 0]), 1].astype(jnp.int32)
        return best, mean

    kw = (
        "check_vma"
        if "check_vma" in __import__("inspect").signature(
            _shard_map
        ).parameters
        else "check_rep"
    )
    return _shard_map(
        body, mesh=mesh, in_specs=(P(NODE_AXIS), P()),
        out_specs=(P(), P()), **{kw: False},
    )
'''

# name -> (literal pattern, replacement, layers that MUST catch it)
SPMD_MUTANTS = {
    "dropped-psum": (
        "        total = jax.lax.psum(x.sum(), NODE_AXIS)\n",
        "        total = x.sum()\n",
        ("ast", "budget"),
    ),
    "wrong-axis": (
        "        hi = jax.lax.pmax(x.max(), NODE_AXIS)\n",
        '        hi = jax.lax.pmax(x.max(), "nodez")\n',
        ("ast",),
    ),
    "replicated-double-count": (
        "        mean = total / (n_dev * x.shape[0])\n",
        "        total = jax.lax.psum(total, NODE_AXIS)\n"
        "        mean = total / (n_dev * x.shape[0])\n",
        ("ast", "budget"),
    ),
    "extra-gather-over-budget": (
        "        best = cand[jnp.argmax(cand[:, 0]), 1].astype(jnp.int32)\n",
        "        extra = jax.lax.all_gather(scaled.min(), NODE_AXIS)\n"
        "        best = cand[jnp.argmax(cand[:, 0]), 1].astype(jnp.int32)\n"
        "        best = best + extra.astype(jnp.int32).min() * 0\n",
        ("budget",),
    ),
}


def mutate(name: str) -> str:
    pattern, replacement, _ = SPMD_MUTANTS[name]
    mutated = BASE.replace(pattern, replacement)
    if mutated == BASE:
        raise ValueError(
            f"mutant {name!r}: pattern no longer matches the BASE "
            "module — the harness drifted from its own source"
        )
    return mutated


def _ast_findings(source: str, workdir: str) -> list:
    """The spmd-collective family's findings on `source` (written to a
    scratch module so the normal lint path runs unchanged)."""
    from kubernetes_scheduler_tpu.analysis.core import run_lint

    path = os.path.join(workdir, "spmd_mutant_mod.py")
    with open(path, "w", encoding="utf-8") as f:
        f.write(source)
    return [
        v
        for v in run_lint([path], rules=["spmd-collective"])
        if not v.waived
    ]


def _budget_findings(source: str, workdir: str) -> list:
    """Trace the module's surface and diff against its own declared
    BUDGET (the same walk the repo-level gate runs against
    COLLECTIVE_BUDGET.json). A module that fails to trace counts as
    caught — the mutation broke the program outright."""
    import importlib.util

    import jax

    from kubernetes_scheduler_tpu.analysis.contracts import (
        COLLECTIVE_KINDS,
        collective_counts,
    )

    path = os.path.join(workdir, "spmd_mutant_traced.py")
    with open(path, "w", encoding="utf-8") as f:
        f.write(source)
    spec = importlib.util.spec_from_file_location("_spmd_mutant_mod", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn = mod.make_score_fn(mod.make_mesh())
    n = 16 * max(1, jax.device_count())
    x = jax.ShapeDtypeStruct((n,), "float32")
    w = jax.ShapeDtypeStruct((4,), "float32")
    try:
        counts = collective_counts(fn, x, w)
    except Exception as e:  # noqa: BLE001 — a broken trace IS a catch
        return [Violation(
            RULE, "spmd_mutant_traced.py", 1, f"trace failed: {e}",
        )]
    return [
        Violation(
            RULE, "spmd_mutant_traced.py", 1,
            f"{kind}: traced {counts.get(kind, 0)} != budgeted "
            f"{mod.BUDGET.get(kind, 0)}",
        )
        for kind in COLLECTIVE_KINDS
        if counts.get(kind, 0) != mod.BUDGET.get(kind, 0)
    ]


def run_spmd_mutant(name: str, workdir: str | None = None) -> dict:
    """{"ast": [findings], "budget": [findings]} for one mutant."""
    source = mutate(name)
    with tempfile.TemporaryDirectory() as tmp:
        wd = workdir or tmp
        return {
            "ast": _ast_findings(source, wd),
            "budget": _budget_findings(source, wd),
        }


def check_spmd_mutants() -> list[Violation]:
    """The lint entry point: [] when the unmutated base is clean on
    both layers and every mutant is caught by every layer it declares.
    A survived mutant means the SPMD analyzer (or the budget walk)
    lost its teeth for that bug class — a checker regression, not a
    code bug."""
    out: list[Violation] = []
    with tempfile.TemporaryDirectory() as tmp:
        base_ast = _ast_findings(BASE, tmp)
        base_budget = _budget_findings(BASE, tmp)
        for v in base_ast + base_budget:
            out.append(Violation(
                RULE, MUTANTS_PATH, 1,
                "the UNMUTATED spmd-mutant base module is dirty "
                f"(every catch would be vacuous): {v.message}",
            ))
        if out:
            return out
        for name, (_, _, expect) in SPMD_MUTANTS.items():
            try:
                got = run_spmd_mutant(name, workdir=tmp)
            except Exception as e:  # noqa: BLE001
                out.append(Violation(
                    RULE, MUTANTS_PATH, 1,
                    f"seeded SPMD mutant `{name}` harness error: {e}",
                ))
                continue
            for layer in expect:
                if not got[layer]:
                    out.append(Violation(
                        RULE, MUTANTS_PATH, 1,
                        f"seeded SPMD mutant `{name}` SURVIVED the "
                        f"{layer} layer — the analyzer lost its teeth "
                        "for this bug class (see "
                        f"SPMD_MUTANTS[{name!r}])",
                    ))
    return out
