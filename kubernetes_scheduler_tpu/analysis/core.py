"""graftlint core: violations, inline waivers, file collection, runner.

Rules are pure functions `check(ctx) -> list[Violation]` registered in
rules/__init__.py. The runner parses every in-scope file once; rules pick
their own file subsets (kernel dirs, host cycle path, bridge) unless the
caller passed explicit paths (fixture mode), in which case every given
file is in scope for every requested rule.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import os
import re
from dataclasses import dataclass, field

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_PKG_DIR = os.path.join(_REPO_ROOT, "kubernetes_scheduler_tpu")

# generated / vendored files never linted
_EXCLUDE = ("*_pb2.py",)

# graftlint: disable=<rule>[,<rule>|all] -- <justification>
_WAIVER_RE = re.compile(
    r"#\s*graftlint:\s*disable=([\w,\-]+)(?:\s+--\s*(\S.*))?"
)


@dataclass
class Violation:
    rule: str
    path: str          # repo-relative
    line: int
    message: str
    waived: bool = False
    waiver_reason: str | None = None

    def format(self) -> str:
        tag = " (waived: %s)" % self.waiver_reason if self.waived else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


@dataclass
class SourceFile:
    path: str          # repo-relative, forward slashes
    abspath: str
    source: str
    tree: ast.AST
    lines: list[str] = field(default_factory=list)
    # line -> (set of rule names | {"all"}, reason | None)
    waivers: dict[int, tuple[set, str | None]] = field(default_factory=dict)
    # (start, end, rules, reason) spans: a waiver above a decorator
    # covers the whole def; one on a multi-line statement covers every
    # line of the statement
    waiver_spans: list[tuple] = field(default_factory=list)

    def matches(self, patterns) -> bool:
        return any(fnmatch.fnmatch(self.path, p) for p in patterns)

    def waiver_for(self, line: int, rule: str):
        """(rules, reason) of the waiver covering `line` for `rule`, or
        None — exact-line waivers first, then statement/def spans."""
        w = self.waivers.get(line)
        if w and (rule in w[0] or "all" in w[0]):
            return w
        for start, end, rules, reason in self.waiver_spans:
            if start <= line <= end and (rule in rules or "all" in rules):
                return (rules, reason)
        return None


@dataclass
class Context:
    root: str
    files: list[SourceFile]
    # explicit file list given (fixture mode): rules scan everything
    explicit: bool = False
    # proto override for the wire-schema rule (tests)
    proto_path: str | None = None
    # the run's shared parse-once ModuleIndex (analysis/dataflow.py),
    # built lazily by dataflow.get_index and reused by every family
    _index: object | None = None

    def scoped(self, patterns) -> list[SourceFile]:
        if self.explicit:
            return self.files
        return [f for f in self.files if f.matches(patterns)]


def _parse_waivers(sf: SourceFile) -> list[Violation]:
    """Populate sf.waivers; a waiver with no justification is itself a
    violation (`bad-waiver`, unwaivable)."""
    bad = []
    for i, line in enumerate(sf.lines, start=1):
        m = _WAIVER_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = m.group(2)
        if not reason:
            bad.append(
                Violation(
                    "bad-waiver", sf.path, i,
                    "waiver missing justification: write "
                    "`# graftlint: disable=<rule> -- <why this is safe>`",
                )
            )
            continue
        target = i
        # a comment-only line waives the NEXT line
        if line.split("#", 1)[0].strip() == "":
            target = i + 1
        entry = sf.waivers.setdefault(target, (set(), reason.strip()))
        entry[0].update(rules)
    _resolve_waiver_spans(sf)
    return bad


def _resolve_waiver_spans(sf: SourceFile) -> None:
    """Widen line-targeted waivers whose target is structural:

    - a waiver landing on a DECORATOR line (a comment above `@jit(...)`)
      waives the whole decorated def — the finding it suppresses is a
      property of the function, not of the one line the parser happened
      to attribute it to;
    - a waiver landing on the first line of a MULTI-LINE simple
      statement covers every line of that statement (a violating
      `dtype=` keyword two lines into a call is the same finding).

    Waivers already inside the def/statement keep exact-line semantics —
    widening those would let one waiver silence unrelated findings."""
    if not sf.waivers:
        return
    dec_spans = []   # (first decorator line, def line, def end)
    stmt_spans = {}  # lineno -> end_lineno for multi-line simple stmts
    for node in ast.walk(sf.tree):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ) and node.decorator_list:
            first = min(d.lineno for d in node.decorator_list)
            dec_spans.append((first, node.lineno, node.end_lineno or node.lineno))
        elif isinstance(node, ast.stmt) and not isinstance(
            node,
            (
                ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.If, ast.For, ast.AsyncFor, ast.While, ast.With,
                ast.AsyncWith, ast.Try,
            ),
        ):
            end = node.end_lineno or node.lineno
            if end > node.lineno:
                stmt_spans[node.lineno] = max(
                    end, stmt_spans.get(node.lineno, 0)
                )
    for target, (rules, reason) in sf.waivers.items():
        for first, def_line, def_end in dec_spans:
            if first <= target < def_line:
                sf.waiver_spans.append((first, def_end, rules, reason))
                break
        else:
            if target in stmt_spans:
                sf.waiver_spans.append(
                    (target, stmt_spans[target], rules, reason)
                )


def load_file(abspath: str, root: str) -> SourceFile | None:
    with open(abspath, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=abspath)
    except SyntaxError:
        return None
    rel = os.path.relpath(abspath, root).replace(os.sep, "/")
    return SourceFile(
        path=rel, abspath=abspath, source=source, tree=tree,
        lines=source.splitlines(),
    )


def collect_files(root: str | None = None) -> list[str]:
    """Every lintable .py file in the package (the linter's own code
    included — it must hold itself to the repo's invariants)."""
    root = root or _REPO_ROOT
    out = []
    for dirpath, dirnames, filenames in os.walk(
        os.path.join(root, "kubernetes_scheduler_tpu")
    ):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            if any(fnmatch.fnmatch(name, p) for p in _EXCLUDE):
                continue
            out.append(os.path.join(dirpath, name))
    return out


def run_lint(
    paths: list[str] | None = None,
    *,
    rules: list[str] | None = None,
    root: str | None = None,
    proto_path: str | None = None,
    ctx_out: list | None = None,
) -> list[Violation]:
    """Lint `paths` (default: the whole package) with `rules` (default:
    all). Returns every violation, waived ones flagged. `ctx_out`, if
    given, receives the run's Context (the CLI's --changed-only mode
    reuses its parse-once index for the reverse-dependency closure
    instead of re-parsing the repo)."""
    from kubernetes_scheduler_tpu.analysis.rules import RULES

    root = root or _REPO_ROOT
    explicit = paths is not None
    abspaths = (
        [os.path.abspath(p) for p in paths]
        if explicit
        else collect_files(root)
    )
    files = []
    violations: list[Violation] = []
    for p in abspaths:
        sf = load_file(p, root)
        if sf is None:
            violations.append(
                Violation(
                    "parse", os.path.relpath(p, root).replace(os.sep, "/"),
                    1, "file does not parse",
                )
            )
            continue
        violations.extend(_parse_waivers(sf))
        files.append(sf)
    ctx = Context(
        root=root, files=files, explicit=explicit, proto_path=proto_path
    )
    if ctx_out is not None:
        ctx_out.append(ctx)
    selected = rules or list(RULES)
    unknown = set(selected) - set(RULES)
    if unknown:
        raise ValueError(f"unknown lint rules: {sorted(unknown)}")
    for name in selected:
        violations.extend(RULES[name](ctx))
    if not explicit and rules is None:
        violations.extend(_check_readme_rules(root, RULES))
    # apply waivers
    by_path = {f.path: f for f in files}
    for v in violations:
        sf = by_path.get(v.path)
        if sf is None or v.rule == "bad-waiver":
            continue
        w = sf.waiver_for(v.line, v.rule)
        if w is not None:
            v.waived = True
            v.waiver_reason = w[1]
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return violations


def _check_readme_rules(root: str, rules: dict) -> list[Violation]:
    """README's lint table must name EXACTLY the registered rule
    families — drift in either direction fails `make lint` (pseudo-rule
    `docs-drift`, unwaivable like bad-waiver). The table is the block of
    `| \\`rule\\` | ... |` rows under the "## Static analysis" heading."""
    readme = os.path.join(root, "README.md")
    if not os.path.exists(readme):
        return []
    with open(readme, encoding="utf-8") as f:
        text = f.read()
    m = re.search(r"^## Static analysis.*?$", text, re.M)
    if m is None:
        return [
            Violation(
                "docs-drift", "README.md", 1,
                "README has no `## Static analysis` section documenting "
                "the lint families",
            )
        ]
    section = text[m.end():]
    # the families table lives in the section intro; subsections (the
    # contract and protocol-model layers) may carry tables of their own
    # (model inventories), which are not rule rows
    nxt = re.search(r"^#{2,3} ", section, re.M)
    if nxt:
        section = section[: nxt.start()]
    documented: dict[str, int] = {}
    base_line = text[: m.end()].count("\n") + 1
    for i, line in enumerate(section.splitlines()):
        row = re.match(r"\|\s*`([a-z][\w-]*)`\s*\|", line)
        if row:
            documented[row.group(1)] = base_line + i
    out = []
    for name in sorted(set(rules) - set(documented)):
        out.append(
            Violation(
                "docs-drift", "README.md", base_line,
                f"registered lint family `{name}` is missing from the "
                "README's Static analysis table",
            )
        )
    for name, line in sorted(documented.items()):
        if name not in rules:
            out.append(
                Violation(
                    "docs-drift", "README.md", line,
                    f"README's Static analysis table documents `{name}`, "
                    "which is not a registered lint family",
                )
            )
    return out


# ---- changed-only scoping (fast pre-commit loop) ---------------------------


def changed_vs_ref(root: str, ref: str) -> set[str]:
    """Repo-relative paths changed vs `ref` (committed diff + working
    tree + untracked). A change to bridge/schedule.proto counts as a
    change to the bridge modules that encode it — the wire-schema and
    capability-completeness families check .py files against the proto,
    so a proto-only edit must still pull them into scope."""
    import subprocess

    out: set[str] = set()
    for args in (
        ["git", "diff", "--name-only", ref, "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            res = subprocess.run(
                args, cwd=root, capture_output=True, text=True,
                check=True, timeout=30,
            )
        except (OSError, subprocess.SubprocessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            raise ValueError(
                f"--changed-only {ref}: {' '.join(args)} failed: "
                f"{detail.strip()}"
            ) from e
        out.update(p.strip() for p in res.stdout.splitlines() if p.strip())
    changed: set[str] = set()
    for p in out:
        p = p.replace(os.sep, "/")
        if p.endswith("schedule.proto"):
            changed.update((
                "kubernetes_scheduler_tpu/bridge/client.py",
                "kubernetes_scheduler_tpu/bridge/server.py",
                "kubernetes_scheduler_tpu/bridge/codec.py",
            ))
        elif p.endswith("COLLECTIVE_BUDGET.json"):
            # a budget edit must re-trace the sharded surfaces it pins
            changed.add("kubernetes_scheduler_tpu/parallel/engine.py")
        elif p.endswith(".py") and p.startswith("kubernetes_scheduler_tpu/"):
            changed.add(p)
    return changed


def reverse_dependency_closure(ctx: Context, changed: set[str]) -> set[str]:
    """`changed` plus every package file that depends on one of them,
    transitively — dependence meaning a module import OR a resolved
    call-graph edge into the file (the shared parse-once ModuleIndex).
    A pre-commit lint scoped to this closure sees every finding the
    edit could have created or fixed; findings wholly outside it are
    unaffected by construction (pinned: changed-only findings are a
    subset of the full run's)."""
    from kubernetes_scheduler_tpu.analysis import dataflow

    index = dataflow.get_index(ctx)
    known = {f.path for f in ctx.files}
    # file -> files it depends on (imports + call edges)
    deps: dict[str, set[str]] = {p: set() for p in known}
    for path, imports in index.imports.items():
        for dotted in imports.values():
            # `from pkg.mod import name` records pkg.mod.name; resolve
            # the longest module prefix actually in the package
            parts = dotted.split(".")
            for i in range(len(parts), 0, -1):
                target = index.by_module.get(".".join(parts[:i]))
                if target is not None:
                    if target.path != path:
                        deps[path].add(target.path)
                    break
    for caller, edges in index.call_graph().items():
        cfile = caller.split("::", 1)[0]
        for callee, _ in edges:
            tfile = callee.split("::", 1)[0]
            if tfile != cfile and cfile in deps:
                deps[cfile].add(tfile)
    closure = set(changed) & known
    frontier = list(closure)
    rev: dict[str, list[str]] = {}
    for p, targets in deps.items():
        for t in targets:
            rev.setdefault(t, []).append(p)
    while frontier:
        t = frontier.pop()
        for p in rev.get(t, ()):
            if p not in closure:
                closure.add(p)
                frontier.append(p)
    # the declared thread model couples its root modules: a cross-file
    # race pairs a write in one root's file with a read reachable from
    # another root's, so a change to any thread-root module (or to the
    # model itself) pulls EVERY root module into scope — the thread-race
    # family must see both sides of each pair. Closure only grows, so
    # changed-only stays a subset of the full run.
    from kubernetes_scheduler_tpu.analysis.threads import THREAD_ROOTS

    root_paths = {r.path for r in THREAD_ROOTS} & known
    model_path = "kubernetes_scheduler_tpu/analysis/threads.py"
    if closure & (root_paths | {model_path}):
        closure |= root_paths
    return closure


# ---- baseline (CI suppression) file ---------------------------------------

BASELINE_NAME = "LINT_BASELINE.json"

# hygiene pseudo-rules police the suppression machinery itself — letting
# the baseline waive them would let it silence its own failure modes
UNBASELINABLE = frozenset(
    {"bad-waiver", "docs-drift", "bad-baseline", "stale-baseline"}
)


def load_baseline(path: str) -> list[dict]:
    """Entries of a checked-in baseline file: each {"rule", "path",
    "contains", "reason"} suppresses active findings whose rule+path
    match and whose message contains the fragment. CI diffs findings
    against this instead of grepping logs."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or not isinstance(doc.get("entries"), list):
        raise ValueError(f"{path}: baseline must be {{'entries': [...]}}")
    return doc["entries"]


def apply_baseline(
    violations: list[Violation], entries: list[dict], baseline_path: str,
    check_stale: bool = True,
) -> list[Violation]:
    """Waive findings matched by baseline entries. Returns EXTRA
    violations: an entry with no reason, and an entry matching nothing
    (stale — the finding it blessed is gone), both fail lint so the
    baseline can only hold explained, live suppressions. Pass
    check_stale=False for path/rule-scoped runs: an entry whose target
    is outside the scope produces no finding to match, and only the
    full-repo run can tell 'out of scope' from 'actually stale'."""
    rel = os.path.basename(baseline_path)
    extra: list[Violation] = []
    for i, e in enumerate(entries):
        if not isinstance(e, dict):
            extra.append(
                Violation(
                    "bad-baseline", rel, i + 1,
                    f"baseline entry {i} is {type(e).__name__!s}, not an "
                    "object — each entry must be {rule, path, contains, "
                    "reason}",
                )
            )
            continue
        reason = (e.get("reason") or "").strip()
        if not reason:
            extra.append(
                Violation(
                    "bad-baseline", rel, i + 1,
                    f"baseline entry {i} ({e.get('rule')}: {e.get('path')}) "
                    "has no reason — every suppression must be explained",
                )
            )
            continue
        if e.get("rule") in UNBASELINABLE:
            extra.append(
                Violation(
                    "bad-baseline", rel, i + 1,
                    f"baseline entry {i} targets hygiene pseudo-rule "
                    f"`{e.get('rule')}` — waiver/baseline/docs findings "
                    "cannot be suppressed",
                )
            )
            continue
        matched = False
        for v in violations:
            if v.waived or v.rule != e.get("rule"):
                continue
            if v.path != e.get("path"):
                continue
            if e.get("contains") and e["contains"] not in v.message:
                continue
            v.waived = True
            v.waiver_reason = f"baseline: {reason}"
            matched = True
        if not matched and check_stale:
            extra.append(
                Violation(
                    "stale-baseline", rel, i + 1,
                    f"baseline entry {i} ({e.get('rule')}: {e.get('path')}) "
                    "matches no current finding — delete it",
                )
            )
    return extra


# ---- shared AST helpers ---------------------------------------------------


def dotted_name(node: ast.AST) -> str | None:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def has_kwarg(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)
