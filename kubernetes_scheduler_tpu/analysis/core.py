"""graftlint core: violations, inline waivers, file collection, runner.

Rules are pure functions `check(ctx) -> list[Violation]` registered in
rules/__init__.py. The runner parses every in-scope file once; rules pick
their own file subsets (kernel dirs, host cycle path, bridge) unless the
caller passed explicit paths (fixture mode), in which case every given
file is in scope for every requested rule.
"""

from __future__ import annotations

import ast
import fnmatch
import os
import re
from dataclasses import dataclass, field

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_PKG_DIR = os.path.join(_REPO_ROOT, "kubernetes_scheduler_tpu")

# generated / vendored files never linted
_EXCLUDE = ("*_pb2.py",)

# graftlint: disable=<rule>[,<rule>|all] -- <justification>
_WAIVER_RE = re.compile(
    r"#\s*graftlint:\s*disable=([\w,\-]+)(?:\s+--\s*(\S.*))?"
)


@dataclass
class Violation:
    rule: str
    path: str          # repo-relative
    line: int
    message: str
    waived: bool = False
    waiver_reason: str | None = None

    def format(self) -> str:
        tag = " (waived: %s)" % self.waiver_reason if self.waived else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


@dataclass
class SourceFile:
    path: str          # repo-relative, forward slashes
    abspath: str
    source: str
    tree: ast.AST
    lines: list[str] = field(default_factory=list)
    # line -> (set of rule names | {"all"}, reason | None)
    waivers: dict[int, tuple[set, str | None]] = field(default_factory=dict)

    def matches(self, patterns) -> bool:
        return any(fnmatch.fnmatch(self.path, p) for p in patterns)


@dataclass
class Context:
    root: str
    files: list[SourceFile]
    # explicit file list given (fixture mode): rules scan everything
    explicit: bool = False
    # proto override for the wire-schema rule (tests)
    proto_path: str | None = None

    def scoped(self, patterns) -> list[SourceFile]:
        if self.explicit:
            return self.files
        return [f for f in self.files if f.matches(patterns)]


def _parse_waivers(sf: SourceFile) -> list[Violation]:
    """Populate sf.waivers; a waiver with no justification is itself a
    violation (`bad-waiver`, unwaivable)."""
    bad = []
    for i, line in enumerate(sf.lines, start=1):
        m = _WAIVER_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = m.group(2)
        if not reason:
            bad.append(
                Violation(
                    "bad-waiver", sf.path, i,
                    "waiver missing justification: write "
                    "`# graftlint: disable=<rule> -- <why this is safe>`",
                )
            )
            continue
        target = i
        # a comment-only line waives the NEXT line
        if line.split("#", 1)[0].strip() == "":
            target = i + 1
        entry = sf.waivers.setdefault(target, (set(), reason.strip()))
        entry[0].update(rules)
    return bad


def load_file(abspath: str, root: str) -> SourceFile | None:
    with open(abspath, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=abspath)
    except SyntaxError:
        return None
    rel = os.path.relpath(abspath, root).replace(os.sep, "/")
    return SourceFile(
        path=rel, abspath=abspath, source=source, tree=tree,
        lines=source.splitlines(),
    )


def collect_files(root: str | None = None) -> list[str]:
    """Every lintable .py file in the package (the linter's own code
    included — it must hold itself to the repo's invariants)."""
    root = root or _REPO_ROOT
    out = []
    for dirpath, dirnames, filenames in os.walk(
        os.path.join(root, "kubernetes_scheduler_tpu")
    ):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            if any(fnmatch.fnmatch(name, p) for p in _EXCLUDE):
                continue
            out.append(os.path.join(dirpath, name))
    return out


def run_lint(
    paths: list[str] | None = None,
    *,
    rules: list[str] | None = None,
    root: str | None = None,
    proto_path: str | None = None,
) -> list[Violation]:
    """Lint `paths` (default: the whole package) with `rules` (default:
    all). Returns every violation, waived ones flagged."""
    from kubernetes_scheduler_tpu.analysis.rules import RULES

    root = root or _REPO_ROOT
    explicit = paths is not None
    abspaths = (
        [os.path.abspath(p) for p in paths]
        if explicit
        else collect_files(root)
    )
    files = []
    violations: list[Violation] = []
    for p in abspaths:
        sf = load_file(p, root)
        if sf is None:
            violations.append(
                Violation(
                    "parse", os.path.relpath(p, root).replace(os.sep, "/"),
                    1, "file does not parse",
                )
            )
            continue
        violations.extend(_parse_waivers(sf))
        files.append(sf)
    ctx = Context(
        root=root, files=files, explicit=explicit, proto_path=proto_path
    )
    selected = rules or list(RULES)
    unknown = set(selected) - set(RULES)
    if unknown:
        raise ValueError(f"unknown lint rules: {sorted(unknown)}")
    for name in selected:
        violations.extend(RULES[name](ctx))
    # apply waivers
    by_path = {f.path: f for f in files}
    for v in violations:
        sf = by_path.get(v.path)
        if sf is None or v.rule == "bad-waiver":
            continue
        w = sf.waivers.get(v.line)
        if w and (v.rule in w[0] or "all" in w[0]):
            v.waived = True
            v.waiver_reason = w[1]
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return violations


# ---- shared AST helpers ---------------------------------------------------


def dotted_name(node: ast.AST) -> str | None:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def has_kwarg(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)
