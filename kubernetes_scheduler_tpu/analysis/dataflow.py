"""Interprocedural dataflow core shared by the graftlint rule families.

Layer 1 of the two-layer analysis engine (layer 2 — the eval_shape
contract checker — lives in analysis/contracts.py). One build per lint
run, cached on the Context:

- a parse-once, WALK-once module index: every file's AST node list,
  function/class/import tables, and dotted-module resolution, so the
  sixteen rule families share one traversal instead of re-walking the
  tree per family (the wall-time budget `make lint` asserts rides on
  this);
- a project call graph with call-site attribution, resolved through
  imports (`from kubernetes_scheduler_tpu import engine` →
  `engine.apply_snapshot_delta` lands on the real def in engine.py),
  same-file scopes, `self.method` dispatch within a class, and a
  conservative bare-name fallback (over-approximation flags at worst an
  extra waivable site — the same contract _jitgraph established);
- per-function def-use with BRANCH PATHS: each load/store/call carries
  the tuple of enclosing suites, so a rule can tell "after the call on
  the same control path" from a read in a mutually exclusive arm;
- donation summaries: `donate_argnums` positions read off jit
  decorators and PROPAGATED through wrappers (a helper that passes its
  own parameter into a donated position donates that parameter too) —
  the machinery donation-aliasing needs to catch a re-read a
  single-file AST scan cannot see;
- a lockset walker: per-class `with self._lock:` contexts threaded
  through intra-class helper calls to a fixpoint of entry locksets
  (lockset-race's engine).

Everything here is name-based and syntactic — no imports of the
analyzed code, no type inference. Precision choices are documented at
each helper; the inline-waiver syntax absorbs the residue.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    SourceFile,
    dotted_name,
)

# ---- module index ---------------------------------------------------------


@dataclass
class FuncInfo:
    """One function/method def, with enough scope context to resolve
    calls against it."""

    qname: str                  # "<path>::Outer.inner" — unique per def
    name: str                   # bare name
    sf: SourceFile
    node: ast.AST               # FunctionDef | AsyncFunctionDef
    cls: ast.ClassDef | None    # enclosing class, if a method
    module: str                 # dotted module ("kubernetes_scheduler_tpu.engine")


def module_dotted(path: str) -> str:
    """Repo-relative path -> dotted module name."""
    mod = path[:-3] if path.endswith(".py") else path
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


class ModuleIndex:
    """Parse-once/walk-once project index. Built lazily by
    `Context.index` and shared by every rule family in the run."""

    def __init__(self, files: list[SourceFile]):
        self.files = files
        self._walks: dict[str, list[ast.AST]] = {}
        self.funcs: dict[str, FuncInfo] = {}
        self.by_name: dict[str, list[FuncInfo]] = {}
        # class name -> [(sf, ClassDef)] (name collisions kept — resolution
        # stays conservative)
        self.classes: dict[str, list[tuple]] = {}
        self.by_module: dict[str, SourceFile] = {}
        # path -> alias -> dotted target ("np" -> "numpy",
        # "engine" -> "kubernetes_scheduler_tpu.engine",
        # "apply_snapshot_delta" -> "kubernetes_scheduler_tpu.engine.apply_snapshot_delta")
        self.imports: dict[str, dict[str, str]] = {}
        self._call_graph: dict[str, list[tuple[str, ast.Call]]] | None = None
        # (callee qname, id(call)) pairs where the edge comes from a bare
        # function REFERENCE passed as an argument, not a direct call
        self._ref_edges: set[tuple[str, int]] = set()
        self._jit_reachable: set[str] | None = None
        for sf in files:
            self.by_module[module_dotted(sf.path)] = sf
            self._index_file(sf)

    # -- construction --

    def _index_file(self, sf: SourceFile) -> None:
        nodes = list(ast.walk(sf.tree))
        self._walks[sf.path] = nodes
        imports: dict[str, str] = {}
        self.imports[sf.path] = imports
        pkg = module_dotted(sf.path).rsplit(".", 1)[0]
        for node in nodes:
            if isinstance(node, ast.Import):
                for a in node.names:
                    imports[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:  # relative: resolve against this package
                    up = pkg.split(".")
                    up = up[: len(up) - (node.level - 1)]
                    base = ".".join(up + ([base] if base else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    imports[a.asname or a.name] = f"{base}.{a.name}"
            elif isinstance(node, ast.ClassDef):
                self.classes.setdefault(node.name, []).append((sf, node))
        self._index_scope(sf, sf.tree, (), None)

    def _index_scope(self, sf, node, scope, cls) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qname = f"{sf.path}::{'.'.join(scope + (child.name,))}"
                fi = FuncInfo(
                    qname=qname, name=child.name, sf=sf, node=child,
                    cls=cls, module=module_dotted(sf.path),
                )
                self.funcs[qname] = fi
                self.by_name.setdefault(child.name, []).append(fi)
                self._index_scope(sf, child, scope + (child.name,), cls)
            elif isinstance(child, ast.ClassDef):
                self._index_scope(
                    sf, child, scope + (child.name,), child
                )
            else:
                self._index_scope(sf, child, scope, cls)

    # -- shared traversal --

    def walk(self, sf: SourceFile) -> list[ast.AST]:
        """The file's full node list from the ONE walk done at index
        build — rules filter by isinstance instead of re-walking."""
        return self._walks[sf.path]

    def functions(self, sf: SourceFile) -> list[FuncInfo]:
        return [fi for fi in self.funcs.values() if fi.sf is sf]

    # -- call resolution --

    def resolve_call(
        self, fi: FuncInfo, call: ast.Call, *, loose: bool = True
    ) -> list[FuncInfo]:
        """Candidate defs a call may land on. Resolution order: `self.m`
        within the enclosing class; imported names (module attr chains
        included); same-file bare names; then — with loose=True — every
        same-named def project-wide (the _jitgraph over-approximation,
        minus `self.` chains, which never leave the class)."""
        dn = dotted_name(call.func)
        if dn is None:
            return []
        parts = dn.split(".")
        if parts[0] == "self":
            if len(parts) == 2 and fi.cls is not None:
                return [
                    cand
                    for cand in self.by_name.get(parts[1], ())
                    if cand.cls is fi.cls
                ]
            return []
        imports = self.imports.get(fi.sf.path, {})
        if parts[0] in imports:
            target = ".".join([imports[parts[0]]] + parts[1:])
            mod, _, name = target.rpartition(".")
            sf2 = self.by_module.get(mod)
            if sf2 is None:
                # suffix match: fixture files are linted by explicit
                # path, so `from helper_mod import f` must still land on
                # the sibling file indexed as tests.….helper_mod
                for m2, cand_sf in self.by_module.items():
                    if m2 == mod or m2.endswith("." + mod):
                        sf2 = cand_sf
                        break
            if sf2 is not None:
                return [
                    cand
                    for cand in self.by_name.get(name, ())
                    if cand.sf is sf2 and cand.cls is None
                ]
            # import of something outside the project (numpy, jax, ...)
            return []
        same_file = [
            cand
            for cand in self.by_name.get(parts[-1], ())
            if cand.sf is fi.sf
        ]
        if same_file or not loose:
            return same_file
        return list(self.by_name.get(parts[-1], ()))

    def call_graph(self) -> dict[str, list[tuple[str, ast.Call]]]:
        """qname -> [(callee qname, call site)] over every resolved call
        (and bare function reference passed as an argument — scan/vmap
        bodies transfer control too)."""
        if self._call_graph is not None:
            return self._call_graph
        graph: dict[str, list[tuple[str, ast.Call]]] = {}
        for fi in self.funcs.values():
            edges: list[tuple[str, ast.Call]] = []
            for node in shallow_walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                for callee in self.resolve_call(fi, node):
                    edges.append((callee.qname, node))
                for arg in list(node.args) + [
                    kw.value for kw in node.keywords
                ]:
                    aname = dotted_name(arg)
                    if aname and not aname.startswith("self."):
                        for cand in self.by_name.get(
                            aname.rsplit(".", 1)[-1], ()
                        ):
                            edges.append((cand.qname, node))
                            self._ref_edges.add((cand.qname, id(node)))
            graph[fi.qname] = edges
        self._call_graph = graph
        return graph

    def ref_edges(self) -> set[tuple[str, int]]:
        """(callee qname, id(call site)) for every bare-reference edge in
        the call graph. Reachability WANTS these (a scan body transfers
        control); argument-position analyses must SKIP them — the outer
        call's positional args do not line up with the referenced
        callee's signature, so indexing them invents facts."""
        self.call_graph()
        return self._ref_edges

    def callees(self, qname: str) -> set[str]:
        return {c for c, _ in self.call_graph().get(qname, ())}

    def reachable_from(self, roots: set[str]) -> set[str]:
        """Transitive closure over the call graph."""
        seen: set[str] = set()
        stack = [q for q in roots if q in self.funcs]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            stack.extend(c for c in self.callees(q) if c not in seen)
        return seen

    # -- jit reachability (project-wide) --

    def jit_entries(self) -> set[str]:
        """qnames of defs that are jax.jit/pjit entry points (decorator
        or `jax.jit(fn)` expression forms)."""
        entries: set[str] = set()
        for fi in self.funcs.values():
            if any(
                _decorator_is_jit(d)
                for d in getattr(fi.node, "decorator_list", ())
            ):
                entries.add(fi.qname)
        for sf in self.files:
            for node in self.walk(sf):
                if isinstance(node, ast.Call) and (
                    dotted_name(node.func) in _JIT_MAKERS
                ):
                    for arg in node.args[:1]:
                        name = dotted_name(arg)
                        if not name:
                            continue
                        for cand in self.by_name.get(
                            name.rsplit(".", 1)[-1], ()
                        ):
                            entries.add(cand.qname)
        return entries

    def jit_reachable(self) -> set[str]:
        if self._jit_reachable is None:
            self._jit_reachable = self.reachable_from(self.jit_entries())
        return self._jit_reachable


_JIT_MAKERS = {"jit", "jax.jit", "pjit", "jax.experimental.pjit.pjit"}


def _decorator_is_jit(dec: ast.AST) -> bool:
    name = dotted_name(dec)
    if name in _JIT_MAKERS:
        return True
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func)
        if fname in _JIT_MAKERS:
            return True
        if fname in ("functools.partial", "partial") and dec.args:
            return dotted_name(dec.args[0]) in _JIT_MAKERS
    return False


def get_index(ctx: Context) -> ModuleIndex:
    """The run's shared index, built once and cached on the Context."""
    idx = getattr(ctx, "_index", None)
    if idx is None:
        idx = ModuleIndex(ctx.files)
        ctx._index = idx
    return idx


# ---- scope-bounded traversal ---------------------------------------------

_FN_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_SUITE_FIELDS = ("body", "orelse", "finalbody")


def shallow_walk(fn: ast.AST):
    """Every node in `fn`'s own scope — nested function/class bodies
    excluded (they are indexed as their own scopes)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _FN_DEFS + (ast.ClassDef,)):
            stack.extend(ast.iter_child_nodes(node))


def _shallow_stmt(node):
    """The statement plus its expression-level parts — never descending
    into nested suites (those get their own branch path) or nested
    function scopes."""
    yield node
    for fname, value in ast.iter_fields(node):
        if fname in _SUITE_FIELDS or fname == "handlers":
            continue
        for child in value if isinstance(value, list) else [value]:
            if isinstance(child, ast.AST) and not isinstance(child, _FN_DEFS):
                yield from _shallow_stmt(child)


def visit_suites(stmts, path, sink):
    """Walk statement suites recording each node's BRANCH PATH — a tuple
    of (enclosing statement id, suite field) — so a dataflow rule can
    tell 'after the call on the same control path' from a load in a
    mutually exclusive arm. `sink(node, path)` is called for every
    expression-level node."""
    for st in stmts:
        if isinstance(st, _FN_DEFS):
            continue  # separate scope: indexed as its own function
        for node in _shallow_stmt(st):
            sink(node, path)
        for fname in _SUITE_FIELDS:
            suite = getattr(st, fname, None)
            if suite:
                visit_suites(suite, path + ((id(st), fname),), sink)
        for h in getattr(st, "handlers", None) or ():
            visit_suites(h.body, path + ((id(st), id(h)),), sink)
        # match arms: each case body is its own mutually-exclusive suite
        # (match_case.body is a suite field _shallow_stmt rightly skips,
        # but Match itself has no `body`, so without this the arms were
        # invisible to every def_use-based rule)
        for case in getattr(st, "cases", None) or ():
            visit_suites(case.body, path + ((id(st), id(case)),), sink)


def path_prefix(a: tuple, b: tuple) -> bool:
    """True when branch path `a` structurally precedes `b` (same control
    path or an enclosing one)."""
    return b[: len(a)] == a


@dataclass
class DefUse:
    """Flat def-use facts for one function body, branch paths attached.
    Loads/assigns track full dotted names (`x`, `self._state.snapshot`),
    so attribute chains participate in donation tracking too."""

    calls: list = field(default_factory=list)    # (lineno, ast.Call, path)
    assigns: list = field(default_factory=list)  # (lineno, dotted target, path)
    loads: list = field(default_factory=list)    # (lineno, dotted name, path)


def def_use(fn: ast.AST) -> DefUse:
    du = DefUse()

    def sink(node, path):
        if isinstance(node, ast.Call):
            du.calls.append((node.lineno, node, path))
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                for leaf in ast.walk(t):
                    dn = dotted_name(leaf)
                    if dn:
                        du.assigns.append((node.lineno, dn, path))
                    elif isinstance(leaf, ast.Name):
                        du.assigns.append((node.lineno, leaf.id, path))
        elif isinstance(node, (ast.Attribute, ast.Name)) and isinstance(
            getattr(node, "ctx", None), ast.Load
        ):
            dn = dotted_name(node)
            if dn:
                du.loads.append((node.lineno, dn, path))

    visit_suites(fn.body, (), sink)
    return du


# ---- donation summaries ---------------------------------------------------


def donation_summaries(index: ModuleIndex) -> dict[str, tuple[int, ...]]:
    """qname -> donated positional indices. Seeded from jit decorators
    (`donate_argnums` in `jax.jit(...)` / `functools.partial(jax.jit,
    ...)` forms), then propagated to a fixpoint through wrappers: a
    function that passes its own parameter into a donated position of a
    known donator donates that parameter's index too — the helper
    indirection a single-file scan cannot see. For a jitted METHOD, jax
    counts the bound `self` at position 0, so the declared indices are
    shifted down by one here — call sites index their arguments after
    the receiver is dropped, and the two numberings must agree."""
    donors: dict[str, tuple[int, ...]] = {}
    for fi in index.funcs.values():
        pos = _donated_positions(fi.node)
        if not pos:
            continue
        fparams = fi.node.args.posonlyargs + fi.node.args.args
        if fi.cls is not None and fparams and fparams[0].arg == "self":
            pos = tuple(p - 1 for p in pos if p >= 1)
        if pos:
            donors[fi.qname] = pos
    graph = index.call_graph()
    refs = index.ref_edges()
    changed = True
    while changed:
        changed = False
        for fi in index.funcs.values():
            params = [
                a.arg
                for a in fi.node.args.posonlyargs + fi.node.args.args
            ]
            if fi.cls is not None and params and params[0] == "self":
                params = params[1:]
            if not params:
                continue
            mine = set(donors.get(fi.qname, ()))
            before = len(mine)
            for callee_q, call in graph.get(fi.qname, ()):
                if (callee_q, id(call)) in refs:
                    # reference edge: `call` is dispatch(callee, ...) —
                    # its positional args are NOT callee's args, so
                    # indexing them would invent phantom donations
                    continue
                dpos = donors.get(callee_q)
                if not dpos:
                    continue
                args = _positional_args(call)
                for i in dpos:
                    if i < len(args):
                        nm = dotted_name(args[i])
                        if nm in params:
                            mine.add(params.index(nm))
            if len(mine) > before:
                donors[fi.qname] = tuple(sorted(mine))
                changed = True
    return donors


def _positional_args(call: ast.Call) -> list[ast.AST]:
    """Positional args with the bound receiver dropped for `self.m(...)`
    style calls — donated indices then line up with the donor's
    self-stripped parameter list."""
    return list(call.args)


def _donated_positions(fn: ast.AST) -> tuple[int, ...]:
    """Positional argument indices a def donates, read off its jit
    decorators; () when it donates nothing. Indices are RAW jax
    numbering (a method's bound `self` counts at 0 — jax sees the
    unbound function); donation_summaries shifts methods onto the
    self-stripped numbering call sites use."""
    for dec in getattr(fn, "decorator_list", ()):
        if not isinstance(dec, ast.Call):
            continue
        callee = dotted_name(dec.func)
        is_partial_jit = callee in ("functools.partial", "partial") and (
            dec.args and dotted_name(dec.args[0]) in ("jax.jit", "jit")
        )
        is_jit_call = callee in ("jax.jit", "jit")
        if not (is_partial_jit or is_jit_call):
            continue
        for kw in dec.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, int)
                )
    return ()


def donated_device_put_arg(call: ast.Call) -> ast.AST | None:
    """The buffer argument of a donating `jax.device_put(x, ...,
    donate=True)` call, else None."""
    if dotted_name(call.func) not in ("jax.device_put", "device_put"):
        return None
    for kw in call.keywords:
        if (
            kw.arg == "donate"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
            and call.args
        ):
            return call.args[0]
    return None


# ---- jax-value taint ------------------------------------------------------

_JAX_PREFIXES = ("jnp.", "jax.", "lax.", "jax.numpy.", "jax.lax.")
# jax.* APIs that return HOST values (strings, ints, specs) — not
# device-array sources
_JAX_HOST_RETURNS = {
    "jax.device_get", "jax.eval_shape", "jax.default_backend",
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.process_index", "jax.process_count",
    "jax.ShapeDtypeStruct",
}
# converting through these MATERIALIZES on host: the call is the sync
# (host-transfer flags it where it matters), but the NAME bound to the
# result is host numpy from then on — not tainted (len() needs no
# entry: static_meta_node_ids exempts its whole subtree — no sync)
_HOST_MATERIALIZERS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jax.device_get", "float", "int", "bool",
}
# reading these attributes off a device value yields STATIC host
# metadata (shapes are fixed at trace time — no sync, no tracer):
# `n = y.shape[0]` binds a Python int, not a jax value
_STATIC_META_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes"}


def static_meta_node_ids(node: ast.AST) -> set[int]:
    """ids of every sub-node living under a static-metadata read —
    `x.shape[0]`, `y.ndim`, `len(x)` — taint walks skip these: the
    value is host metadata even when the base is a device array."""
    meta: set[int] = set()
    for sub in ast.walk(node):
        if id(sub) in meta:
            continue
        is_meta_attr = (
            isinstance(sub, ast.Attribute) and sub.attr in _STATIC_META_ATTRS
        )
        is_len = (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Name)
            and sub.func.id == "len"
        )
        if is_meta_attr or is_len:
            meta.update(id(inner) for inner in ast.walk(sub))
    return meta


def jax_tainted_names(fn: ast.AST, extra_sources: set[str] = frozenset()) -> set[str]:
    """Names in `fn`'s scope ever bound to a jax expression: a call into
    jnp./jax./lax., a call whose final segment names a known
    device-returning project function (`extra_sources` — the index's
    jit entries, typically), an attribute/method chain hanging off an
    already-tainted name, or a tuple-unpack of either. Flow-insensitive
    by design: one binding taints the name for the whole function
    (precision over bookkeeping — a rebind-to-host pattern earns an
    inline waiver and a fixture)."""
    tainted: set[str] = set()

    def expr_tainted(node: ast.AST) -> bool:
        meta = static_meta_node_ids(node)
        for sub in ast.walk(node):
            if id(sub) in meta:
                continue
            if isinstance(sub, ast.Call):
                dn = dotted_name(sub.func) or ""
                if dn in _JAX_HOST_RETURNS:
                    continue
                if dn.startswith(_JAX_PREFIXES):
                    return True
                base = dn.split(".")[0]
                if base in tainted:
                    return True
                if dn.rsplit(".", 1)[-1] in extra_sources:
                    return True
            elif isinstance(sub, ast.Name) and isinstance(
                sub.ctx, ast.Load
            ):
                if sub.id in tainted:
                    return True
        return False

    changed = True
    while changed:
        changed = False
        for node in shallow_walk(fn):
            if not isinstance(
                node, (ast.Assign, ast.AugAssign, ast.AnnAssign)
            ):
                continue
            value = node.value
            if value is None or not expr_tainted(value):
                continue
            if (
                isinstance(value, ast.Call)
                and dotted_name(value.func) in _HOST_MATERIALIZERS
            ):
                continue  # x = np.asarray(dev): x is host numpy now
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                # only PLAIN name bindings (tuple unpack included) taint:
                # `self._x = jnp...` stores through an attribute — the
                # base object is not itself a device value
                leaves = (
                    t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                )
                for leaf in leaves:
                    if isinstance(leaf, ast.Name) and leaf.id not in tainted:
                        tainted.add(leaf.id)
                        changed = True
    return tainted


# ---- lockset walker -------------------------------------------------------

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore"}
_MUTATORS = {
    "append", "extend", "insert", "pop", "popitem", "clear", "update",
    "add", "discard", "remove", "setdefault", "appendleft", "popleft",
    "move_to_end",
}


@dataclass
class LockFacts:
    """Per-class lockset facts: which self attributes hold locks, and —
    per method — every self-attr mutation and every intra-class
    `self.m(...)` call with the LOCAL lockset held at that site."""

    locks: set = field(default_factory=set)
    # method -> [(attr, lineno, frozenset(held locks))]
    mutations: dict = field(default_factory=dict)
    # method -> [(callee method name, lineno, frozenset(held locks))]
    self_calls: dict = field(default_factory=dict)
    methods: dict = field(default_factory=dict)  # name -> ast def


def class_lock_facts(cls: ast.ClassDef) -> LockFacts:
    facts = LockFacts()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            fn = node.value.func
            ctor = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if ctor in _LOCK_CTORS:
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        facts.locks.add(t.attr)
        elif isinstance(node, ast.With):
            for item in node.items:
                e = item.context_expr
                if (
                    isinstance(e, ast.Attribute)
                    and isinstance(e.value, ast.Name)
                    and e.value.id == "self"
                    and "lock" in e.attr.lower()
                ):
                    facts.locks.add(e.attr)
    if not facts.locks:
        return facts
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        facts.methods[item.name] = item
        muts: list = []
        calls: list = []
        _walk_locked(item, facts.locks, frozenset(), muts, calls)
        facts.mutations[item.name] = muts
        facts.self_calls[item.name] = calls
    return facts


def _walk_locked(node, locks, held, muts, calls):
    for child in ast.iter_child_nodes(node):
        child_held = held
        if isinstance(child, ast.With):
            acquired = {
                item.context_expr.attr
                for item in child.items
                if (
                    isinstance(item.context_expr, ast.Attribute)
                    and isinstance(item.context_expr.value, ast.Name)
                    and item.context_expr.value.id == "self"
                    and item.context_expr.attr in locks
                )
            }
            if acquired:
                child_held = held | acquired
        mut = _self_attr_mutation(child)
        if mut is not None:
            muts.append((mut[0], mut[1], child_held))
        if (
            isinstance(child, ast.Call)
            and isinstance(child.func, ast.Attribute)
            and isinstance(child.func.value, ast.Name)
            and child.func.value.id == "self"
        ):
            calls.append((child.func.attr, child.lineno, child_held))
        if not isinstance(child, _FN_DEFS):
            _walk_locked(child, locks, child_held, muts, calls)


def keyed_dict_attr(sub) -> str | None:
    """'key' for a `self.__dict__["key"]` Subscript: the memoized-
    property store IS an assignment to `self.key`, and conflating every
    memo under one `__dict__` attr would couple unrelated caches to
    whichever lock guards one of them."""
    if (
        isinstance(sub, ast.Subscript)
        and isinstance(sub.value, ast.Attribute)
        and sub.value.attr == "__dict__"
        and isinstance(sub.value.value, ast.Name)
        and sub.value.value.id == "self"
        and isinstance(sub.slice, ast.Constant)
        and isinstance(sub.slice.value, str)
    ):
        return sub.slice.value
    return None


def _self_attr_mutation(node) -> tuple[str, int] | None:
    """(attr, lineno) when `node` mutates a self attribute (assignment,
    augmented assignment, subscript store, or a mutating method call)."""
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            base = t
            if isinstance(base, ast.Subscript):
                key = keyed_dict_attr(base)
                if key is not None:
                    return key, node.lineno
                base = base.value
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                return base.attr, node.lineno
    elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in _MUTATORS:
            owner = node.func.value
            if isinstance(owner, ast.Subscript):
                key = keyed_dict_attr(owner)
                if key is not None:
                    return key, node.lineno
                owner = owner.value
            if (
                isinstance(owner, ast.Attribute)
                and isinstance(owner.value, ast.Name)
                and owner.value.id == "self"
            ):
                return owner.attr, node.lineno
    return None


def method_entry_locksets(facts: LockFacts) -> dict[str, set[frozenset]]:
    """For each method, the set of locksets it can be ENTERED with.

    Entry model: public methods (no leading underscore), `__init__`-like
    dunders, and private methods never called intra-class are entries
    with the empty lockset (anyone may call them lock-free). A private
    helper with at least one intra-class call site inherits ONLY its
    call-site locksets — the discipline the repo's `called only from X,
    which holds the lock` waivers hand-assert today, promoted into the
    analysis. Propagated to a fixpoint through helper chains."""
    called_privately: set[str] = set()
    for calls in facts.self_calls.values():
        for name, _, _ in calls:
            called_privately.add(name)
    contexts: dict[str, set[frozenset]] = {}
    for name in facts.methods:
        # dunders (__enter__) are public protocol entries; name-mangled
        # privates (__flush) are MORE private than a single underscore
        is_dunder = name.startswith("__") and name.endswith("__")
        is_private = name.startswith("_") and not is_dunder
        if not (is_private and name in called_privately):
            contexts[name] = {frozenset()}
        else:
            contexts[name] = set()
    changed = True
    while changed:
        changed = False
        for caller, calls in facts.self_calls.items():
            if caller == "__init__":
                # construction happens-before publication: a lock-free
                # helper call from __init__ cannot race anything
                continue
            for callee, _, held in calls:
                if callee not in contexts:
                    continue
                # iterate the caller's REAL context set: a private helper
                # whose contexts are still empty this pass propagates
                # nothing yet — the fixpoint revisits once they fill.
                # (Defaulting to {frozenset()} here would inject a
                # spurious lock-free entry that monotone growth could
                # never retract, making findings depend on method
                # definition order.)
                for c in contexts.get(caller, ()):
                    ctx = frozenset(c | held)
                    if ctx not in contexts[callee]:
                        contexts[callee].add(ctx)
                        changed = True
    return contexts
