"""graftmodel CLI: `python -m kubernetes_scheduler_tpu.analysis.model`
(`make model-check`).

Exhausts every shipped protocol model's bounded state space, verifies
each transition's code anchors against the live source, and runs the
mutation harness. Exit codes: 0 = protocol holds, anchors bind, every
mutant caught; 1 = a violation (counterexample schedules printed in
full); 3 = a model could not be exhausted inside --budget-seconds /
--max-states (the bounded proof is incomplete — raise the budget or
shrink the model, never ignore it).

`--json-artifact` drops a machine report (per-model state counts,
reduction stats, mutant verdicts, findings) for CI diffing;
`--format sarif` emits the findings through the shared SARIF renderer.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_scheduler_tpu.analysis.model",
        description="bounded model checking of the session/epoch/"
        "capability protocol (graftmodel)",
    )
    parser.add_argument(
        "--budget-seconds", type=float, default=60.0,
        help="wall budget for the whole layer (models + mutants)",
    )
    parser.add_argument(
        "--max-states", type=int, default=200_000,
        help="per-model explored-state cap",
    )
    parser.add_argument(
        "--no-mutants", action="store_true",
        help="skip the mutation harness (models + anchors only)",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
    )
    parser.add_argument(
        "--json-artifact", metavar="PATH",
        help="also write the machine report to PATH (CI artifact)",
    )
    args = parser.parse_args(argv)

    from kubernetes_scheduler_tpu.analysis.model.anchors import RULE
    from kubernetes_scheduler_tpu.analysis.model.runner import (
        layer_violations,
        run_layer,
    )

    report = run_layer(
        budget_seconds=args.budget_seconds,
        max_states=args.max_states,
        with_mutants=not args.no_mutants,
    )
    violations = layer_violations(report, schedule_sep="\n        ")
    budget_blown = any(
        not res.exhausted for res in report["models"]
    ) or any(not res.exhausted for res in report["mutants"].values())

    doc = {
        "seconds": round(report["seconds"], 3),
        "models": [
            {
                "name": r.model,
                "states": r.states,
                "transitions_fired": r.transitions_fired,
                "transitions_slept": r.transitions_slept,
                "exhausted": r.exhausted,
                "seconds": round(r.seconds, 4),
                "violations": [
                    {"kind": v.kind, "name": v.name, "message": v.message,
                     "schedule": v.schedule}
                    for v in r.violations
                ],
            }
            for r in report["models"]
        ],
        "mutants": {
            name: {
                "caught": bool(res.violations) and res.exhausted,
                "states": res.states,
                "first_finding": (
                    f"{res.violations[0].kind}:{res.violations[0].name}"
                    if res.violations else None
                ),
            }
            for name, res in report["mutants"].items()
        },
        "anchor_drift": [v.__dict__ for v in report["anchor_violations"]],
    }

    if args.json_artifact:
        with open(args.json_artifact, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2)

    if args.format == "json":
        print(json.dumps(doc, indent=2))
    elif args.format == "sarif":
        from kubernetes_scheduler_tpu.analysis.sarif import (
            render_sarif,
            validate_sarif,
        )

        sarif = render_sarif(
            violations,
            {RULE: "bounded model checking of the session/epoch/"
                   "capability protocol"},
        )
        validate_sarif(sarif)
        print(json.dumps(sarif, indent=2))
    else:
        for r in report["models"]:
            red = (
                f", {r.transitions_slept} slept"
                if r.transitions_slept else ""
            )
            status = "ok" if r.ok else (
                "NOT EXHAUSTED" if not r.exhausted else "VIOLATED"
            )
            print(
                f"{r.model}: {r.states} states, "
                f"{r.transitions_fired} transitions{red}, "
                f"{r.seconds * 1e3:.0f} ms — {status}"
            )
            for v in r.violations:
                print("  " + v.render().replace("\n", "\n  "))
        if report["mutants"]:
            caught = sum(
                1 for d in doc["mutants"].values() if d["caught"]
            )
            print(
                f"mutation harness: {caught}/{len(report['mutants'])} "
                "seeded mutants caught"
            )
            for name, d in doc["mutants"].items():
                mark = "caught" if d["caught"] else "SURVIVED"
                via = f" via {d['first_finding']}" if d["caught"] else ""
                print(f"  {name}: {mark}{via}")
        for v in report["anchor_violations"]:
            print(v.format())
        print(
            f"graftmodel: {len(violations)} finding(s) in "
            f"{report['seconds']:.2f}s",
            file=sys.stderr,
        )
    if budget_blown:
        return 3
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
