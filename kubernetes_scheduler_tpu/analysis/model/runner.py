"""Ties the model layer into graftlint: models + drift anchors +
mutation harness, reported as pseudo-rule `protocol-model` violations.

A full-repo `make lint` run calls `check_protocol_layer` (the way it
calls contracts.check_contracts); `make model-check` drives the same
code through the standalone CLI (__main__.py) with a JSON artifact and
richer per-model reporting. Three finding classes:

- an invariant/convergence violation at HEAD (the model caught a real
  protocol bug — the counterexample schedule is in the message);
- an anchor drift (the code moved out from under the model — update
  protocols.py to match the refactor);
- a SURVIVED mutant (the checker lost its teeth for a known bug class
  — a checker/model regression, not a code bug).

Budget handling: one wall-clock budget covers the whole layer; a model
that cannot be exhausted inside it is reported as a violation (the
bounded proof is incomplete), never silently skipped.
"""

from __future__ import annotations

import time

from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    Violation,
    collect_files,
    load_file,
)
from kubernetes_scheduler_tpu.analysis.model import mutants as mutants_mod
from kubernetes_scheduler_tpu.analysis.model.anchors import (
    RULE,
    verify_model_anchors,
)
from kubernetes_scheduler_tpu.analysis.model.checker import check_model
from kubernetes_scheduler_tpu.analysis.model.protocols import build_models

_MODELS_PATH = "kubernetes_scheduler_tpu/analysis/model/protocols.py"

# the files whose edits can break a modeled invariant or drift an
# anchor — a changed-only lint run checks the layer only when its
# closure touches these (every anchor path in protocols.py is here)
SURFACE = (
    "kubernetes_scheduler_tpu/bridge/*.py",
    "kubernetes_scheduler_tpu/host/scheduler.py",
    "kubernetes_scheduler_tpu/host/queue.py",
    "kubernetes_scheduler_tpu/host/snapshot.py",
    "kubernetes_scheduler_tpu/host/resilience.py",
    "kubernetes_scheduler_tpu/sim/faults.py",
    "kubernetes_scheduler_tpu/analysis/model/*.py",
)


def _index_for(ctx: Context | None):
    from kubernetes_scheduler_tpu.analysis import dataflow

    if ctx is None:
        files = []
        from kubernetes_scheduler_tpu.analysis.core import _REPO_ROOT

        for p in collect_files(_REPO_ROOT):
            sf = load_file(p, _REPO_ROOT)
            if sf is not None:
                files.append(sf)
        ctx = Context(root=_REPO_ROOT, files=files)
    return dataflow.get_index(ctx)


def run_layer(
    *,
    ctx: Context | None = None,
    budget_seconds: float = 60.0,
    max_states: int = 200_000,
    with_mutants: bool = True,
) -> dict:
    """The whole layer: {"models": [CheckResult...], "anchor_violations":
    [Violation...], "mutants": {name: CheckResult}, "seconds": float}."""
    t0 = time.monotonic()
    deadline = t0 + budget_seconds
    index = _index_for(ctx)
    models = build_models()
    anchor_violations: list[Violation] = []
    results = []
    for m in models:
        anchor_violations.extend(verify_model_anchors(index, m))
        left = max(0.5, deadline - time.monotonic())
        results.append(
            check_model(m, max_states=max_states, max_seconds=left)
        )
    mutant_results = {}
    if with_mutants:
        for name in mutants_mod.MUTANTS:
            left = max(0.5, deadline - time.monotonic())
            mutant_results[name] = mutants_mod.run_mutant(
                name, max_states=max_states, max_seconds=left
            )
    return {
        "models": results,
        "anchor_violations": anchor_violations,
        "mutants": mutant_results,
        "seconds": time.monotonic() - t0,
    }


def layer_violations(report: dict, *, schedule_sep: str = " | ") -> list:
    """Flatten a run_layer report into lint Violations."""
    out: list[Violation] = list(report["anchor_violations"])
    for res in report["models"]:
        for v in res.violations:
            msg = f"[{v.kind}:{v.name}] {v.message}"
            if v.schedule:
                msg += schedule_sep + schedule_sep.join(v.schedule)
            out.append(Violation(RULE, _MODELS_PATH, 1, msg))
    mutants_path = "kubernetes_scheduler_tpu/analysis/model/mutants.py"
    for name, res in report["mutants"].items():
        if not res.exhausted:
            # a truncated run proves nothing either way: this is a
            # budget problem, not a lost-teeth checker regression —
            # misdiagnosing it as SURVIVED would send the maintainer
            # hunting the wrong bug
            out.append(
                Violation(
                    RULE, mutants_path, 1,
                    f"seeded mutant `{name}` run NOT EXHAUSTED within "
                    "the layer budget — the bounded proof over the "
                    "mutant is incomplete; raise the budget",
                )
            )
        elif not res.violations:
            out.append(
                Violation(
                    RULE, mutants_path, 1,
                    f"seeded mutant `{name}` SURVIVED the checker — the "
                    "model layer lost its teeth for this bug class "
                    "(checker or model regression; see "
                    f"mutants.MUTANTS[{name!r}].__doc__)",
                )
            )
    return out


def check_protocol_layer(
    ctx: Context | None = None, *, budget_seconds: float = 60.0
) -> list:
    """The lint entry point: every finding of the model layer as
    `protocol-model` Violations (empty when the protocol holds, the
    anchors bind, and every mutant is caught)."""
    return layer_violations(run_layer(ctx=ctx, budget_seconds=budget_seconds))
