"""The mutation harness: seeded protocol-bug reintroductions.

A model checker that has never caught anything is an assertion, not a
tool. Each mutant below re-introduces a real (or realistic) protocol
bug class into one model — the PR-3 mid-stream-downgrade bug among
them — and the checker MUST report at least one violation with a
rendered counterexample schedule for every one of them. `make
model-check` (and the lint layer) runs the harness on every build;
tests/test_model.py asserts each mutant one by one, so a checker
regression that silently blinds one invariant fails CI by name.

Every mutant is a pure transformation of a fresh model instance
(protocols.replace_transition) — the shipped models are never mutated
in place.
"""

from __future__ import annotations

import dataclasses

from kubernetes_scheduler_tpu.analysis.model import protocols
from kubernetes_scheduler_tpu.analysis.model.checker import (
    ProtocolModel,
    Transition,
    check_model,
)
from kubernetes_scheduler_tpu.analysis.model.protocols import (
    _ALL_LATCH,
    _LATCHES,
    replace_transition,
)


def _swap(model: ProtocolModel, name: str, **overrides) -> ProtocolModel:
    old = next(t for t in model.transitions if t.name == name)
    return replace_transition(
        model, name, dataclasses.replace(old, **overrides)
    )


# ---- client-session mutants ----------------------------------------------


def mutant_invalidate_keeps_latches() -> ProtocolModel:
    """THE PR-3 BUG: a failed send resets the wire field cache but
    keeps the capability latches trusting the dead sidecar's
    advertisement — the client retries unparseable sends forever
    (caught as a `downgrade-relearned` livelock)."""
    m = protocols.client_session_model()
    return _swap(
        m, "rpc_fail_invalidate",
        effect=lambda s: {
            "wire_cache": False, "cli_base": False, "churn": False,
        },
        writes=frozenset({"wire_cache", "cli_base", "churn"}),
    )


def mutant_invalidate_keeps_wire_cache() -> ProtocolModel:
    """The dual of the PR-3 bug: the latches reset but the wire cache
    survives invalidation, so the next send references cached tensors
    on a sidecar whose capability is unknown (caught by the
    `no-marker-without-latch` invariant)."""
    m = protocols.client_session_model()
    return _swap(
        m, "rpc_fail_invalidate",
        effect=lambda s: dict(
            {l: "u" for l in _LATCHES},
            cli_base=False, churn=False,
        ),
        writes=_ALL_LATCH | frozenset({"cli_base", "churn"}),
    )


def mutant_partial_probe() -> ProtocolModel:
    """A probe that resolves only the field-cache latch (a new
    capability bit wired into Health but not into the shared probe) —
    the latch set desyncs (caught by `latches-resolved-together`)."""
    m = protocols.client_session_model()
    return _swap(
        m, "probe_health",
        effect=lambda s: {
            "l_cache": ("t" if s["build"] == "new" else "f")
            if s["l_cache"] == "u" else s["l_cache"],
        },
        writes=frozenset({"l_cache"}),
    )


def mutant_delta_across_layout_churn() -> ProtocolModel:
    """Skip the flush-to-full on layout churn: a row-diff delta derived
    across a layout change ships and applies — silent resident-state
    divergence (caught by `resident-state-faithful`)."""
    m = protocols.client_session_model()
    old = next(
        t for t in m.transitions if t.name == "rpc_delta_applied"
    )
    return replace_transition(
        m, "rpc_delta_applied",
        dataclasses.replace(
            old,
            guard=lambda s: (
                s["l_res"] == "t" and s["cli_base"]
                and s["build"] == "new" and s["srv_sess"] == "base"
            ),
            effect=lambda s: dict(
                protocols._caches_after_send(s),
                corrupt=s["corrupt"] or s["churn"],
            ),
            reads=old.reads | frozenset({"corrupt"}),
            writes=old.writes | frozenset({"corrupt"}),
        ),
    )


# ---- queue mutant --------------------------------------------------------


def mutant_defer_restores_to_back() -> ProtocolModel:
    """Restore a deferred gang to the BACK of the front-restoring
    Python queue: the prefetched window's pods overtake the gang, so
    the gang no longer leads the next pop and serial/pipelined pop
    orders diverge (caught by `deferred-gang-leads-next-pop`)."""
    m = protocols.gang_queue_model(front=True)
    return _swap(
        m, "resolve_window",
        effect=lambda s: protocols._resolve_effect(
            s, front=True, defer_to_back=True
        ),
    )


# ---- pipeline mutants ----------------------------------------------------


def mutant_fail_keeps_resident_commit() -> ProtocolModel:
    """The failure path forgets to roll back the optimistic resident
    commit: the next cycle deltas against a base the engine may not
    hold (caught by `failure-invalidates-resident`)."""
    m = protocols.pipeline_slot_model()
    return _swap(
        m, "complete_fail",
        effect=lambda s: {
            "inflight": 0, "spec": "none", "last_fail": True,
            "fail_budget": s["fail_budget"] - 1,
        },
        writes=frozenset({"inflight", "spec", "last_fail", "fail_budget"}),
    )


def mutant_dispatch_scores_stale_batch() -> ProtocolModel:
    """Dispatch adopts the speculative pod batch without re-checking
    the layout fingerprint — a stale batch (selector/node churn since
    the prebuild) gets scored (caught by
    `stale-spec-batch-never-scored`)."""
    m = protocols.pipeline_slot_model()
    old = next(t for t in m.transitions if t.name == "dispatch")
    return replace_transition(
        m, "dispatch",
        dataclasses.replace(
            old,
            effect=lambda s: {
                "inflight": 1, "spec": "none", "resident_ok": True,
                "last_fail": False,
                "scored_stale": s["scored_stale"] or s["spec"] == "stale",
            },
            reads=old.reads | frozenset({"scored_stale"}),
            writes=old.writes | frozenset({"scored_stale"}),
        ),
    )


# ---- replica mutant ------------------------------------------------------


def mutant_unfenced_replica_bind() -> ProtocolModel:
    """Replica B binds without the epoch CAS (no first-bind-wins
    fence): a blind overwrite of an already-bound pod (caught by
    `no-double-bind`)."""
    m = protocols.replica_bind_model()
    old = next(t for t in m.transitions if t.name == "bind_win_b")
    return replace_transition(
        m, "bind_win_b",
        dataclasses.replace(
            old,
            guard=lambda s: s["rb"] == "holds",
            effect=lambda s: {
                "pod_bound": "b",
                "pod_epoch": s["pod_epoch"] + 1,
                "rb": "idle",
                "double_bound": s["double_bound"]
                or s["pod_bound"] not in ("", "b"),
            },
            reads=old.reads | frozenset({"double_bound"}),
            writes=old.writes | frozenset({"double_bound"}),
        ),
    )


def mutant_shared_delta_unfenced() -> ProtocolModel:
    """The shared pool ships replica B's coalesced dispatch as a
    row-diff delta WITHOUT checking the resident epoch fence: after a
    flush/crash dropped the sidecar's base, a blind delta applies
    against state the engine no longer holds (caught by
    `shared-delta-fenced` via the ghost variable)."""
    m = protocols.replica_bind_model()
    return _swap(
        m, "dispatch_b",
        effect=lambda s: protocols._dispatch_effect(s, "b", fenced=False),
    )


# ---- degradation-ladder mutants ------------------------------------------


def mutant_ladder_skips_rung() -> ProtocolModel:
    """A failure path that drops a subsystem TWO rungs in one event —
    the silent multi-rung skip the one-rung demote contract forbids
    (caught by `never-skips-a-rung` via the ghost variable)."""
    from kubernetes_scheduler_tpu.analysis.model.protocols import (
        _LADDER_BOTTOM,
        _BRK_THRESHOLD,
    )

    m = protocols.degradation_ladder_model()

    def skip_effect(s):
        new_rung = min(s["rung"] + 2, _LADDER_BOTTOM)
        fails = min(s["fails"] + 1, _BRK_THRESHOLD)
        opens = s["breaker"] == "half" or fails >= _BRK_THRESHOLD
        return {
            "fails": fails,
            "breaker": "open" if opens else s["breaker"],
            "rung": new_rung,
            "probed": False,
            "skipped": s["skipped"] or (new_rung - s["rung"] > 1),
        }

    return _swap(m, "attempt_fail", effect=skip_effect)


def mutant_promote_without_probe() -> ProtocolModel:
    """Recovery that climbs a rung without re-probing the degraded
    path (the guard dropped) — optimistic promotion re-enters the
    failure it degraded away from (caught by `recovery-re-probes`)."""
    m = protocols.degradation_ladder_model()
    old = next(t for t in m.transitions if t.name == "recover")
    return replace_transition(
        m, "recover",
        dataclasses.replace(
            old,
            guard=lambda s: (
                s["rung"] > 0 and not s["fault"] and s["breaker"] != "open"
            ),
        ),
    )


# ---- harness -------------------------------------------------------------

# name -> factory; ordered, so reports and tests stay deterministic
MUTANTS = {
    "invalidate-keeps-latches": mutant_invalidate_keeps_latches,
    "invalidate-keeps-wire-cache": mutant_invalidate_keeps_wire_cache,
    "partial-probe": mutant_partial_probe,
    "delta-across-layout-churn": mutant_delta_across_layout_churn,
    "defer-restores-to-back": mutant_defer_restores_to_back,
    "fail-keeps-resident-commit": mutant_fail_keeps_resident_commit,
    "dispatch-scores-stale-batch": mutant_dispatch_scores_stale_batch,
    "unfenced-replica-bind": mutant_unfenced_replica_bind,
    "shared-delta-unfenced": mutant_shared_delta_unfenced,
    "ladder-skips-rung": mutant_ladder_skips_rung,
    "promote-without-probe": mutant_promote_without_probe,
}


def run_mutant(name: str, **kw):
    """CheckResult for one seeded mutant (kw forwarded to check_model)."""
    return check_model(MUTANTS[name](), **kw)


def run_all(**kw) -> dict:
    """name -> CheckResult for the whole harness."""
    return {name: run_mutant(name, **kw) for name in MUTANTS}
