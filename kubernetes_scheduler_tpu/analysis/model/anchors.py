"""The drift layer: every model transition is bound to real code.

A protocol model that nobody updates is worse than prose — it would
keep "passing" while the code moves out from under it. So each
transition in protocols.py declares one or more `Anchor`s naming the
function it abstracts, source fragments that must appear inside that
function, and call-graph edges that must exist — all verified against
the shared parse-once ModuleIndex (analysis/dataflow.py), the same way
contracts.py binds shape specs via jax.eval_shape. Renaming
`_invalidate_session`, moving the latch reset out of it, or dropping
the `restore_window` call from `_defer_gang` fails lint with a
`protocol-model` finding naming the transition whose model-code bond
broke.

Fragment matching is substring within the resolved def's CODE —
`ast.unparse` with docstrings dropped, so a docstring or comment that
merely mentions the fragment cannot keep a dead anchor alive (the
verify drive caught exactly that: seeding the PR-3 bug back into
`_invalidate_session` left its docstring's table mention satisfying
the raw-source match). Deliberately simple beyond that: an anchor is a
tripwire, not a proof (the proof is the model + the mutation harness).
Call edges go through the real resolved call graph, so a refactor that
reroutes a transition through a helper updates the anchor or fails
loudly.
"""

from __future__ import annotations

import ast
import copy
from dataclasses import dataclass

from kubernetes_scheduler_tpu.analysis.core import Violation

RULE = "protocol-model"


@dataclass(frozen=True)
class Anchor:
    """One model-transition <-> code-site bond.

    path:          repo-relative file ("kubernetes_scheduler_tpu/...")
    func:          dotted def name within the file ("Cls.method" / "fn")
    must_contain:  source fragments that must occur inside the def
    calls:         bare callee names the def must reach (call graph)
    """

    path: str
    func: str
    must_contain: tuple = ()
    calls: tuple = ()


def _resolve(index, anchor: Anchor):
    """The FuncInfo for anchor.path::anchor.func, or None."""
    qname = f"{anchor.path}::{anchor.func}"
    fi = index.funcs.get(qname)
    if fi is not None:
        return fi
    # nested scopes index as Outer.inner; accept a unique suffix match
    # on the same file so anchors survive a class rename-with-alias
    tail = "." + anchor.func
    cands = [
        f for q, f in index.funcs.items()
        if q.startswith(anchor.path + "::") and q.endswith(tail)
    ]
    return cands[0] if len(cands) == 1 else None


def _def_source(fi) -> str:
    """The def's CODE: comments are gone by construction (ast), and
    docstrings are stripped before unparsing — a fragment match against
    this is a match against executable source, never prose."""
    node = copy.deepcopy(fi.node)
    for n in ast.walk(node):
        body = getattr(n, "body", None)
        if (
            isinstance(body, list) and body
            and isinstance(body[0], ast.Expr)
            and isinstance(body[0].value, ast.Constant)
            and isinstance(body[0].value.value, str)
        ):
            n.body = body[1:] or [ast.Pass()]
    return ast.unparse(node)


def verify_anchor(index, model_name: str, tname: str, anchor: Anchor) -> list:
    """Violations for one anchor against the live index."""
    out = []
    fi = _resolve(index, anchor)
    if fi is None:
        out.append(
            Violation(
                RULE, anchor.path, 1,
                f"model `{model_name}` transition `{tname}` is anchored "
                f"to `{anchor.func}`, which no longer exists in this "
                "file — update the protocol model (analysis/model/"
                "protocols.py) to match the refactor, or restore the "
                "function",
            )
        )
        return out
    src = _def_source(fi)
    line = fi.node.lineno
    for frag in anchor.must_contain:
        if frag not in src:
            out.append(
                Violation(
                    RULE, anchor.path, line,
                    f"model `{model_name}` transition `{tname}`: "
                    f"`{anchor.func}` no longer contains `{frag}` — the "
                    "code moved out from under the protocol model; "
                    "re-derive the transition (analysis/model/"
                    "protocols.py) against the new code",
                )
            )
    if anchor.calls:
        callee_names = {
            q.rsplit("::", 1)[-1].rsplit(".", 1)[-1]
            for q in index.callees(fi.qname)
        }
        for want in anchor.calls:
            if want not in callee_names and f"{want}(" not in src:
                out.append(
                    Violation(
                        RULE, anchor.path, line,
                        f"model `{model_name}` transition `{tname}`: "
                        f"`{anchor.func}` no longer calls `{want}` — "
                        "the transition's effect is modeled on that "
                        "edge; update the model or the code",
                    )
                )
    return out


def verify_model_anchors(index, model) -> list:
    out = []
    for t in model.transitions:
        for anchor in t.anchors:
            out.extend(verify_anchor(index, model.name, t.name, anchor))
    return out
