"""The declared protocol state machines (see package docstring).

Each model abstracts one protocol whose invariants previously lived in
comments, with every transition ANCHORED to the code site it abstracts
(anchors.py — lint fails when the code moves). Variables range over
small declared domains and environment churn is budget-bounded, so the
checker exhausts the state space.

Abstraction notes, per model:

- `client-session`: RPCs are atomic transitions (the client holds one
  RPC in flight at a time — bridge/client.py's single async worker);
  concurrency enters through environment transitions (sidecar restart/
  downgrade/upgrade, session eviction, layout churn) interleaving
  between them. Epochs are modeled RELATIVELY: `srv_sess` says whether
  the sidecar's retained resident state is the client's current delta
  base ("base"), someone else's ("stale"), or gone ("none") — which is
  exactly what the epoch comparison decides, without unbounded
  counters. `corrupt` is a ghost variable: it can only become True if a
  row-diff delta derived across a layout change is ever applied (the
  silent-divergence bug class `snapshot_delta`'s None-on-churn contract
  exists to prevent).
- `gang-queue-front` / `gang-queue-native`: four pods (a 2-gang whose
  second member arrives late, two plains), window cap 2, a pipelined
  prefetch slot — the smallest world where "deferred gang straddles a
  prefetched window" can happen. The two variants encode the two
  restore semantics `SchedulingQueue.RESTORES_TO_FRONT` documents and
  `Scheduler._defer_gang` branches on.
- `pipeline-slot`: the 1-deep pipelined driver's in-flight slot,
  speculative pod batch (fresh/stale under informer churn), and the
  optimistic resident commit that a failure path must roll back.
  `last_fail` / `scored_stale` are ghost variables making the two
  failure-path obligations state-visible.
- `replica-bind`: the cross-replica conflict protocol, SHIPPED as
  host/replica.py (the replicated fleet over the partitioned queue):
  two replicas whose queue partitions transiently overlap on one pod,
  binds fenced by the BindTable epoch CAS (first bind wins), the loser
  requeueing via restore_window and dropping on re-pop when the table
  shows the pod bound. Checked BEFORE the scale-out PR existed; its
  anchors now bind to the shipped primitives (ReplicaCoordinator.
  pop_window/bind_lose/drop_bound, BindTable.try_bind, and the
  binder's 404/409 arm the conflict raise lands in) — anchor drift
  fails lint, so the model is a proof about the code that runs.
  Extended for the fleet-shared engine (host/engine_pool.py): each
  replica's window now DISPATCHES through the shared pool before it
  can bind, and the pool's resident epoch is modeled RELATIVELY (the
  same abstraction `client-session` uses for `srv_sess`): `pool_base`
  says whether the sidecar retains the pool's current delta base
  ("held") or a flush/crash dropped it ("none"). `stale_delta` is a
  ghost variable: it can only become True if a coalesced dispatch
  ever ships a row-diff delta against a base the sidecar no longer
  holds — the bug class the pool's epoch fence (classify-full on a
  dropped base) exists to prevent.
"""

from __future__ import annotations

import dataclasses

from kubernetes_scheduler_tpu.analysis.model.anchors import Anchor
from kubernetes_scheduler_tpu.analysis.model.checker import (
    Convergence,
    Invariant,
    ProtocolModel,
    Transition,
)

_CLIENT = "kubernetes_scheduler_tpu/bridge/client.py"
_SERVER = "kubernetes_scheduler_tpu/bridge/server.py"
_SCHED = "kubernetes_scheduler_tpu/host/scheduler.py"
_QUEUE = "kubernetes_scheduler_tpu/host/queue.py"
_SNAP = "kubernetes_scheduler_tpu/host/snapshot.py"
_RESIL = "kubernetes_scheduler_tpu/host/resilience.py"
_REPLICA = "kubernetes_scheduler_tpu/host/replica.py"
_POOL = "kubernetes_scheduler_tpu/host/engine_pool.py"
_FAULTS = "kubernetes_scheduler_tpu/sim/faults.py"

# ---- model 1: RemoteEngine client session / sidecar session state --------

_LATCHES = ("l_cache", "l_res", "l_win", "l_gang", "l_fmm")
_ALL_LATCH = frozenset(_LATCHES)


def _probe_effect(s):
    new = "t" if s["build"] == "new" else "f"
    return {l: (new if s[l] == "u" else s[l]) for l in _LATCHES}


def _caches_after_send(s):
    on = s["l_cache"] == "t"
    return {"wire_cache": on, "srv_cache": on}


def _invalidate_effect(s):
    out = {l: "u" for l in _LATCHES}
    out.update(wire_cache=False, cli_base=False, churn=False)
    return out


def client_session_model() -> ProtocolModel:
    t = []
    t.append(Transition(
        name="probe_health",
        process="host",
        guard=lambda s: any(s[l] == "u" for l in _LATCHES),
        effect=_probe_effect,
        reads=frozenset({"build"}) | _ALL_LATCH,
        writes=_ALL_LATCH,
        anchors=(
            Anchor(_CLIENT, "RemoteEngine._probe_capabilities",
                   must_contain=("CAPABILITY_LATCHES",),
                   calls=("health_info",)),
            Anchor(_SERVER, "EngineService.health",
                   must_contain=("CAPABILITY_SWITCHES",)),
        ),
    ))
    t.append(Transition(
        name="rpc_delta_applied",
        process="host",
        guard=lambda s: (
            s["l_res"] == "t" and s["cli_base"] and not s["churn"]
            and s["build"] == "new" and s["srv_sess"] == "base"
        ),
        effect=_caches_after_send,
        reads=frozenset(
            {"l_res", "l_cache", "cli_base", "churn", "build", "srv_sess"}
        ),
        writes=frozenset({"wire_cache", "srv_cache"}),
        anchors=(
            Anchor(_CLIENT, "RemoteEngine._resident_call",
                   must_contain=("resident-epoch-mismatch",)),
            Anchor(_SERVER, "EngineService._resident_snapshot",
                   must_contain=("resident-epoch-mismatch",
                                 "request.resident_epoch")),
        ),
    ))
    t.append(Transition(
        name="rpc_delta_mismatch_full_resend",
        process="host",
        guard=lambda s: (
            s["l_res"] == "t" and s["cli_base"] and not s["churn"]
            and s["build"] == "new" and s["srv_sess"] != "base"
        ),
        effect=lambda s: dict(_caches_after_send(s), srv_sess="base"),
        reads=frozenset(
            {"l_res", "l_cache", "cli_base", "churn", "build", "srv_sess"}
        ),
        writes=frozenset({"srv_sess", "wire_cache", "srv_cache"}),
        anchors=(
            Anchor(_CLIENT, "RemoteEngine._resident_call",
                   must_contain=("build_request(False)",)),
        ),
    ))
    t.append(Transition(
        name="rpc_full_resident",
        process="host",
        guard=lambda s: (
            s["l_res"] == "t" and (not s["cli_base"] or s["churn"])
            and s["build"] == "new"
        ),
        effect=lambda s: dict(
            _caches_after_send(s), srv_sess="base", cli_base=True,
            churn=False,
        ),
        reads=frozenset({"l_res", "l_cache", "cli_base", "churn", "build"}),
        writes=frozenset(
            {"srv_sess", "cli_base", "churn", "wire_cache", "srv_cache"}
        ),
        anchors=(
            Anchor(_SCHED, "Scheduler._derive_resident_delta",
                   must_contain=("snapshot_delta",)),
            Anchor(_CLIENT, "RemoteEngine.schedule_resident",
                   must_contain=("resident_full",)),
        ),
    ))
    t.append(Transition(
        name="rpc_fail_invalidate",
        process="host",
        guard=lambda s: (
            s["build"] == "old" and any(s[l] == "t" for l in _LATCHES)
        ),
        effect=_invalidate_effect,
        reads=frozenset({"build"}) | _ALL_LATCH,
        writes=_ALL_LATCH | frozenset({"wire_cache", "cli_base", "churn"}),
        anchors=(
            Anchor(_CLIENT, "RemoteEngine._invalidate_session",
                   must_contain=("CAPABILITY_LATCHES", "_wire_cache.clear")),
            Anchor(_CLIENT, "RemoteEngine._call_cached",
                   calls=("_invalidate_session",)),
            Anchor(_SCHED, "Scheduler._invalidate_resident",
                   must_contain=("_resident_prev",)),
        ),
    ))
    t.append(Transition(
        name="rpc_cache_miss_full_resend",
        process="host",
        guard=lambda s: (
            s["build"] == "new" and s["l_cache"] == "t"
            and s["wire_cache"] and not s["srv_cache"]
        ),
        effect=lambda s: {"srv_cache": True},
        reads=frozenset({"build", "l_cache", "wire_cache", "srv_cache"}),
        writes=frozenset({"srv_cache"}),
        anchors=(
            Anchor(_CLIENT, "RemoteEngine._call_cached",
                   must_contain=("field-cache-miss",)),
        ),
    ))
    t.append(Transition(
        name="host_flush_resident",
        process="host",
        guard=lambda s: s["cli_base"],
        effect=lambda s: {
            "cli_base": False, "churn": False,
            "srv_sess": "stale" if s["srv_sess"] == "base" else s["srv_sess"],
        },
        reads=frozenset({"cli_base", "srv_sess"}),
        writes=frozenset({"cli_base", "churn", "srv_sess"}),
        anchors=(
            Anchor(_SCHED, "Scheduler._invalidate_resident",
                   must_contain=("_resident_ok",)),
        ),
    ))
    t.append(Transition(
        name="layout_churn",
        process="env",
        guard=lambda s: s["cli_base"] and not s["churn"],
        effect=lambda s: {"churn": True},
        reads=frozenset({"cli_base", "churn"}),
        writes=frozenset({"churn"}),
        anchors=(
            Anchor(_SNAP, "snapshot_delta", must_contain=("return None",)),
        ),
    ))
    t.append(Transition(
        name="sidecar_restart",
        process="env",
        guard=lambda s: s["env_budget"] > 0,
        effect=lambda s: {
            "srv_sess": "none", "srv_cache": False,
            "env_budget": s["env_budget"] - 1,
        },
        reads=frozenset({"env_budget"}),
        writes=frozenset({"srv_sess", "srv_cache", "env_budget"}),
        anchors=(
            Anchor(_SERVER, "EngineService._session",
                   must_contain=("_MAX_CACHE_SESSIONS",)),
        ),
    ))
    t.append(Transition(
        name="sidecar_downgrade",
        process="env",
        guard=lambda s: s["env_budget"] > 0 and s["build"] == "new",
        effect=lambda s: {
            "build": "old", "srv_sess": "none", "srv_cache": False,
            "env_budget": s["env_budget"] - 1,
        },
        reads=frozenset({"env_budget", "build"}),
        writes=frozenset({"build", "srv_sess", "srv_cache", "env_budget"}),
        anchors=(
            Anchor(_SERVER, "EngineService.health",
                   must_contain=("CAPABILITY_SWITCHES",)),
        ),
    ))
    t.append(Transition(
        name="sidecar_upgrade",
        process="env",
        guard=lambda s: s["env_budget"] > 0 and s["build"] == "old",
        effect=lambda s: {
            "build": "new", "srv_sess": "none", "srv_cache": False,
            "env_budget": s["env_budget"] - 1,
        },
        reads=frozenset({"env_budget", "build"}),
        writes=frozenset({"build", "srv_sess", "srv_cache", "env_budget"}),
    ))
    return ProtocolModel(
        name="client-session",
        description=(
            "RemoteEngine session protocol: wire field cache + the five "
            "capability latches + resident delta base vs the sidecar's "
            "session-keyed state, under restart/downgrade/eviction/"
            "layout churn"
        ),
        init={
            "build": "new", "srv_sess": "none", "srv_cache": False,
            "l_cache": "u", "l_res": "u", "l_win": "u", "l_gang": "u",
            "l_fmm": "u",
            "wire_cache": False, "cli_base": False, "churn": False,
            "corrupt": False, "env_budget": 2,
        },
        transitions=tuple(t),
        invariants=(
            Invariant(
                "latches-resolved-together",
                lambda s: len({s[l] == "u" for l in _LATCHES}) == 1,
                "capability latches must be probed and invalidated as a "
                "set — a partially-unknown latch set means some failure "
                "path reset one latch but not the others (the PR-3 "
                "mid-stream-downgrade class)",
            ),
            Invariant(
                "no-marker-without-latch",
                lambda s: not s["wire_cache"] or s["l_cache"] == "t",
                "the client may only reference server-cached tensors "
                "while the field-cache latch is affirmatively resolved — "
                "an invalidation that reset the latch but kept the wire "
                "cache would send markers an unknown sidecar cannot "
                "resolve",
            ),
            Invariant(
                "no-delta-base-without-latch",
                lambda s: not s["cli_base"] or s["l_res"] == "t",
                "the host may only hold a resident delta base while the "
                "resident capability latch is affirmatively resolved "
                "(failure paths invalidate both together)",
            ),
            Invariant(
                "resident-state-faithful",
                lambda s: not s["corrupt"],
                "a row-diff delta derived across a layout change must "
                "never be applied — snapshot_delta's None-on-churn "
                "contract (silent binding divergence otherwise)",
            ),
        ),
        convergences=(
            Convergence(
                "epoch-desync-converges",
                trigger=lambda s: (
                    s["l_res"] == "t" and s["cli_base"]
                    and s["srv_sess"] == "stale"
                ),
                goal=lambda s: s["srv_sess"] == "base" or s["l_res"] != "t",
                description=(
                    "an epoch desync (sidecar retaining someone else's "
                    "base) must always converge to a full resend or a "
                    "session invalidation — never loop on rejected deltas"
                ),
            ),
            Convergence(
                "downgrade-relearned",
                trigger=lambda s: (
                    s["build"] == "old"
                    and any(s[l] == "t" for l in _LATCHES)
                ),
                goal=lambda s: (
                    s["build"] == "new"
                    or all(s[l] != "t" for l in _LATCHES)
                ),
                description=(
                    "after a mid-stream downgrade the client must stop "
                    "trusting the dead sidecar's advertisement: every "
                    "path re-learns the capabilities (or the sidecar "
                    "comes back new) — a latch left True forever retries "
                    "unparseable sends every cycle"
                ),
            ),
        ),
    )


# ---- models 2a/2b: gang deferral over the two queue restore semantics ----

_GANG = ("g1", "g2")
_GANG_SIZE = 2
_MAX_DEFERS = 1
_WINDOW_CAP = 2


def _gang_members(s, window):
    if s["split"]:
        return []
    return [p for p in window if p in _GANG]


def _resolve_effect(s, *, front: bool, defer_to_back: bool = False):
    window = s["window"]
    gang = _gang_members(s, window)
    plains = [p for p in window if p not in gang]
    updates = {"window": (), "just_deferred": False}
    bound = list(s["bound"])
    if gang and len(gang) < _GANG_SIZE:
        bound.extend(plains)
        if s["defers"] >= _MAX_DEFERS:
            # budget exhausted: split policy — members become
            # individuals and requeue at ordinary (back) cadence
            updates["split"] = True
            updates["queue"] = s["queue"] + tuple(gang)
        else:
            updates["defers"] = s["defers"] + 1
            updates["just_deferred"] = True
            if front and not defer_to_back:
                # front-restoring queue: hand the prefetched window
                # back FIRST, then the gang — the gang leads the next
                # pop exactly as the serial driver would pop it
                updates["queue"] = tuple(gang) + s["prefetch"] + s["queue"]
                updates["prefetch"] = ()
            elif front and defer_to_back:
                # the seeded mutant: members restored to the BACK of a
                # front-restoring queue
                updates["queue"] = s["prefetch"] + s["queue"] + tuple(gang)
                updates["prefetch"] = ()
            else:
                # back-restoring queue (native heap): the prefetch is
                # KEPT and the gang re-enters at the back
                updates["queue"] = s["queue"] + tuple(gang)
    else:
        bound.extend(window)
    updates["bound"] = tuple(sorted(bound))
    return updates


def _conservation_ok(s):
    have = sorted(s["queue"] + s["window"] + s["prefetch"] + s["bound"])
    want = sorted(("g1", "p1", "p2") + (("g2",) if s["arrived2"] else ()))
    return have == want


def gang_queue_model(*, front: bool) -> ProtocolModel:
    name = "gang-queue-front" if front else "gang-queue-native"
    restore_anchor = (
        Anchor(_QUEUE, "SchedulingQueue.restore_window",
               must_contain=("_front_floor",))
        if front
        else Anchor(_QUEUE, "NativeBackedQueue.restore_window",
                    calls=("push",))
    )
    t = (
        Transition(
            name="pop_window",
            process="driver",
            guard=lambda s: s["window"] == () and (
                s["prefetch"] != () or s["queue"] != ()
            ),
            effect=lambda s: (
                {"window": s["prefetch"], "prefetch": (),
                 "just_deferred": False}
                if s["prefetch"] != ()
                else {"window": s["queue"][:_WINDOW_CAP],
                      "queue": s["queue"][_WINDOW_CAP:],
                      "just_deferred": False}
            ),
            reads=frozenset({"window", "prefetch", "queue"}),
            writes=frozenset({"window", "prefetch", "queue",
                              "just_deferred"}),
            anchors=(
                Anchor(_QUEUE, "SchedulingQueue.pop_window",
                       calls=("_drain_backoff",)),
                Anchor(_SCHED, "Scheduler._take_prefetched"),
            ),
        ),
        Transition(
            name="prefetch_window",
            process="driver",
            guard=lambda s: (
                s["window"] != () and s["prefetch"] == ()
                and s["queue"] != ()
            ),
            effect=lambda s: {
                "prefetch": s["queue"][:_WINDOW_CAP],
                "queue": s["queue"][_WINDOW_CAP:],
            },
            reads=frozenset({"window", "prefetch", "queue"}),
            writes=frozenset({"prefetch", "queue"}),
            anchors=(
                Anchor(_SCHED, "Scheduler._prefetch_next",
                       must_contain=("pop_window",)),
            ),
        ),
        Transition(
            name="resolve_window",
            process="driver",
            guard=lambda s: s["window"] != (),
            effect=lambda s, front=front: _resolve_effect(s, front=front),
            reads=frozenset(
                {"window", "queue", "prefetch", "defers", "split", "bound"}
            ),
            writes=frozenset(
                {"window", "queue", "prefetch", "defers", "split", "bound",
                 "just_deferred"}
            ),
            anchors=(
                Anchor(_SCHED, "Scheduler._resolve_gangs",
                       must_contain=("mask_partial_gangs_np",),
                       calls=("_defer_gang",)),
                Anchor(_SCHED, "Scheduler._defer_gang",
                       must_contain=("RESTORES_TO_FRONT",
                                     "gang_defer_policy"),
                       calls=("restore_window",)),
                restore_anchor,
            ),
        ),
        Transition(
            name="arrive_g2",
            process="arrivals",
            guard=lambda s: not s["arrived2"],
            effect=lambda s: {
                "queue": s["queue"] + ("g2",), "arrived2": True,
            },
            reads=frozenset({"queue", "arrived2"}),
            writes=frozenset({"queue", "arrived2"}),
            anchors=(Anchor(_QUEUE, "SchedulingQueue.push"),),
        ),
    )
    invariants = [
        Invariant(
            "gang-never-partially-admitted",
            lambda s: s["split"] or not (
                0 < sum(1 for p in _GANG if p in s["bound"]) < _GANG_SIZE
            ),
            "an unsplit gang binds whole or not at all — a deferred "
            "gang is restored whole or split, never partially admitted",
        ),
        Invariant(
            "no-pod-lost-or-duplicated",
            _conservation_ok,
            "every arrived pod is in exactly one of queue/window/"
            "prefetch/bound — deferral must neither drop nor duplicate "
            "a popped pod",
        ),
    ]
    if front:
        invariants.append(Invariant(
            "deferred-gang-leads-next-pop",
            lambda s: not s["just_deferred"] or (
                s["queue"] != () and s["queue"][0] in _GANG
            ),
            "on a front-restoring queue an in-budget deferral hands the "
            "prefetched window back first and the gang second, so the "
            "gang leads the next pop (serial/pipelined pop-order "
            "parity; Scheduler._defer_gang)",
        ))
    return ProtocolModel(
        name=name,
        description=(
            "gang all-or-nothing deferral against a "
            f"{'front' if front else 'back'}-restoring queue, with a "
            "pipelined prefetch slot and a late-arriving member"
        ),
        init={
            "queue": ("g1", "p1", "p2"), "window": (), "prefetch": (),
            "arrived2": False, "defers": 0, "split": False, "bound": (),
            "just_deferred": False,
        },
        transitions=t,
        invariants=tuple(invariants),
        convergences=(
            Convergence(
                "every-pod-settles",
                trigger=lambda s: True,
                goal=lambda s: (
                    s["arrived2"] and s["queue"] == ()
                    and s["window"] == () and s["prefetch"] == ()
                    and len(s["bound"]) == 4
                ),
                description=(
                    "deferral is bounded: every pod (gang members "
                    "included, split or admitted) eventually binds — no "
                    "defer/restore livelock"
                ),
            ),
        ),
    )


# ---- model 3: the pipelined driver's in-flight slot ----------------------


def pipeline_slot_model() -> ProtocolModel:
    t = (
        Transition(
            name="dispatch",
            process="driver",
            guard=lambda s: s["inflight"] == 0,
            effect=lambda s: {
                "inflight": 1,
                # a stale speculative batch is REBUILT, never scored
                "spec": "none",
                # optimistic resident commit: the dispatched snapshot
                # becomes the next delta base
                "resident_ok": True,
                "last_fail": False,
            },
            reads=frozenset({"inflight", "spec"}),
            writes=frozenset({"inflight", "spec", "resident_ok",
                              "last_fail"}),
            anchors=(
                Anchor(_SCHED, "Scheduler._dispatch_window",
                       must_contain=("_layout_fingerprint",)),
                Anchor(_SCHED, "Scheduler._dispatch_resident",
                       calls=("_commit_resident",)),
            ),
        ),
        Transition(
            name="prefetch_spec_batch",
            process="driver",
            guard=lambda s: s["inflight"] == 1 and s["spec"] == "none",
            effect=lambda s: {"spec": "fresh"},
            reads=frozenset({"inflight", "spec"}),
            writes=frozenset({"spec"}),
            anchors=(
                Anchor(_SCHED, "Scheduler._prefetch_next",
                       must_contain=("_spec_batch",)),
            ),
        ),
        Transition(
            name="complete_ok",
            process="driver",
            guard=lambda s: s["inflight"] == 1,
            effect=lambda s: {"inflight": 0},
            reads=frozenset({"inflight"}),
            writes=frozenset({"inflight"}),
            anchors=(Anchor(_SCHED, "Scheduler._complete_window"),),
        ),
        Transition(
            name="complete_fail",
            process="driver",
            guard=lambda s: s["inflight"] == 1 and s["fail_budget"] > 0,
            effect=lambda s: {
                "inflight": 0,
                # the failure path must BOTH drop speculative state and
                # roll the optimistic resident commit back
                "spec": "none",
                "resident_ok": False,
                "last_fail": True,
                "fail_budget": s["fail_budget"] - 1,
            },
            reads=frozenset({"inflight", "fail_budget"}),
            writes=frozenset({"inflight", "spec", "resident_ok",
                              "last_fail", "fail_budget"}),
            anchors=(
                # the failure arm moved into the split-phase completion
                # when run_cycle_split/complete grew the dispatch seam
                # (fleet-shared engine PR); the obligations are the same
                Anchor(_SCHED, "Scheduler._complete_cycle_split",
                       must_contain=("_invalidate_resident",
                                     "_discard_speculative")),
            ),
        ),
        Transition(
            name="informer_churn",
            process="env",
            guard=lambda s: s["spec"] == "fresh" and s["churn_budget"] > 0,
            effect=lambda s: {
                "spec": "stale", "churn_budget": s["churn_budget"] - 1,
            },
            reads=frozenset({"spec", "churn_budget"}),
            writes=frozenset({"spec", "churn_budget"}),
            anchors=(
                Anchor(_SCHED, "Scheduler._layout_fingerprint",
                       must_contain=("selectors",)),
            ),
        ),
    )
    return ProtocolModel(
        name="pipeline-slot",
        description=(
            "the 1-deep pipelined driver: in-flight slot, speculative "
            "pod batch under informer churn, optimistic resident commit "
            "vs the failure path"
        ),
        init={
            "inflight": 0, "spec": "none", "resident_ok": False,
            "last_fail": False, "scored_stale": False,
            "fail_budget": 2, "churn_budget": 2,
        },
        transitions=t,
        invariants=(
            Invariant(
                "single-rpc-in-flight",
                lambda s: s["inflight"] <= 1,
                "the pipelined driver keeps at most ONE engine call in "
                "flight (bridge client: one async worker)",
            ),
            Invariant(
                "failure-invalidates-resident",
                lambda s: not s["last_fail"] or not s["resident_ok"],
                "a failed cycle must roll back the optimistic resident "
                "commit — the next dispatch uploads in full, never a "
                "delta against a base the engine may not hold",
            ),
            Invariant(
                "stale-spec-batch-never-scored",
                lambda s: not s["scored_stale"],
                "a speculative pod batch whose layout fingerprint no "
                "longer matches is rebuilt, never dispatched",
            ),
        ),
    )


# ---- model 4: 2-replica cross-partition bind conflict (host/replica.py) --


def _bind_win(r):
    def guard(s):
        return (
            s[f"r{r}"] == "holds" and s[f"disp_{r}"]
            and s["pod_bound"] == ""
            and s[f"seen_{r}"] == s["pod_epoch"]
        )

    def effect(s):
        return {
            "pod_bound": r, "pod_epoch": s["pod_epoch"] + 1,
            f"r{r}": "idle", f"disp_{r}": False,
        }

    return guard, effect


def _bind_lose(r):
    def guard(s):
        return s[f"r{r}"] == "holds" and s[f"disp_{r}"] and not (
            s["pod_bound"] == "" and s[f"seen_{r}"] == s["pod_epoch"]
        )

    def effect(s):
        # first bind wins; the loser requeues its copy via
        # restore_window and retries from the queue
        return {f"r{r}": "idle", f"avail_{r}": True, f"disp_{r}": False}

    return guard, effect


def _dispatch_effect(s, r, *, fenced: bool = True):
    """One coalesced dispatch through the shared pool for replica
    `r`'s held window. With the shipped fence, a row-diff delta ships
    ONLY while the sidecar retains the pool's current base
    (`_classify` returns "full" on a dropped base); either way the
    dispatch re-establishes the base at the advanced epoch. The
    mutant harness flips `fenced` to ship the delta blindly."""
    ships_delta = s["pool_base"] == "held" if fenced else True
    return {
        f"disp_{r}": True,
        "pool_base": "held",
        "stale_delta": s["stale_delta"]
        or (ships_delta and s["pool_base"] != "held"),
    }


def replica_bind_model() -> ProtocolModel:
    t = []
    for r in ("a", "b"):
        wg, we = _bind_win(r)
        lg, le = _bind_lose(r)
        t.extend([
            Transition(
                name=f"pop_{r}",
                process=f"replica_{r}",
                guard=lambda s, r=r: (
                    s[f"avail_{r}"] and s[f"r{r}"] == "idle"
                    and s["pod_bound"] == ""
                ),
                effect=lambda s, r=r: {
                    f"r{r}": "holds", f"avail_{r}": False,
                    f"seen_{r}": s["pod_epoch"],
                },
                reads=frozenset({f"avail_{r}", f"r{r}", "pod_bound",
                                 "pod_epoch"}),
                writes=frozenset({f"r{r}", f"avail_{r}", f"seen_{r}"}),
                anchors=(
                    # the replica's partition pop: filters table-bound
                    # pods (drop_bound) and records the epoch each
                    # surviving pod was seen at — the fence operand
                    Anchor(_REPLICA, "ReplicaCoordinator.pop_window",
                           must_contain=("epoch",),
                           calls=("pop_window",)),
                ),
            ),
            Transition(
                name=f"dispatch_{r}",
                process=f"replica_{r}",
                guard=lambda s, r=r: (
                    s[f"r{r}"] == "holds" and not s[f"disp_{r}"]
                ),
                effect=lambda s, r=r: _dispatch_effect(s, r),
                reads=frozenset({f"r{r}", f"disp_{r}", "pool_base",
                                 "stale_delta"}),
                writes=frozenset({f"disp_{r}", "pool_base",
                                  "stale_delta"}),
                anchors=(
                    # the executor drains every queued replica window
                    # into one fused dispatch; the base delta is
                    # classified against the pool's retained copy —
                    # a dropped base (flush raced) classifies "full"
                    Anchor(_POOL, "SharedEnginePool._settle",
                           must_contain=("self._executing = True",)),
                    Anchor(_POOL, "SharedEnginePool._execute_group",
                           must_contain=(
                               "self._classify(self._prev, base)",
                           ),
                           calls=("snapshot_delta",)),
                    Anchor(_POOL, "SharedEnginePool._classify",
                           must_contain=("prev is None",)),
                ),
            ),
            Transition(
                name=f"bind_win_{r}",
                process=f"replica_{r}",
                guard=wg,
                effect=we,
                reads=frozenset({f"r{r}", f"disp_{r}", "pod_bound",
                                 f"seen_{r}", "pod_epoch"}),
                writes=frozenset({"pod_bound", "pod_epoch", f"r{r}",
                                  f"disp_{r}"}),
                anchors=(
                    # THE CAS: unbound + current epoch, or rejected;
                    # success installs the winner and advances the epoch
                    Anchor(_REPLICA, "BindTable.try_bind",
                           must_contain=("seen_epoch != rec[0]",
                                         "rec[0] += 1")),
                    # the loser's raise lands in the binder's existing
                    # first-write-wins 409 arm (drop, never requeue)
                    Anchor(_SCHED, "Scheduler._bind",
                           must_contain=("404, 409",)),
                ),
            ),
            Transition(
                name=f"bind_lose_{r}",
                process=f"replica_{r}",
                guard=lg,
                effect=le,
                reads=frozenset({f"r{r}", f"disp_{r}", "pod_bound",
                                 f"seen_{r}", "pod_epoch"}),
                writes=frozenset({f"r{r}", f"avail_{r}", f"disp_{r}"}),
                anchors=(
                    Anchor(_REPLICA, "ReplicaCoordinator.bind_lose",
                           calls=("restore_window",)),
                    # the requeue preserves per-partition front-restore
                    # semantics — the same machinery gang deferral uses
                    Anchor(_QUEUE, "SchedulingQueue.restore_window",
                           must_contain=("_front_floor",)),
                ),
            ),
            Transition(
                name=f"drop_bound_{r}",
                process=f"replica_{r}",
                guard=lambda s, r=r: (
                    s[f"avail_{r}"] and s[f"r{r}"] == "idle"
                    and s["pod_bound"] != ""
                ),
                effect=lambda s, r=r: {f"avail_{r}": False},
                reads=frozenset({f"avail_{r}", f"r{r}", "pod_bound"}),
                writes=frozenset({f"avail_{r}"}),
                anchors=(
                    Anchor(_REPLICA, "ReplicaCoordinator.drop_bound",
                           calls=("mark_scheduled",)),
                ),
            ),
        ])
    t.append(Transition(
        name="engine_flush",
        process="env",
        guard=lambda s: s["flush_budget"] > 0 and s["pool_base"] == "held",
        effect=lambda s: {
            "pool_base": "none", "flush_budget": s["flush_budget"] - 1,
        },
        reads=frozenset({"pool_base", "flush_budget"}),
        writes=frozenset({"pool_base", "flush_budget"}),
        anchors=(
            # sidecar crash mid-batch (_fail fans the error out and
            # drops the base) and external invalidation both leave the
            # pool baseless — the next dispatch MUST re-sync full
            Anchor(_POOL, "SharedEnginePool._fail",
                   must_contain=("self._prev = None",)),
            Anchor(_POOL, "SharedEnginePool.invalidate",
                   must_contain=("self._prev = None",)),
        ),
    ))
    return ProtocolModel(
        name="replica-bind",
        description=(
            "horizontal scale-out conflict protocol (host/replica.py + "
            "host/engine_pool.py): two scheduler replicas transiently "
            "share one pod (partition handoff overlap) and dispatch "
            "through ONE fleet-shared engine; binds are fenced by the "
            "BindTable epoch CAS, first bind wins, the loser requeues "
            "via restore_window and drops on re-pop once the table "
            "shows the pod bound; the shared resident base is fenced by "
            "the pool epoch — a flushed base re-syncs full, never a "
            "blind delta"
        ),
        init={
            "pod_bound": "", "pod_epoch": 0,
            "ra": "idle", "rb": "idle",
            "avail_a": True, "avail_b": True,
            "seen_a": 0, "seen_b": 0,
            "disp_a": False, "disp_b": False,
            "pool_base": "none", "flush_budget": 2,
            "double_bound": False, "stale_delta": False,
        },
        transitions=tuple(t),
        invariants=(
            Invariant(
                "no-double-bind",
                lambda s: not s["double_bound"],
                "a pod is bound by at most one replica — the epoch CAS "
                "(first bind wins) must fence every bind",
            ),
            Invariant(
                "bound-pod-never-re-popped",
                lambda s: not (
                    s["pod_bound"] != "" and (
                        (s["ra"] == "holds" and s["seen_a"] >= s["pod_epoch"])
                        or (s["rb"] == "holds"
                            and s["seen_b"] >= s["pod_epoch"])
                    )
                ),
                "a replica holding the pod after someone bound it must "
                "hold a STALE epoch — its bind attempt is then fenced "
                "off by the CAS",
            ),
            Invariant(
                "shared-delta-fenced",
                lambda s: not s["stale_delta"],
                "a coalesced dispatch ships a row-diff delta only while "
                "the sidecar retains the pool's current resident base — "
                "a replica that raced a flush re-syncs with a fenced "
                "full upload, never a blind delta against state the "
                "engine no longer holds (SharedEnginePool._classify)",
            ),
        ),
        convergences=(
            Convergence(
                "conflict-resolves",
                trigger=lambda s: s["ra"] == "holds" and s["rb"] == "holds",
                goal=lambda s: (
                    s["pod_bound"] != "" and s["ra"] == "idle"
                    and s["rb"] == "idle" and not s["avail_a"]
                    and not s["avail_b"]
                ),
                description=(
                    "when both replicas hold the pod, exactly one bind "
                    "wins and the loser's requeued copy drains — no "
                    "requeue livelock, no stuck copies"
                ),
            ),
        ),
    )


# ---- model 5: the degradation ladder + circuit breaker -------------------
#
# Abstracts host/resilience.py as wired by host/scheduler.py: one
# subsystem's rung (0 = top, abstract depth 3 so a rung SKIP is
# expressible), the probe-before-promote recovery discipline, and the
# engine circuit breaker (closed/open/half-open, threshold 2) whose
# open state must imply a degraded rung (Scheduler._on_breaker_transition
# demotes when the breaker opens). Faults are budget-bounded environment
# churn (sim/faults.FaultInjector windows). Ghost variables: `skipped`
# can only become True if a demote ever moves more than one rung at a
# time; `unprobed_climb` only if a promote fires without a recorded
# probe — the two silent-recovery bug classes the ladder exists to
# forbid.

_LADDER_BOTTOM = 2
_BRK_THRESHOLD = 2


def degradation_ladder_model() -> ProtocolModel:
    def fail_effect(s):
        new_rung = min(s["rung"] + 1, _LADDER_BOTTOM)
        fails = min(s["fails"] + 1, _BRK_THRESHOLD)
        opens = s["breaker"] == "half" or fails >= _BRK_THRESHOLD
        return {
            "fails": fails,
            "breaker": "open" if opens else s["breaker"],
            "rung": new_rung,
            "probed": False,
            "skipped": s["skipped"] or (new_rung - s["rung"] > 1),
        }

    def recover_effect(s):
        return {
            "rung": s["rung"] - 1,
            "probed": False,
            "fails": 0,
            "breaker": "closed" if s["breaker"] == "half" else s["breaker"],
            "unprobed_climb": s["unprobed_climb"] or not s["probed"],
        }

    t = (
        Transition(
            name="attempt_fail",
            process="host",
            guard=lambda s: s["fault"] and s["breaker"] != "open",
            effect=fail_effect,
            reads=frozenset(
                {"fault", "breaker", "fails", "rung", "skipped", "probed"}
            ),
            writes=frozenset(
                {"fails", "breaker", "rung", "probed", "skipped"}
            ),
            anchors=(
                Anchor(_SCHED, "Scheduler._engine_failure",
                       calls=("record_failure", "demote")),
                Anchor(_RESIL, "CircuitBreaker.record_failure",
                       must_contain=("OPEN",)),
                Anchor(_RESIL, "DegradationLadder.demote",
                       must_contain=("d + 1",)),
                Anchor(_SCHED, "Scheduler._on_breaker_transition",
                       calls=("demote",)),
            ),
        ),
        Transition(
            name="probe",
            process="host",
            guard=lambda s: (
                s["rung"] > 0 and not s["probed"] and s["breaker"] != "open"
            ),
            effect=lambda s: {"probed": True},
            reads=frozenset({"rung", "probed", "breaker"}),
            writes=frozenset({"probed"}),
            anchors=(
                Anchor(_RESIL, "DegradationLadder.probe",
                       must_contain=("_probed",)),
                Anchor(_SCHED, "Scheduler._ladder_cycle_end",
                       calls=("probe", "promote")),
            ),
        ),
        Transition(
            name="recover",
            process="host",
            guard=lambda s: (
                s["rung"] > 0 and s["probed"] and not s["fault"]
                and s["breaker"] != "open"
            ),
            effect=recover_effect,
            reads=frozenset(
                {"rung", "probed", "fault", "breaker", "fails",
                 "unprobed_climb"}
            ),
            writes=frozenset(
                {"rung", "probed", "fails", "breaker", "unprobed_climb"}
            ),
            anchors=(
                Anchor(_RESIL, "DegradationLadder.promote",
                       must_contain=("_probed",)),
                Anchor(_RESIL, "CircuitBreaker.record_success"),
            ),
        ),
        Transition(
            name="half_open",
            process="env",
            guard=lambda s: s["breaker"] == "open",
            effect=lambda s: {"breaker": "half"},
            reads=frozenset({"breaker"}),
            writes=frozenset({"breaker"}),
            anchors=(
                Anchor(_RESIL, "CircuitBreaker.allow",
                       must_contain=("HALF_OPEN",)),
            ),
        ),
        Transition(
            name="fault_hit",
            process="env",
            guard=lambda s: not s["fault"] and s["fault_budget"] > 0,
            effect=lambda s: {
                "fault": True, "fault_budget": s["fault_budget"] - 1,
            },
            reads=frozenset({"fault", "fault_budget"}),
            writes=frozenset({"fault", "fault_budget"}),
            anchors=(
                Anchor(_FAULTS, "FaultInjector.check",
                       must_contain=("active",)),
            ),
        ),
        Transition(
            name="fault_clear",
            process="env",
            guard=lambda s: s["fault"],
            effect=lambda s: {"fault": False},
            reads=frozenset({"fault"}),
            writes=frozenset({"fault"}),
            anchors=(
                Anchor(_FAULTS, "FaultInjector.quiesced",
                       must_contain=("last_end",)),
            ),
        ),
    )
    return ProtocolModel(
        name="degradation-ladder",
        description=(
            "the degradation-ladder state machine + engine circuit "
            "breaker under budget-bounded faults: one-rung demotes with "
            "recorded reasons, probe-before-promote recovery, and the "
            "breaker-open-implies-degraded coupling"
        ),
        init={
            "rung": 0, "probed": False, "breaker": "closed", "fails": 0,
            "fault": False, "fault_budget": 2,
            "skipped": False, "unprobed_climb": False,
        },
        transitions=t,
        invariants=(
            Invariant(
                "never-skips-a-rung",
                lambda s: not s["skipped"],
                "every demote moves exactly ONE rung with a recorded "
                "reason — a multi-rung drop is a silent skip the event "
                "log (and operators) never see",
            ),
            Invariant(
                "recovery-re-probes",
                lambda s: not s["unprobed_climb"],
                "a subsystem may only climb a rung after its degraded "
                "path was explicitly re-probed — optimistic un-probed "
                "promotion re-enters the failure it degraded away from",
            ),
            Invariant(
                "breaker-open-implies-degraded",
                lambda s: s["breaker"] == "closed" or s["rung"] >= 1,
                "an open (or probing half-open) engine breaker means "
                "the engine subsystem is NOT at its top rung — the "
                "ladder and the breaker must never disagree about an "
                "outage",
            ),
        ),
        convergences=(
            Convergence(
                "outage-recovers",
                trigger=lambda s: (
                    s["rung"] > 0 and not s["fault"]
                    and s["fault_budget"] == 0
                ),
                goal=lambda s: s["rung"] == 0 and s["breaker"] == "closed",
                description=(
                    "once the faults stop, every path climbs back to "
                    "the top rung with the breaker closed — no probe/"
                    "demote livelock, no rung stuck degraded forever"
                ),
            ),
        ),
    )


# ---- registry ------------------------------------------------------------


def build_models() -> tuple[ProtocolModel, ...]:
    """Fresh instances of every shipped model, in checking order."""
    return (
        client_session_model(),
        gang_queue_model(front=True),
        gang_queue_model(front=False),
        pipeline_slot_model(),
        replica_bind_model(),
        degradation_ladder_model(),
    )


def replace_transition(model: ProtocolModel, name: str, new) -> ProtocolModel:
    """A copy of `model` with transition `name` swapped for `new` —
    the mutation harness's primitive."""
    if not any(t.name == name for t in model.transitions):
        raise KeyError(f"{model.name} has no transition `{name}`")
    return dataclasses.replace(
        model,
        transitions=tuple(
            new if t.name == name else t for t in model.transitions
        ),
    )
