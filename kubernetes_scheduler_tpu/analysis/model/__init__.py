"""graftmodel: explicit-state bounded model checking of the
session/epoch/capability protocol, run as part of lint.

The invariants that keep the resident-state bridge correct used to live
in prose ("latched like the field cache and INVALIDATED TOGETHER with
it", bridge/client.py) — and were violated once (the PR-3
mid-stream-downgrade bug). This package makes them CHECKED artifacts:

- `checker` — the engine: a deterministic explicit-state explorer over
  declared protocol state machines, with sleep-set partial-order
  reduction, state/time budgets, and counterexamples rendered as
  readable event schedules;
- `protocols` — the models: the RemoteEngine client session (wire
  field cache + capability latches + resident epoch under
  failure/restart/version-skew), the sidecar's session-keyed state,
  the queue's restore_window/gang-deferral semantics (front- and
  back-restoring variants), the pipelined driver's in-flight slot,
  and a 2-replica model of the PROPOSED cross-replica bind-conflict
  protocol (ROADMAP's horizontal scale-out item, de-risked before it
  is written);
- `anchors` — the drift layer: every model transition is bound to the
  real code site it abstracts via the shared ModuleIndex/call-graph
  (the way contracts.py binds shape specs via jax.eval_shape), so the
  model FAILS LINT when the code moves out from under it;
- `mutants` — the teeth: seeded reintroductions of known protocol bug
  classes (invalidate-without-the-field-cache — the PR-3 class;
  delta-across-layout-churn; restore-to-the-back on the Python queue;
  unfenced cross-replica binds) that the checker must each catch.

`python -m kubernetes_scheduler_tpu.analysis.model` is the CLI
(`make model-check`); a full-repo graftlint run folds the whole layer
in as pseudo-rule `protocol-model`.
"""

from kubernetes_scheduler_tpu.analysis.model.checker import (  # noqa: F401
    CheckResult,
    Convergence,
    Invariant,
    ProtocolModel,
    Transition,
    check_model,
)
