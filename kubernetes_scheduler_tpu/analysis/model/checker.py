"""The explicit-state bounded model checker under analysis/model/.

Models are finite-state by construction (every variable ranges over a
small declared domain; environment churn is budget-bounded), so the
checker can EXHAUST the reachable state space and prove the declared
invariants rather than sample them:

- **States** are flat dicts of hashable variable values; a transition
  is a (guard, effect) pair plus declared read/write sets — the effect
  returns only the variables it changes, and the checker validates the
  writes against the declaration at fire time (a lying annotation would
  make the reduction below unsound, so it is an error, not a comment).
- **Exploration** is depth-first and fully deterministic: transitions
  fire in declaration order, so the same model yields the same
  traversal, the same counterexamples, byte for byte, on every run.
- **Partial-order reduction** is the sleep-set algorithm (Godefroid):
  after exploring transition t from state s, t is put to sleep for the
  subtrees of t's independent siblings — the interleaving t;u is
  explored, u;t is not, when the two commute. Sleep sets prune
  redundant TRANSITIONS, never states: every reachable state is still
  visited, so checking invariants at each visited state stays sound
  (tests/test_model.py pins POR state sets == full state sets on every
  shipped model). Two transitions are independent iff they belong to
  different processes and neither writes what the other reads or
  writes.
- **Invariants** are checked at every state on first visit; a
  violation renders the event schedule from the initial state (the
  parent pointers of first discovery — deterministic, shortest-ish).
- **Convergence properties** ("epoch desync always converges to a full
  resend") are AF checks: from every reachable `trigger` state, every
  maximal path must reach a `goal` state. These are evaluated on the
  FULL edge relation (a reduced edge set could hide a goal-avoiding
  cycle), which the checker re-explores without POR when a model
  declares any — the models are small enough that soundness is cheaper
  than cleverness. A violation renders the path into the goal-avoiding
  cycle (livelock) or dead end.
- **Budgets** bound states and wall time; a model that does not
  exhaust its space inside them is reported un-exhausted and the
  caller (CLI exit 3, lint violation) fails loudly — a bounded proof
  that silently covered half the space would be worse than none.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Transition:
    """One protocol step. `guard(state) -> bool`, `effect(state) ->
    dict of updated variables` (only variables in `writes`). `reads`
    must cover every variable the guard or effect examines — the
    independence relation (and so the reduction) is computed from these
    declarations."""

    name: str
    process: str
    guard: object
    effect: object
    reads: frozenset
    writes: frozenset
    # code sites this transition abstracts (anchors.Anchor); verified
    # against the live ModuleIndex by the drift layer
    anchors: tuple = ()


@dataclass(frozen=True)
class Invariant:
    """Must hold in EVERY reachable state."""

    name: str
    check: object
    description: str = ""


@dataclass(frozen=True)
class Convergence:
    """AF property: from every reachable state satisfying `trigger`,
    every maximal path reaches a state satisfying `goal`."""

    name: str
    trigger: object
    goal: object
    description: str = ""


@dataclass(frozen=True)
class ProtocolModel:
    name: str
    description: str
    init: dict
    transitions: tuple
    invariants: tuple = ()
    convergences: tuple = ()
    # where findings anchor in lint output (repo-relative path, line)
    origin: tuple = ("kubernetes_scheduler_tpu/analysis/model/protocols.py", 1)


@dataclass
class ModelViolation:
    model: str
    kind: str          # "invariant" | "convergence" | "budget"
    name: str
    message: str
    schedule: list = field(default_factory=list)  # rendered event lines

    def render(self) -> str:
        out = [f"{self.model}: {self.kind} `{self.name}`: {self.message}"]
        out.extend(f"    {line}" for line in self.schedule)
        return "\n".join(out)


@dataclass
class CheckResult:
    model: str
    states: int
    transitions_fired: int
    transitions_slept: int
    exhausted: bool
    violations: list
    seconds: float

    @property
    def ok(self) -> bool:
        return self.exhausted and not self.violations


def _key(state: dict) -> tuple:
    return tuple(sorted(state.items()))


def _fmt_state(state: dict, keys=None) -> str:
    items = sorted(state.items()) if keys is None else [
        (k, state[k]) for k in keys if k in state
    ]
    return "{" + ", ".join(f"{k}={v!r}" for k, v in items) + "}"


def _independent(a: Transition, b: Transition) -> bool:
    if a.process == b.process:
        return False
    return not (
        (a.writes & (b.reads | b.writes))
        or (b.writes & a.reads)
    )


def _apply(t: Transition, state: dict) -> dict:
    updates = t.effect(state)
    bad = set(updates) - set(t.writes)
    if bad:
        raise ValueError(
            f"transition `{t.name}` wrote undeclared variables "
            f"{sorted(bad)} — its `writes` set is wrong, which would "
            "make the partial-order reduction unsound"
        )
    new = dict(state)
    new.update(updates)
    return new


def _schedule_to(parents: dict, key: tuple, init_key: tuple) -> list[str]:
    """Render the first-discovery path init -> key as event lines."""
    names = []
    k = key
    while k != init_key:
        pk, tname = parents[k]
        names.append(tname)
        k = pk
    names.reverse()
    lines = [f"schedule ({len(names)} events from init):"]
    lines.extend(f"{i + 1}. {n}" for i, n in enumerate(names))
    return lines


@dataclass
class _Exploration:
    states: dict          # key -> state dict (insertion = discovery order)
    parents: dict         # key -> (parent key, transition name)
    edges: dict | None    # key -> [(transition name, succ key)] (full runs)
    fired: int
    slept: int
    exhausted: bool
    init_key: tuple


def _explore(
    model: ProtocolModel,
    *,
    por: bool,
    record_edges: bool,
    max_states: int,
    deadline: float | None,
) -> _Exploration:
    init = dict(model.init)
    init_key = _key(init)
    states = {init_key: init}
    parents: dict = {}
    edges: dict | None = {} if record_edges else None
    # sleep sets each state has been EXPANDED under (Godefroid's
    # sleep-sets-with-state-matching): re-expand only when arriving
    # with a sleep set no previous expansion subsumes — a previous
    # expansion under S' ⊆ S already fired everything S would. This is
    # what keeps sleep sets sound next to state caching: every
    # reachable state is still visited.
    expanded: dict[tuple, list[frozenset]] = {}
    indep: dict[tuple, bool] = {}
    for a in model.transitions:
        for b in model.transitions:
            indep[(a.name, b.name)] = _independent(a, b)
    fired = 0
    slept = 0
    exhausted = True
    edge_seen: set = set()
    # DFS stack of (state key, sleep set); deterministic order
    stack: list[tuple[tuple, frozenset]] = [(init_key, frozenset())]
    expanded[init_key] = [frozenset()]
    while stack:
        if len(states) > max_states or (
            deadline is not None and time.monotonic() > deadline
        ):
            exhausted = False
            break
        skey, sleep = stack.pop()
        state = states[skey]
        cur_sleep = set(sleep)
        for t in model.transitions:
            if not t.guard(state):
                continue
            if t.name in cur_sleep:
                slept += 1
                continue
            succ = _apply(t, state)
            ckey = _key(succ)
            fired += 1
            if edges is not None and (skey, t.name) not in edge_seen:
                edge_seen.add((skey, t.name))
                edges.setdefault(skey, []).append((t.name, ckey))
            if ckey not in states:
                states[ckey] = succ
                parents[ckey] = (skey, t.name)
            child_sleep = frozenset(
                u for u in cur_sleep if por and indep[(t.name, u)]
            )
            prev = expanded.setdefault(ckey, [])
            if not any(p <= child_sleep for p in prev):
                prev.append(child_sleep)
                stack.append((ckey, child_sleep))
            cur_sleep.add(t.name)
    return _Exploration(
        states=states, parents=parents, edges=edges, fired=fired,
        slept=slept, exhausted=exhausted, init_key=init_key,
    )


def _check_invariants(model: ProtocolModel, ex: _Exploration) -> list:
    out = []
    seen_inv: set[str] = set()
    for skey, state in ex.states.items():
        for inv in model.invariants:
            if inv.name in seen_inv:
                continue  # first (discovery-order) counterexample only
            if inv.check(state):
                continue
            seen_inv.add(inv.name)
            sched = _schedule_to(ex.parents, skey, ex.init_key)
            sched.append(f"reaches {_fmt_state(state)}")
            out.append(
                ModelViolation(
                    model=model.name, kind="invariant", name=inv.name,
                    message=inv.description or "invariant violated",
                    schedule=sched,
                )
            )
    return out


def _sccs(nodes: list, succ: dict) -> list[list]:
    """Tarjan (iterative), deterministic order."""
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    out: list[list] = []
    counter = [0]
    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(succ.get(root, ())))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for child in it:
                if child not in index:
                    index[child] = low[child] = counter[0]
                    counter[0] += 1
                    stack.append(child)
                    on_stack.add(child)
                    work.append((child, iter(succ.get(child, ()))))
                    advanced = True
                    break
                if child in on_stack:
                    low[node] = min(low[node], index[child])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)
    return out


def _check_convergences(model: ProtocolModel, ex: _Exploration) -> list:
    """AF(trigger -> goal) on the FULL edge relation. A violation is a
    trigger state from which some maximal path never meets goal: a path
    into a goal-avoiding cycle (livelock) or a goal-avoiding dead end."""
    assert ex.edges is not None
    out = []
    goal_cache: dict[str, dict] = {}
    for conv in model.convergences:
        is_goal = {
            k: bool(conv.goal(s)) for k, s in ex.states.items()
        }
        goal_cache[conv.name] = is_goal
        # subgraph of non-goal states
        sub_succ: dict = {}
        for k, outs in ex.edges.items():
            if is_goal[k]:
                continue
            sub_succ[k] = [
                (t, c) for t, c in outs if not is_goal[c]
            ]
        sub_nodes = [k for k in ex.states if not is_goal[k]]
        succ_keys = {
            k: [c for _, c in v] for k, v in sub_succ.items()
        }
        # bad seeds: non-goal dead ends (no successors AT ALL) and
        # states on cycles inside the non-goal subgraph
        seeds: set = set()
        for k in sub_nodes:
            if not ex.edges.get(k):
                seeds.add(k)
        for comp in _sccs(sub_nodes, succ_keys):
            if len(comp) > 1:
                seeds.update(comp)
            else:
                k = comp[0]
                if k in succ_keys.get(k, ()):  # self-loop
                    seeds.add(k)
        # states (within the non-goal subgraph) that can reach a seed
        rev: dict = {}
        for k, outs in succ_keys.items():
            for c in outs:
                rev.setdefault(c, []).append(k)
        bad = set(seeds)
        frontier = list(seeds)
        while frontier:
            k = frontier.pop()
            for p in rev.get(k, ()):
                if p not in bad:
                    bad.add(p)
                    frontier.append(p)
        # first (discovery-order) triggering bad state
        witness = None
        for k, s in ex.states.items():
            if k in bad and conv.trigger(s):
                witness = k
                break
        if witness is None:
            continue
        sched = _schedule_to(ex.parents, witness, ex.init_key)
        sched.append(f"reaches trigger state {_fmt_state(ex.states[witness])}")
        sched.extend(_lasso_from(witness, sub_succ, ex, seeds, succ_keys))
        out.append(
            ModelViolation(
                model=model.name, kind="convergence", name=conv.name,
                message=(
                    conv.description
                    or "a maximal path from a trigger state never reaches "
                    "the goal"
                ),
                schedule=sched,
            )
        )
    return out


def _lasso_from(start, sub_succ, ex, seeds, succ_keys) -> list[str]:
    """Render the goal-avoiding continuation: BFS (deterministic) to the
    nearest seed, then one goal-avoiding cycle or the dead end."""
    par = {start: None}
    order = [start]
    seed = start if start in seeds else None
    i = 0
    while seed is None and i < len(order):
        k = order[i]
        i += 1
        for t, c in sub_succ.get(k, ()):
            if c not in par:
                par[c] = (k, t)
                order.append(c)
                if c in seeds:
                    seed = c
                    break
    lines = []
    if seed is not None and seed != start:
        path = []
        k = seed
        while par[k] is not None:
            pk, t = par[k]
            path.append(t)
            k = pk
        path.reverse()
        lines.append("then (staying goal-free): " + " -> ".join(path))
    tail = seed if seed is not None else start
    if not ex.edges.get(tail):
        lines.append(f"dead end at {_fmt_state(ex.states[tail])}")
        return lines
    # one cycle through `tail` inside the non-goal subgraph
    cyc_par = {tail: None}
    cyc_order = [tail]
    closed = None
    j = 0
    while closed is None and j < len(cyc_order):
        k = cyc_order[j]
        j += 1
        for t, c in sub_succ.get(k, ()):
            if c == tail:
                closed = (k, t)
                break
            if c not in cyc_par:
                cyc_par[c] = (k, t)
                cyc_order.append(c)
    if closed is not None:
        k, t = closed
        cyc = [t]
        while cyc_par[k] is not None:
            pk, pt = cyc_par[k]
            cyc.append(pt)
            k = pk
        cyc.reverse()
        lines.append(
            "livelock cycle (repeats forever): " + " -> ".join(cyc)
        )
    return lines


def check_model(
    model: ProtocolModel,
    *,
    por: bool = True,
    max_states: int = 200_000,
    max_seconds: float | None = 30.0,
    mutate=None,
) -> CheckResult:
    """Exhaust the model's bounded state space and check every declared
    property. `mutate(model) -> model` (mutants.py) swaps in a seeded
    bug before checking."""
    if mutate is not None:
        model = mutate(model)
    t0 = time.monotonic()
    deadline = t0 + max_seconds if max_seconds is not None else None
    # record edges only when THIS exploration's relation will be used:
    # convergence checking always re-explores without POR (below), so a
    # reduced pass never needs them
    ex = _explore(
        model, por=por, record_edges=not por,
        max_states=max_states, deadline=deadline,
    )
    violations: list[ModelViolation] = []
    if not ex.exhausted:
        violations.append(
            ModelViolation(
                model=model.name, kind="budget", name="state-budget",
                message=(
                    f"state space not exhausted within max_states="
                    f"{max_states} / max_seconds={max_seconds} "
                    f"({len(ex.states)} states explored) — the bounded "
                    "proof is incomplete"
                ),
            )
        )
    violations.extend(_check_invariants(model, ex))
    if ex.exhausted and model.convergences:
        if ex.edges is None or por:
            # convergence needs the FULL edge relation: re-explore
            # without reduction (a reduced edge set could hide a
            # goal-avoiding cycle)
            ex_full = _explore(
                model, por=False, record_edges=True,
                max_states=max_states, deadline=deadline,
            )
        else:
            ex_full = ex
        if ex_full.exhausted:
            violations.extend(_check_convergences(model, ex_full))
        else:
            violations.append(
                ModelViolation(
                    model=model.name, kind="budget", name="state-budget",
                    message=(
                        "full (unreduced) re-exploration for convergence "
                        "checking blew the budget"
                    ),
                )
            )
    return CheckResult(
        model=model.name,
        states=len(ex.states),
        transitions_fired=ex.fired,
        transitions_slept=ex.slept,
        exhausted=ex.exhausted,
        violations=violations,
        seconds=time.monotonic() - t0,
    )
