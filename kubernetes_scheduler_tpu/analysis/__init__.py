"""graftlint: repo-native static analysis, two layers.

The scheduler's correctness rests on invariants no test can check
exhaustively — pure jitted scoring kernels, donated resident buffers,
lock-guarded shared caches between the driver/bridge/exporter threads,
a stable wire schema between host and sidecar. This package
machine-enforces them:

Layer 1 — fourteen AST rule families over the repo's own source. The
per-file era families (jit-purity, host-sync, lock-discipline,
wire-schema, dtype-shape, timeout-hygiene, pallas-vmem, metric-hygiene,
sim-determinism, span-hygiene) plus four interprocedural families built
on the shared dataflow core (analysis/dataflow.py — parse-once module
index, project call graph, branch-path def-use, donation summaries,
lockset fixpoint):

  donation-aliasing  donated buffer re-read, across modules/helpers
  host-transfer      implicit device→host syncs in the hot-path modules
  tracer-leak        tracers stored where they outlive the traced call
  lockset-race       guarded attrs need a consistent call-graph lockset

Layer 2 — engine contracts (analysis/contracts.py): every engine entry
point's shape/dtype contract verified by jax.eval_shape tracing on CPU
across a bucket-shape grid, fused and unfused paths diffed against the
same declaration.

Run:  python -m kubernetes_scheduler_tpu.analysis   (or `make lint`)

A genuine-but-intended site is waived inline with a justification:

  x = a.item()  # graftlint: disable=host-sync -- host numpy by contract

A waiver without the `-- reason` clause is itself a violation; a waiver
above a decorator covers the whole def, one on a multi-line statement
covers the statement. CI artifacts: `--format json|sarif`,
`--json-artifact`, and the LINT_BASELINE.json suppression file (stale
or unexplained entries fail lint).
"""

from kubernetes_scheduler_tpu.analysis.core import (  # noqa: F401
    Context,
    Violation,
    run_lint,
)
