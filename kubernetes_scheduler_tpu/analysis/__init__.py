"""graftlint: repo-native static analysis, three layers.

The scheduler's correctness rests on invariants no test can check
exhaustively — pure jitted scoring kernels, donated resident buffers,
lock-guarded shared caches between the driver/bridge/exporter threads,
a stable wire schema between host and sidecar, a session/epoch/
capability protocol across it. This package machine-enforces them:

Layer 1 — sixteen AST rule families over the repo's own source. The
per-file era families (jit-purity, host-sync, lock-discipline,
wire-schema, dtype-shape, timeout-hygiene, pallas-vmem, metric-hygiene,
sim-determinism, span-hygiene) plus the families built on the shared
dataflow core (analysis/dataflow.py — parse-once module index, project
call graph, branch-path def-use, donation summaries, lockset fixpoint):

  donation-aliasing  donated buffer re-read, across modules/helpers
  host-transfer      implicit device→host syncs in the hot-path modules
  tracer-leak        tracers stored where they outlive the traced call
  lockset-race       guarded attrs need a consistent call-graph lockset

plus capability-completeness (every HealthReply capability bit wired
end to end: latch/switch tables vs the .proto both ways, table-driven
probe/invalidate, accessors, per-RPC except-path discipline) and
spmd-collective (analysis/spmd.py — a replication-lattice abstract
interpreter over the mesh-sharded engine's shard_map bodies:
double-counting psums, unbound axis names, redundant gathers,
out_specs replication the body never establishes).

Layer 2 — engine contracts (analysis/contracts.py): every engine entry
point's shape/dtype contract verified by jax.eval_shape tracing on CPU
across a bucket-shape grid, fused and unfused paths diffed against the
same declaration — the mesh-sharded surfaces traced THROUGH shard_map
on the virtual multi-device topology, with the sharded==dense spec
pin, the COLLECTIVE_BUDGET.json collective-count gate, and the seeded
SPMD mutant harness (analysis/spmd_mutants.py).

Layer 3 — protocol models (analysis/model/): the session/epoch/
capability protocol, the queue's gang-deferral semantics, the
pipelined in-flight slot, and the proposed 2-replica bind-conflict
protocol as declared state machines, EXHAUSTIVELY model-checked with
transition anchors that fail lint on code drift and a seeded mutation
harness proving the checker's teeth (`make model-check`).

Run:  python -m kubernetes_scheduler_tpu.analysis   (or `make lint`)

A genuine-but-intended site is waived inline with a justification:

  x = a.item()  # graftlint: disable=host-sync -- host numpy by contract

A waiver without the `-- reason` clause is itself a violation; a waiver
above a decorator covers the whole def, one on a multi-line statement
covers the statement. CI artifacts: `--format json|sarif`,
`--json-artifact`, and the LINT_BASELINE.json suppression file (stale
or unexplained entries fail lint).
"""

from kubernetes_scheduler_tpu.analysis.core import (  # noqa: F401
    Context,
    Violation,
    run_lint,
)
