"""graftlint: repo-native static analysis.

The scheduler's correctness rests on invariants no test can check
exhaustively — pure jitted scoring kernels, lock-guarded shared caches
between the advisor/queue/bridge threads, a stable wire schema between
host and sidecar. This package machine-enforces them as AST-level lint
rules over the repo's own source:

  jit-purity       no side effects reachable from jax.jit entry points
  host-sync        no device barriers / per-element syncs in the cycle path
  lock-discipline  attrs mutated under a class's lock stay under it
  wire-schema      schedule_pb2 field usage must exist in schedule.proto
  dtype-shape      no float64 promotion / traced-bool branching in kernels
  timeout-hygiene  external calls (HTTP, subprocess, waits) carry timeouts

Run:  python -m kubernetes_scheduler_tpu.analysis   (or `make lint`)

A genuine-but-intended site is waived inline with a justification:

  x = a.item()  # graftlint: disable=host-sync -- host numpy by contract

A waiver without the `-- reason` clause is itself a violation.
"""

from kubernetes_scheduler_tpu.analysis.core import (  # noqa: F401
    Context,
    Violation,
    run_lint,
)
