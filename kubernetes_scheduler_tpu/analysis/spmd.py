"""SPMD replication/collective analysis over shard_map bodies.

The one engine surface the AST families could not see into is
parallel/engine.py: 800+ lines of shard_map bodies whose correctness
rests on REPLICATION facts — which values are identical on every shard
(replicated), which hold one shard of a global array (sharded), and
which are genuinely device-varying (an axis_index offset, a
pcast-varying carry). SPMD bugs here are silent in exactly the way this
repo's lint exists to prevent: a `psum` of an already-replicated value
double-counts by the axis size, a collective on an axis name the mesh
never bound deadlocks or miscounts, and `out_specs` declaring
replication the body never establishes ships one shard's garbage as
the global answer.

This module is an abstract interpreter on the PR-9 dataflow core: it
finds `shard_map(body, mesh=..., in_specs=..., out_specs=...)` regions,
seeds each body parameter's replication state from its `in_specs` leaf
(`P()` -> replicated, any sharding axis -> sharded), and propagates a
four-point lattice

    replicated < sharded < varying < unknown

through the body flow-sensitively: assignments strong-update, `if`
arms analyze separately and join, `lax.scan`/`while_loop`/`fori_loop`
bodies run to a carry fixpoint, and project-local helper calls are
analyzed interprocedurally (memoized per argument-state tuple, depth-
and cycle-guarded to `unknown`). Collectives are the lattice's
transfer-function anchors: `psum`/`pmax`/`pmin`/`all_gather` over the
mesh axes produce REPLICATED values regardless of operand (every shard
computes the same reduction), `axis_index`/`pcast(..., to="varying")`
produce VARYING ones, and everything else — jnp math, project helpers,
NamedTuple constructors — is a pure function of its operands, so its
state is the JOIN of theirs (deterministic SPMD execution: identical
inputs on every device produce identical outputs; this is also why a
pmax over provably-equal values is the identity, the sanctioned
re-replication discharge at parallel/engine.py `_sharded_greedy`).

Checks (rule family `spmd-collective`):

- unbound-axis: a collective whose axis-name operand resolves to a
  string (or tuple of strings) not declared by any mesh in the linted
  file set (`*_AXIS` module constants, `Mesh(..., (names,))` tuples) —
  the wrong-axis class that deadlocks or miscounts on hardware;
- replicated-psum: `psum` applied to a provably-replicated operand —
  the double-count class. `psum(1, axes)`/`psum(literal, axes)` is the
  sanctioned device-count idiom and exempt;
- replicated-gather: `all_gather` of a provably-replicated operand —
  D identical copies for one collective launch, always a latency bug;
- gather-axis-misuse: `all_gather(..., axis=<axis name>)` — `axis` is
  the INSERTION POSITION (an int); the mesh axis name is the second
  positional (`axis_name`). Statically a string there is always wrong;
- out-spec-replication: a body return leaf whose `out_specs` leaf is
  `P()` (replicated) but whose abstract state is provably sharded or
  varying — the body never established the replication it declares.
  The discharge pattern is the engine's pmax-over-equal idiom:
  `x = jax.lax.pmax(x, axes)` is the identity on equal values and
  makes replication provable (to this analysis AND jax's vma checker).

Everything unresolvable degrades to `unknown`, which can never fire a
finding — the rule reports what the AST proves, like pallas-vmem. The
traced half of the story (sharded contracts, collective budgets
counted from real jaxprs) lives in analysis/contracts.py; the seeded
mutant harness proving both halves catch their classes lives in
analysis/spmd_mutants.py.
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis import dataflow
from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    SourceFile,
    Violation,
    dotted_name,
)

RULE = "spmd-collective"

# ---- the lattice ----------------------------------------------------------

REP = "replicated"
SHD = "sharded"
VAR = "varying"
UNK = "unknown"

_RANK = {REP: 0, SHD: 1, VAR: 2, UNK: 3}

# state encodings: a bare rank string, ("T", s0, s1, ...) for tuples,
# ("F", (("field", s), ...)) for keyword-constructed records, and
# ("FN", name, id(def node)) for local function values (scan bodies)


def is_scalar(s) -> bool:
    return isinstance(s, str)


def collapse(s) -> str:
    """Fold a structured state to one lattice point (join of leaves)."""
    if is_scalar(s):
        return s
    if s[0] == "FN":
        return REP  # a Python function object is host data
    if s[0] == "T":
        parts = s[1:]
    else:  # "F"
        parts = tuple(v for _, v in s[1])
    if not parts:
        return REP
    return max((collapse(p) for p in parts), key=lambda x: _RANK[x])


def join(a, b):
    if a == b:
        return a
    if is_scalar(a) or is_scalar(b):
        sa, sb = collapse(a), collapse(b)
        return sa if _RANK[sa] >= _RANK[sb] else sb
    if a[0] == "T" and b[0] == "T" and len(a) == len(b):
        return ("T",) + tuple(join(x, y) for x, y in zip(a[1:], b[1:]))
    if a[0] == "F" and b[0] == "F":
        da, db = dict(a[1]), dict(b[1])
        if set(da) == set(db):
            return (
                "F",
                tuple(sorted((k, join(da[k], db[k])) for k in da)),
            )
    return join(collapse(a), collapse(b))


def join_all(states):
    states = list(states)
    if not states:
        return REP
    out = states[0]
    for s in states[1:]:
        out = join(out, s)  # a scalar seed would collapse structure
    return out


# ---- collective / varying-source tables -----------------------------------

# final-segment names treated as mesh collectives; value = index of the
# axis-name positional
COLLECTIVES = {
    "psum": 1,
    "pmax": 1,
    "pmin": 1,
    "all_gather": 1,
    "all_to_all": 1,
    "pbroadcast": 1,
    "psum_scatter": 1,
    "axis_index": 0,
}
# collectives whose RESULT is replicated over the reduced axes (every
# shard computes the identical value). psum_scatter is NOT here: it
# hands each shard a DIFFERENT chunk of the reduced array — sharded.
_REPLICATING = {
    "psum", "pmax", "pmin", "all_gather", "pbroadcast",
}
# axis_size is NOT here: its result is the same integer on every shard
_VARYING_SOURCES = {"axis_index", "pcast", "_pcast_varying"}
# shape-only constructors: the VALUE is fresh replicated data even when
# the shape donor is sharded
_SHAPE_ONLY = {"zeros_like", "ones_like", "empty_like"}

_MAX_DEPTH = 7


def _tail(name: str | None) -> str | None:
    return name.rsplit(".", 1)[-1] if name else None


# ---- declared mesh axis names ---------------------------------------------


def declared_axis_names(
    files: list[SourceFile], index: dataflow.ModuleIndex
) -> set[str]:
    """Every axis name the linted file set declares: module-level
    `*_AXIS = "name"` string constants plus string literals inside the
    axis tuple of a `Mesh(devices, (names...))` construction. Rides
    the index's parse-once node lists — no re-walk."""
    out: set[str] = set()
    for sf in files:
        for node in index.walk(sf):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if (
                    isinstance(t, ast.Name)
                    and t.id.endswith("_AXIS")
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                ):
                    out.add(node.value.value)
            elif isinstance(node, ast.Call) and (
                _tail(dotted_name(node.func)) == "Mesh"
            ):
                for arg in list(node.args[1:2]) + [
                    kw.value for kw in node.keywords
                    if kw.arg == "axis_names"
                ]:
                    if isinstance(arg, (ast.Tuple, ast.List)):
                        for el in arg.elts:
                            if isinstance(el, ast.Constant) and isinstance(
                                el.value, str
                            ):
                                out.add(el.value)
    return out


def _module_str_consts(sf: SourceFile) -> dict[str, str]:
    out: dict[str, str] = {}
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if (
                isinstance(t, ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                out[t.id] = node.value.value
    return out


def resolve_axis_operand(expr: ast.AST, consts: dict[str, str]):
    """The axis names a collective's axis operand denotes, as a list of
    strings — or None when unresolvable (a runtime `axes` parameter)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    if isinstance(expr, ast.Name) and expr.id in consts:
        return [consts[expr.id]]
    if isinstance(expr, (ast.Tuple, ast.List)):
        names = []
        for el in expr.elts:
            got = resolve_axis_operand(el, consts)
            if got is None:
                return None
            names.extend(got)
        return names
    return None


# ---- spec resolution (in_specs / out_specs -> spec-state trees) -----------


def _spec_of_p_call(call: ast.Call) -> str:
    """P() -> replicated spec; P(...) with any non-None axis -> sharded."""
    parts = list(call.args) + [kw.value for kw in call.keywords]
    for a in parts:
        if isinstance(a, ast.Starred):
            return SHD
        if not (isinstance(a, ast.Constant) and a.value is None):
            return SHD
    return REP


class SpecResolver:
    """Syntactic resolver for PartitionSpec expressions: direct `P(...)`
    calls, names bound in the enclosing function or module, tuple
    unpacking through a local helper call (`axes, node, rep, ... =
    _mesh_specs(...)`), and NamedTuple-style constructors — keyword
    fields and the `Cls(**{f: spec for f in Cls._fields})` uniform-tree
    idiom the engine's `_mesh_specs` uses."""

    def __init__(self, index: dataflow.ModuleIndex, sf: SourceFile):
        self.index = index
        self.sf = sf

    def resolve(self, expr: ast.AST, scope: ast.AST | None, depth: int = 0):
        # the engine's spec indirection (in_specs tuple -> name ->
        # unpack -> _mesh_specs return -> ctor -> dict-comp -> P())
        # is eight hops deep; the bound only guards pathological cycles
        if depth > 16 or expr is None:
            return UNK
        if isinstance(expr, ast.Call):
            fname = _tail(dotted_name(expr.func))
            if fname in ("P", "PartitionSpec"):
                return _spec_of_p_call(expr)
            # Cls(**{f: spec for f in Cls._fields}) -> uniform tree
            if len(expr.keywords) == 1 and expr.keywords[0].arg is None:
                v = expr.keywords[0].value
                if isinstance(v, ast.DictComp):
                    return self.resolve(v.value, scope, depth + 1)
            if expr.keywords and not expr.args:
                fields = []
                for kw in expr.keywords:
                    if kw.arg is None:
                        return UNK
                    fields.append(
                        (kw.arg, self.resolve(kw.value, scope, depth + 1))
                    )
                return ("F", tuple(sorted(fields)))
            # a call into a local helper returning a literal tuple
            ret = self._local_return(expr)
            if ret is not None:
                fn, retexpr = ret
                return self.resolve(retexpr, fn, depth + 1)
            return UNK
        if isinstance(expr, (ast.Tuple, ast.List)):
            return ("T",) + tuple(
                self.resolve(el, scope, depth + 1) for el in expr.elts
            )
        if isinstance(expr, ast.Name):
            return self._resolve_name(expr.id, scope, depth)
        return UNK

    def _local_return(self, call: ast.Call):
        fname = _tail(dotted_name(call.func))
        if not fname:
            return None
        cands = [
            fi for fi in self.index.by_name.get(fname, ())
            if fi.sf is self.sf and fi.cls is None
        ]
        if len(cands) != 1:
            return None
        rets = [
            n for n in dataflow.shallow_walk(cands[0].node)
            if isinstance(n, ast.Return) and n.value is not None
        ]
        if len(rets) != 1:
            return None
        return cands[0].node, rets[0].value

    def _resolve_name(self, name: str, scope: ast.AST | None, depth: int):
        scopes = [s for s in (scope, self.sf.tree) if s is not None]
        for sc in scopes:
            walker = (
                dataflow.shallow_walk(sc)
                if not isinstance(sc, ast.Module)
                else ast.walk(sc)
            )
            for node in walker:
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return self.resolve(node.value, scope, depth + 1)
                    if isinstance(t, ast.Tuple):
                        for i, el in enumerate(t.elts):
                            if isinstance(el, ast.Name) and el.id == name:
                                v = self.resolve(
                                    node.value, scope, depth + 1
                                )
                                if (
                                    not is_scalar(v)
                                    and v[0] == "T"
                                    and i < len(v) - 1
                                ):
                                    return v[1 + i]
                                return UNK
        return UNK


# ---- the abstract interpreter ---------------------------------------------


class Analyzer:
    """One lint run's SPMD interpreter: shared across regions so helper
    summaries memoize across shard_map call sites."""

    def __init__(self, ctx: Context, report):
        self.ctx = ctx
        self.index = dataflow.get_index(ctx)
        self.report = report            # (sf, lineno, message) sink
        self._memo: dict = {}
        self._stack: list = []
        # def node id -> AST node, registered at every fnval creation
        # site, per RUN (a class-level cache would pin every linted
        # module's subtrees for the process lifetime — the mutant
        # harness lints scratch modules every run)
        self._fnval_nodes: dict[int, object] = {}
        # per-file FuncInfo lists + enclosing-def memo: _eval_call asks
        # for the enclosing def once per Call; a repo-wide scan there
        # would be the interpreter's hot path
        self._file_funcs: dict[str, list] = {}
        self._enclosing_memo: dict = {}
        # set whenever a computation hits the depth/recursion cutoff:
        # such summaries depend on the call stack and are not memoized
        self._degraded = False

    def _fnval(self, name: str, node: ast.AST):
        """("FN", name, id) — a local function value; the def node is
        registered so application never re-walks the repo."""
        self._fnval_nodes[id(node)] = node
        return ("FN", name, id(node))

    # -- public entry --

    def analyze_region(self, sf: SourceFile, call: ast.Call) -> None:
        """One shard_map(body, ..., in_specs=..., out_specs=...) region:
        seed the body params from in_specs, run the body, diff the
        return states against out_specs."""
        body_fi = self._body_func(sf, call)
        if body_fi is None:
            return
        resolver = SpecResolver(self.index, sf)
        scope = self._enclosing_def(sf, call)
        in_specs = next(
            (kw.value for kw in call.keywords if kw.arg == "in_specs"), None
        )
        out_specs = next(
            (kw.value for kw in call.keywords if kw.arg == "out_specs"),
            None,
        )
        spec_tree = resolver.resolve(in_specs, scope)
        params = [
            a.arg
            for a in body_fi.node.args.posonlyargs + body_fi.node.args.args
        ]
        env: dict[str, object] = {}
        if not is_scalar(spec_tree) and spec_tree[0] == "T":
            leaves = list(spec_tree[1:])
        else:
            leaves = [spec_tree] * len(params)
        for p, s in zip(params, leaves + [UNK] * len(params)):
            env[p] = s
        rets = self._run_function(body_fi.node, env, sf, depth=0)
        want = resolver.resolve(out_specs, scope)
        for ret_node, state in rets:
            self._diff_out_spec(sf, ret_node, state, want)

    # -- out_specs diff --

    def _diff_out_spec(self, sf, ret_node, state, spec, field="") -> None:
        if spec == UNK or state == UNK:
            return
        if is_scalar(spec):
            if spec == REP and collapse(state) in (SHD, VAR):
                where = f" (field `{field}`)" if field else ""
                self.report(
                    sf, ret_node.lineno,
                    f"out_specs declares a replicated output{where} but the "
                    f"body's value is provably {collapse(state)} — establish "
                    "replication before returning (the sanctioned discharge "
                    "is the pmax-over-equal idiom: `x = jax.lax.pmax(x, "
                    "axes)` is the identity on equal values and makes "
                    "replication provable)",
                )
            return
        if is_scalar(state):
            # uniform value tree against a structured spec: check every
            # replicated spec leaf against the one state
            for leaf_field, leaf in self._spec_leaves(spec):
                self._diff_out_spec(sf, ret_node, state, leaf, leaf_field)
            return
        if spec[0] == "F" and state[0] == "F":
            ds, dv = dict(spec[1]), dict(state[1])
            for k in set(ds) & set(dv):
                self._diff_out_spec(sf, ret_node, dv[k], ds[k], k)
            return
        if spec[0] == "T" and state[0] == "T" and len(spec) == len(state):
            for i, (sp, st) in enumerate(zip(spec[1:], state[1:])):
                self._diff_out_spec(sf, ret_node, st, sp, field or str(i))
            return

    @staticmethod
    def _spec_leaves(spec, prefix=""):
        if is_scalar(spec):
            yield prefix, spec
            return
        if spec[0] == "F":
            for k, v in spec[1]:
                yield from Analyzer._spec_leaves(v, k)
        elif spec[0] == "T":
            for i, v in enumerate(spec[1:]):
                yield from Analyzer._spec_leaves(v, prefix or str(i))

    # -- region discovery helpers --

    def _body_func(self, sf: SourceFile, call: ast.Call):
        if not call.args:
            return None
        name = _tail(dotted_name(call.args[0]))
        if not name:
            return None
        cands = [
            fi
            for fi in self.index.by_name.get(name, ())
            if fi.sf is sf
        ]
        if len(cands) == 1:
            return cands[0]
        # several same-named defs (every factory names its body `body`):
        # the one the call references is the nearest PRECEDING def
        before = [
            fi for fi in cands
            if (fi.node.end_lineno or fi.node.lineno) < call.lineno
        ]
        if before:
            return max(before, key=lambda fi: fi.node.lineno)
        return None

    def _enclosing_def(self, sf: SourceFile, call: ast.Call):
        fi = self._enclosing_fi(sf, call)
        return fi.node if fi is not None else None

    # -- function execution --

    def _run_function(self, fn, env, sf, depth):
        """Execute a function body; returns [(return node, state)]."""
        rets: list = []
        self._exec_suite(fn.body, env, sf, depth, rets)
        return rets

    def _summary(self, fi, arg_states, depth, kw_states=None):
        """Return-state of a project function under positional
        `arg_states` and keyword `kw_states` ({name: state}), memoized;
        UNK on recursion or depth exhaustion — and a summary whose
        computation HIT either cutoff is not memoized at all (its value
        depends on the call stack it was computed under, and caching it
        would make findings flip with analysis order)."""
        kw_states = kw_states or {}
        key = (
            fi.qname,
            tuple(self._key_of(s) for s in arg_states),
            tuple(sorted(
                (k, self._key_of(v)) for k, v in kw_states.items()
            )),
        )
        if key in self._memo:
            return self._memo[key]
        if depth >= _MAX_DEPTH or fi.qname in self._stack:
            self._degraded = True
            return UNK
        self._stack.append(fi.qname)
        env = self._seed_params(fi.node, fi.cls, arg_states, kw_states)
        was_degraded, self._degraded = self._degraded, False
        rets = self._run_function(fi.node, env, fi.sf, depth + 1)
        self._stack.pop()
        out = join_all([s for _, s in rets]) if rets else REP
        if not self._degraded:
            self._memo[key] = out
        self._degraded = self._degraded or was_degraded
        return out

    @staticmethod
    def _seed_params(fn, cls, arg_states, kw_states):
        """Bind a call's argument states onto a def's parameters:
        positionals in order, keywords by name, and UNMATCHED params
        from their literal default when it is a constant — anything
        else degrades to UNK (never REP: a mis-seeded parameter on the
        replicated end of the lattice FIRES findings)."""
        params = list(fn.args.posonlyargs + fn.args.args)
        if cls is not None and params and params[0].arg == "self":
            params = params[1:]
        env = {p.arg: s for p, s in zip(params, arg_states)}
        defaults = dict(
            zip(
                [p.arg for p in params[len(params) - len(fn.args.defaults):]],
                fn.args.defaults,
            )
        )
        for a, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
            if d is not None:
                defaults[a.arg] = d
        declared = {p.arg for p in params + list(fn.args.kwonlyargs)}
        leftover_kw = []
        for name, state in kw_states.items():
            if name in declared:
                env[name] = state
            else:
                leftover_kw.append(state)
        for p in params + list(fn.args.kwonlyargs):
            if p.arg not in env:
                d = defaults.get(p.arg)
                env[p.arg] = (
                    REP if isinstance(d, ast.Constant) else UNK
                )
        # *args / **kwargs catch-alls: the join of whatever spilled
        # past the declared parameters (a sharded value passed through
        # *vals must not fall to the replicated Name fallback)
        spill = list(arg_states[len(params):])
        if fn.args.vararg:
            env[fn.args.vararg.arg] = (
                collapse(join_all(spill)) if spill else REP
            )
        if fn.args.kwarg:
            env[fn.args.kwarg.arg] = (
                collapse(join_all(leftover_kw)) if leftover_kw else REP
            )
        return env

    @staticmethod
    def _key_of(s):
        if is_scalar(s):
            return s
        if s[0] == "FN":
            return ("FN", s[1])
        if s[0] == "T":
            return ("T",) + tuple(Analyzer._key_of(x) for x in s[1:])
        return ("F", tuple((k, Analyzer._key_of(v)) for k, v in s[1]))

    # -- statements --

    def _exec_suite(self, stmts, env, sf, depth, rets):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                env[st.name] = self._fnval(st.name, st)
                continue
            if isinstance(st, ast.Return):
                state = (
                    self._eval(st.value, env, sf, depth)
                    if st.value is not None
                    else REP
                )
                rets.append((st, state))
                continue
            if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                self._exec_assign(st, env, sf, depth)
                continue
            if isinstance(st, ast.If):
                self._eval(st.test, env, sf, depth)
                e1, e2 = dict(env), dict(env)
                self._exec_suite(st.body, e1, sf, depth, rets)
                self._exec_suite(st.orelse, e2, sf, depth, rets)
                env.clear()
                env.update(self._join_envs(e1, e2))
                continue
            if isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                # two passes to a (cheap) fixpoint over the loop carry
                if isinstance(st, ast.For):
                    it = self._eval(st.iter, env, sf, depth)
                    self._bind_target(st.target, it, env)
                else:
                    self._eval(st.test, env, sf, depth)
                for _ in range(2):
                    before = dict(env)
                    self._exec_suite(st.body, env, sf, depth, rets)
                    env.update(self._join_envs(before, env))
                self._exec_suite(st.orelse, env, sf, depth, rets)
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    self._eval(item.context_expr, env, sf, depth)
                self._exec_suite(st.body, env, sf, depth, rets)
                continue
            if isinstance(st, ast.Try):
                self._exec_suite(st.body, env, sf, depth, rets)
                for h in st.handlers:
                    self._exec_suite(h.body, dict(env), sf, depth, rets)
                self._exec_suite(st.orelse, env, sf, depth, rets)
                self._exec_suite(st.finalbody, env, sf, depth, rets)
                continue
            if isinstance(st, ast.Expr):
                self._eval(st.value, env, sf, depth)
                continue
            if isinstance(st, ast.Raise):
                continue
            # anything else (Pass, Assert, imports, ...): evaluate child
            # expressions for their collective-call side effects
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._eval(child, env, sf, depth)

    @staticmethod
    def _join_envs(e1, e2):
        out = {}
        for k in set(e1) | set(e2):
            if k in e1 and k in e2:
                out[k] = join(e1[k], e2[k])
            else:
                out[k] = e1.get(k, e2.get(k))
        return out

    def _exec_assign(self, st, env, sf, depth):
        value = st.value
        if value is None:
            return
        state = self._eval(value, env, sf, depth)
        if isinstance(st, ast.AugAssign):
            if isinstance(st.target, ast.Name):
                old = env.get(st.target.id, REP)
                env[st.target.id] = join(old, state)
            return
        targets = st.targets if isinstance(st, ast.Assign) else [st.target]
        for t in targets:
            self._bind_target(t, state, env)

    def _bind_target(self, target, state, env):
        if isinstance(target, ast.Name):
            env[target.id] = state
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            if not is_scalar(state) and state[0] == "T" and len(
                state
            ) - 1 == len(elts):
                for el, s in zip(elts, state[1:]):
                    self._bind_target(el, s, env)
            else:
                flat = collapse(state)
                for el in elts:
                    self._bind_target(el, flat, env)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, collapse(state), env)
        # attribute/subscript stores: no tracked base mutation

    # -- expressions --

    def _eval(self, node, env, sf, depth):
        if node is None:
            return REP
        if isinstance(node, ast.Constant):
            return REP
        if isinstance(node, ast.Name):
            if node.id in env:
                return env[node.id]
            # local defs visible before flow reaches them (rare) and
            # module-level names: host config -> replicated
            cands = [
                fi for fi in self.index.by_name.get(node.id, ())
                if fi.sf is sf
            ]
            if len(cands) == 1:
                return self._fnval(node.id, cands[0].node)
            return REP
        if isinstance(node, ast.Attribute):
            if node.attr in dataflow._STATIC_META_ATTRS:
                return REP  # shapes/dtypes are trace-time host metadata
            base = self._eval(node.value, env, sf, depth)
            if not is_scalar(base) and base[0] == "F":
                d = dict(base[1])
                if node.attr in d:
                    return d[node.attr]
                return collapse(base)
            return collapse(base)
        if isinstance(node, ast.Subscript):
            base = self._eval(node.value, env, sf, depth)
            idx = self._eval(node.slice, env, sf, depth)
            return join(collapse(base), collapse(idx))
        if isinstance(node, (ast.Tuple, ast.List)):
            return ("T",) + tuple(
                self._eval(el, env, sf, depth) for el in node.elts
            )
        if isinstance(node, ast.Dict):
            return join_all(
                [
                    self._eval(v, env, sf, depth)
                    for v in list(node.keys) + list(node.values)
                    if v is not None
                ]
            )
        if isinstance(node, ast.BinOp):
            return join(
                collapse(self._eval(node.left, env, sf, depth)),
                collapse(self._eval(node.right, env, sf, depth)),
            )
        if isinstance(node, ast.BoolOp):
            return join_all(
                [collapse(self._eval(v, env, sf, depth)) for v in node.values]
            )
        if isinstance(node, ast.UnaryOp):
            return collapse(self._eval(node.operand, env, sf, depth))
        if isinstance(node, ast.Compare):
            return join_all(
                [collapse(self._eval(node.left, env, sf, depth))]
                + [
                    collapse(self._eval(c, env, sf, depth))
                    for c in node.comparators
                ]
            )
        if isinstance(node, ast.IfExp):
            self._eval(node.test, env, sf, depth)
            return join(
                self._eval(node.body, env, sf, depth),
                self._eval(node.orelse, env, sf, depth),
            )
        if isinstance(node, ast.Call):
            return self._eval_call(node, env, sf, depth)
        if isinstance(node, ast.NamedExpr):
            # walrus: bind the target so the later Name lookup sees the
            # real state instead of the replicated-config fallback
            state = self._eval(node.value, env, sf, depth)
            if isinstance(node.target, ast.Name):
                env[node.target.id] = state
            return state
        if isinstance(node, ast.Starred):
            return self._eval(node.value, env, sf, depth)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            states = [
                self._eval(g.iter, env, sf, depth) for g in node.generators
            ]
            return join_all([collapse(s) for s in states] + [REP])
        if isinstance(node, ast.Lambda):
            return self._fnval("<lambda>", node)
        if isinstance(node, ast.Slice):
            return join_all(
                [
                    collapse(self._eval(p, env, sf, depth))
                    for p in (node.lower, node.upper, node.step)
                    if p is not None
                ]
            )
        if isinstance(node, ast.JoinedStr):
            return REP
        return UNK

    def _eval_call(self, call: ast.Call, env, sf, depth):
        fname = dotted_name(call.func)
        tail = _tail(fname) or (
            call.func.attr if isinstance(call.func, ast.Attribute) else None
        )
        arg_states = [self._eval(a, env, sf, depth) for a in call.args]
        kw_states = [
            self._eval(kw.value, env, sf, depth) for kw in call.keywords
        ]

        # control-flow special forms first
        if tail == "scan":
            return self._eval_scan(call, env, sf, depth, arg_states)
        if tail == "while_loop":
            return self._eval_while_loop(call, env, sf, depth, arg_states)
        if tail == "fori_loop":
            return self._eval_fori_loop(call, env, sf, depth, arg_states)
        if tail == "cond" and fname and "lax" in fname:
            # lax.cond(pred, true_fn, false_fn, *operands): operand
            # states start AFTER the predicate and the branch functions
            branches = [
                a for a in call.args if self._as_fnval(a, env, sf)
            ]
            operands = arg_states[1 + len(branches):]
            states = [
                self._apply_fnval(
                    self._as_fnval(b, env, sf), operands, env, sf, depth,
                )
                for b in branches
            ]
            return join_all(states) if states else UNK

        if tail in COLLECTIVES:
            self._check_collective(call, tail, arg_states, env, sf)
            if tail in _REPLICATING:
                return REP
            if tail == "axis_index":
                return VAR
            if tail == "psum_scatter":
                # each shard receives a distinct reduced chunk: at
                # LEAST sharded, whatever the operand was
                return join(SHD, collapse(join_all(arg_states)))
            return collapse(join_all(arg_states + kw_states))
        if tail in _VARYING_SOURCES:
            return VAR
        if tail in _SHAPE_ONLY:
            return join_all([REP] + kw_states)

        # project-local resolution through the shared index; keyword
        # arguments bind BY NAME onto the callee's parameters (a
        # sharded value passed by keyword must not fall through to the
        # unmatched-parameter default)
        fi_caller = self._enclosing_fi(sf, call)
        cands = (
            self.index.resolve_call(fi_caller, call, loose=False)
            if fi_caller is not None
            else []
        )
        named_kw = {
            kw.arg: s
            for kw, s in zip(call.keywords, kw_states)
            if kw.arg is not None
        }
        splat_kw = [
            s
            for kw, s in zip(call.keywords, kw_states)
            if kw.arg is None
        ]
        if cands:
            summaries = [
                self._summary(fi, arg_states, depth, named_kw)
                for fi in cands
            ]
            # a **spread cannot be mapped onto parameters: join its
            # states in (the old conservative treatment)
            return join_all(
                summaries + [collapse(s) for s in splat_kw]
            )
        # a direct call of a local function value (nested def)
        fn = self._as_fnval(call.func, env, sf)
        if fn is not None:
            return self._apply_fnval(
                fn, arg_states, env, sf, depth, named_kw
            )

        # NamedTuple-style ctor: a BARE NAME called with keywords only
        # -> record state (an Attribute callee is a method — `x.sum(
        # axis=1)` — whose state is its receiver's, never a ctor)
        if (
            isinstance(call.func, ast.Name)
            and call.keywords
            and not call.args
            and all(kw.arg is not None for kw in call.keywords)
        ):
            return (
                "F",
                tuple(
                    sorted(
                        (kw.arg, s)
                        for kw, s in zip(call.keywords, kw_states)
                    )
                ),
            )
        # method calls on tracked values (x.sum(), snap._replace(...)):
        # join receiver and arguments; everything else is a pure
        # function of its operands under deterministic SPMD execution
        recv = []
        if isinstance(call.func, ast.Attribute):
            recv = [self._eval(call.func.value, env, sf, depth)]
        return collapse(join_all(recv + arg_states + kw_states))

    def _enclosing_fi(self, sf, node):
        key = (sf.path, node.lineno)
        if key in self._enclosing_memo:
            return self._enclosing_memo[key]
        funcs = self._file_funcs.get(sf.path)
        if funcs is None:
            funcs = [
                fi for fi in self.index.funcs.values() if fi.sf is sf
            ]
            self._file_funcs[sf.path] = funcs
        best = None
        for fi in funcs:
            n = fi.node
            if n.lineno <= node.lineno <= (n.end_lineno or n.lineno):
                if best is None or n.lineno > best.node.lineno:
                    best = fi
        self._enclosing_memo[key] = best
        return best

    def _as_fnval(self, expr, env, sf):
        if isinstance(expr, ast.Name):
            v = env.get(expr.id)
            if isinstance(v, tuple) and v and v[0] == "FN":
                return v
            cands = [
                fi for fi in self.index.by_name.get(expr.id, ())
                if fi.sf is sf
            ]
            if len(cands) == 1:
                return self._fnval(expr.id, cands[0].node)
        if isinstance(expr, tuple) and expr and expr[0] == "FN":
            return expr
        return None

    def _apply_fnval(self, fn, arg_states, env, sf, depth, kw_states=None):
        node = self._node_of_fnval(fn)
        if node is None or depth >= _MAX_DEPTH:
            self._degraded = True
            return UNK
        if isinstance(node, ast.Lambda):
            params = [
                a.arg for a in node.args.posonlyargs + node.args.args
            ]
            inner = dict(env)
            inner.update(dict(zip(params, arg_states)))
            return self._eval(node.body, inner, sf, depth + 1)
        inner = dict(env)  # closure environment; params shadow it
        inner.update(
            self._seed_params(node, None, arg_states, kw_states or {})
        )
        rets = self._run_function(node, inner, sf, depth + 1)
        return join_all([s for _, s in rets]) if rets else REP

    def _node_of_fnval(self, fn):
        # every fnval is minted by self._fnval, which registered the
        # def node — O(1), no repo walk
        return self._fnval_nodes.get(fn[2])

    # -- lax control-flow forms --

    def _eval_scan(self, call, env, sf, depth, arg_states):
        fn = self._as_fnval(call.args[0], env, sf) if call.args else None
        init = arg_states[1] if len(arg_states) > 1 else REP
        for kw in call.keywords:
            if kw.arg == "init":
                init = self._eval(kw.value, env, sf, depth)
        xs = arg_states[2] if len(arg_states) > 2 else REP
        for kw in call.keywords:
            if kw.arg == "xs":
                xs = self._eval(kw.value, env, sf, depth)
        if fn is None:
            return ("T", collapse(join(init, xs)), UNK)
        carry = init
        ys = REP
        for _ in range(3):  # carry fixpoint on a 4-point lattice
            out = self._apply_fnval(
                fn, [carry, collapse(xs)], env, sf, depth
            )
            if not is_scalar(out) and out[0] == "T" and len(out) == 3:
                new_carry, ys = out[1], out[2]
            else:
                new_carry, ys = collapse(out), collapse(out)
            joined = join(carry, new_carry)
            if joined == carry:
                break
            carry = joined
        return ("T", carry, ys)

    def _eval_while_loop(self, call, env, sf, depth, arg_states):
        cond = (
            self._as_fnval(call.args[0], env, sf) if call.args else None
        )
        body = (
            self._as_fnval(call.args[1], env, sf)
            if len(call.args) > 1
            else None
        )
        carry = arg_states[2] if len(arg_states) > 2 else REP
        if body is None:
            return collapse(carry) if is_scalar(carry) else carry
        for _ in range(3):
            if cond is not None:
                # the cond body runs every round too: collectives
                # inside it must pass the same checks (its boolean
                # result does not feed the carry)
                self._apply_fnval(cond, [carry], env, sf, depth)
            out = self._apply_fnval(body, [carry], env, sf, depth)
            joined = join(carry, out)
            if joined == carry:
                break
            carry = joined
        return carry

    def _eval_fori_loop(self, call, env, sf, depth, arg_states):
        body = (
            self._as_fnval(call.args[2], env, sf)
            if len(call.args) > 2
            else None
        )
        carry = arg_states[3] if len(arg_states) > 3 else REP
        if body is None:
            return collapse(carry) if is_scalar(carry) else carry
        for _ in range(3):
            out = self._apply_fnval(body, [REP, carry], env, sf, depth)
            joined = join(carry, out)
            if joined == carry:
                break
            carry = joined
        return carry

    # -- the collective checks (replication-dependent half) --

    def _check_collective(self, call, tail, arg_states, env, sf) -> None:
        if tail == "psum" and call.args:
            operand = call.args[0]
            is_literal = isinstance(operand, ast.Constant)
            if not is_literal and collapse(arg_states[0]) == REP:
                self.report(
                    sf, call.lineno,
                    "psum of a provably-replicated operand double-counts "
                    "by the axis size — every shard contributes the same "
                    "value; reduce one shard's contribution, use the value "
                    "directly, or multiply by axis size explicitly "
                    "(`psum(1, axes)` over a literal is the sanctioned "
                    "device-count idiom)",
                )
        if tail == "all_gather":
            if call.args and collapse(arg_states[0]) == REP:
                self.report(
                    sf, call.lineno,
                    "all_gather of a provably-replicated operand stacks D "
                    "identical copies for one collective launch — use the "
                    "value directly (every shard already holds it)",
                )
            for kw in call.keywords:
                if kw.arg == "axis" and (
                    (
                        isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)
                    )
                    or (
                        isinstance(kw.value, ast.Name)
                        and kw.value.id
                        in _module_str_consts(sf)
                    )
                ):
                    self.report(
                        sf, kw.value.lineno,
                        "all_gather's `axis=` is the insertion POSITION "
                        "(an int); the mesh axis name goes in the second "
                        "positional (`axis_name`) — a string here always "
                        "misindexes the gathered dimension",
                    )


# ---- context-free axis-name check -----------------------------------------


def check_axis_names(
    files: list[SourceFile],
    declared: set[str],
    report,
    index: dataflow.ModuleIndex,
) -> None:
    """Every collective whose axis operand RESOLVES to string names must
    use names some mesh declares — the wrong-axis class. Runtime axis
    parameters (`axes` threaded through the engine) are skipped, not
    guessed."""
    if not declared:
        return
    for sf in files:
        consts = _module_str_consts(sf)
        for node in index.walk(sf):
            if not isinstance(node, ast.Call):
                continue
            tail = _tail(dotted_name(node.func))
            if tail not in COLLECTIVES:
                continue
            pos = COLLECTIVES[tail]
            axis_expr = None
            if len(node.args) > pos:
                axis_expr = node.args[pos]
            else:
                for kw in node.keywords:
                    if kw.arg == "axis_name":
                        axis_expr = kw.value
            if axis_expr is None:
                continue
            names = resolve_axis_operand(axis_expr, consts)
            if names is None:
                continue
            for name in names:
                if name not in declared:
                    report(
                        sf, axis_expr.lineno,
                        f"collective `{tail}` uses axis name {name!r}, "
                        "which no mesh in the linted set declares "
                        f"(declared: {sorted(declared)}) — an unbound "
                        "axis deadlocks or miscounts on hardware",
                    )


# ---- rule entry -----------------------------------------------------------


def _shard_map_calls(index, sf: SourceFile):
    for node in index.walk(sf):
        if not isinstance(node, ast.Call):
            continue
        tail = _tail(dotted_name(node.func))
        if tail in ("shard_map", "_shard_map"):
            kws = {kw.arg for kw in node.keywords}
            if "in_specs" in kws and "out_specs" in kws:
                yield node


def check_files(ctx: Context, scoped: list[SourceFile]) -> list[Violation]:
    """The spmd-collective family over `scoped` (dedup across the many
    analysis paths that can reach one call site)."""
    seen: set = set()
    out: list[Violation] = []

    def report(sf, lineno, message):
        key = (sf.path, lineno, message)
        if key in seen:
            return
        seen.add(key)
        out.append(Violation(RULE, sf.path, lineno, message))

    analyzer = Analyzer(ctx, report)
    for sf in scoped:
        for call in _shard_map_calls(analyzer.index, sf):
            analyzer.analyze_region(sf, call)
    check_axis_names(
        scoped,
        declared_axis_names(ctx.files, analyzer.index),
        report,
        analyzer.index,
    )
    out.sort(key=lambda v: (v.path, v.line))
    return out
