"""SARIF 2.1.0 output for graftlint (`--format sarif` / `make
lint-sarif`).

SARIF is the exchange format CI code-scanning UIs ingest (GitHub code
scanning among them), which makes lint findings diffable artifacts
instead of grepped logs. `render_sarif` emits the minimal conforming
document: one run, the registered rule families (plus the runner's
pseudo-rules) as `tool.driver.rules`, every finding as a `result` with
a physical location; waived findings ship with `suppressions` so the
reviewable allow-list survives into the artifact.

`validate_sarif` structurally checks a document against the SARIF 2.1.0
schema's required surface (the image has no network for the real JSON
schema; the checks below mirror its required properties and enum
values for the subset we emit). `make lint-sarif` and
tests/test_bench_smoke.py run it over the fresh artifact.
"""

from __future__ import annotations

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)

_LEVELS = {"none", "note", "warning", "error"}


def render_sarif(violations, rule_docs: dict[str, str]) -> dict:
    """One-run SARIF document. `rule_docs` maps rule id -> one-line
    description (the registry's module docstring headlines); findings
    referencing pseudo-rules (bad-waiver, docs-drift, engine-contract,
    parse, *-baseline) are added to the driver rules on the fly so every
    result's ruleId resolves."""
    ids = dict(rule_docs)
    for v in violations:
        ids.setdefault(v.rule, "graftlint runner check")
    rules = [
        {
            "id": rid,
            "shortDescription": {"text": ids[rid]},
        }
        for rid in sorted(ids)
    ]
    index = {r["id"]: i for i, r in enumerate(rules)}
    results = []
    for v in violations:
        res = {
            "ruleId": v.rule,
            "ruleIndex": index[v.rule],
            "level": "warning" if v.waived else "error",
            "message": {"text": v.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": v.path},
                        "region": {"startLine": max(1, int(v.line))},
                    }
                }
            ],
        }
        if v.waived:
            res["suppressions"] = [
                {
                    "kind": "inSource",
                    "justification": v.waiver_reason or "",
                }
            ]
        results.append(res)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "informationUri": (
                            "kubernetes_scheduler_tpu/analysis/"
                        ),
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }


def validate_sarif(doc) -> None:
    """Raise ValueError on any departure from the SARIF 2.1.0 required
    surface (for the subset graftlint emits)."""

    def need(cond, msg):
        if not cond:
            raise ValueError(f"SARIF: {msg}")

    need(isinstance(doc, dict), "document must be an object")
    need(doc.get("version") == SARIF_VERSION,
         f"version must be '{SARIF_VERSION}'")
    need("sarif-schema-2.1.0" in str(doc.get("$schema", "")),
         "$schema must reference the 2.1.0 schema")
    runs = doc.get("runs")
    need(isinstance(runs, list) and runs, "runs must be a non-empty array")
    for run in runs:
        driver = (run.get("tool") or {}).get("driver")
        need(isinstance(driver, dict), "runs[].tool.driver required")
        need(
            isinstance(driver.get("name"), str) and driver["name"],
            "tool.driver.name must be a non-empty string",
        )
        rules = driver.get("rules", [])
        need(isinstance(rules, list), "driver.rules must be an array")
        rule_ids = set()
        for r in rules:
            need(isinstance(r.get("id"), str) and r["id"],
                 "rule.id must be a non-empty string")
            need(
                isinstance(
                    (r.get("shortDescription") or {}).get("text"), str
                ),
                f"rule {r.get('id')}: shortDescription.text required",
            )
            rule_ids.add(r["id"])
        results = run.get("results")
        need(isinstance(results, list), "run.results must be an array")
        for res in results:
            rid = res.get("ruleId")
            need(isinstance(rid, str) and rid, "result.ruleId required")
            need(rid in rule_ids,
                 f"result.ruleId `{rid}` not in driver.rules")
            need(res.get("level") in _LEVELS,
                 f"result.level must be one of {sorted(_LEVELS)}")
            need(
                isinstance((res.get("message") or {}).get("text"), str),
                "result.message.text required",
            )
            for loc in res.get("locations", ()):
                phys = loc.get("physicalLocation") or {}
                uri = (phys.get("artifactLocation") or {}).get("uri")
                need(isinstance(uri, str) and uri,
                     "physicalLocation.artifactLocation.uri required")
                start = (phys.get("region") or {}).get("startLine")
                need(isinstance(start, int) and start >= 1,
                     "region.startLine must be a positive integer")
