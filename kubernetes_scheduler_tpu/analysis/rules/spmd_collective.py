"""spmd-collective: SPMD replication/collective discipline inside
shard_map bodies — psum double-counts, unbound axis names, redundant
gathers of replicated values, and out_specs declaring replication the
body never establishes.

Thin registry shim: the replication-lattice abstract interpreter that
powers the family lives in analysis/spmd.py (it rides the shared
parse-once ModuleIndex the way the donation/lockset families ride the
dataflow core). Scope is the shard_map surface — parallel/ — plus, in
fixture mode, whatever files the caller passed."""

from __future__ import annotations

from kubernetes_scheduler_tpu.analysis import spmd
from kubernetes_scheduler_tpu.analysis.core import Context, Violation

RULE = spmd.RULE

SCOPE = ("kubernetes_scheduler_tpu/parallel/*.py",)


def check(ctx: Context) -> list[Violation]:
    return spmd.check_files(ctx, ctx.scoped(SCOPE))
