"""lockset-race: every mutation of a lock-guarded attribute must hold a
CONSISTENT lockset — computed through the class's call graph, not per
method body.

The per-file `lock-discipline` family sees only lexical `with
self._lock:` blocks, so a private helper that mutates guarded state with
the lock held BY ITS CALLER needs a hand-written waiver asserting the
call-site discipline. This family promotes that assertion into the
analysis: per class, every `with self.<lock>:` context is threaded
through intra-class `self.m(...)` calls to a fixpoint of ENTRY locksets
(analysis/dataflow.py `method_entry_locksets`):

- public methods are entries with the empty lockset — the scheduling
  loop, the bridge's gRPC worker threads, and the /metrics scrape can
  all call them lock-free, which is exactly the cross-thread shape the
  pipelined driver's completion stage vs. the exporter's reader takes;
- a private helper inherits the locksets of its intra-class call sites,
  so `_flush` called only under `self._lock` mutates guarded state
  SAFELY — no waiver needed, the call graph proves it;
- a mutation site's effective locksets are its entry contexts unioned
  with the locks lexically held at the site.

A violation is an attribute with one mutation site always guarded by
some lock and another site reachable (through the call graph) holding
NO common lock — the torn-write window between the driver thread and a
bridge/exporter thread. The seeded targets this family exists for:
`engine.ResidentState`'s retained snapshot swap, the bridge server's
session maps (`_field_cache`), and the host scheduler's metrics state
shared with the exporter thread.

`__init__` stays exempt (construction happens-before publication).
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis.core import Context, Violation
from kubernetes_scheduler_tpu.analysis import dataflow

RULE = "lockset-race"

SCOPE = ("kubernetes_scheduler_tpu/**/*.py", "kubernetes_scheduler_tpu/*.py")


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    index = dataflow.get_index(ctx)
    for sf in ctx.scoped(SCOPE):
        for node in index.walk(sf):
            if isinstance(node, ast.ClassDef):
                _check_class(sf, node, out)
    return out


def _check_class(sf, cls: ast.ClassDef, out: list[Violation]) -> None:
    facts = dataflow.class_lock_facts(cls)
    if not facts.locks:
        return
    contexts = dataflow.method_entry_locksets(facts)
    # attr -> [(method, line, set of effective locksets)]
    sites: dict[str, list] = {}
    for method, muts in facts.mutations.items():
        if method == "__init__":
            continue
        entry = contexts.get(method, {frozenset()})
        if not entry:
            # a private helper whose only intra-class callers are
            # __init__ (or a helper chain rooted there) has an EMPTY
            # context set: it is unreachable after publication, so its
            # mutations inherit __init__'s happens-before exemption
            continue
        for attr, line, held in muts:
            if attr in facts.locks:
                continue
            effective = {frozenset(c | held) for c in entry}
            sites.setdefault(attr, []).append((method, line, effective))
    for attr, slist in sorted(sites.items()):
        # locks held on EVERY path into each site
        guards = [
            (method, line, frozenset.intersection(*eff) if eff else frozenset())
            for method, line, eff in slist
        ]
        always_guarded = [g for g in guards if g[2]]
        if not always_guarded:
            continue  # never guarded anywhere: not a lockset claim
        # the lock(s) the guarded sites agree on
        common = frozenset.intersection(*[g[2] for g in always_guarded])
        all_guards = sorted(set().union(*[g[2] for g in always_guarded]))
        for method, line, locks in guards:
            if common and common & locks:
                continue
            if locks:
                # the site DOES hold a lock — just not one every other
                # guarded site agrees on (two locks "guarding" one attr
                # guard nothing): say that, not "no lock"
                msg = (
                    f"{cls.name}.{method} mutates `self.{attr}` under an "
                    f"inconsistent lockset (`{', '.join(sorted(locks))}` "
                    f"here vs `{', '.join(all_guards)}` elsewhere in this "
                    "class — no common lock serializes the writes)"
                )
            else:
                guard_names = ", ".join(sorted(common)) or ", ".join(
                    all_guards
                )
                msg = (
                    f"{cls.name}.{method} mutates `self.{attr}` on a path "
                    f"holding no common lock, but `{guard_names}` guards "
                    "it elsewhere in this class (reachable lock-free "
                    "through the class's call graph)"
                )
            out.append(Violation(RULE, sf.path, line, msg))
