"""Rule registry: name -> check(ctx) -> list[Violation]."""

from kubernetes_scheduler_tpu.analysis.rules import (
    dtype_shape,
    host_sync,
    jit_purity,
    lock_discipline,
    metric_hygiene,
    pallas_vmem,
    sim_determinism,
    span_hygiene,
    timeout_hygiene,
    wire_schema,
)

RULES = {
    jit_purity.RULE: jit_purity.check,
    host_sync.RULE: host_sync.check,
    lock_discipline.RULE: lock_discipline.check,
    wire_schema.RULE: wire_schema.check,
    dtype_shape.RULE: dtype_shape.check,
    timeout_hygiene.RULE: timeout_hygiene.check,
    pallas_vmem.RULE: pallas_vmem.check,
    metric_hygiene.RULE: metric_hygiene.check,
    sim_determinism.RULE: sim_determinism.check,
    span_hygiene.RULE: span_hygiene.check,
}
