"""Rule registry: name -> check(ctx) -> list[Violation].

Eighteen families. The first ten are the per-file era; donation-
aliasing, host-transfer, tracer-leak, and lockset-race ride the
interprocedural dataflow core (analysis/dataflow.py) — call-graph,
def-use, and lockset analyses a single-file AST scan cannot express —
capability-completeness pins the bridge's HealthReply capability
wiring (latch/switch tables, probe/invalidate discipline, RPC
except-paths) against the .proto, the static twin of the
analysis/model/ protocol checker, and spmd-collective runs the
replication-lattice abstract interpreter (analysis/spmd.py) over the
mesh-sharded engine's shard_map bodies — double-counting psums,
unbound axis names, redundant gathers, out_specs replication drift.
thread-race and determinism-taint ride the declared thread model
(analysis/threads.py): cross-thread access pairs with no common
lockset and no happens-before edge, check-then-act atomicity, and
wall-clock/set-order/id-order taint flowing into replay-pinned journal
and engine operands.
The README's Static analysis table must name exactly this registry
(checked both ways by the `docs-drift` runner check).
"""

from kubernetes_scheduler_tpu.analysis.rules import (
    capability_completeness,
    determinism_taint,
    donation_aliasing,
    dtype_shape,
    host_sync,
    host_transfer,
    jit_purity,
    lock_discipline,
    lockset_race,
    metric_hygiene,
    pallas_vmem,
    sim_determinism,
    span_hygiene,
    spmd_collective,
    thread_race,
    timeout_hygiene,
    tracer_leak,
    wire_schema,
)

RULES = {
    jit_purity.RULE: jit_purity.check,
    host_sync.RULE: host_sync.check,
    lock_discipline.RULE: lock_discipline.check,
    wire_schema.RULE: wire_schema.check,
    dtype_shape.RULE: dtype_shape.check,
    timeout_hygiene.RULE: timeout_hygiene.check,
    pallas_vmem.RULE: pallas_vmem.check,
    metric_hygiene.RULE: metric_hygiene.check,
    sim_determinism.RULE: sim_determinism.check,
    span_hygiene.RULE: span_hygiene.check,
    donation_aliasing.RULE: donation_aliasing.check,
    host_transfer.RULE: host_transfer.check,
    tracer_leak.RULE: tracer_leak.check,
    lockset_race.RULE: lockset_race.check,
    capability_completeness.RULE: capability_completeness.check,
    spmd_collective.RULE: spmd_collective.check,
    thread_race.RULE: thread_race.check,
    determinism_taint.RULE: determinism_taint.check,
}
