"""timeout-hygiene: external calls carry explicit timeout policies.

The host loop's degradation story (ADVICE/SURVEY: advisor outage
requeues the window, sidecar outage flips one cycle to scalar) only
works if nothing in the cycle path can block forever. Flagged across the
whole package:

- `urllib.request.urlopen(...)` without a `timeout=`;
- `subprocess.run/call/check_call/check_output/Popen.communicate(...)`
  without a `timeout=`;
- zero-argument `.wait()` — a threading.Event / grpc event wait with no
  timeout blocks a thread unboundedly on a peer that may never signal
  (`wait_for_termination` serve loops are intentionally unbounded and
  not flagged);
- zero-argument `.join()` on thread-like receivers (name contains
  "thread") — joining a wedged worker hangs shutdown.
- broad exception SWALLOWS on boundary calls: a `try` whose body makes
  an external call (a `timeout=`-bearing call, urlopen, subprocess)
  guarded by a bare `except:` / `except Exception:` handler that
  neither re-raises, nor counts a metric (`.inc`/`.observe`/a counter
  `+=`), nor feeds the circuit breaker
  (`record_failure`/`record_success`, host/resilience.py). A silent
  swallow at a boundary is how an outage stays invisible: the call
  keeps timing out, nothing trips the breaker, no dashboard moves —
  the `RemoteEngine.healthy()` class of bug. Handlers that account
  for the failure (or narrow catches like `grpc.RpcError` routed into
  classification) pass.
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis import dataflow
from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    Violation,
    dotted_name,
    has_kwarg,
)

RULE = "timeout-hygiene"

SCOPE = ("kubernetes_scheduler_tpu/**/*.py", "kubernetes_scheduler_tpu/*.py")

_SUBPROCESS = {
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
}

# handler calls that COUNT as accounting for a boundary failure: metric
# emission and circuit-breaker feeds (host/resilience.CircuitBreaker)
_ACCOUNTING_CALLS = {"inc", "observe", "record_failure", "record_success"}


def _is_boundary_call(node: ast.Call) -> bool:
    """An external call: carries an explicit timeout= (the family's own
    discipline marks boundaries that way), or is one of the known
    boundary callables."""
    if has_kwarg(node, "timeout"):
        return True
    name = dotted_name(node.func) or ""
    return name in ("urllib.request.urlopen", "urlopen") or name in _SUBPROCESS


def _broad_handler(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True
    names = list(t.elts) if isinstance(t, ast.Tuple) else [t]
    return any(
        isinstance(n, ast.Name) and n.id in ("Exception", "BaseException")
        for n in names
    )


def _handler_accounts(h: ast.ExceptHandler) -> bool:
    """Does the handler re-raise, count a metric, or feed the
    breaker? An augmented add on an attribute (self.failures += 1)
    counts as a metric bump."""
    for n in ast.walk(h):
        if isinstance(n, ast.Raise):
            return True
        if isinstance(n, ast.Call):
            callee = (
                n.func.attr
                if isinstance(n.func, ast.Attribute)
                else (n.func.id if isinstance(n.func, ast.Name) else None)
            )
            if callee in _ACCOUNTING_CALLS:
                return True
        if (
            isinstance(n, ast.AugAssign)
            and isinstance(n.op, ast.Add)
            and isinstance(n.target, ast.Attribute)
        ):
            # an ATTRIBUTE bump (self.failures += 1) is a counter
            # someone can read; a local `attempts += 1` is loop
            # bookkeeping, not accounting
            return True
    return False


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    for sf in ctx.scoped(SCOPE):
        for node in dataflow.get_index(ctx).walk(sf):
            if isinstance(node, ast.Try):
                if not any(
                    isinstance(sub, ast.Call) and _is_boundary_call(sub)
                    for stmt in node.body
                    for sub in ast.walk(stmt)
                ):
                    continue
                for h in node.handlers:
                    if _broad_handler(h) and not _handler_accounts(h):
                        out.append(
                            Violation(
                                RULE, sf.path, h.lineno,
                                "broad except swallows a boundary-call "
                                "failure without counting a metric or "
                                "feeding the breaker — the outage stays "
                                "invisible (count it, feed "
                                "record_failure, or re-raise)",
                            )
                        )
                continue
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            attr = (
                node.func.attr
                if isinstance(node.func, ast.Attribute)
                else None
            )
            if (
                name in ("urllib.request.urlopen", "urlopen")
                or name in _SUBPROCESS
                or attr == "communicate"
            ):
                if not has_kwarg(node, "timeout"):
                    out.append(
                        Violation(
                            RULE, sf.path, node.lineno,
                            f"`{name or attr}(...)` without timeout= — an "
                            "external call in a scheduler must bound its "
                            "wait",
                        )
                    )
            elif (
                attr == "wait"
                and not node.args
                and not node.keywords
            ):
                out.append(
                    Violation(
                        RULE, sf.path, node.lineno,
                        ".wait() with no timeout blocks a thread "
                        "unboundedly on a peer that may never signal",
                    )
                )
            elif (
                attr == "join"
                and not node.args
                and not node.keywords
            ):
                recv = dotted_name(node.func.value) or ""
                if "thread" in recv.lower():
                    out.append(
                        Violation(
                            RULE, sf.path, node.lineno,
                            f"`{recv}.join()` with no timeout — a wedged "
                            "worker thread would hang shutdown",
                        )
                    )
    return out
