"""timeout-hygiene: external calls carry explicit timeout policies.

The host loop's degradation story (ADVICE/SURVEY: advisor outage
requeues the window, sidecar outage flips one cycle to scalar) only
works if nothing in the cycle path can block forever. Flagged across the
whole package:

- `urllib.request.urlopen(...)` without a `timeout=`;
- `subprocess.run/call/check_call/check_output/Popen.communicate(...)`
  without a `timeout=`;
- zero-argument `.wait()` — a threading.Event / grpc event wait with no
  timeout blocks a thread unboundedly on a peer that may never signal
  (`wait_for_termination` serve loops are intentionally unbounded and
  not flagged);
- zero-argument `.join()` on thread-like receivers (name contains
  "thread") — joining a wedged worker hangs shutdown.
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis import dataflow
from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    Violation,
    dotted_name,
    has_kwarg,
)

RULE = "timeout-hygiene"

SCOPE = ("kubernetes_scheduler_tpu/**/*.py", "kubernetes_scheduler_tpu/*.py")

_SUBPROCESS = {
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
}


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    for sf in ctx.scoped(SCOPE):
        for node in dataflow.get_index(ctx).walk(sf):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            attr = (
                node.func.attr
                if isinstance(node.func, ast.Attribute)
                else None
            )
            if (
                name in ("urllib.request.urlopen", "urlopen")
                or name in _SUBPROCESS
                or attr == "communicate"
            ):
                if not has_kwarg(node, "timeout"):
                    out.append(
                        Violation(
                            RULE, sf.path, node.lineno,
                            f"`{name or attr}(...)` without timeout= — an "
                            "external call in a scheduler must bound its "
                            "wait",
                        )
                    )
            elif (
                attr == "wait"
                and not node.args
                and not node.keywords
            ):
                out.append(
                    Violation(
                        RULE, sf.path, node.lineno,
                        ".wait() with no timeout blocks a thread "
                        "unboundedly on a peer that may never signal",
                    )
                )
            elif (
                attr == "join"
                and not node.args
                and not node.keywords
            ):
                recv = dotted_name(node.func.value) or ""
                if "thread" in recv.lower():
                    out.append(
                        Violation(
                            RULE, sf.path, node.lineno,
                            f"`{recv}.join()` with no timeout — a wedged "
                            "worker thread would hang shutdown",
                        )
                    )
    return out
