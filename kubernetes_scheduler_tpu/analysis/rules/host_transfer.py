"""host-transfer: implicit device→host syncs on jax values in the
hot-path modules.

`host-sync` catches the SHAPE of a bad sync (barriers, per-element
syncs in loops). This family catches the VALUE: a local bound to a jax
expression (def-use taint over the function body — `x = jnp.sum(...)`,
`r = schedule_batch(...)`, chains hanging off either) that then flows
into an implicit transfer:

- `.item()` — one blocking device round-trip;
- `float(x)` / `int(x)` — calls `__float__`/`__int__`, a hidden
  `.item()`;
- `np.asarray(x)` / `np.array(x)` — a full device→host copy;
- `if x:` / `while x:` / `assert x` / `not x` — `__bool__` on a
  concrete device array blocks (and on a tracer it raises at trace
  time).

Scope is the HOT PATH only — engine.py, ops/, host/scheduler.py,
host/snapshot.py — by configuration here, not by per-site waiver: cold
modules (CLI, sim, tests plumbing) convert freely and waiving every one
of those sites would bury the signal. The ONE intended bulk sync per
dispatch carries an inline waiver naming the contract, which is exactly
the reviewable allow-list the cycle's sync budget wants.

Untainted receivers are NOT flagged: if local dataflow cannot show the
value came from jax, staying quiet beats burying real syncs in noise
(the clean fixture pins host-numpy patterns as unflagged).
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    Violation,
    dotted_name,
)
from kubernetes_scheduler_tpu.analysis import dataflow

RULE = "host-transfer"

SCOPE = (
    "kubernetes_scheduler_tpu/engine.py",
    "kubernetes_scheduler_tpu/ops/*.py",
    "kubernetes_scheduler_tpu/host/scheduler.py",
    "kubernetes_scheduler_tpu/host/snapshot.py",
)

_CONVERTERS = {"float", "int", "bool", "complex"}
_COPIERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
            "jax.device_get"}


def _tainted_expr(node: ast.AST, tainted: set[str]) -> str | None:
    """The tainted name a (sub)expression reads, or None. Direct jnp/jax
    calls count too — `float(jnp.sum(x))` syncs without a binding.
    Static-metadata reads (`float(y.ndim)`) are host values, not
    syncs — same exemption the taint binder applies."""
    meta = dataflow.static_meta_node_ids(node)
    for sub in ast.walk(node):
        if id(sub) in meta:
            continue
        if isinstance(sub, ast.Name) and sub.id in tainted:
            return sub.id
        if isinstance(sub, ast.Call):
            dn = dotted_name(sub.func) or ""
            if dn.startswith(("jnp.", "jax.numpy.", "lax.", "jax.lax.")):
                return dn
    return None


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    index = dataflow.get_index(ctx)
    # device-returning project entry points: names of jitted defs — a
    # call like `engine.schedule_batch(...)` taints its binding even
    # though the jit wrapper lives in another module
    jitted_names = {
        index.funcs[q].name for q in index.jit_entries() if q in index.funcs
    }
    for sf in ctx.scoped(SCOPE):
        for fi in index.functions(sf):
            tainted = dataflow.jax_tainted_names(fi.node, jitted_names)
            # parameters annotated as jax arrays are device values too
            # (keyword-only included — `def f(*, scores: jax.Array)`)
            for a in (
                fi.node.args.args
                + fi.node.args.posonlyargs
                + fi.node.args.kwonlyargs
            ):
                ann = a.annotation
                if ann is not None and (
                    (dotted_name(ann) or "").startswith(("jnp.", "jax."))
                ):
                    tainted = tainted | {a.arg}
            # no early-out on an empty taint set: a converter applied
            # DIRECTLY to a jnp call (`float(jnp.mean(x))`) syncs with
            # no binding anywhere
            for node in dataflow.shallow_walk(fi.node):
                if isinstance(node, ast.Call):
                    dn = dotted_name(node.func) or ""
                    attr = (
                        node.func.attr
                        if isinstance(node.func, ast.Attribute)
                        else None
                    )
                    if attr == "item":
                        src = _tainted_expr(node.func.value, tainted)
                        if src:
                            out.append(Violation(
                                RULE, sf.path, node.lineno,
                                f".item() on jax value `{src}` — a blocking "
                                "device→host transfer on the hot path",
                            ))
                    elif dn in _CONVERTERS and node.args:
                        src = _tainted_expr(node.args[0], tainted)
                        if src:
                            out.append(Violation(
                                RULE, sf.path, node.lineno,
                                f"{dn}() on jax value `{src}` — implicit "
                                ".item() device sync on the hot path",
                            ))
                    elif dn in _COPIERS and node.args:
                        src = _tainted_expr(node.args[0], tainted)
                        if src:
                            out.append(Violation(
                                RULE, sf.path, node.lineno,
                                f"{dn}() on jax value `{src}` — device→host "
                                "copy on the hot path; sync once in bulk at "
                                "the dispatch boundary",
                            ))
                elif isinstance(node, (ast.If, ast.While)):
                    src = _bare_tainted_test(node.test, tainted)
                    if src:
                        out.append(Violation(
                            RULE, sf.path, node.test.lineno,
                            f"branch on jax value `{src}` — __bool__ blocks "
                            "on a device array (and raises on a tracer); "
                            "compute the predicate on host or use jnp.where",
                        ))
                elif isinstance(node, ast.Assert):
                    src = _bare_tainted_test(node.test, tainted)
                    if src:
                        out.append(Violation(
                            RULE, sf.path, node.lineno,
                            f"assert on jax value `{src}` — __bool__ device "
                            "sync on the hot path",
                        ))
    return out


def _bare_tainted_test(test: ast.AST, tainted: set[str]) -> str | None:
    """A test that IS a tainted value (bare name, `not name`, or a
    boolean combination of them) — comparisons and shape probes stay
    host-side and are not flagged."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _bare_tainted_test(test.operand, tainted)
    if isinstance(test, ast.BoolOp):
        for v in test.values:
            src = _bare_tainted_test(v, tainted)
            if src:
                return src
        return None
    if isinstance(test, ast.Name) and test.id in tainted:
        return test.id
    return None
