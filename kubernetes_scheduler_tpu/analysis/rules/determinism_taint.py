"""Nondeterminism must never reach replay-pinned outputs: wall/perf
clock reads (`time.time`, `time.perf_counter`, `datetime.now` — outside
the injected-clock and span plumbing), iteration order of `set`s
(`list(s)`, comprehensions, bare `for` over a set — `sorted()` is the
discharge), and `id()`-keyed ordering are TAINT SOURCES; journal record
fields (`record_cycle`/`encode_record` arguments, record-dict literals),
`SnapshotDelta`/`CycleMetrics` construction, and engine operands are
SINKS. Declared timing telemetry (`*_seconds`, `wall_time`) is the
sanctioned wall-clock surface; everything else must be a function of
the seed — the bitwise-replay precondition `sim-determinism` enforces
for RNG, extended to clocks and ordering, repo-wide."""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis.core import Violation, dotted_name
from kubernetes_scheduler_tpu.analysis import dataflow

RULE = "determinism-taint"

WALL_CLOCKS = {
    "time.time", "time.time_ns", "time.perf_counter",
    "time.perf_counter_ns", "time.monotonic", "time.monotonic_ns",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow",
}

# journal fields DECLARED to carry wall/duration telemetry: replay pins
# bindings/operands, not these (trace diff compares decision fields).
# `seconds` is the bench-row walltime column the sim drivers stamp.
_TIMING_FIELDS = ("wall_time", "seconds")


def _timing_field(name: str) -> bool:
    return (
        name in _TIMING_FIELDS
        or name.endswith("_seconds")
        or name.endswith("_ts")
    )


# constructing one of these is a replay-pinned sink in every module
_CTOR_SINKS = {"SnapshotDelta", "CycleMetrics"}
# calls whose arguments land in the journal
_RECORD_CALLS = ("record_cycle", "encode_record")
# engine entry points: operands must be deterministic
_ENGINE_SINKS = {
    "schedule_batch", "schedule_windows", "apply_snapshot_delta",
    "apply_layout_delta", "build_fused_layout",
}

_SET_CTORS = {"set", "frozenset"}


class _FnTaint:
    """Function-local taint: kinds are 'wall-clock', 'set-order',
    'id-order'. `summaries` maps project qnames to their return-taint
    kinds (interprocedural fixpoint, resolved through the shared call
    graph)."""

    def __init__(self, index, fi, class_set_attrs, summaries):
        self.index = index
        self.fi = fi
        self.class_set_attrs = class_set_attrs
        self.summaries = summaries
        self.local_kinds: dict[str, set[str]] = {}
        self.set_locals: set[str] = set()
        self.metrics_locals: set[str] = set()
        self.record_dicts: set[str] = set()

    # -- expression classification --

    def is_set_expr(self, node) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn and dn.rsplit(".", 1)[-1] in _SET_CTORS:
                return True
        if isinstance(node, ast.Name):
            return node.id in self.set_locals
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr in self.class_set_attrs
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitAnd, ast.BitOr, ast.Sub, ast.BitXor)
        ):
            return self.is_set_expr(node.left) or self.is_set_expr(node.right)
        return False

    def taint(self, node) -> set[str]:
        """Taint kinds of an expression (empty set = deterministic)."""
        if node is None:
            return set()
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            name = dn.rsplit(".", 1)[-1] if dn else None
            if dn in WALL_CLOCKS:
                return {"wall-clock"}
            if name == "id":
                return {"id-order"}
            if name == "sorted":
                # the discharge — unless the order key itself is id()
                for kw in node.keywords:
                    if kw.arg == "key" and "id" in (
                        dotted_name(kw.value) or ""
                    ).split("."):
                        return {"id-order"}
                return set()
            if name in ("list", "tuple") and node.args:
                if self.is_set_expr(node.args[0]):
                    return {"set-order"}
                return self.taint(node.args[0])
            if name in ("pop",) and isinstance(node.func, ast.Attribute):
                if self.is_set_expr(node.func.value) and not node.args:
                    return {"set-order"}
            # project calls: return-taint summaries
            out: set[str] = set()
            for cand in self.index.resolve_call(self.fi, node):
                out |= self.summaries.get(cand.qname, set())
            for a in list(node.args) + [k.value for k in node.keywords]:
                if name in ("min", "max", "sum", "len", "sorted", "any",
                            "all", "set", "frozenset"):
                    break  # order-insensitive folds launder set-order
                out |= self.taint(a)
            return out
        if isinstance(node, ast.Name):
            return set(self.local_kinds.get(node.id, ()))
        if isinstance(node, ast.BinOp):
            return self.taint(node.left) | self.taint(node.right)
        if isinstance(node, (ast.List, ast.Tuple)):
            out = set()
            for e in node.elts:
                out |= self.taint(e)
            return out
        if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
            out = set()
            for gen in node.generators:
                if self.is_set_expr(gen.iter):
                    out.add("set-order")
                out |= self.taint(gen.iter)
            out |= self.taint(node.elt)
            return out
        if isinstance(node, ast.IfExp):
            return self.taint(node.body) | self.taint(node.orelse)
        if isinstance(node, ast.Starred):
            return self.taint(node.value)
        if isinstance(node, ast.Subscript):
            return self.taint(node.value)
        if isinstance(node, ast.UnaryOp):
            return self.taint(node.operand)
        return set()

    # -- statement pass (run to a small fixpoint so later-defined
    #    locals feed earlier uses across loop iterations) --

    def seed_locals(self) -> None:
        for _ in range(2):
            for node in dataflow.shallow_walk(self.fi.node):
                if isinstance(node, ast.Assign):
                    kinds = self.taint(node.value)
                    is_set = self.is_set_expr(node.value)
                    is_metrics = (
                        isinstance(node.value, ast.Call)
                        and (dotted_name(node.value.func) or "").rsplit(
                            ".", 1
                        )[-1] == "CycleMetrics"
                    )
                    is_rec = isinstance(node.value, ast.Dict)
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            if kinds:
                                self.local_kinds.setdefault(
                                    t.id, set()
                                ).update(kinds)
                            if is_set:
                                self.set_locals.add(t.id)
                            if is_metrics:
                                self.metrics_locals.add(t.id)
                            if is_rec:
                                self.record_dicts.add(t.id)
                        elif isinstance(t, ast.Tuple) and kinds:
                            # a, b = tainted_call(): taint every name
                            for elt in t.elts:
                                if isinstance(elt, ast.Name):
                                    self.local_kinds.setdefault(
                                        elt.id, set()
                                    ).update(kinds)
                elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name
                ):
                    kinds = self.taint(node.value)
                    if kinds:
                        self.local_kinds.setdefault(
                            node.target.id, set()
                        ).update(kinds)
                elif isinstance(node, ast.For):
                    if self.is_set_expr(node.iter) and isinstance(
                        node.target, ast.Name
                    ):
                        self.local_kinds.setdefault(
                            node.target.id, set()
                        ).add("set-order")
                    it_kinds = self.taint(node.iter)
                    if it_kinds and isinstance(node.target, ast.Name):
                        self.local_kinds.setdefault(
                            node.target.id, set()
                        ).update(it_kinds)
                elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ):
                    # L.append(tainted) taints the accumulator list
                    if node.func.attr in ("append", "extend", "add") \
                            and isinstance(node.func.value, ast.Name):
                        kinds = set()
                        for a in node.args:
                            kinds |= self.taint(a)
                        if kinds:
                            self.local_kinds.setdefault(
                                node.func.value.id, set()
                            ).update(kinds)

    def return_kinds(self) -> set[str]:
        out: set[str] = set()
        for node in dataflow.shallow_walk(self.fi.node):
            if isinstance(node, ast.Return):
                out |= self.taint(node.value)
        return out


def _class_set_attrs(index) -> dict[str, set[str]]:
    """class key -> attrs assigned `set()`/set literals anywhere in the
    class (the mirror's dirty-row sets)."""
    out: dict[str, set[str]] = {}
    for fi in index.funcs.values():
        if fi.cls is None:
            continue
        key = f"{fi.sf.path}::{fi.cls.name}"
        for node in dataflow.shallow_walk(fi.node):
            if isinstance(node, ast.Assign):
                is_set = isinstance(node.value, (ast.Set, ast.SetComp)) or (
                    isinstance(node.value, ast.Call)
                    and (dotted_name(node.value.func) or "").rsplit(".", 1)[-1]
                    in _SET_CTORS
                )
                if not is_set:
                    continue
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        out.setdefault(key, set()).add(t.attr)
    return out


def _summaries(index, set_attrs) -> dict[str, set[str]]:
    """Return-taint fixpoint over the project call graph (two passes
    reach every realistic helper chain)."""
    summaries: dict[str, set[str]] = {}
    for _ in range(2):
        changed = False
        for qname, fi in index.funcs.items():
            owner = (
                f"{fi.sf.path}::{fi.cls.name}" if fi.cls is not None else None
            )
            ft = _FnTaint(
                index, fi, set_attrs.get(owner, set()), summaries
            )
            ft.seed_locals()
            kinds = ft.return_kinds()
            if kinds - summaries.get(qname, set()):
                summaries[qname] = summaries.get(qname, set()) | kinds
                changed = True
        if not changed:
            break
    return summaries


def _kind_hint(kinds: set[str]) -> str:
    hints = {
        "wall-clock": (
            "inject the clock (a `clock=` parameter / `self._clock`) so "
            "replay can pin it, or route the value to a declared timing "
            "field (`*_seconds`, `wall_time`)"
        ),
        "set-order": "materialize with `sorted(...)` before it escapes",
        "id-order": (
            "key on a stable identity (name/uid/index), never `id()`"
        ),
    }
    return "; ".join(hints[k] for k in sorted(kinds))


def check(ctx) -> list[Violation]:
    index = dataflow.get_index(ctx)
    set_attrs = _class_set_attrs(index)
    summaries = _summaries(index, set_attrs)
    out: list[Violation] = []
    for sf in ctx.files:
        for fi in index.functions(sf):
            owner = (
                f"{fi.sf.path}::{fi.cls.name}" if fi.cls is not None else None
            )
            ft = _FnTaint(
                index, fi, set_attrs.get(owner, set()), summaries
            )
            ft.seed_locals()
            in_recorder = "record" in fi.name or "journal" in fi.name
            for node in dataflow.shallow_walk(fi.node):
                if isinstance(node, ast.Call):
                    dn = dotted_name(node.func) or ""
                    name = dn.rsplit(".", 1)[-1]
                    if name in _CTOR_SINKS:
                        for kw in node.keywords:
                            kinds = ft.taint(kw.value)
                            if kinds and not _timing_field(kw.arg or ""):
                                out.append(Violation(
                                    RULE, sf.path, node.lineno,
                                    f"{'/'.join(sorted(kinds))} value "
                                    f"flows into `{name}({kw.arg}=...)` "
                                    "— a replay-pinned operand must be "
                                    "deterministic given the seed; "
                                    f"{_kind_hint(kinds)}",
                                ))
                        for i, a in enumerate(node.args):
                            kinds = ft.taint(a)
                            if kinds:
                                out.append(Violation(
                                    RULE, sf.path, node.lineno,
                                    f"{'/'.join(sorted(kinds))} value "
                                    f"flows into `{name}(...)` arg {i} "
                                    "— a replay-pinned operand must be "
                                    "deterministic given the seed; "
                                    f"{_kind_hint(kinds)}",
                                ))
                    elif name in _ENGINE_SINKS or any(
                        r in name for r in _RECORD_CALLS
                    ):
                        sink_kind = (
                            "journal record field"
                            if any(r in name for r in _RECORD_CALLS)
                            else "engine operand"
                        )
                        args = list(node.args) + [
                            k.value for k in node.keywords
                            if not _timing_field(k.arg or "")
                        ]
                        for a in args:
                            kinds = ft.taint(a)
                            if isinstance(a, ast.Name) and (
                                a.id in ft.record_dicts
                            ):
                                continue  # dict literals audited below
                            if kinds:
                                out.append(Violation(
                                    RULE, sf.path, node.lineno,
                                    f"{'/'.join(sorted(kinds))} value "
                                    f"reaches `{name}(...)` — a "
                                    f"{sink_kind} must be deterministic "
                                    "given the seed; "
                                    f"{_kind_hint(kinds)}",
                                ))
                elif isinstance(node, ast.Assign):
                    # record-dict / CycleMetrics field stores
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Name)
                            and t.value.id in ft.record_dicts
                            and isinstance(t.slice, ast.Constant)
                            and isinstance(t.slice.value, str)
                        ):
                            fieldname = t.slice.value
                            kinds = ft.taint(node.value)
                            if kinds and not _timing_field(fieldname):
                                out.append(Violation(
                                    RULE, sf.path, node.lineno,
                                    f"{'/'.join(sorted(kinds))} value "
                                    "stamped into journal-record field "
                                    f"`{fieldname}` — replay pins "
                                    "record fields; declared timing "
                                    "fields (`wall_time`, `*_seconds`) "
                                    "are the sanctioned surface; "
                                    f"{_kind_hint(kinds)}",
                                ))
                        elif (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id in ft.metrics_locals
                        ):
                            kinds = ft.taint(node.value)
                            if kinds and not _timing_field(t.attr):
                                out.append(Violation(
                                    RULE, sf.path, node.lineno,
                                    f"{'/'.join(sorted(kinds))} value "
                                    "assigned to journaled CycleMetrics "
                                    f"field `{t.attr}` — only timing "
                                    "fields (`*_seconds`) may carry "
                                    "clock-derived values; "
                                    f"{_kind_hint(kinds)}",
                                ))
                    # dict-literal record construction inside recorder-
                    # shaped functions (or dicts that flow to a record
                    # call): audit the literal's fields
                    if isinstance(node.value, ast.Dict):
                        is_record = in_recorder or any(
                            isinstance(t, ast.Name)
                            and t.id in ft.record_dicts
                            for t in node.targets
                        )
                        if is_record:
                            for k, v in zip(
                                node.value.keys, node.value.values
                            ):
                                if not (
                                    isinstance(k, ast.Constant)
                                    and isinstance(k.value, str)
                                ):
                                    continue
                                kinds = ft.taint(v)
                                if kinds and not _timing_field(k.value):
                                    out.append(Violation(
                                        RULE, sf.path, v.lineno,
                                        f"{'/'.join(sorted(kinds))} "
                                        "value stamped into journal-"
                                        f"record field `{k.value}` — "
                                        "replay pins record fields; "
                                        "declared timing fields "
                                        "(`wall_time`, `*_seconds`) are "
                                        "the sanctioned surface; "
                                        f"{_kind_hint(kinds)}",
                                    ))
    return out
