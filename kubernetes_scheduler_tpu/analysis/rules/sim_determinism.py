"""sim-determinism: every random draw in sim/ flows from an explicit
seed.

The simulators are not decoration — scenario journals are REPLAY-PINNED
(`trace replay` diffs bindings bitwise) and double as the learned-policy
training-data generator, so a scenario run must be a pure function of
(name, seed, scale). One module-level `np.random.random()` or stdlib
`random.choice()` breaks that silently: the run still "works", the
journal still replays, but the same seed no longer reproduces the same
traffic and every cross-run comparison (bench deltas, parity suites,
regression bisects) quietly measures noise. Flagged in sim/ files:

- `np.random.*` / `numpy.random.*` calls — the GLOBAL numpy RNG
  (process-wide state, import-order dependent). Includes
  `np.random.seed(...)`: seeding the global RNG still leaves every
  other module sharing the stream.
- unseeded `default_rng()` / `np.random.default_rng()` — a fresh OS-
  entropy generator per call; `default_rng(seed)` is the clean form.
- stdlib `random.*` calls — the other global RNG.

Clean: `default_rng(seed)` and anything drawn from a generator object
(`rng.integers(...)`, `rng.choice(...)`), which is how every shipped
simulator threads its seed.
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis import dataflow
from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    Violation,
    dotted_name,
)

RULE = "sim-determinism"

SCOPE = (
    "kubernetes_scheduler_tpu/sim/*.py",
    "kubernetes_scheduler_tpu/sim/**/*.py",
)

# stdlib `random` module functions (dotted root `random.`); a bare
# attribute probe is not a draw, only calls are flagged
_STDLIB_ROOT = "random."


def _is_default_rng(name: str) -> bool:
    return name == "default_rng" or name.endswith(".default_rng")


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    for sf in ctx.scoped(SCOPE):
        for node in dataflow.get_index(ctx).walk(sf):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None:
                continue
            if _is_default_rng(name):
                if not node.args and not node.keywords:
                    out.append(Violation(
                        RULE, sf.path, node.lineno,
                        "unseeded default_rng(): a fresh OS-entropy "
                        "generator per call — pass the scenario/config "
                        "seed (default_rng(seed)) so runs reproduce",
                    ))
                continue
            if name.startswith(("np.random.", "numpy.random.")):
                out.append(Violation(
                    RULE, sf.path, node.lineno,
                    f"`{name}` draws from numpy's GLOBAL RNG "
                    "(process-wide, import-order dependent) — create a "
                    "generator with default_rng(seed) and draw from it",
                ))
                continue
            if name.startswith(_STDLIB_ROOT) and name.count(".") == 1:
                out.append(Violation(
                    RULE, sf.path, node.lineno,
                    f"`{name}` draws from the stdlib GLOBAL RNG — "
                    "create a generator with default_rng(seed) and "
                    "draw from it",
                ))
    return out
