"""wire-schema: wire contracts must match their declared schemas.

Two wire surfaces, one rule family:

gRPC bridge — the hand-written stubs mean no compiler checks that the
Python side's field names still exist in the .proto; a renamed field
would silently serialize nothing (proto3 default) instead of failing.
This rule parses the .proto's message blocks and checks, in every file
that imports a `*_pb2` module:

- keyword arguments of `pb.<Message>(...)` constructors;
- first-level attribute access on variables whose Message type is known
  (parameter annotations `x: pb.Message` and direct `x = pb.Message(...)`
  assignments).

Protobuf runtime API names (CopyFrom, SerializeToString, ...) pass.

Trace journal (trace/schema.py) — the flight recorder's record layout
is declared as a JOURNAL_FIELDS tag table plus a TENSOR_DTYPES pinning
map, and the same schema-drift failure modes apply: a reused tag makes
old journals decode into the wrong field, an unpinned or drifted dtype
makes "bitwise replay parity" silently meaningless. In any file that
declares those tables the rule checks: field tags are unique integer
LITERALS (a computed tag has no stable wire identity), field names are
unique, kinds come from the declared set, every tensor dtype is a
literal from the pinned dtype set (float64 is deliberately absent), and
every dtype key's field prefix is a declared `tensors`-kind field.
"""

from __future__ import annotations

import ast
import os
import re

from kubernetes_scheduler_tpu.analysis import dataflow
from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    SourceFile,
    Violation,
    dotted_name,
)

RULE = "wire-schema"

SCOPE = ("kubernetes_scheduler_tpu/bridge/*.py",)
TRACE_SCOPE = ("kubernetes_scheduler_tpu/trace/*.py",)

# the journal's pinned dtype vocabulary — float64 deliberately absent
# (device parity is float32; a silent f64 leaf would diff every replay)
_PINNED_DTYPES = {"float32", "int32", "int64", "bool", "uint8"}
_JOURNAL_KINDS = {"u64", "f64", "str", "json", "tensors"}

_DEFAULT_PROTO = os.path.join(
    "kubernetes_scheduler_tpu", "bridge", "schedule.proto"
)

_PROTOBUF_API = {
    "CopyFrom", "MergeFrom", "SerializeToString", "FromString",
    "ParseFromString", "HasField", "ClearField", "WhichOneof",
    "ByteSize", "IsInitialized", "DESCRIPTOR", "Clear",
}

_MSG_RE = re.compile(r"^\s*message\s+(\w+)\s*\{", re.M)
_FIELD_RE = re.compile(
    r"^\s*(?:repeated\s+|optional\s+)?"
    r"(map\s*<[^>]+>|[\w.]+)\s+(\w+)\s*=\s*\d+\s*;",
)


def parse_proto_fields(path: str) -> dict[str, dict[str, str]]:
    """message name -> {field name: declared type}, by brace-tracking
    text scan (enough for the proto3 subset this repo uses). The ONE
    proto tokenizer: parse_proto derives its name sets from this, and
    capability_completeness filters HealthReply's bool fields off the
    types."""
    messages: dict[str, dict[str, str]] = {}
    current = None
    depth = 0
    with open(path, encoding="utf-8") as f:
        for raw in f:
            line = raw.split("//", 1)[0]
            m = _MSG_RE.match(line)
            if m and depth == 0:
                current = m.group(1)
                messages[current] = {}
                # count the rest of the line too: `message Empty {}`
                # opens and closes in one line
                depth = line.count("{") - line.count("}")
                if depth <= 0:
                    current = None
                    depth = 0
                continue
            if current is not None:
                if depth == 1:
                    fm = _FIELD_RE.match(line)
                    if fm:
                        messages[current][fm.group(2)] = fm.group(1)
                depth += line.count("{") - line.count("}")
                if depth <= 0:
                    current = None
                    depth = 0
    return messages


def parse_proto(path: str) -> dict[str, set]:
    """message name -> set of field names (parse_proto_fields sans
    types — the shape the wire-schema checks key on)."""
    return {
        msg: set(fields) for msg, fields in parse_proto_fields(path).items()
    }


def _pb_aliases(tree: ast.AST) -> set:
    """Local names bound to a *_pb2 module import."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith("_pb2"):
                    out.add(a.asname or a.name.split(".")[-1])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name.endswith("_pb2"):
                    out.add(a.asname or a.name)
    return out


def _proto_for(ctx: Context, sf: SourceFile) -> str | None:
    if ctx.proto_path:
        return ctx.proto_path
    sibling_dir = os.path.dirname(sf.abspath)
    for name in sorted(os.listdir(sibling_dir)):
        if name.endswith(".proto"):
            return os.path.join(sibling_dir, name)
    default = os.path.join(ctx.root, _DEFAULT_PROTO)
    return default if os.path.exists(default) else None


def _message_of(node: ast.AST, aliases: set) -> str | None:
    """Message name when `node` is `pb.<Message>` / `pb.<Message>(...)`."""
    if isinstance(node, ast.Call):
        node = node.func
    name = dotted_name(node)
    if not name:
        return None
    parts = name.split(".")
    if len(parts) == 2 and parts[0] in aliases:
        return parts[1]
    return None


def _const(node) -> object:
    return node.value if isinstance(node, ast.Constant) else _NOT_CONST


_NOT_CONST = object()


def _journal_tables(tree: ast.AST):
    """Top-level JOURNAL_FIELDS / TENSOR_DTYPES assignments, or Nones."""
    fields_node = dtypes_node = None
    for node in getattr(tree, "body", ()):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            if node.targets[0].id == "JOURNAL_FIELDS":
                fields_node = node.value
            elif node.targets[0].id == "TENSOR_DTYPES":
                dtypes_node = node.value
    return fields_node, dtypes_node


def _check_journal_schema(sf: SourceFile) -> list[Violation]:
    out: list[Violation] = []
    fields_node, dtypes_node = _journal_tables(sf.tree)
    if fields_node is None and dtypes_node is None:
        return out
    tensor_fields: set[str] = set()
    have_fields = fields_node is not None
    if have_fields:
        seen_tags: dict[int, str] = {}
        seen_names: set[str] = set()
        elts = (
            fields_node.elts
            if isinstance(fields_node, (ast.Tuple, ast.List))
            else ()
        )
        for e in elts:
            if not (
                isinstance(e, ast.Call)
                and dotted_name(e.func) in ("Field",)
            ):
                continue
            slots = {"tag": None, "name": None, "kind": None}
            for pos, arg in zip(("tag", "name", "kind"), e.args):
                slots[pos] = arg
            for kw in e.keywords:
                if kw.arg in slots:
                    slots[kw.arg] = kw.value
            tag = _const(slots["tag"]) if slots["tag"] is not None else _NOT_CONST
            name = _const(slots["name"]) if slots["name"] is not None else _NOT_CONST
            kind = _const(slots["kind"]) if slots["kind"] is not None else _NOT_CONST
            if not isinstance(tag, int) or isinstance(tag, bool) or tag <= 0:
                out.append(
                    Violation(
                        RULE, sf.path, e.lineno,
                        "journal field tag must be a positive integer "
                        "LITERAL — tags are wire identity and a computed "
                        "tag has no stable value to keep",
                    )
                )
            elif tag in seen_tags:
                out.append(
                    Violation(
                        RULE, sf.path, e.lineno,
                        f"journal field tag {tag} reused (already "
                        f"`{seen_tags[tag]}`) — reuse makes old journals "
                        "decode into the wrong field",
                    )
                )
            else:
                seen_tags[tag] = name if isinstance(name, str) else "?"
            if isinstance(name, str):
                if name in seen_names:
                    out.append(
                        Violation(
                            RULE, sf.path, e.lineno,
                            f"journal field name `{name}` declared twice",
                        )
                    )
                seen_names.add(name)
                if kind == "tensors":
                    tensor_fields.add(name)
            if not isinstance(kind, str):
                # a computed or missing kind has no stable wire identity
                # — the same drift class as a computed tag
                out.append(
                    Violation(
                        RULE, sf.path, e.lineno,
                        "journal field kind must be a string LITERAL "
                        f"from {sorted(_JOURNAL_KINDS)}",
                    )
                )
            elif kind not in _JOURNAL_KINDS:
                out.append(
                    Violation(
                        RULE, sf.path, e.lineno,
                        f"unknown journal field kind {kind!r}; expected "
                        f"one of {sorted(_JOURNAL_KINDS)}",
                    )
                )
    if dtypes_node is not None and isinstance(dtypes_node, ast.Dict):
        seen_keys: set[str] = set()
        for k, v in zip(dtypes_node.keys, dtypes_node.values):
            key = _const(k) if k is not None else _NOT_CONST
            val = _const(v)
            line = (k or v).lineno
            if not isinstance(key, str):
                out.append(
                    Violation(
                        RULE, sf.path, line,
                        "TENSOR_DTYPES keys must be string literals "
                        "(`<field>.<leaf>`)",
                    )
                )
                continue
            if key in seen_keys:
                out.append(
                    Violation(
                        RULE, sf.path, line,
                        f"TENSOR_DTYPES key `{key}` declared twice",
                    )
                )
            seen_keys.add(key)
            prefix = key.split(".", 1)[0]
            if have_fields and prefix not in tensor_fields:
                out.append(
                    Violation(
                        RULE, sf.path, line,
                        f"TENSOR_DTYPES key `{key}`: `{prefix}` is not a "
                        "declared `tensors`-kind journal field",
                    )
                )
            if not isinstance(val, str) or val not in _PINNED_DTYPES:
                shown = val if val is not _NOT_CONST else "<non-literal>"
                out.append(
                    Violation(
                        RULE, sf.path, v.lineno,
                        f"tensor dtype for `{key}` must be a literal from "
                        f"{sorted(_PINNED_DTYPES)}; got {shown!r} — an "
                        "unpinned dtype makes bitwise replay parity "
                        "unverifiable",
                    )
                )
    return out


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    for sf in ctx.scoped(TRACE_SCOPE):
        out.extend(_check_journal_schema(sf))
    for sf in ctx.scoped(SCOPE):
        aliases = _pb_aliases(sf.tree)
        if not aliases:
            continue
        proto = _proto_for(ctx, sf)
        if proto is None:
            out.append(
                Violation(
                    RULE, sf.path, 1,
                    "imports a *_pb2 module but no .proto schema found "
                    "to check against",
                )
            )
            continue
        messages = parse_proto(proto)

        # pass 1: constructor kwargs anywhere in the file
        for node in dataflow.get_index(ctx).walk(sf):
            if not isinstance(node, ast.Call):
                continue
            msg = _message_of(node, aliases)
            if msg is None:
                continue
            if msg not in messages:
                out.append(
                    Violation(
                        RULE, sf.path, node.lineno,
                        f"message `{msg}` does not exist in "
                        f"{os.path.basename(proto)}",
                    )
                )
                continue
            for kw in node.keywords:
                if kw.arg and kw.arg not in messages[msg]:
                    out.append(
                        Violation(
                            RULE, sf.path, kw.value.lineno,
                            f"`{msg}` has no field `{kw.arg}` in "
                            f"{os.path.basename(proto)}",
                        )
                    )

        # pass 2: attribute access on vars of known Message type,
        # function by function
        for fn in dataflow.get_index(ctx).walk(sf):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            var_types: dict[str, str] = {}
            for a in fn.args.args + fn.args.kwonlyargs + fn.args.posonlyargs:
                if a.annotation is not None:
                    msg = _message_of(a.annotation, aliases)
                    if msg:
                        var_types[a.arg] = msg
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    msg = _message_of(node.value, aliases)
                    if msg:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                var_types[t.id] = msg
            if not var_types:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Attribute):
                    continue
                if not (
                    isinstance(node.value, ast.Name)
                    and node.value.id in var_types
                ):
                    continue
                msg = var_types[node.value.id]
                fields = messages.get(msg)
                if fields is None:
                    continue
                if node.attr in fields or node.attr in _PROTOBUF_API:
                    continue
                out.append(
                    Violation(
                        RULE, sf.path, node.lineno,
                        f"`{node.value.id}.{node.attr}`: `{msg}` has no "
                        f"field `{node.attr}` in {os.path.basename(proto)}",
                    )
                )
    return out
