"""wire-schema: schedule_pb2 field usage must exist in schedule.proto.

The bridge's hand-written stubs mean no compiler checks that the Python
side's field names still exist in the .proto — a renamed field would
silently serialize nothing (proto3 default) instead of failing. This
rule parses the .proto's message blocks and checks, in every file that
imports a `*_pb2` module:

- keyword arguments of `pb.<Message>(...)` constructors;
- first-level attribute access on variables whose Message type is known
  (parameter annotations `x: pb.Message` and direct `x = pb.Message(...)`
  assignments).

Protobuf runtime API names (CopyFrom, SerializeToString, ...) pass.
"""

from __future__ import annotations

import ast
import os
import re

from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    SourceFile,
    Violation,
    dotted_name,
)

RULE = "wire-schema"

SCOPE = ("kubernetes_scheduler_tpu/bridge/*.py",)

_DEFAULT_PROTO = os.path.join(
    "kubernetes_scheduler_tpu", "bridge", "schedule.proto"
)

_PROTOBUF_API = {
    "CopyFrom", "MergeFrom", "SerializeToString", "FromString",
    "ParseFromString", "HasField", "ClearField", "WhichOneof",
    "ByteSize", "IsInitialized", "DESCRIPTOR", "Clear",
}

_MSG_RE = re.compile(r"^\s*message\s+(\w+)\s*\{", re.M)
_FIELD_RE = re.compile(
    r"^\s*(?:repeated\s+|optional\s+)?"
    r"(?:map\s*<[^>]+>|[\w.]+)\s+(\w+)\s*=\s*\d+\s*;",
)


def parse_proto(path: str) -> dict[str, set]:
    """message name -> set of field names, by brace-tracking text scan
    (enough for the proto3 subset this repo uses)."""
    messages: dict[str, set] = {}
    current = None
    depth = 0
    with open(path, encoding="utf-8") as f:
        for raw in f:
            line = raw.split("//", 1)[0]
            m = _MSG_RE.match(line)
            if m and depth == 0:
                current = m.group(1)
                messages[current] = set()
                # count the rest of the line too: `message Empty {}`
                # opens and closes in one line
                depth = line.count("{") - line.count("}")
                if depth <= 0:
                    current = None
                    depth = 0
                continue
            if current is not None:
                if depth == 1:
                    fm = _FIELD_RE.match(line)
                    if fm:
                        messages[current].add(fm.group(1))
                depth += line.count("{") - line.count("}")
                if depth <= 0:
                    current = None
                    depth = 0
    return messages


def _pb_aliases(tree: ast.AST) -> set:
    """Local names bound to a *_pb2 module import."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith("_pb2"):
                    out.add(a.asname or a.name.split(".")[-1])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name.endswith("_pb2"):
                    out.add(a.asname or a.name)
    return out


def _proto_for(ctx: Context, sf: SourceFile) -> str | None:
    if ctx.proto_path:
        return ctx.proto_path
    sibling_dir = os.path.dirname(sf.abspath)
    for name in sorted(os.listdir(sibling_dir)):
        if name.endswith(".proto"):
            return os.path.join(sibling_dir, name)
    default = os.path.join(ctx.root, _DEFAULT_PROTO)
    return default if os.path.exists(default) else None


def _message_of(node: ast.AST, aliases: set) -> str | None:
    """Message name when `node` is `pb.<Message>` / `pb.<Message>(...)`."""
    if isinstance(node, ast.Call):
        node = node.func
    name = dotted_name(node)
    if not name:
        return None
    parts = name.split(".")
    if len(parts) == 2 and parts[0] in aliases:
        return parts[1]
    return None


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    for sf in ctx.scoped(SCOPE):
        aliases = _pb_aliases(sf.tree)
        if not aliases:
            continue
        proto = _proto_for(ctx, sf)
        if proto is None:
            out.append(
                Violation(
                    RULE, sf.path, 1,
                    "imports a *_pb2 module but no .proto schema found "
                    "to check against",
                )
            )
            continue
        messages = parse_proto(proto)

        # pass 1: constructor kwargs anywhere in the file
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            msg = _message_of(node, aliases)
            if msg is None:
                continue
            if msg not in messages:
                out.append(
                    Violation(
                        RULE, sf.path, node.lineno,
                        f"message `{msg}` does not exist in "
                        f"{os.path.basename(proto)}",
                    )
                )
                continue
            for kw in node.keywords:
                if kw.arg and kw.arg not in messages[msg]:
                    out.append(
                        Violation(
                            RULE, sf.path, kw.value.lineno,
                            f"`{msg}` has no field `{kw.arg}` in "
                            f"{os.path.basename(proto)}",
                        )
                    )

        # pass 2: attribute access on vars of known Message type,
        # function by function
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            var_types: dict[str, str] = {}
            for a in fn.args.args + fn.args.kwonlyargs + fn.args.posonlyargs:
                if a.annotation is not None:
                    msg = _message_of(a.annotation, aliases)
                    if msg:
                        var_types[a.arg] = msg
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    msg = _message_of(node.value, aliases)
                    if msg:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                var_types[t.id] = msg
            if not var_types:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Attribute):
                    continue
                if not (
                    isinstance(node.value, ast.Name)
                    and node.value.id in var_types
                ):
                    continue
                msg = var_types[node.value.id]
                fields = messages.get(msg)
                if fields is None:
                    continue
                if node.attr in fields or node.attr in _PROTOBUF_API:
                    continue
                out.append(
                    Violation(
                        RULE, sf.path, node.lineno,
                        f"`{node.value.id}.{node.attr}`: `{msg}` has no "
                        f"field `{node.attr}` in {os.path.basename(proto)}",
                    )
                )
    return out
