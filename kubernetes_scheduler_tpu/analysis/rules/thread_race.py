"""Interprocedural cross-thread race detection over the declared thread
model: every object-attribute (and module-global) access reachable from
two or more thread roots must be ordered — by a common lockset, or by a
happens-before edge the model proves (write published before
`Thread.start()`, write-then-`Event.set()` consumed after
`Event.wait()`, reader behind a `.join()`, hand-off through a
`Queue`/`deque`/internally-locked collector) — plus check-then-act
atomicity on shared attributes. Subsumes and strengthens
`lockset-race`: that family checks lock CONSISTENCY within a class;
this one checks cross-thread ORDERING, with the set-before-start and
queue-hand-off patterns proven instead of waived."""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis.core import Violation, dotted_name
from kubernetes_scheduler_tpu.analysis import dataflow, threads

RULE = threads.RULE  # "thread-race"

# the threaded layers; kernel/engine/sim code runs single-threaded under
# the drivers and is exempt by scope configuration, not by waiver
_SCOPE_DIRS = (
    "kubernetes_scheduler_tpu/host/",
    "kubernetes_scheduler_tpu/kube/",
    "kubernetes_scheduler_tpu/bridge/",
    "kubernetes_scheduler_tpu/trace/",
)


def _in_scope(path: str) -> bool:
    if not path.startswith("kubernetes_scheduler_tpu/"):
        return True  # fixtures / scratch mutants: always analyzed
    return path.startswith(_SCOPE_DIRS)


def _conflicting(t1: frozenset, t2: frozenset, concurrent: set) -> str | None:
    """A pair of identity sets conflicts when two DIFFERENT identities
    can execute the sites, or one concurrent identity can execute both
    (two HTTP handler threads in the same method). Returns a rendered
    'a vs b' tag, or None."""
    for a in t1:
        for b in t2:
            if a != b:
                return f"{a} vs {b}"
            if a in concurrent:
                return f"{a} (concurrent instances)"
    return None


def _hb_discharged(cc, w: threads.Access, s: threads.Access) -> bool:
    """True when a proven happens-before edge orders the pair."""
    w_hb = cc.hb.get(w.method)
    s_hb = cc.hb.get(s.method)
    if w_hb is None or s_hb is None:
        return False
    # publication before Thread.start(): everything the spawning method
    # writes before the start() call is visible to the spawned thread
    if any(line >= w.line for line in w_hb.starts):
        return True
    if s.kind == "w" and any(line >= s.line for line in s_hb.starts):
        return True
    # Event publication: writer sets e AFTER the write, observer read
    # comes AFTER waiting on the same e
    for e, set_line in w_hb.sets:
        if set_line >= w.line and any(
            we == e and wait_line <= s.line for we, wait_line in s_hb.waits
        ):
            return True
    for e, set_line in s_hb.sets:
        if s.kind == "w" and set_line >= s.line and any(
            we == e and wait_line <= w.line for we, wait_line in w_hb.waits
        ):
            return True
    # join: an access behind a .join() happens-after the joined thread's
    # writes (either side may be the joiner)
    if any(line <= s.line for line in s_hb.joins):
        return True
    if any(line <= w.line for line in w_hb.joins):
        return True
    return False


def _class_races(index, model, sf, cls, out) -> None:
    cc = threads.class_concurrency(index, sf, cls)
    reported: set = set()
    for attr, accesses in sorted(cc.accesses.items()):
        writes = [
            a for a in accesses
            if a.kind == "w" and a.method != "__init__"
        ]
        if not writes:
            continue
        for w in writes:
            tw = model.threads(w.qname)
            if not tw:
                continue
            for s in accesses:
                if s.method == "__init__":
                    continue  # construction happens-before publication
                if s.kind == "w" and (s.qname, s.line) < (w.qname, w.line):
                    continue  # each unordered write/write pair once
                if s.kind == "r" and s.qname == w.qname and s.line == w.line:
                    continue  # the write's own receiver load
                ts = model.threads(s.qname)
                tag = _conflicting(tw, ts, model.concurrent)
                if tag is None:
                    continue
                gw = threads.guaranteed_locks(cc, w)
                gs = threads.guaranteed_locks(cc, s)
                if gw & gs:
                    continue  # common lockset orders the pair
                if _hb_discharged(cc, w, s):
                    continue
                key = (attr, w.method, s.method, s.kind)
                if key in reported:
                    continue
                reported.add(key)
                verb = "written" if s.kind == "w" else "read"
                # anchor the finding at the LOCK-FREE side — that's the
                # site needing the guard (or the waiver, for an intended
                # bulk-sync read)
                anchor = s.line if (gw and not gs) else w.line
                out.append(Violation(
                    RULE, sf.path, anchor,
                    f"`{cc.cls_name}.{attr}` is written in `{w.method}` "
                    f"(line {w.line}) and {verb} in `{s.method}` (line "
                    f"{s.line}) on different threads ({tag}) with no "
                    "common lockset and no happens-before edge — guard "
                    "both sites with one lock, publish the write before "
                    "the reader's thread starts, pair it with an "
                    "Event.set()/wait(), hand the value off through a "
                    "Queue, or join the writing thread first",
                ))


def _check_then_act(index, model, sf, cls, out) -> None:
    """`if <self.attr test>: ... self.attr = ...` with no lock covering
    both test and act, on an attribute other threads write: the classic
    lost-update latch (two threads both see the un-set state)."""
    cc = threads.class_concurrency(index, sf, cls)
    shared_written = set()
    for attr, accesses in cc.accesses.items():
        idents = set()
        for a in accesses:
            if a.kind == "w" and a.method != "__init__":
                idents |= model.threads(a.qname)
        if len(idents) > 1 or idents & model.concurrent:
            shared_written.add(attr)
    if not shared_written:
        return
    for method, qname in cc.methods.items():
        if method == "__init__":
            continue
        idents = model.threads(qname)
        if not (len(idents) > 1 or idents & model.concurrent):
            continue
        fi = index.funcs.get(qname)
        if fi is None:
            continue
        by_line = {}
        for attr, accesses in cc.accesses.items():
            for a in accesses:
                if a.qname == qname:
                    by_line.setdefault(a.line, []).append(a)

        def held_at(line, kind, attr):
            for a in by_line.get(line, ()):
                if a.attr == attr and a.kind == kind:
                    return threads.guaranteed_locks(cc, a)
            return None

        for node in dataflow.shallow_walk(fi.node):
            if not isinstance(node, ast.If):
                continue
            tested = set()
            for n in ast.walk(node.test):
                if (
                    isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                ):
                    tested.add(n.attr)
                for k in (threads.self_dict_sub(n), threads.self_dict_get(n)):
                    if k is not None:
                        tested.add(k)
            tested &= shared_written
            if not tested:
                continue
            for stmt in ast.walk(node):
                if not isinstance(stmt, (ast.Assign, ast.AugAssign)):
                    continue
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                for t in targets:
                    written_attr = threads.self_dict_sub(t)
                    if written_attr is None:
                        base = t
                        if isinstance(base, ast.Subscript):
                            base = base.value
                        if (
                            isinstance(base, ast.Attribute)
                            and isinstance(base.value, ast.Name)
                            and base.value.id == "self"
                        ):
                            written_attr = base.attr
                    if written_attr not in tested:
                        continue
                    test_held = held_at(node.test.lineno, "r", written_attr)
                    act_held = held_at(stmt.lineno, "w", written_attr)
                    if (
                        test_held is not None and act_held is not None
                        and test_held & act_held
                    ):
                        continue  # one lock covers check AND act
                    out.append(Violation(
                        RULE, sf.path, node.lineno,
                        f"check-then-act on `{cc.cls_name}.{written_attr}` "
                        f"in `{method}`: the test (line "
                        f"{node.test.lineno}) and the write (line "
                        f"{stmt.lineno}) are not covered by one lock, "
                        "and other threads write this attribute — two "
                        "threads can both observe the un-set state; "
                        "take the lock around the whole "
                        "test-and-assign (double-checked re-test under "
                        "the lock is the sanctioned idiom)",
                    ))


def _module_global_races(index, model, sf, out) -> None:
    """Writes to `global X` names from functions on different threads,
    with reads of the same module-level name — module locks
    (`with _LOCK:` over a module-level Lock()) discharge."""
    tree = sf.tree
    module_locks = set()
    mutable_globals = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            cname = dotted_name(node.value.func)
            cname = cname.rsplit(".", 1)[-1] if cname else None
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if cname in ("Lock", "RLock"):
                        module_locks.add(t.id)
                    elif cname in ("dict", "list", "set"):
                        mutable_globals.add(t.id)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and isinstance(
                    node.value, (ast.Dict, ast.List, ast.Set)
                ):
                    mutable_globals.add(t.id)
    writers: dict[str, list] = {}
    readers: dict[str, list] = {}
    for fi in index.functions(sf):
        declared = set()
        for node in dataflow.shallow_walk(fi.node):
            if isinstance(node, ast.Global):
                declared.update(node.names)

        def locked_walk(node, held):
            for child in ast.iter_child_nodes(node):
                child_held = held
                if isinstance(child, ast.With):
                    acq = {
                        dotted_name(i.context_expr)
                        for i in child.items
                    } & module_locks
                    if acq:
                        child_held = held | acq
                if isinstance(child, ast.Assign):
                    for t in child.targets:
                        if isinstance(t, ast.Name) and t.id in declared:
                            writers.setdefault(t.id, []).append(
                                (fi, child.lineno, frozenset(child_held))
                            )
                elif (
                    isinstance(child, ast.Name)
                    and isinstance(child.ctx, ast.Load)
                    and (child.id in declared or child.id in mutable_globals)
                ):
                    readers.setdefault(child.id, []).append(
                        (fi, child.lineno, frozenset(child_held))
                    )
                if not isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    locked_walk(child, child_held)

        locked_walk(fi.node, frozenset())
    for name, wsites in sorted(writers.items()):
        for wfi, wline, wheld in wsites:
            tw = model.threads(wfi.qname)
            for rfi, rline, rheld in readers.get(name, []) + [
                (f, ln, h) for f, ln, h in wsites if (f, ln) != (wfi, wline)
            ]:
                ts = model.threads(rfi.qname)
                tag = _conflicting(tw, ts, model.concurrent)
                if tag is None or (wheld & rheld):
                    continue
                out.append(Violation(
                    RULE, sf.path, wline,
                    f"module global `{name}` is written in "
                    f"`{wfi.name}` (line {wline}) and touched in "
                    f"`{rfi.name}` (line {rline}) on different threads "
                    f"({tag}) with no common module lock — guard both "
                    "sites with one module-level Lock",
                ))
                break  # one finding per write site


def check(ctx) -> list[Violation]:
    index = dataflow.get_index(ctx)
    out: list[Violation] = []
    # declared thread model: anchor drift is a finding, not a crash
    out.extend(threads.verify_thread_roots(index))
    model = threads.build_model(index)
    for sf in ctx.files:
        if not _in_scope(sf.path):
            continue
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                _class_races(index, model, sf, node, out)
                _check_then_act(index, model, sf, node, out)
        _module_global_races(index, model, sf, out)
    return out
