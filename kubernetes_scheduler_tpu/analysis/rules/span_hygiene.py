"""span-hygiene: emitted span (stage) names are registered, well-formed,
and never removed once shipped.

Span names became an API the moment `spans report` grew an attribution
table: the per-stage budget rows, `spans diff`'s regression gate, the
Grafana panels over stage latencies, and Perfetto bookmarks all
reference stages by NAME, long after the emitting code was refactored —
exactly the contract metric names acquired in the metric-hygiene
family, applied to the span layer. Checked in every in-scope file:

- **Name shape** — every emitted name is a non-empty
  `lower_snake_case` identifier (a renamed or typo'd stage silently
  drops out of every report keyed on the old name).
- **The shipped registry** — a `SHIPPED_SPANS` tuple
  (host/observe.py) pins every stage name ever emitted. An emitted
  name missing from the registry is flagged (adding a stage is a
  conscious, reviewable act: the attribution table and dashboards need
  to know about it); a registered name no longer emitted anywhere is
  flagged (a removed stage silently zeroes the budget row and every
  `spans diff` baseline that references it). Registry checks only run
  when a SHIPPED_SPANS declaration is in scope (fixture files carry
  their own).

Emission sites the rule understands (the package's only span surfaces):
`<x>._span("name", ...)` (Scheduler's per-cycle helper),
`<x>.add("name", t0, t1, ...)` (SpanSet.add — three or more positional
args, which keeps ordinary `set.add(value)` calls out of scope), and
`<x>.span("name")` (SpanSet's context manager).
"""

from __future__ import annotations

import ast
import re

from kubernetes_scheduler_tpu.analysis import dataflow
from kubernetes_scheduler_tpu.analysis.core import Context, Violation

RULE = "span-hygiene"

SCOPE = ("kubernetes_scheduler_tpu/**/*.py", "kubernetes_scheduler_tpu/*.py")

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def _const_str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _emitted_name(call: ast.Call) -> str | None:
    """The span name a call emits, or None when the call is not a span
    emission site. `.add` needs >= 3 positional args (name, t0, t1) so
    `set.add(x)` / protobuf `repeated.add(...)` never match."""
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return None
    if fn.attr == "_span" and call.args:
        return _const_str(call.args[0])
    if fn.attr == "add" and len(call.args) >= 3:
        return _const_str(call.args[0])
    if fn.attr == "span" and call.args:
        return _const_str(call.args[0])
    return None


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    # name -> (path, line) of the first emission site
    emitted: dict[str, tuple] = {}
    # (path, line, names) per SHIPPED_SPANS declaration
    registries: list[tuple] = []

    for sf in ctx.scoped(SCOPE):
        for node in dataflow.get_index(ctx).walk(sf):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Name)
                        and t.id == "SHIPPED_SPANS"
                        and isinstance(node.value, (ast.Tuple, ast.List))
                    ):
                        names = []
                        seen: set[str] = set()
                        for el in node.value.elts:
                            s = _const_str(el)
                            if s is None:
                                continue
                            if s in seen:
                                out.append(Violation(
                                    RULE, sf.path, el.lineno,
                                    f"span `{s}` registered twice in "
                                    "SHIPPED_SPANS",
                                ))
                            seen.add(s)
                            names.append(s)
                        registries.append((sf.path, node.lineno, names))
            elif isinstance(node, ast.Call):
                name = _emitted_name(node)
                if name is None:
                    continue
                if not _NAME_RE.match(name):
                    out.append(Violation(
                        RULE, sf.path, node.lineno,
                        f"span name {name!r} is not lower_snake_case — "
                        "reports and dashboards key stages by name, so "
                        "names follow one shape",
                    ))
                    continue
                emitted.setdefault(name, (sf.path, node.lineno))

    if registries:
        shipped: dict[str, tuple] = {}
        for path, line, names in registries:
            for n in names:
                shipped.setdefault(n, (path, line))
        for name, (path, line) in sorted(emitted.items()):
            if name not in shipped:
                out.append(Violation(
                    RULE, path, line,
                    f"span `{name}` is not registered in SHIPPED_SPANS "
                    "— append it (and never remove it): `spans report` "
                    "attribution tables and dashboards reference stages "
                    "by name",
                ))
        for name, (path, line) in sorted(shipped.items()):
            if name not in emitted:
                out.append(Violation(
                    RULE, path, line,
                    f"shipped span `{name}` is no longer emitted "
                    "anywhere — a removed stage silently zeroes its "
                    "budget row and every `spans diff` baseline that "
                    "references it",
                ))
    return out
