"""donation-aliasing: a value passed through a donated position is read
again on a path after the call — including through helper functions and
across modules.

`donate_argnums` hands the buffer's storage to XLA: by the time the call
returns, the donated array may already back the OUTPUT, so any later
read returns garbage on some backends and a deleted-buffer error on
others. This repo has been bitten once (the resident-state
`apply_snapshot_delta` path); the single-file AST check that came out of
that incident could not see the two shapes this interprocedural version
exists for:

- the donator is defined in ANOTHER module (`engine.apply_snapshot_delta`
  called from host/scheduler.py or bridge/server.py) — resolved through
  the project import index;
- the donation happens inside a HELPER (`def step(s): return
  apply_snapshot_delta(s, d)`) — donation summaries propagate to a
  fixpoint, so `step(snap); snap.sum()` is flagged in the caller.

Also tracked: attribute-chain arguments (`self._state.snapshot` donated
and re-read — the session-keyed resident maps), and donating
`jax.device_put(x, ..., donate=True)`.

Rebinding the result to the donated name (`x = f(x)`, or assigning any
prefix of the donated attribute chain) clears the taint — that IS the
idiomatic donation pattern. A load in a mutually exclusive branch arm is
not a read-after-donation (branch-path prefixes, same discipline as the
original check: precision over recall, because this gate fails
`make lint`).
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    Violation,
    dotted_name,
)
from kubernetes_scheduler_tpu.analysis import dataflow

RULE = "donation-aliasing"

SCOPE = ("kubernetes_scheduler_tpu/**/*.py", "kubernetes_scheduler_tpu/*.py")


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    index = dataflow.get_index(ctx)
    donors = dataflow.donation_summaries(index)
    scoped = set(id(sf) for sf in ctx.scoped(SCOPE))
    for fi in index.funcs.values():
        if id(fi.sf) not in scoped:
            continue
        du = dataflow.def_use(fi.node)
        # (call line, call end line, donated name, callee, branch path)
        donations: list[tuple[int, int, str, str, tuple]] = []
        for line, call, path in du.calls:
            end = call.end_lineno or line
            dp = dataflow.donated_device_put_arg(call)
            if dp is not None:
                nm = dotted_name(dp)
                if nm:
                    donations.append((line, end, nm, "jax.device_put", path))
                continue
            positions: set[int] = set()
            for cand in index.resolve_call(fi, call):
                positions.update(donors.get(cand.qname, ()))
            if not positions:
                continue
            callee = dotted_name(call.func) or "<call>"
            for i in sorted(positions):
                if i < len(call.args):
                    nm = dotted_name(call.args[i])
                    if nm:
                        donations.append((line, end, nm, callee, path))
        if not donations:
            continue
        # one finding per (name, line) across ALL donations: `f(a);
        # f(a); a.sum()` is one bad re-read, not one per earlier call
        flagged: set[tuple[str, int]] = set()
        for call_line, call_end, name, callee, cpath in donations:
            for load_line, nm, lpath in du.loads:
                # loads inside the donating call's own (possibly
                # multi-line) expression are the argument itself
                if load_line <= call_end or (name, load_line) in flagged:
                    continue
                if nm != name and not nm.startswith(name + "."):
                    continue
                if not dataflow.path_prefix(cpath, lpath):
                    continue  # mutually exclusive arm / sibling branch
                if any(
                    (nm2 == name or name.startswith(nm2 + ".")
                     or nm2.startswith(name + "."))
                    and call_line <= aline <= load_line
                    and dataflow.path_prefix(apath, lpath)
                    for aline, nm2, apath in du.assigns
                ):
                    continue  # rebound (x = f(x)) before the read
                flagged.add((name, load_line))
                out.append(
                    Violation(
                        RULE, fi.sf.path, load_line,
                        f"`{name}` re-read after being donated to "
                        f"`{callee}` — the buffer may already be reused "
                        "for the output; rebind the result to the name "
                        "instead",
                    )
                )
    return out
