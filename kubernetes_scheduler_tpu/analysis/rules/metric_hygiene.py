"""metric-hygiene: exported metric names are documented, unit-suffixed,
and never removed once shipped.

Metrics are an API: dashboards, alerts, and the SLO review reference
them by NAME, long after the code that emitted them was refactored. The
reference exported nothing (SURVEY.md §5); now that this scheduler and
its sidecar export real surfaces (host/observe.py `render_prometheus`
gauges + the labeled Histogram/Counter/Gauge layer), the names need the
same schema discipline the wire-schema family gives proto fields and
journal tags. Checked in every in-scope file:

- **HELP coverage** — keys of a `*_HELP` dict literal must carry a
  non-empty help string, and every metric emitted through the runtime
  `extra` side channel (`extra.update(name_total=...)` /
  `extra["name_total"] = ...`) must have a HELP entry declared
  somewhere in scope: render_prometheus falls back to an empty HELP
  line at runtime, but an undocumented metric is a lint failure.
- **Unit suffixes** — every name ends in a unit (`_seconds`, `_bytes`,
  `_per_sec`, ...) or `_total`; `Counter(...)` names must end `_total`
  specifically (Prometheus counter convention).
- **Help text** — `Histogram(...)`/`Counter(...)`/`Gauge(...)`
  constructions must pass a non-empty help string (second positional or
  `help=`).
- **The shipped registry** — a `SHIPPED_METRICS` tuple (observe.py)
  pins every name ever exported. A pinned name no longer declared
  anywhere in scope is flagged (a removed metric silently zeroes
  dashboards); a declared name missing from the registry is flagged so
  adding a metric is a conscious, reviewable act. Registry checks only
  run when a SHIPPED_METRICS declaration is in scope (fixture files
  carry their own).
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis import dataflow
from kubernetes_scheduler_tpu.analysis.core import Context, Violation

RULE = "metric-hygiene"

SCOPE = ("kubernetes_scheduler_tpu/**/*.py", "kubernetes_scheduler_tpu/*.py")

# the unit vocabulary: `_total` for counters, real units for everything
# else. `_count` covers live-object gauges (resident_sessions_count);
# `_mean`/`_per_sec` are shipped derived-statistic names; `_rung` is
# the degradation ladder's position unit (host/resilience.py — 0 = top).
UNIT_SUFFIXES = (
    "_total", "_seconds", "_bytes", "_ratio", "_per_sec", "_count",
    "_mean", "_info", "_rung",
)

_COLLECTOR_CTORS = {"Histogram", "Counter", "Gauge"}


def _const_str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _ctor_name(call: ast.Call) -> str | None:
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None
    )
    return name if name in _COLLECTOR_CTORS else None


def _suffix_ok(name: str) -> bool:
    return any(name.endswith(s) for s in UNIT_SUFFIXES)


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    # name -> (path, line) of a declaration (HELP key or collector ctor)
    declared: dict[str, tuple] = {}
    # names emitted through the runtime `extra` side channel
    emitted_extra: dict[str, tuple] = {}
    help_keys: set[str] = set()
    # (path, line, tuple_of_names) per SHIPPED_METRICS declaration
    registries: list[tuple] = []

    for sf in ctx.scoped(SCOPE):
        for node in dataflow.get_index(ctx).walk(sf):
            # ---- *_HELP dict literals ---------------------------------
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    tname = t.id if isinstance(t, ast.Name) else None
                    if tname and "HELP" in tname and isinstance(
                        node.value, ast.Dict
                    ):
                        seen: set[str] = set()
                        for k, v in zip(
                            node.value.keys, node.value.values
                        ):
                            key = _const_str(k)
                            if key is None:
                                continue
                            if key in seen:
                                out.append(Violation(
                                    RULE, sf.path, k.lineno,
                                    f"metric `{key}` declared twice in "
                                    f"{tname}",
                                ))
                            seen.add(key)
                            help_keys.add(key)
                            declared.setdefault(
                                key, (sf.path, k.lineno)
                            )
                            if not _suffix_ok(key):
                                out.append(Violation(
                                    RULE, sf.path, k.lineno,
                                    f"metric `{key}` has no unit suffix "
                                    f"— names must end in one of "
                                    f"{UNIT_SUFFIXES}",
                                ))
                            text = _const_str(v)
                            if not text:
                                out.append(Violation(
                                    RULE, sf.path, k.lineno,
                                    f"metric `{key}` has an empty HELP "
                                    "string — document what the number "
                                    "means",
                                ))
                    if (
                        tname == "SHIPPED_METRICS"
                        and isinstance(node.value, (ast.Tuple, ast.List))
                    ):
                        names = tuple(
                            s
                            for el in node.value.elts
                            if (s := _const_str(el)) is not None
                        )
                        registries.append((sf.path, node.lineno, names))
                # extra["name"] = ... (the exporter side channel)
                for t in node.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "extra"
                    ):
                        key = _const_str(t.slice)
                        if key is not None:
                            emitted_extra.setdefault(
                                key, (sf.path, t.lineno)
                            )

            # ---- collector constructions ------------------------------
            elif isinstance(node, ast.Call):
                ctor = _ctor_name(node)
                if ctor is not None and node.args:
                    name = _const_str(node.args[0])
                    if name is None:
                        continue
                    declared.setdefault(name, (sf.path, node.lineno))
                    if ctor == "Counter" and not name.endswith("_total"):
                        out.append(Violation(
                            RULE, sf.path, node.lineno,
                            f"Counter `{name}` must end in `_total` "
                            "(Prometheus counter convention)",
                        ))
                    elif not _suffix_ok(name):
                        out.append(Violation(
                            RULE, sf.path, node.lineno,
                            f"{ctor} `{name}` has no unit suffix — "
                            f"names must end in one of {UNIT_SUFFIXES}",
                        ))
                    help_arg = None
                    if len(node.args) > 1:
                        help_arg = node.args[1]
                    else:
                        for kw in node.keywords:
                            if kw.arg == "help":
                                help_arg = kw.value
                    if help_arg is None or not _const_str(help_arg):
                        out.append(Violation(
                            RULE, sf.path, node.lineno,
                            f"{ctor} `{name}` has no (or an empty) help "
                            "string — document what the number means",
                        ))
                # extra.update(name_total=...)
                fn = node.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr == "update"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "extra"
                ):
                    for kw in node.keywords:
                        if kw.arg is not None:
                            emitted_extra.setdefault(
                                kw.arg, (sf.path, node.lineno)
                            )

    # ---- cross-file contracts ---------------------------------------
    for name, (path, line) in sorted(emitted_extra.items()):
        if name not in help_keys:
            out.append(Violation(
                RULE, path, line,
                f"metric `{name}` is emitted through `extra` but has no "
                "HELP entry in any *_HELP table in scope",
            ))
        if not _suffix_ok(name):
            out.append(Violation(
                RULE, path, line,
                f"metric `{name}` has no unit suffix — names must end "
                f"in one of {UNIT_SUFFIXES}",
            ))

    if registries:
        shipped: dict[str, tuple] = {}
        for path, line, names in registries:
            for n in names:
                shipped.setdefault(n, (path, line))
        all_known = dict(declared)
        for n, where in emitted_extra.items():
            all_known.setdefault(n, where)
        for name, (path, line) in sorted(shipped.items()):
            if name not in all_known:
                out.append(Violation(
                    RULE, path, line,
                    f"shipped metric `{name}` is no longer declared "
                    "anywhere — a removed metric silently zeroes every "
                    "dashboard and alert that references it",
                ))
        for name, (path, line) in sorted(all_known.items()):
            if name not in shipped:
                out.append(Violation(
                    RULE, path, line,
                    f"metric `{name}` is not registered in "
                    "SHIPPED_METRICS — append it (and never remove it)",
                ))
    return out
