"""jit-purity: no side effects inside functions reachable from jax.jit.

A jitted program traces once and replays as XLA: a `print` fires only at
trace time (silently lying thereafter), host RNG freezes its first draw
into the compiled artifact, and mutation of module state is a
trace-order-dependent heisenbug. Flagged inside the jit-reachable set:

- calls into host-side effect land: print/open/input/breakpoint,
  time.*, logging, stdlib random.* and np.random.* (jax.random is fine —
  it is functional);
- `global` / `nonlocal` declarations;
- assignments through an attribute/subscript whose base name is not a
  local binding (module-state mutation at trace time).
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    Violation,
    dotted_name,
)
from kubernetes_scheduler_tpu.analysis.rules._jitgraph import jit_reachable

RULE = "jit-purity"

SCOPE = (
    "kubernetes_scheduler_tpu/engine.py",
    "kubernetes_scheduler_tpu/ops/*.py",
    "kubernetes_scheduler_tpu/parallel/*.py",
    "kubernetes_scheduler_tpu/models/*.py",
)

_BANNED_EXACT = {"print", "input", "open", "breakpoint", "exec", "eval"}
_BANNED_PREFIX = (
    "time.", "random.", "np.random.", "numpy.random.", "logging.",
    "log.", "os.", "sys.stdout.", "sys.stderr.",
)


def _local_names(fn: ast.AST) -> set:
    """Parameters + every name bound by assignment/for/with/comprehension
    inside `fn` (nested defs excluded — they have their own scopes)."""
    names: set[str] = set()
    args = fn.args
    for a in (
        args.posonlyargs + args.args + args.kwonlyargs
        + ([args.vararg] if args.vararg else [])
        + ([args.kwarg] if args.kwarg else [])
    ):
        names.add(a.arg)

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                names.add(child.name)
                continue  # separate scope
            if isinstance(child, ast.Name) and isinstance(
                child.ctx, ast.Store
            ):
                names.add(child.id)
            walk(child)

    walk(fn)
    return names


def _base_name(node: ast.AST) -> str | None:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    files = ctx.scoped(SCOPE)
    for sf, fn in jit_reachable(ctx, files):
        local = _local_names(fn)
        for node in ast.walk(fn):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                out.append(
                    Violation(
                        RULE, sf.path, node.lineno,
                        f"`{type(node).__name__.lower()}` inside "
                        f"jit-reachable `{fn.name}` mutates outer state "
                        "at trace time",
                    )
                )
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name is None:
                    continue
                if name in _BANNED_EXACT or any(
                    name.startswith(p) for p in _BANNED_PREFIX
                ):
                    out.append(
                        Violation(
                            RULE, sf.path, node.lineno,
                            f"side-effecting call `{name}(...)` inside "
                            f"jit-reachable `{fn.name}` (fires at trace "
                            "time only)",
                        )
                    )
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if not isinstance(t, (ast.Attribute, ast.Subscript)):
                        continue
                    base = _base_name(t)
                    if base is not None and base not in local:
                        out.append(
                            Violation(
                                RULE, sf.path, node.lineno,
                                f"jit-reachable `{fn.name}` assigns "
                                f"through non-local `{base}` (module-state "
                                "mutation at trace time)",
                            )
                        )
    return out
