"""Shared jit call-graph: which functions are reachable from jax.jit /
pjit entry points.

Resolution is name-based and conservative: every FunctionDef (nested
included) across the given files is indexed by bare name; a call or a
bare function reference (e.g. `lax.scan(step, ...)`) to a known name
marks every same-named def reachable. Over-approximation flags at worst
an extra site — the waiver syntax absorbs those — while attribute calls
on `self.` are skipped so host-object plumbing never leaks in.
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis.core import SourceFile, dotted_name

_JIT_MAKERS = {"jit", "jax.jit", "pjit", "jax.experimental.pjit.pjit"}


def _decorator_is_jit(dec: ast.AST) -> bool:
    name = dotted_name(dec)
    if name in _JIT_MAKERS:
        return True
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func)
        if fname in _JIT_MAKERS:
            return True
        # functools.partial(jax.jit, ...) / partial(jit, ...)
        if fname in ("functools.partial", "partial") and dec.args:
            return dotted_name(dec.args[0]) in _JIT_MAKERS
    return False


def _collect_defs(files: list[SourceFile]):
    """name -> [(SourceFile, FunctionDef)] over every def, nested included."""
    defs: dict[str, list] = {}
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append((sf, node))
    return defs


def _entry_names(files: list[SourceFile]) -> set[str]:
    entries: set[str] = set()
    for sf in files:
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_decorator_is_jit(d) for d in node.decorator_list):
                    entries.add(node.name)
            elif isinstance(node, ast.Call):
                # jax.jit(fn, ...) applied as an expression
                if dotted_name(node.func) in _JIT_MAKERS:
                    for arg in node.args[:1]:
                        name = dotted_name(arg)
                        if name:
                            entries.add(name.rsplit(".", 1)[-1])
    return entries


def _referenced_names(fn: ast.AST) -> set[str]:
    """Names this function may transfer control to: called names and
    bare function references passed as call arguments (scan/vmap/cond
    bodies). `self.x(...)` attribute chains are skipped — bound host
    objects are not kernel code."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        cname = dotted_name(node.func)
        if cname and not cname.startswith("self."):
            out.add(cname.rsplit(".", 1)[-1])
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            aname = dotted_name(arg)
            if aname and not aname.startswith("self."):
                out.add(aname.rsplit(".", 1)[-1])
    return out


def jit_reachable(files: list[SourceFile]):
    """[(SourceFile, FunctionDef)] reachable from any jit entry point in
    `files`, the entry defs included."""
    defs = _collect_defs(files)
    seen_ids: set[int] = set()
    out = []
    queue = sorted(_entry_names(files))
    visited_names: set[str] = set()
    while queue:
        name = queue.pop()
        if name in visited_names:
            continue
        visited_names.add(name)
        for sf, fn in defs.get(name, ()):
            if id(fn) in seen_ids:
                continue
            seen_ids.add(id(fn))
            out.append((sf, fn))
            for ref in _referenced_names(fn):
                if ref in defs and ref not in visited_names:
                    queue.append(ref)
    return out
