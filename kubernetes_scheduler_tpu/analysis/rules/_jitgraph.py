"""Shared jit call-graph: which functions are reachable from jax.jit /
pjit entry points.

Resolution is name-based and conservative: every FunctionDef (nested
included) across the given files is indexed by bare name; a call or a
bare function reference (e.g. `lax.scan(step, ...)`) to a known name
marks every same-named def reachable. Over-approximation flags at worst
an extra site — the waiver syntax absorbs those — while attribute calls
on `self.` are skipped so host-object plumbing never leaks in.

This traversal deliberately DIFFERS from `ModuleIndex.jit_reachable()`:
it is scoped to the caller's `files` (each family polices its own
SCOPE, while the index always answers for the whole project), and its
bare-name resolution marks EVERY same-named def reachable rather than
resolving through imports. Only the entry detection
(`_decorator_is_jit` / `_JIT_MAKERS`, imported below) must stay
shared — a new jit spelling belongs in dataflow, nowhere else.
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis.core import SourceFile, dotted_name

# ONE jit-entry detector for the whole package: _jitgraph and the
# ModuleIndex must never disagree about what is jit-reachable (a new
# jit spelling added in only one place would silently split the
# families' notions of the kernel set)
from kubernetes_scheduler_tpu.analysis.dataflow import (  # noqa: E402
    _JIT_MAKERS,
    _decorator_is_jit,
)


def _collect_defs(ctx, files: list[SourceFile]):
    """name -> [(SourceFile, FunctionDef)] over every def, nested
    included — read off the run's shared walk-once index."""
    from kubernetes_scheduler_tpu.analysis import dataflow

    index = dataflow.get_index(ctx)
    defs: dict[str, list] = {}
    for sf in files:
        for node in index.walk(sf):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append((sf, node))
    return defs


def _entry_names(ctx, files: list[SourceFile]) -> set[str]:
    from kubernetes_scheduler_tpu.analysis import dataflow

    index = dataflow.get_index(ctx)
    entries: set[str] = set()
    for sf in files:
        for node in index.walk(sf):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_decorator_is_jit(d) for d in node.decorator_list):
                    entries.add(node.name)
            elif isinstance(node, ast.Call):
                # jax.jit(fn, ...) applied as an expression
                if dotted_name(node.func) in _JIT_MAKERS:
                    for arg in node.args[:1]:
                        name = dotted_name(arg)
                        if name:
                            entries.add(name.rsplit(".", 1)[-1])
    return entries


def _referenced_names(fn: ast.AST) -> set[str]:
    """Names this function may transfer control to: called names and
    bare function references passed as call arguments (scan/vmap/cond
    bodies). `self.x(...)` attribute chains are skipped — bound host
    objects are not kernel code."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        cname = dotted_name(node.func)
        if cname and not cname.startswith("self."):
            out.add(cname.rsplit(".", 1)[-1])
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            aname = dotted_name(arg)
            if aname and not aname.startswith("self."):
                out.add(aname.rsplit(".", 1)[-1])
    return out


def jit_reachable(ctx, files: list[SourceFile]):
    """[(SourceFile, FunctionDef)] reachable from any jit entry point in
    `files`, the entry defs included."""
    defs = _collect_defs(ctx, files)
    seen_ids: set[int] = set()
    out = []
    queue = sorted(_entry_names(ctx, files))
    visited_names: set[str] = set()
    while queue:
        name = queue.pop()
        if name in visited_names:
            continue
        visited_names.add(name)
        for sf, fn in defs.get(name, ()):
            if id(fn) in seen_ids:
                continue
            seen_ids.add(id(fn))
            out.append((sf, fn))
            for ref in _referenced_names(fn):
                if ref in defs and ref not in visited_names:
                    queue.append(ref)
    return out
