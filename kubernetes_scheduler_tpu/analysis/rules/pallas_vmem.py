"""pallas-vmem: Pallas kernel hygiene — tiling, VMEM budget, f32
accumulators, no host callbacks in kernel bodies.

A Pallas kernel runs inside one XLA custom call: the grid steps over
(block)-shaped tiles resident in VMEM, so the static facts that make or
break it are checkable from the AST:

- tiling: every resolvable BlockSpec block shape must divide the padded
  axes it tiles — on TPU the minor (lane) dimension must be a multiple
  of 128; a non-dividing block forces a relayout on every grid step and
  leaves ragged tail tiles the kernel body never sees (the host pads TO
  the tile — `_pad_axis(x, axis, tile)` in ops/pallas_fused.py — so a
  128-aligned tile divides by construction);
- VMEM budget: the summed bytes of all resolvable blocks (in_specs +
  out_specs, f32) must leave double-buffering headroom under the
  ~16 MB/core VMEM — an over-budget block set fails at compile time on
  hardware but silently "works" under the interpreter;
- accumulators stay f32: a reduced-precision accumulator (bfloat16/
  float16 dtype on zeros/full/sum/dot, or .astype inside the body)
  loses mantissa on long reductions and diverges from the unfused
  reference path the parity tests pin;
- no host callbacks inside kernel bodies: jax.debug.print/callback,
  io_callback, pure_callback, plain print — none can fire from inside a
  TPU kernel (they fail late on hardware or silently no-op under
  interpret mode, hiding the breakage until deployment).

Unresolvable dimensions (runtime values like `n_res`) are skipped, not
guessed — the rule only reports what the AST proves.
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis import dataflow
from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    Violation,
    dotted_name,
)

RULE = "pallas-vmem"

SCOPE = ("kubernetes_scheduler_tpu/ops/pallas_*.py",)

LANE = 128                      # TPU minor-axis tiling (f32 lanes)
VMEM_BUDGET_BYTES = 14 << 20    # ~16 MB/core minus double-buffer headroom

_HOST_CALLBACKS = {
    "print",
    "breakpoint",
    "jax.debug.print",
    "jax.debug.callback",
    "jax.pure_callback",
    "jax.experimental.io_callback",
    "io_callback",
    "pure_callback",
    "host_callback.call",
    "hcb.call",
}

_LOW_PRECISION = {
    "jnp.bfloat16", "jnp.float16", "jax.numpy.bfloat16",
    "jax.numpy.float16", "np.float16", "numpy.float16",
    "bfloat16", "float16",
}

# accumulation/materialization calls whose dtype defines an accumulator
_ACC_FUNCS = (
    "zeros", "zeros_like", "full", "ones", "empty", "sum", "cumsum",
    "dot", "matmul", "einsum", "dot_general", "astype",
)


def _dtype_token(node: ast.AST) -> str | None:
    """'jnp.bfloat16' for attribute chains, 'bfloat16' for strings."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return dotted_name(node)


def _module_consts(tree: ast.AST) -> dict[str, int]:
    out: dict[str, int] = {}
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if (
                isinstance(t, ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)
            ):
                out[t.id] = node.value.value
    return out


def _fn_bindings(fn: ast.AST, consts: dict[str, int]) -> dict[str, int]:
    """Parameter defaults + simple local int assigns, resolved against
    the module constants (`tile_p: int = TILE_P` resolves through
    TILE_P = 256) — arithmetic assigns included, so the per-shard
    `n_local = N_NODES // MESH_DEVICES` split a shard_map'd kernel
    tiles over resolves to the per-shard dimension. A name assigned
    more than once, or a local assign shadowing a parameter/module
    constant (`n_loc = n_loc // 2`), is UNRESOLVABLE — skipped, not
    guessed: a single flow-insensitive value would check some
    BlockSpec in the function against the wrong dimension."""
    out = dict(consts)
    args = fn.args
    named = args.posonlyargs + args.args + args.kwonlyargs
    defaults = args.defaults + args.kw_defaults
    for a, d in zip(named[len(named) - len(defaults):], defaults):
        if d is None:
            continue
        if isinstance(d, ast.Constant) and isinstance(d.value, int):
            out[a.arg] = d.value
        elif isinstance(d, ast.Name) and d.id in consts:
            out[a.arg] = consts[d.id]
    assigns: dict[str, list] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                assigns.setdefault(t.id, []).append(node.value)
    poisoned = {
        name
        for name, values in assigns.items()
        if len(values) > 1 or name in out
    }
    for name in poisoned:
        out.pop(name, None)
    # fixpoint: single-assigned fresh names may reference each other
    # in any ast.walk order
    changed = True
    while changed:
        changed = False
        for name, values in assigns.items():
            if name in poisoned or name in out:
                continue
            v = _resolve_expr(values[0], out)
            if v is not None:
                out[name] = v
                changed = True
    return out


def _resolve_expr(node: ast.AST, env: dict[str, int]) -> int | None:
    """Resolve a dimension expression to an int where the AST proves it:
    constants, bound names, and +/-/*/'//' arithmetic over resolvable
    operands — the `4 * n_sel`-style stacked-row shapes the fused
    megakernel's BlockSpecs use, and the `n // MESH_DEVICES` per-shard
    node-axis split a kernel invoked under shard_map tiles over (the
    node axis is divided by the mesh size BEFORE tiling, so the lane
    check must see the per-shard dimension, not the global one). A
    runtime operand anywhere makes the whole dimension unresolvable,
    skipped not guessed; a floor division that does not divide evenly
    is likewise skipped — the true per-shard dim is not what the
    expression computes, and shard_map would reject the layout first."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.Add, ast.Sub, ast.Mult, ast.FloorDiv)
    ):
        left = _resolve_expr(node.left, env)
        right = _resolve_expr(node.right, env)
        if left is None or right is None:
            return None
        if isinstance(node.op, ast.Add):
            return left + right
        if isinstance(node.op, ast.Sub):
            return left - right
        if isinstance(node.op, ast.FloorDiv):
            if right == 0 or left % right:
                return None
            return left // right
        return left * right
    return None


def _resolve_dims(shape: ast.AST, env: dict[str, int]) -> list[int | None]:
    if not isinstance(shape, ast.Tuple):
        return []
    # a non-positive resolution (a - b with a < b) is a wrong guess, not
    # a provable dimension — treat it as unresolvable so it can never
    # SUBTRACT from the VMEM total
    return [
        v if v is None or v > 0 else None
        for v in (_resolve_expr(el, env) for el in shape.elts)
    ]


def _block_specs(call: ast.Call):
    """Every BlockSpec(...) Call under in_specs/out_specs."""
    for kw in call.keywords:
        if kw.arg not in ("in_specs", "out_specs"):
            continue
        roots = (
            kw.value.elts
            if isinstance(kw.value, (ast.List, ast.Tuple))
            else [kw.value]
        )
        for node in roots:
            if isinstance(node, ast.Call) and (
                (dotted_name(node.func) or "").rsplit(".", 1)[-1]
                == "BlockSpec"
            ):
                yield node


def _spec_shape(spec: ast.Call) -> ast.AST | None:
    if spec.args:
        return spec.args[0]
    for kw in spec.keywords:
        if kw.arg == "block_shape":
            return kw.value
    return None


def _kernel_names(call: ast.Call) -> list[str]:
    """The kernel function name(s) a pallas_call dispatches, unwrapping
    functools.partial."""
    if not call.args:
        return []
    k = call.args[0]
    if isinstance(k, ast.Call) and (
        (dotted_name(k.func) or "").rsplit(".", 1)[-1] == "partial"
    ):
        k = k.args[0] if k.args else None
    name = dotted_name(k) if k is not None else None
    return [name.rsplit(".", 1)[-1]] if name else []


def _check_kernel_body(fn: ast.AST, sf, out: list[Violation]) -> None:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name in _HOST_CALLBACKS:
            out.append(
                Violation(
                    RULE, sf.path, node.lineno,
                    f"host callback `{name}(...)` inside kernel body "
                    f"`{fn.name}` — cannot fire from a TPU kernel",
                )
            )
            continue
        tail = name.rsplit(".", 1)[-1] if name else (
            node.func.attr if isinstance(node.func, ast.Attribute) else None
        )
        if tail not in _ACC_FUNCS:
            continue
        cands = [kw.value for kw in node.keywords if kw.arg == "dtype"]
        if tail == "astype" and node.args:
            cands.append(node.args[0])
        for cand in cands:
            tok = _dtype_token(cand)
            if tok in _LOW_PRECISION:
                out.append(
                    Violation(
                        RULE, sf.path, node.lineno,
                        f"accumulator dtype `{tok}` inside kernel body "
                        f"`{fn.name}` — accumulate in f32 (cast on the "
                        "final store if a narrow output is wanted)",
                    )
                )


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    for sf in ctx.scoped(SCOPE):
        consts = _module_consts(sf.tree)
        fns = [
            n for n in dataflow.get_index(ctx).walk(sf)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        checked_kernels: set[str] = set()
        seen_calls: set[int] = set()
        for fn in fns:
            env = _fn_bindings(fn, consts)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if (dotted_name(node.func) or "").rsplit(".", 1)[-1] != (
                    "pallas_call"
                ):
                    continue
                # a call inside a nested def is walked by both scopes;
                # the inner (more local env) pass runs first in source
                # order only by accident — dedupe on identity
                if id(node) in seen_calls:
                    continue
                seen_calls.add(id(node))
                total_bytes = 0
                for spec in _block_specs(node):
                    dims = _resolve_dims(_spec_shape(spec), env)
                    if not dims:
                        continue
                    last = dims[-1]
                    if last is not None and last % LANE:
                        out.append(
                            Violation(
                                RULE, sf.path, spec.lineno,
                                f"BlockSpec minor axis {last} is not a "
                                f"multiple of {LANE}: the block cannot "
                                "divide the lane-padded axis (ragged "
                                "tail tiles + per-step relayout)",
                            )
                        )
                    if all(d is not None for d in dims):
                        size = 4
                        for d in dims:
                            size *= d
                        total_bytes += size
                if total_bytes > VMEM_BUDGET_BYTES:
                    out.append(
                        Violation(
                            RULE, sf.path, node.lineno,
                            f"resolvable blocks total "
                            f"{total_bytes / (1 << 20):.1f} MB — over the "
                            "~16 MB/core VMEM budget once double-buffered",
                        )
                    )
                for kname in _kernel_names(node):
                    if kname in checked_kernels:
                        continue
                    checked_kernels.add(kname)
                    for kfn in fns:
                        if kfn.name == kname:
                            _check_kernel_body(kfn, sf, out)
    return out
