"""dtype-shape: no float64 promotion or traced-bool branching in
kernels.

The engine is a float32 machine end to end (the codec's allowed dtypes,
the Pallas tiles, the wire contract): one float64 leaf silently doubles
transfer volume and, under jax's default x64-disabled config, produces
weights that differ between host and device paths. And a Python `if` on
a traced predicate (`.any()` / `.all()` / `.item()` / `bool(...)`) is a
TracerBoolConversionError at best, a trace-time-frozen branch at worst.

Flagged in the kernel dirs:

- dtype arguments / astype targets that resolve to float64 (`float`,
  `np.float64`, `jnp.float64`, `"float64"`, `"double"`);
- `if`/`while` tests inside jit-reachable functions that call
  `.any()` / `.all()` / `.item()` / `bool(...)` on traced values.

Donated-buffer re-reads, which this family caught per-file through
PR 8, moved to the interprocedural `donation-aliasing` family — it sees
cross-module donators and helper indirection this scan could not.

Static-shape branching (`if x.shape[0] < n:`) is idiomatic JAX and
deliberately NOT flagged — shapes are Python ints at trace time.
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    Violation,
    dotted_name,
)
from kubernetes_scheduler_tpu.analysis.rules._jitgraph import jit_reachable

RULE = "dtype-shape"

SCOPE = (
    "kubernetes_scheduler_tpu/engine.py",
    "kubernetes_scheduler_tpu/ops/*.py",
    "kubernetes_scheduler_tpu/parallel/*.py",
    "kubernetes_scheduler_tpu/models/*.py",
)

_F64_NAMES = {
    "float", "np.float64", "numpy.float64", "jnp.float64", "np.double",
    "numpy.double", "jnp.double",
}
_F64_STRINGS = {"float64", "double", "f8", "<f8"}


def _is_f64(node: ast.AST) -> bool:
    name = dotted_name(node)
    if name in _F64_NAMES:
        return True
    return (
        isinstance(node, ast.Constant)
        and isinstance(node.value, str)
        and node.value in _F64_STRINGS
    )


def _check_f64(ctx, sf, out: list[Violation]) -> None:
    from kubernetes_scheduler_tpu.analysis import dataflow

    for node in dataflow.get_index(ctx).walk(sf):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else None
        if attr == "astype" and node.args and _is_f64(node.args[0]):
            out.append(
                Violation(
                    RULE, sf.path, node.lineno,
                    "astype to float64 in kernel code (the engine is "
                    "float32 end to end)",
                )
            )
        for kw in node.keywords:
            if kw.arg == "dtype" and _is_f64(kw.value):
                out.append(
                    Violation(
                        RULE, sf.path, kw.value.lineno,
                        "float64 dtype argument in kernel code (the "
                        "engine is float32 end to end)",
                    )
                )


_TRACED_BOOL_ATTRS = {"any", "all", "item"}


def _traced_bool_call(test: ast.AST) -> ast.Call | None:
    for node in ast.walk(test):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in _TRACED_BOOL_ATTRS
        ):
            return node
        if isinstance(fn, ast.Name) and fn.id == "bool":
            return node
    return None


def _check_branching(sf, fn, out: list[Violation]) -> None:
    for node in ast.walk(fn):
        if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
            continue
        bad = _traced_bool_call(node.test)
        if bad is not None:
            what = dotted_name(bad.func) or "bool"
            out.append(
                Violation(
                    RULE, sf.path, node.test.lineno,
                    f"Python branch on `{what}(...)` inside jit-reachable "
                    f"`{fn.name}` — a traced predicate cannot drive host "
                    "control flow (use jnp.where / lax.cond)",
                )
            )


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    files = ctx.scoped(SCOPE)
    for sf in files:
        _check_f64(ctx, sf, out)
    for sf, fn in jit_reachable(ctx, files):
        _check_branching(sf, fn, out)
    return out
