"""dtype-shape: no float64 promotion, traced-bool branching, or
donated-buffer re-reads in kernels.

The engine is a float32 machine end to end (the codec's allowed dtypes,
the Pallas tiles, the wire contract): one float64 leaf silently doubles
transfer volume and, under jax's default x64-disabled config, produces
weights that differ between host and device paths. And a Python `if` on
a traced predicate (`.any()` / `.all()` / `.item()` / `bool(...)`) is a
TracerBoolConversionError at best, a trace-time-frozen branch at worst.

Flagged in the kernel dirs:

- dtype arguments / astype targets that resolve to float64 (`float`,
  `np.float64`, `jnp.float64`, `"float64"`, `"double"`);
- `if`/`while` tests inside jit-reachable functions that call
  `.any()` / `.all()` / `.item()` / `bool(...)` on traced values;
- re-reading a buffer after donating it to a `donate_argnums` jitted
  function (the resident-state apply_snapshot_delta signature): XLA may
  already have reused the donated storage for the output, so the read
  returns garbage (or a deleted-buffer error) depending on backend.
  Rebinding the name to the call's result (`x = f(x)`) is the idiomatic
  donation pattern and clears the taint.

Static-shape branching (`if x.shape[0] < n:`) is idiomatic JAX and
deliberately NOT flagged — shapes are Python ints at trace time.
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    Violation,
    dotted_name,
)
from kubernetes_scheduler_tpu.analysis.rules._jitgraph import jit_reachable

RULE = "dtype-shape"

SCOPE = (
    "kubernetes_scheduler_tpu/engine.py",
    "kubernetes_scheduler_tpu/ops/*.py",
    "kubernetes_scheduler_tpu/parallel/*.py",
    "kubernetes_scheduler_tpu/models/*.py",
)

_F64_NAMES = {
    "float", "np.float64", "numpy.float64", "jnp.float64", "np.double",
    "numpy.double", "jnp.double",
}
_F64_STRINGS = {"float64", "double", "f8", "<f8"}


def _is_f64(node: ast.AST) -> bool:
    name = dotted_name(node)
    if name in _F64_NAMES:
        return True
    return (
        isinstance(node, ast.Constant)
        and isinstance(node.value, str)
        and node.value in _F64_STRINGS
    )


def _check_f64(sf, tree, out: list[Violation]) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else None
        if attr == "astype" and node.args and _is_f64(node.args[0]):
            out.append(
                Violation(
                    RULE, sf.path, node.lineno,
                    "astype to float64 in kernel code (the engine is "
                    "float32 end to end)",
                )
            )
        for kw in node.keywords:
            if kw.arg == "dtype" and _is_f64(kw.value):
                out.append(
                    Violation(
                        RULE, sf.path, kw.value.lineno,
                        "float64 dtype argument in kernel code (the "
                        "engine is float32 end to end)",
                    )
                )


_TRACED_BOOL_ATTRS = {"any", "all", "item"}


def _traced_bool_call(test: ast.AST) -> ast.Call | None:
    for node in ast.walk(test):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in _TRACED_BOOL_ATTRS
        ):
            return node
        if isinstance(fn, ast.Name) and fn.id == "bool":
            return node
    return None


def _check_branching(sf, fn, out: list[Violation]) -> None:
    for node in ast.walk(fn):
        if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
            continue
        bad = _traced_bool_call(node.test)
        if bad is not None:
            what = dotted_name(bad.func) or "bool"
            out.append(
                Violation(
                    RULE, sf.path, node.test.lineno,
                    f"Python branch on `{what}(...)` inside jit-reachable "
                    f"`{fn.name}` — a traced predicate cannot drive host "
                    "control flow (use jnp.where / lax.cond)",
                )
            )


def _donated_positions(fn: ast.AST) -> tuple[int, ...]:
    """Positional argument indices a function donates, read off its
    decorators: `functools.partial(jax.jit, donate_argnums=...)` and
    `jax.jit(donate_argnums=...)` forms; () when it donates nothing."""
    for dec in getattr(fn, "decorator_list", ()):
        if not isinstance(dec, ast.Call):
            continue
        callee = dotted_name(dec.func)
        is_partial_jit = callee in ("functools.partial", "partial") and (
            dec.args and dotted_name(dec.args[0]) in ("jax.jit", "jit")
        )
        is_jit_call = callee in ("jax.jit", "jit")
        if not (is_partial_jit or is_jit_call):
            continue
        for kw in dec.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                )
    return ()


_FN_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_SUITE_FIELDS = ("body", "orelse", "finalbody")


def _shallow(node):
    """The node plus its expression-level children — never descending
    into nested suites (those get their own branch path) or nested
    function scopes (analyzed as their own functions)."""
    yield node
    for fname, value in ast.iter_fields(node):
        if fname in _SUITE_FIELDS or fname == "handlers":
            continue
        for child in value if isinstance(value, list) else [value]:
            if isinstance(child, ast.AST) and not isinstance(child, _FN_DEFS):
                yield from _shallow(child)


def _visit_suites(stmts, path, sink):
    """Walk statement suites recording each node's branch path — a tuple
    of (enclosing statement id, suite field) — so the donation check can
    tell 'after the call on the same control path' from a load in a
    mutually exclusive arm."""
    for st in stmts:
        if isinstance(st, _FN_DEFS):
            continue  # separate scope: iterated as its own function
        for node in _shallow(st):
            sink(node, path)
        for fname in _SUITE_FIELDS:
            suite = getattr(st, fname, None)
            if suite:
                _visit_suites(suite, path + ((id(st), fname),), sink)
        for h in getattr(st, "handlers", None) or ():
            _visit_suites(h.body, path + ((id(st), id(h)),), sink)


def _check_donation(sf, tree, out: list[Violation]) -> None:
    """Flag re-reads of a Name after it was passed in a donated position
    of a donate_argnums-jitted function defined in the same file. Only
    plain Name arguments are tracked (an attribute like `self._state`
    rebound right at the call site is the caller's own discipline); an
    assignment to the name at or after the call line — including the
    idiomatic `x = f(x)` rebind — clears the taint. A load is only
    flagged when the donating call's branch path is a prefix of the
    load's (the call structurally precedes it on the same control path):
    a read in the other arm of an `if` never executes after the
    donation, so it is not a violation (at the cost of missing a
    donation inside one arm read after the join — precision over
    recall, this gate fails `make lint`)."""
    donators: dict[str, tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            pos = _donated_positions(node)
            if pos:
                donators[node.name] = pos
    if not donators:
        return
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        calls: list[tuple[int, str, str, tuple]] = []
        assigns: list[tuple[int, str, tuple]] = []
        loads: list[tuple[int, str, tuple]] = []

        def sink(node, path):
            if isinstance(node, ast.Call):
                callee = dotted_name(node.func) or ""
                pos = donators.get(callee.split(".")[-1])
                if pos:
                    for i in pos:
                        if i < len(node.args) and isinstance(
                            node.args[i], ast.Name
                        ):
                            calls.append(
                                (node.lineno, node.args[i].id, callee, path)
                            )
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name):
                            assigns.append((node.lineno, leaf.id, path))
            elif isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ):
                loads.append((node.lineno, node.id, path))

        _visit_suites(fn.body, (), sink)

        def prefix(a, b):
            return b[: len(a)] == a

        for call_line, name, callee, cpath in calls:
            for load_line, nm, lpath in loads:
                if nm != name or load_line <= call_line:
                    continue
                if not prefix(cpath, lpath):
                    continue  # mutually exclusive arm / sibling branch
                if any(
                    nm2 == name
                    and call_line <= aline <= load_line
                    and prefix(apath, lpath)
                    for aline, nm2, apath in assigns
                ):
                    continue  # rebound (x = f(x)) before the read
                out.append(
                    Violation(
                        RULE, sf.path, load_line,
                        f"`{name}` re-read after being donated to "
                        f"`{callee}` (donate_argnums) — the buffer may "
                        "already be reused for the output; rebind the "
                        "result to the name instead",
                    )
                )


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    files = ctx.scoped(SCOPE)
    for sf in files:
        _check_f64(sf, sf.tree, out)
        _check_donation(sf, sf.tree, out)
    for sf, fn in jit_reachable(files):
        _check_branching(sf, fn, out)
    return out
