"""host-sync: no device barriers or per-element syncs in the cycle path.

The scheduling cycle's contract is ONE bulk host<->device sync per
dispatch (`np.asarray` on the whole result). Flagged in the cycle-path
files:

- `jax.block_until_ready(...)` / `.block_until_ready()` anywhere — a
  full device barrier has no place in the serving path (benchmarks waive
  it with a justification);
- `.item()` inside a loop/comprehension — on a device array this is one
  blocking transfer per element;
- `np.asarray(...)` / `jax.device_get(...)` inside a loop/comprehension
  — hoist one bulk conversion out of the loop instead.

Sites operating on host numpy by construction are waived inline — the
per-site triage IS the allow-list, kept next to the code it blesses.
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    Violation,
    dotted_name,
)

RULE = "host-sync"

SCOPE = (
    "kubernetes_scheduler_tpu/engine.py",
    "kubernetes_scheduler_tpu/host/scheduler.py",
    "kubernetes_scheduler_tpu/host/queue.py",
    "kubernetes_scheduler_tpu/host/observe.py",
    "kubernetes_scheduler_tpu/bridge/client.py",
    "kubernetes_scheduler_tpu/bridge/server.py",
)

_LOOPY_SYNCS = {"np.asarray", "numpy.asarray", "jax.device_get"}


def _iter_children_with_loop(node: ast.AST, in_loop: bool):
    """(child, in_loop) pairs. A loop's per-iteration parts (body, each
    element expression) count as in-loop; its once-evaluated parts do
    not — `for x in np.asarray(xs):` IS the recommended bulk hoist, and
    a comprehension's FIRST source iterable likewise runs exactly once."""
    if isinstance(node, (ast.For, ast.AsyncFor)):
        yield node.target, in_loop
        yield node.iter, in_loop  # evaluated once, before iteration
        for stmt in node.body + node.orelse:
            yield stmt, True
        return
    if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                         ast.GeneratorExp)):
        for i, gen in enumerate(node.generators):
            # the first generator's source is evaluated once; nested
            # generators' sources re-evaluate per outer iteration
            yield gen.iter, in_loop if i == 0 else True
            yield gen.target, True
            for cond in gen.ifs:
                yield cond, True
        if isinstance(node, ast.DictComp):
            yield node.key, True
            yield node.value, True
        else:
            yield node.elt, True
        return
    for child in ast.iter_child_nodes(node):
        yield child, in_loop or isinstance(child, ast.While)


def _visit(node: ast.AST, in_loop: bool, sf, out: list[Violation]) -> None:
    for child, child_in_loop in _iter_children_with_loop(node, in_loop):
        if isinstance(child, ast.Call):
            name = dotted_name(child.func)
            attr = (
                child.func.attr
                if isinstance(child.func, ast.Attribute)
                else None
            )
            if attr == "block_until_ready" or name == "jax.block_until_ready":
                out.append(
                    Violation(
                        RULE, sf.path, child.lineno,
                        "device barrier (block_until_ready) in the host "
                        "cycle path",
                    )
                )
            elif child_in_loop and attr == "item":
                out.append(
                    Violation(
                        RULE, sf.path, child.lineno,
                        ".item() inside a loop — one blocking device "
                        "transfer per element; sync once in bulk outside",
                    )
                )
            elif child_in_loop and name in _LOOPY_SYNCS:
                out.append(
                    Violation(
                        RULE, sf.path, child.lineno,
                        f"{name}() inside a loop — hoist one bulk "
                        "conversion out of the loop",
                    )
                )
        _visit(child, child_in_loop, sf, out)


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    for sf in ctx.scoped(SCOPE):
        _visit(sf.tree, False, sf, out)
    return out
