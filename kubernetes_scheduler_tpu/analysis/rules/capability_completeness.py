"""capability-completeness: every HealthReply capability bit is wired
end to end, and every RPC failure path invalidates the session.

A capability bit that exists in the proto but is only half-wired is the
version-skew bug factory: a bit the client probes but never invalidates
survives a mid-stream downgrade (the PR-3 class); a bit the server
never answers reads as permanently absent; a latch without a supports_*
accessor gates nothing. The contract, pinned here against
bridge/schedule.proto in BOTH directions:

- the client's `CAPABILITY_LATCHES` table names exactly the HealthReply
  bool fields, `_probe_capabilities` and `_invalidate_session` are
  table-driven (one probe resolves the set, one failure drops the set),
  and every latch attribute is read by at least one accessor method —
  a latch nobody reads gates nothing;
- the server's `CAPABILITY_SWITCHES` table names exactly the same
  fields, `health` renders through it, and every switch attribute is
  assigned in the class (a missing assignment would make Health raise
  — or worse, getattr-default its way to False);
- every method that sends through `self._call_with_retry` directly
  must reference `_invalidate_session` — the except-path discipline
  `_call_cached` implements, required of EVERY RPC surface (the
  Preempt path historically skipped it).

The table-driven shape is what makes the NEXT capability bit cheap:
add the proto field, one entry per table, one switch default, one
accessor — this family fails the build until all four exist, and the
parametrized downgrade regression tests pick the new entry up for
free. The probe/invalidate PROTOCOL itself (all-or-nothing latch
discipline under restart/downgrade interleavings) is model-checked by
analysis/model/; this family is the static side: the wiring exists.
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    Violation,
)
from kubernetes_scheduler_tpu.analysis.rules.wire_schema import (
    _proto_for,
    parse_proto_fields,
)

RULE = "capability-completeness"

SCOPE = (
    "kubernetes_scheduler_tpu/bridge/client.py",
    "kubernetes_scheduler_tpu/bridge/server.py",
)

_LATCH_TABLE = "CAPABILITY_LATCHES"
_SWITCH_TABLE = "CAPABILITY_SWITCHES"

_HEALTH_MSG = "HealthReply"


def health_bool_fields(proto_path: str) -> set[str]:
    """The bool fields of message HealthReply — the capability bits
    (wire_schema's one proto tokenizer, filtered on declared type)."""
    fields = parse_proto_fields(proto_path).get(_HEALTH_MSG, {})
    return {name for name, ftype in fields.items() if ftype == "bool"}


def _dict_literal(sf, name: str):
    """(lineno, {key: value}) for a module-level `name = {...}` of
    string constants, or None."""
    for node in sf.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == name for t in node.targets
        ):
            continue
        if not isinstance(node.value, ast.Dict):
            return node.lineno, None
        table = {}
        for k, v in zip(node.value.keys, node.value.values):
            if isinstance(k, ast.Constant) and isinstance(v, ast.Constant):
                table[str(k.value)] = str(v.value)
        return node.lineno, table
    return None


def _refs_name(fn: ast.AST, name: str) -> bool:
    """Does the CODE of `fn` reference `name`? AST-based, so a
    docstring or comment that merely MENTIONS the table cannot satisfy
    the check (the verify drive caught exactly that false negative:
    seeding the PR-3 bug left the docstring's table mention behind)."""
    return any(
        isinstance(n, ast.Name) and n.id == name for n in ast.walk(fn)
    )


def _refs_attr_of_self(fn: ast.AST, attr: str, *, ctx: type | None = None) -> bool:
    """Does `fn` access `self.<attr>`? `ctx=ast.Load` restricts to
    reads (a write-only reference is not an accessor), `ast.Store` to
    assignments."""
    for n in ast.walk(fn):
        if (
            isinstance(n, ast.Attribute)
            and n.attr == attr
            and isinstance(n.value, ast.Name)
            and n.value.id == "self"
            and (ctx is None or isinstance(n.ctx, ctx))
        ):
            return True
    return False


def _calls_self_method(fn: ast.AST, method: str) -> bool:
    for n in ast.walk(fn):
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == method
            and isinstance(n.func.value, ast.Name)
            and n.func.value.id == "self"
        ):
            return True
    return False


def _reaches_invalidate(fn: ast.AST) -> bool:
    """Any CODE reference to `_invalidate_session` (call or handler)."""
    for n in ast.walk(fn):
        if isinstance(n, ast.Attribute) and n.attr == "_invalidate_session":
            return True
        if isinstance(n, ast.Name) and n.id == "_invalidate_session":
            return True
    return False


def _methods(cls: ast.ClassDef):
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield item


def _class_with(sf, method_name: str) -> ast.ClassDef | None:
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef) and any(
            m.name == method_name for m in _methods(node)
        ):
            return node
    return None


def _check_table_vs_proto(sf, lineno, table, fields, what, out):
    for missing in sorted(fields - set(table)):
        out.append(Violation(
            RULE, sf.path, lineno,
            f"HealthReply bool `{missing}` is missing from {what} — a "
            "capability bit that is not in the table is never "
            f"{'latched/invalidated' if what == _LATCH_TABLE else 'advertised'}",
        ))
    for ghost in sorted(set(table) - fields):
        out.append(Violation(
            RULE, sf.path, lineno,
            f"{what} entry `{ghost}` names no HealthReply bool field — "
            "stale table entry (field renamed or removed in the proto?)",
        ))


def _check_client(sf, fields, out) -> None:
    hit = _dict_literal(sf, _LATCH_TABLE)
    if hit is None:
        out.append(Violation(
            RULE, sf.path, 1,
            f"bridge client module defines no {_LATCH_TABLE} table — "
            "capability latches must be declared in the one canonical "
            "table (probe/invalidate/tests all key off it)",
        ))
        return
    lineno, table = hit
    if table is None:
        out.append(Violation(
            RULE, sf.path, lineno,
            f"{_LATCH_TABLE} must be a literal dict of str -> str "
            "(proto field -> latch attribute)",
        ))
        return
    _check_table_vs_proto(sf, lineno, table, fields, _LATCH_TABLE, out)
    cls = _class_with(sf, "_invalidate_session")
    if cls is None:
        out.append(Violation(
            RULE, sf.path, lineno,
            "no class with `_invalidate_session` found beside "
            f"{_LATCH_TABLE} — the latch table has no consumer",
        ))
        return
    methods = {m.name: m for m in _methods(cls)}
    for fn_name in ("_probe_capabilities", "_invalidate_session"):
        fn = methods.get(fn_name)
        if fn is None:
            out.append(Violation(
                RULE, sf.path, cls.lineno,
                f"class {cls.name} has no `{fn_name}` — every capability "
                "latch must be probed and invalidated through the shared "
                "path",
            ))
        elif not _refs_name(fn, _LATCH_TABLE):
            out.append(Violation(
                RULE, sf.path, fn.lineno,
                f"`{cls.name}.{fn_name}` does not iterate "
                f"{_LATCH_TABLE} — a hand-rolled latch list WILL drift "
                "from the table the next time a bit is added (the PR-3 "
                "invalidate-together bug class)",
            ))
    # every latch needs an accessor: some method beyond the shared
    # probe/invalidate/init must READ the attribute, else nothing is
    # actually gated on the capability
    plumbing = {"_probe_capabilities", "_invalidate_session", "__init__"}
    for fieldname, attr in sorted(table.items()):
        readers = [
            m.name for m in _methods(cls)
            if m.name not in plumbing
            and _refs_attr_of_self(m, attr, ctx=ast.Load)
        ]
        if not readers:
            out.append(Violation(
                RULE, sf.path, lineno,
                f"latch `{attr}` (HealthReply.{fieldname}) has no "
                "accessor — no method outside the probe/invalidate "
                "plumbing reads it, so the capability gates nothing",
            ))
    # except-path discipline: a direct _call_with_retry sender must
    # reach _invalidate_session (directly or via its handlers)
    for m in _methods(cls):
        if m.name in ("_call_with_retry", "_invalidate_session"):
            continue
        if _calls_self_method(m, "_call_with_retry") and not \
                _reaches_invalidate(m):
            out.append(Violation(
                RULE, sf.path, m.lineno,
                f"`{cls.name}.{m.name}` sends through _call_with_retry "
                "but never reaches `_invalidate_session` — a failed RPC "
                "on this surface would leave the wire field cache and "
                "the capability latches trusting a sidecar that may "
                "have been replaced",
            ))


def _check_server(sf, fields, out) -> None:
    hit = _dict_literal(sf, _SWITCH_TABLE)
    if hit is None:
        out.append(Violation(
            RULE, sf.path, 1,
            f"bridge server module defines no {_SWITCH_TABLE} table — "
            "capability switches must be declared in the one canonical "
            "table health() renders through",
        ))
        return
    lineno, table = hit
    if table is None:
        out.append(Violation(
            RULE, sf.path, lineno,
            f"{_SWITCH_TABLE} must be a literal dict of str -> str "
            "(proto field -> switch attribute)",
        ))
        return
    _check_table_vs_proto(sf, lineno, table, fields, _SWITCH_TABLE, out)
    cls = _class_with(sf, "health")
    if cls is None:
        out.append(Violation(
            RULE, sf.path, lineno,
            "no class with a `health` method found beside "
            f"{_SWITCH_TABLE} — the switch table has no renderer",
        ))
        return
    health = next(m for m in _methods(cls) if m.name == "health")
    if not _refs_name(health, _SWITCH_TABLE):
        out.append(Violation(
            RULE, sf.path, health.lineno,
            f"`{cls.name}.health` does not render through "
            f"{_SWITCH_TABLE} — a bit added to the table would never "
            "reach the wire",
        ))
    for fieldname, attr in sorted(table.items()):
        if not _refs_attr_of_self(cls, attr, ctx=ast.Store):
            out.append(Violation(
                RULE, sf.path, lineno,
                f"switch `{attr}` (HealthReply.{fieldname}) is never "
                f"assigned in class {cls.name} — health() would raise "
                "(or default) instead of advertising a real capability",
            ))


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    for sf in ctx.scoped(SCOPE):
        has_latches = _dict_literal(sf, _LATCH_TABLE) is not None
        has_switches = _dict_literal(sf, _SWITCH_TABLE) is not None
        if ctx.explicit and not (has_latches or has_switches):
            continue  # fixture mode: only capability-shaped modules
        proto = _proto_for(ctx, sf)
        if proto is None:
            continue
        fields = health_bool_fields(proto)
        is_client = has_latches or sf.path.endswith("bridge/client.py")
        is_server = has_switches or sf.path.endswith("bridge/server.py")
        if is_client:
            _check_client(sf, fields, out)
        if is_server:
            _check_server(sf, fields, out)
    return out
