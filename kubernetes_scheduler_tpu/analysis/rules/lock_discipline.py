"""lock-discipline: attrs mutated under a class's lock stay under it.

For every class that takes a threading lock (`self._lock = Lock()` /
`RLock()`, or any `with self.<x>lock:` usage), the set of self-attributes
mutated inside a lock block in ANY method defines that class's guarded
state. Mutating a guarded attribute lock-free in another method (or
outside the lock in the same method) is the cross-thread torn-write
pattern the advisor/queue/bridge classes are built to avoid.

`__init__` is exempt (construction happens-before publication). A
helper method that mutates guarded state with the lock held BY ITS
CALLER does fire (the rule cannot see call-site locking) — waive it
inline, naming the callers that hold the lock; the helper's own writes
never count as guarded. Mutations through local aliases
(`d = self._x; d[k] = v`) are invisible — keep lock-guarded mutation on
the attribute itself where the rule can see it.
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis import dataflow
from kubernetes_scheduler_tpu.analysis.core import Context, Violation

RULE = "lock-discipline"

SCOPE = ("kubernetes_scheduler_tpu/**/*.py", "kubernetes_scheduler_tpu/*.py")

_MUTATORS = {
    "append", "extend", "insert", "pop", "popitem", "clear", "update",
    "add", "discard", "remove", "setdefault", "appendleft", "popleft",
}
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore"}


def _lock_attrs(cls: ast.ClassDef) -> set:
    """self.<attr> holding a threading lock, plus any self.<attr> used as
    a with-context whose name mentions 'lock'."""
    locks: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            fn = node.value.func
            ctor = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None
            )
            if ctor in _LOCK_CTORS:
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        locks.add(t.attr)
        elif isinstance(node, ast.With):
            for item in node.items:
                e = item.context_expr
                if (
                    isinstance(e, ast.Attribute)
                    and isinstance(e.value, ast.Name)
                    and e.value.id == "self"
                    and "lock" in e.attr.lower()
                ):
                    locks.add(e.attr)
    return locks


def _is_lock_with(node: ast.With, locks: set) -> bool:
    for item in node.items:
        e = item.context_expr
        if (
            isinstance(e, ast.Attribute)
            and isinstance(e.value, ast.Name)
            and e.value.id == "self"
            and e.attr in locks
        ):
            return True
    return False


def _self_attr_of_mutation(node: ast.AST) -> tuple[str, int] | None:
    """(attr, lineno) when `node` mutates a self attribute: assignment to
    self.X / self.X[...], augmented assignment, or a mutating method call
    self.X.append(...)."""
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            base = t
            if isinstance(base, ast.Subscript):
                key = dataflow.keyed_dict_attr(base)
                if key is not None:
                    return key, node.lineno
                base = base.value
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                return base.attr, node.lineno
    elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in _MUTATORS:
            owner = node.func.value
            if isinstance(owner, ast.Subscript):
                key = dataflow.keyed_dict_attr(owner)
                if key is not None:
                    return key, node.lineno
                owner = owner.value
            if (
                isinstance(owner, ast.Attribute)
                and isinstance(owner.value, ast.Name)
                and owner.value.id == "self"
            ):
                return owner.attr, node.lineno
    return None


def _walk_mutations(node: ast.AST, locks: set, in_lock: bool, acc: list):
    """(attr, lineno, under_lock) for every self-attr mutation under
    `node`, tracking lock context through nested statements and defs."""
    for child in ast.iter_child_nodes(node):
        child_in_lock = in_lock or (
            isinstance(child, ast.With) and _is_lock_with(child, locks)
        )
        mut = _self_attr_of_mutation(child)
        if mut is not None:
            acc.append((mut[0], mut[1], child_in_lock))
        _walk_mutations(child, locks, child_in_lock, acc)


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    for sf in ctx.scoped(SCOPE):
        for cls in dataflow.get_index(ctx).walk(sf):
            if not isinstance(cls, ast.ClassDef):
                continue
            locks = _lock_attrs(cls)
            if not locks:
                continue
            # method name -> [(attr, line, under_lock)]
            per_method: dict[str, list] = {}
            for item in cls.body:
                if not isinstance(
                    item, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                acc: list = []
                _walk_mutations(item, locks, False, acc)
                per_method[item.name] = acc
            guarded = {
                attr
                for muts in per_method.values()
                for attr, _, under in muts
                if under
            } - locks
            if not guarded:
                continue
            for method, muts in per_method.items():
                if method == "__init__":
                    continue
                for attr, line, under in muts:
                    if attr in guarded and not under:
                        out.append(
                            Violation(
                                RULE, sf.path, line,
                                f"{cls.name}.{method} mutates `self.{attr}` "
                                "without the lock that guards it elsewhere "
                                "in this class",
                            )
                        )
    return out
