"""tracer-leak: tracers stored where they outlive the traced call.

Inside a jit-traced function every argument-derived value is a Tracer.
Storing one onto an object that survives the trace — `self.cache = x`,
`param.field = x`, `slots.history.append(x)` — leaks an abstract value
into post-trace code: the next read gets a `JaxprTracer` that raises
`TracerLeakError`/`UnexpectedTracer` far from the store, usually in an
unrelated cycle. The jit-purity family covers MODULE state (globals,
nonlocal); this family covers ARGUMENT-OBJECT state, which jit-purity
deliberately exempts because a parameter base is a local binding.

Reachability is the project call graph (analysis/dataflow.py), so a
helper called from a jitted entry — across modules — is analyzed too;
that is the interprocedural case a per-file scan misses.

Flagged inside jit-reachable functions:

- `<param>.attr = value` / `<param>[k] = value` where `value` derives
  from arguments or jnp expressions (constants are fine — shape tables
  and config stores are not tracers);
- mutating-method calls (`append`/`update`/`setdefault`/...) on an
  attribute-chained container reached FROM a parameter (`slots.history`,
  `obj.cache`) with a traced argument.

Deliberately NOT flagged: NamedTuple `_replace` and functional
`.at[...].set(...)` construct NEW values — no store happens; and a
mutator on a BARE parameter (`accum.append(x)`) is the trace-local
accumulator idiom (the `_affinity_update` pattern — a list built and
consumed within one trace), not an escape, so only attribute-chained
containers count as outliving the call.
"""

from __future__ import annotations

import ast

from kubernetes_scheduler_tpu.analysis.core import (
    Context,
    Violation,
    dotted_name,
)
from kubernetes_scheduler_tpu.analysis import dataflow

RULE = "tracer-leak"

SCOPE = (
    "kubernetes_scheduler_tpu/engine.py",
    "kubernetes_scheduler_tpu/ops/*.py",
    "kubernetes_scheduler_tpu/parallel/*.py",
    "kubernetes_scheduler_tpu/models/*.py",
)

# method-call mutators only: subscript stores (`obj.cache[k] = x`)
# arrive as ast.Assign and are handled by the store branch instead
_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "appendleft"}


def _params(fn: ast.AST) -> set[str]:
    args = fn.args
    return {
        a.arg
        for a in (
            args.posonlyargs + args.args + args.kwonlyargs
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        )
    }


def _value_is_traced(value: ast.AST, traced: set[str]) -> bool:
    """True when the stored value can be a tracer: reads a traced name
    or calls into jnp/jax/lax. Pure constants/shape-tuple stores are
    host values even at trace time."""
    for sub in ast.walk(value):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
            if sub.id in traced:
                return True
        elif isinstance(sub, ast.Call):
            dn = dotted_name(sub.func) or ""
            if dn.startswith(("jnp.", "jax.numpy.", "lax.", "jax.lax.")):
                return True
    return False


def _pallas_kernel_names(sf) -> set[str]:
    """Function names dispatched as Pallas kernels in this module
    (pl.pallas_call's first argument, unwrapping functools.partial).
    A kernel's WHOLE CALLING CONVENTION is mutating its Ref arguments —
    `out_ref[...] = value` IS the kernel's return surface, not a tracer
    escaping into host state — so kernels are exempt from the
    store-onto-argument check."""
    from kubernetes_scheduler_tpu.analysis.rules.pallas_vmem import (
        _kernel_names,
    )

    names: set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and (
            dotted_name(node.func) or ""
        ).rsplit(".", 1)[-1] == "pallas_call":
            names.update(_kernel_names(node))
    return names


def check(ctx: Context) -> list[Violation]:
    out: list[Violation] = []
    index = dataflow.get_index(ctx)
    scoped = {id(sf) for sf in ctx.scoped(SCOPE)}
    reachable = index.jit_reachable()
    kernel_cache: dict[int, set[str]] = {}
    for qname in sorted(reachable):
        fi = index.funcs[qname]
        if id(fi.sf) not in scoped:
            continue
        fn = fi.node
        kernels = kernel_cache.get(id(fi.sf))
        if kernels is None:
            kernels = kernel_cache[id(fi.sf)] = _pallas_kernel_names(fi.sf)
        if fn.name in kernels:
            continue
        params = _params(fn)
        # every param is abstract under trace; so is anything derived
        traced = params | dataflow.jax_tainted_names(fn)
        for node in dataflow.shallow_walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                value = node.value
                for t in targets:
                    if not isinstance(t, (ast.Attribute, ast.Subscript)):
                        continue
                    base = t
                    while isinstance(base, (ast.Attribute, ast.Subscript)):
                        base = base.value
                    if (
                        isinstance(base, ast.Name)
                        and base.id in params
                        and value is not None
                        and _value_is_traced(value, traced)
                    ):
                        out.append(Violation(
                            RULE, fi.sf.path, node.lineno,
                            f"jit-reachable `{fn.name}` stores a traced "
                            f"value onto argument object `{base.id}` — the "
                            "tracer outlives the traced call; return the "
                            "value instead of mutating the argument",
                        ))
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                if node.func.attr not in _MUTATORS:
                    continue
                base = node.func.value
                chain = []
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    if isinstance(base, ast.Attribute):
                        chain.append(base.attr)
                    base = base.value
                # `x.at[...].add(v)` is jax's FUNCTIONAL update — a new
                # array, no store; and a bare list param mutated between
                # kernel helpers is a trace-LOCAL accumulator (the
                # _affinity_update pattern), not an escape — only
                # attribute-chained containers (self.cache, obj.slots)
                # outlive the call
                if "at" in chain or not chain:
                    continue
                if not (isinstance(base, ast.Name) and base.id in params):
                    continue
                if any(
                    _value_is_traced(a, traced)
                    for a in list(node.args)
                    + [kw.value for kw in node.keywords]
                ):
                    out.append(Violation(
                        RULE, fi.sf.path, node.lineno,
                        f"jit-reachable `{fn.name}` appends a traced value "
                        f"into argument container `{base.id}` — the tracer "
                        "outlives the traced call",
                    ))
    return out
