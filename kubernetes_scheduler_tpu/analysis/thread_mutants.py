"""Seeded thread/determinism mutants: families 17-18 must catch each one.

The PR-10/PR-11 lesson applied to the concurrency layer: a race
detector that has never caught a race is an assertion, not a tool.
BASE below is a miniature mirror — a worker thread filling a
lock-guarded dirty-set, an Event publishing a counter to the serving
thread, a lock-covered check-then-act cache latch, a join-then-read
shutdown, and a journal record built from `sorted()` rows plus a
sanctioned `wall_time` stamp — clean under BOTH new families by
construction. Each mutant re-introduces one real bug class and the
family that owns it MUST report it:

- `drop-mirror-lock`: the lock around the dirty-set insert deleted —
  the writer races the `sorted(self._dirty)` reader. thread-race
  (lockset discharge gone, and the Event fired BEFORE these writes so
  no happens-before edge covers them);
- `event-set-before-write`: `self._ready.set()` hoisted above the
  write it publishes — the waiter can read the stale value. thread-race
  (the set-then-write order breaks the Event publication discharge);
- `unsorted-dirty-iter`: `sorted(self._dirty)` weakened to
  `list(self._dirty)` — set iteration order leaks into the journal
  record. determinism-taint (set-order source reaching a record field
  through the snapshot return);
- `wallclock-journal-field`: a decision field (`seq`) stamped from
  `time.time()` — only declared timing fields (`wall_time`,
  `*_seconds`) may carry the clock. determinism-taint;
- `latch-check-then-act`: the lock around the cache latch deleted —
  two threads both observe `cache is None` and both initialize.
  thread-race (check-then-act + lock-free cross-thread access pair);
- `unjoined-shutdown-read`: `close()` stops joining the worker before
  reading its final counter — shutdown reads a value the still-running
  thread may yet write. thread-race (the join happens-before edge was
  the only discharge for that pair).

`check_thread_mutants()` runs on every full-repo lint next to the SPMD
harness: the unmutated BASE must be clean under both families, and a
survived mutant is itself a lint violation — the analyzer lost its
teeth for that bug class. tests/test_analysis.py asserts the harness
one mutant at a time by name, with the rendered access-pair evidence.
"""

from __future__ import annotations

import os
import tempfile

from kubernetes_scheduler_tpu.analysis.core import Violation

RULE = "thread-mutant"

MUTANTS_PATH = "kubernetes_scheduler_tpu/analysis/thread_mutants.py"

FAMILIES = ("thread-race", "determinism-taint")

# --changed-only runs re-arm the harness when the closure touches the
# threaded layers or the analyzer itself (same shape as contracts.SURFACE)
SURFACE = (
    "kubernetes_scheduler_tpu/analysis/threads.py",
    "kubernetes_scheduler_tpu/analysis/thread_mutants.py",
    "kubernetes_scheduler_tpu/analysis/rules/thread_race.py",
    "kubernetes_scheduler_tpu/analysis/rules/determinism_taint.py",
    "kubernetes_scheduler_tpu/host/*.py",
    "kubernetes_scheduler_tpu/kube/*.py",
    "kubernetes_scheduler_tpu/bridge/*.py",
    "kubernetes_scheduler_tpu/trace/*.py",
)

# the miniature mirror every mutant perturbs
BASE = '''\
"""Thread-mutant base: a miniature mirror with one worker thread."""

import threading
import time

JOURNAL = []


def record_cycle(rec):
    JOURNAL.append(rec)


class MiniMirror:
    def __init__(self, seed):
        self.seed = seed
        self._lock = threading.Lock()
        self._ready = threading.Event()
        self._dirty = set()
        self.published = 0
        self.cache = None
        self._worker = None

    def start(self):
        self._worker = threading.Thread(target=self._pump, daemon=True)
        self._worker.start()

    def _pump(self):
        self.published = self.seed + 4
        self._ready.set()
        for seq in range(4):
            self.ensure_cache()
            with self._lock:
                self._dirty.add("row-%d" % seq)

    def ensure_cache(self):
        with self._lock:
            if self.cache is None:
                self.cache = {}
            return self.cache

    def snapshot(self):
        self._ready.wait()
        count = self.published
        with self._lock:
            rows = sorted(self._dirty)
        return rows, count

    def close(self):
        if self._worker is not None:
            self._worker.join(timeout=1.0)
        return self.published


def drive(n):
    m = MiniMirror(seed=n)
    m.start()
    m.ensure_cache()
    rows, count = m.snapshot()
    rec = {"seq": n, "rows": rows, "count": count,
           "wall_time": time.time()}
    record_cycle(rec)
    return m.close()
'''

# name -> (literal pattern, replacement, family that MUST catch it)
THREAD_MUTANTS = {
    "drop-mirror-lock": (
        "            with self._lock:\n"
        '                self._dirty.add("row-%d" % seq)\n',
        '            self._dirty.add("row-%d" % seq)\n',
        "thread-race",
    ),
    "event-set-before-write": (
        "        self.published = self.seed + 4\n"
        "        self._ready.set()\n",
        "        self._ready.set()\n"
        "        self.published = self.seed + 4\n",
        "thread-race",
    ),
    "unsorted-dirty-iter": (
        "            rows = sorted(self._dirty)\n",
        "            rows = list(self._dirty)\n",
        "determinism-taint",
    ),
    "wallclock-journal-field": (
        '    rec = {"seq": n, "rows": rows, "count": count,\n',
        '    rec = {"seq": int(time.time()), "rows": rows, "count": count,\n',
        "determinism-taint",
    ),
    "latch-check-then-act": (
        "        with self._lock:\n"
        "            if self.cache is None:\n"
        "                self.cache = {}\n"
        "            return self.cache\n",
        "        if self.cache is None:\n"
        "            self.cache = {}\n"
        "        return self.cache\n",
        "thread-race",
    ),
    "unjoined-shutdown-read": (
        "        if self._worker is not None:\n"
        "            self._worker.join(timeout=1.0)\n"
        "        return self.published\n",
        "        return self.published\n",
        "thread-race",
    ),
}


def mutate(name: str) -> str:
    pattern, replacement, _ = THREAD_MUTANTS[name]
    mutated = BASE.replace(pattern, replacement)
    if mutated == BASE:
        raise ValueError(
            f"mutant {name!r}: pattern no longer matches the BASE "
            "module — the harness drifted from its own source"
        )
    return mutated


def _findings(source: str, family: str, workdir: str) -> list:
    """One family's findings on `source` (written to a scratch module so
    the normal lint path — index build, model build, rule — runs
    unchanged)."""
    from kubernetes_scheduler_tpu.analysis.core import run_lint

    path = os.path.join(workdir, "thread_mutant_mod.py")
    with open(path, "w", encoding="utf-8") as f:
        f.write(source)
    return [v for v in run_lint([path], rules=[family]) if not v.waived]


def run_thread_mutant(name: str, workdir: str | None = None) -> dict:
    """{family: [findings]} for one mutant, across both families."""
    source = mutate(name)
    with tempfile.TemporaryDirectory() as tmp:
        wd = workdir or tmp
        return {fam: _findings(source, fam, wd) for fam in FAMILIES}


def check_thread_mutants() -> list[Violation]:
    """The lint entry point: [] when the unmutated base is clean under
    both families and every mutant is caught by the family that owns
    its bug class. A survived mutant means the thread model / taint
    tracker lost its teeth — a checker regression, not a code bug."""
    out: list[Violation] = []
    with tempfile.TemporaryDirectory() as tmp:
        for fam in FAMILIES:
            for v in _findings(BASE, fam, tmp):
                out.append(Violation(
                    RULE, MUTANTS_PATH, 1,
                    "the UNMUTATED thread-mutant base module is dirty "
                    f"under {fam} (every catch would be vacuous): "
                    f"{v.message}",
                ))
        if out:
            return out
        for name, (_, _, family) in THREAD_MUTANTS.items():
            try:
                source = mutate(name)
                got = _findings(source, family, tmp)
            except Exception as e:  # noqa: BLE001
                out.append(Violation(
                    RULE, MUTANTS_PATH, 1,
                    f"seeded thread mutant `{name}` harness error: {e}",
                ))
                continue
            if not got:
                out.append(Violation(
                    RULE, MUTANTS_PATH, 1,
                    f"seeded thread mutant `{name}` SURVIVED the "
                    f"{family} family — the analyzer lost its teeth for "
                    f"this bug class (see THREAD_MUTANTS[{name!r}])",
                ))
    return out
