"""Learned scoring policy: a two-tower scorer trained to imitate (or
improve on) the heuristic policies.

Design rationale: the heuristic score is a fixed formula of two utilization
series; a learned scorer consumes the full feature set the advisor already
collects (CPU, memory, disk-IO, both network directions — the reference
scrapes all five series but its live formula uses only two,
pkg/yoda/advisor/advisor.go:16-20 vs score/algorithm.go:105-111) plus the
resource-fit state. Two towers (pod MLP, node MLP) meet in a single
[p, d] x [d, n] matmul — the MXU-friendly shape — so scoring P pods on N
nodes is one batched contraction rather than P.N formula evaluations.

Sharding (the framework's "training parallelism"): on a dp x node mesh the
example/pod axis is data-parallel over `dp` and the node axis — our long
"sequence" axis — shards over `node`; parameters are replicated. The score
matmul then has lhs sharded on dp, rhs on node: XLA turns the loss
reduction into psums over both axes. This is exercised multi-chip by
__graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import flax.linen as nn
import optax

from kubernetes_scheduler_tpu.engine import PodBatch, SnapshotArrays
from kubernetes_scheduler_tpu.ops.stats import CPU_DIVISOR, DISK_IO_DIVISOR

POD_FEATURES = 6
NODE_FEATURES = 8


def make_features(
    snapshot: SnapshotArrays, pods: PodBatch
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(pod_x[p, POD_FEATURES], node_x[n, NODE_FEATURES]) in roughly [0, 1]
    ranges. Kept in float32 host-side; towers cast to bfloat16 internally."""
    r = snapshot.allocatable
    safe_alloc = jnp.maximum(r, 1.0)
    free_frac = (r - snapshot.requested) / safe_alloc          # [n, r]
    node_x = jnp.concatenate(
        [
            snapshot.cpu_pct[:, None] / CPU_DIVISOR,
            snapshot.mem_pct[:, None] / 100.0,
            snapshot.disk_io[:, None] / DISK_IO_DIVISOR,
            snapshot.net_up[:, None] / 100.0,
            snapshot.net_down[:, None] / 100.0,
            free_frac[:, :3],
        ],
        axis=1,
    )
    req = pods.request
    pod_x = jnp.concatenate(
        [
            req[:, 0:1] / 32000.0,              # cpu milli vs largest node
            req[:, 1:2] / (64.0 * 2**30),       # memory vs largest node
            req[:, 2:3] / 110.0,                # pod-slot demand
            pods.r_io[:, None] / DISK_IO_DIVISOR,
            pods.priority[:, None].astype(jnp.float32) / 10.0,
            pods.want_number[:, None].astype(jnp.float32) / 8.0,
        ],
        axis=1,
    )
    return pod_x, node_x


class Tower(nn.Module):
    width: int
    depth: int
    out: int

    @nn.compact
    def __call__(self, x):
        x = x.astype(jnp.bfloat16)
        for _ in range(self.depth):
            x = nn.Dense(self.width)(x)
            x = nn.gelu(x)
        return nn.Dense(self.out)(x)


class NodeScorer(nn.Module):
    """Two-tower scorer: score[p, n] = pod_emb @ node_emb^T / sqrt(d) + b[n]."""

    d_model: int = 128
    width: int = 256
    depth: int = 2

    @nn.compact
    def __call__(self, pod_x: jnp.ndarray, node_x: jnp.ndarray) -> jnp.ndarray:
        pod_e = Tower(self.width, self.depth, self.d_model, name="pod_tower")(pod_x)
        node_e = Tower(self.width, self.depth, self.d_model, name="node_tower")(node_x)
        bias = nn.Dense(1, name="node_bias")(
            node_x.astype(jnp.bfloat16)
        )[:, 0]
        scale = jnp.asarray(1.0 / jnp.sqrt(self.d_model), jnp.bfloat16)
        scores = pod_e @ node_e.T * scale + bias[None, :]
        return scores.astype(jnp.float32)


class TrainState(NamedTuple):
    params: dict
    opt_state: optax.OptState
    step: jnp.ndarray


def init_train_state(
    rng: jax.Array,
    *,
    model: NodeScorer | None = None,
    learning_rate: float = 1e-3,
) -> tuple[TrainState, NodeScorer, optax.GradientTransformation]:
    model = model or NodeScorer()
    params = model.init(
        rng, jnp.zeros((1, POD_FEATURES)), jnp.zeros((1, NODE_FEATURES))
    )
    tx = optax.adamw(learning_rate)
    return TrainState(params, tx.init(params), jnp.zeros((), jnp.int32)), model, tx


def imitation_loss(
    model: NodeScorer,
    params,
    pod_x: jnp.ndarray,
    node_x: jnp.ndarray,
    teacher_scores: jnp.ndarray,
    node_mask: jnp.ndarray,
    pod_mask: jnp.ndarray,
) -> jnp.ndarray:
    """Masked listwise KL to the teacher's softmax placement distribution
    plus a small MSE anchor on raw scores. The teacher is any heuristic
    policy's raw score matrix (engine.compute_scores)."""
    pred = model.apply(params, pod_x, node_x)                   # [p, n]
    neg = jnp.asarray(-1e30, pred.dtype)
    mask2 = node_mask[None, :] & pod_mask[:, None]
    t_logp = jax.nn.log_softmax(jnp.where(mask2, teacher_scores, neg), axis=-1)
    p_logp = jax.nn.log_softmax(jnp.where(mask2, pred, neg), axis=-1)
    valid = jnp.maximum(pod_mask.sum(), 1.0)
    kl = (jnp.exp(t_logp) * (t_logp - p_logp) * mask2).sum() / valid
    mse = (((pred - teacher_scores) ** 2) * mask2).sum() / jnp.maximum(
        mask2.sum(), 1.0
    )
    return kl + 0.01 * mse


def train_step(
    state: TrainState,
    model: NodeScorer,
    tx: optax.GradientTransformation,
    pod_x: jnp.ndarray,
    node_x: jnp.ndarray,
    teacher_scores: jnp.ndarray,
    node_mask: jnp.ndarray,
    pod_mask: jnp.ndarray,
) -> tuple[TrainState, jnp.ndarray]:
    """One optimizer step. Pure; callers jit it (optionally with sharded
    inputs — the loss reductions become cross-device psums under GSPMD)."""
    loss, grads = jax.value_and_grad(
        lambda p: imitation_loss(
            model, p, pod_x, node_x, teacher_scores, node_mask, pod_mask
        )
    )(state.params)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, state.step + 1), loss


def save_checkpoint(path: str, state: TrainState) -> None:
    """Persist a TrainState with orbax (the checkpoint/resume subsystem the
    reference lacks entirely — SURVEY.md §5; here it carries the learned
    scorer across sidecar restarts, which are otherwise stateless)."""
    import os
    import shutil

    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    # write-to-temp + rename so a crash mid-save never destroys the last
    # good checkpoint
    tmp = f"{path}.tmp.{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.join(tmp, "state"), state)
    backup = f"{path}.old.{os.getpid()}"
    if os.path.exists(path):
        os.replace(path, backup)
    os.replace(tmp, path)
    if os.path.exists(backup):
        shutil.rmtree(backup)


def restore_checkpoint(path: str, like: TrainState) -> TrainState:
    """Restore a TrainState saved by save_checkpoint; `like` supplies the
    tree structure/shapes (from init_train_state on the same model)."""
    import os

    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(
            os.path.join(os.path.abspath(path), "state"), target=like
        )
    return TrainState(*restored) if not isinstance(restored, TrainState) else restored


class LearnedEngine:
    """LocalEngine-compatible engine scoring with the two-tower model.

    Drop-in for host.Scheduler's `engine` attribute: same schedule_batch
    surface as engine.LocalEngine, but the policy score matrix comes from
    one pod_emb @ node_emb^T contraction (MXU) instead of a heuristic
    formula. Feasibility, normalization, (anti)affinity and assignment
    reuse the exact engine machinery (engine.finish_cycle), so every hard
    and soft constraint holds identically. The `policy` argument is
    accepted and ignored — this engine IS the policy ("learned").
    """

    def __init__(self, params, *, model: NodeScorer | None = None):
        import functools

        from kubernetes_scheduler_tpu.engine import (
            compute_feasibility,
            finish_cycle,
            normalize_scores,
        )

        self.model = model or NodeScorer()
        self.params = params

        def _one_cycle(params, snapshot, pods, *, assigner, normalizer,
                       affinity_aware, soft, auction_rounds,
                       auction_price_frac):
            """Score with the two-tower model, then the exact engine
            tail — the ONE scoring pipeline both the single-batch and
            windows paths run (they must not diverge)."""
            pod_x, node_x = make_features(snapshot, pods)
            raw = self.model.apply(params, pod_x, node_x)
            feasible = compute_feasibility(
                snapshot, pods, include_pod_affinity=not affinity_aware
            )
            norm = normalize_scores(raw, snapshot.node_mask, normalizer)
            return finish_cycle(
                snapshot, pods, raw, norm, feasible,
                assigner=assigner, affinity_aware=affinity_aware, soft=soft,
                auction_rounds=auction_rounds,
                auction_price_frac=auction_price_frac,
            )

        @functools.partial(
            jax.jit,
            static_argnames=(
                "assigner", "normalizer", "affinity_aware", "soft",
                "auction_rounds", "auction_price_frac",
            ),
        )
        def _run(params, snapshot, pods, *, assigner, normalizer,
                 affinity_aware, soft, auction_rounds, auction_price_frac):
            return _one_cycle(
                params, snapshot, pods, assigner=assigner,
                normalizer=normalizer, affinity_aware=affinity_aware,
                soft=soft, auction_rounds=auction_rounds,
                auction_price_frac=auction_price_frac,
            )

        self._run = _run

        @functools.partial(
            jax.jit,
            static_argnames=(
                "assigner", "normalizer", "affinity_aware", "soft",
                "auction_rounds", "auction_price_frac",
            ),
        )
        def _run_windows(params, snapshot, pods_w, *, assigner, normalizer,
                         affinity_aware, soft, auction_rounds,
                         auction_price_frac):
            from kubernetes_scheduler_tpu.engine import run_windows_scan

            def cycle(snap, w):
                return _one_cycle(
                    params, snap, w, assigner=assigner,
                    normalizer=normalizer, affinity_aware=affinity_aware,
                    soft=soft, auction_rounds=auction_rounds,
                    auction_price_frac=auction_price_frac,
                )

            return run_windows_scan(snapshot, pods_w, cycle)

        self._run_windows = _run_windows

    def schedule_batch(
        self,
        snapshot,
        pods,
        *,
        policy: str = "learned",
        assigner: str = "greedy",
        normalizer: str = "min_max",
        fused: bool = False,  # no fused kernel for the learned scorer
        affinity_aware: bool = True,
        soft: bool = False,
        auction_rounds: int = 1024,
        auction_price_frac: float = 1.0,
    ):
        return self._run(
            self.params, snapshot, pods, assigner=assigner,
            normalizer=normalizer, affinity_aware=affinity_aware, soft=soft,
            auction_rounds=auction_rounds,
            auction_price_frac=auction_price_frac,
        )

    def schedule_windows(
        self,
        snapshot,
        pods_windows,
        *,
        policy: str = "learned",
        assigner: str = "greedy",
        normalizer: str = "min_max",
        fused: bool = False,
        affinity_aware: bool = True,
        soft: bool = False,
        auction_rounds: int = 1024,
        auction_price_frac: float = 1.0,
    ):
        """Whole-backlog scheduling with the learned scorer: the same
        capacity- and affinity-carrying window scan as
        engine.schedule_windows (sharing its fold), scored per window by
        the two-tower model against the CARRIED snapshot state — so the
        host's deep-queue backlog cycles work under policy='learned'
        too."""
        return self._run_windows(
            self.params, snapshot, pods_windows, assigner=assigner,
            normalizer=normalizer, affinity_aware=affinity_aware, soft=soft,
            auction_rounds=auction_rounds,
            auction_price_frac=auction_price_frac,
        )

    def healthy(self) -> bool:
        return True

    def close(self) -> None:
        pass


def load_learned_engine(
    checkpoint_path: str, *, model: NodeScorer | None = None
) -> LearnedEngine:
    """Restore a trained scorer into a ready LearnedEngine."""
    model = model or NodeScorer()
    like, _, _ = init_train_state(jax.random.key(0), model=model)
    state = restore_checkpoint(checkpoint_path, like)
    return LearnedEngine(state.params, model=model)


def make_sharded_learned_fn(params, mesh, *, model: NodeScorer | None = None,
                            windows: bool = False, **kw):
    """The learned two-tower policy on a device mesh.

    The scorer is embarrassingly shardable along the node axis: the node
    tower reads only per-node features (node-local on each shard), the
    pod tower is replicated, and the [p, n_local] contraction is
    per-shard MXU work — so it plugs into the sharded engine's
    `score_fn` hook with NO extra collectives of its own (normalization
    bounds are already global pmax/pmin inside the sharded pipeline).

    Returns a jitted shard_map'd function with the same surface as
    make_sharded_schedule_fn (or make_sharded_windows_fn when
    windows=True). `params` are closed over; pass replicated.
    """
    from kubernetes_scheduler_tpu.parallel.engine import (
        make_sharded_schedule_fn,
        make_sharded_windows_fn,
    )

    scorer = model or NodeScorer()

    def score_fn(snapshot, pods):
        pod_x, node_x = make_features(snapshot, pods)
        return scorer.apply(params, pod_x, node_x)

    factory = make_sharded_windows_fn if windows else make_sharded_schedule_fn
    return factory(mesh, score_fn=score_fn, **kw)
