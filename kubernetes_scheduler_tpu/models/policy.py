"""Heuristic policy registry.

The reference hard-wires one live policy and keeps three alternates dead in
comments or unreachable branches (pkg/yoda/score/algorithm.go:90-96). Here
every policy is a first-class registry entry selectable per cycle; each
maps to a kernel dispatched inside engine.compute_scores.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class PolicyInfo:
    name: str
    description: str
    reference: str  # file:line in /root/reference
    live_in_reference: bool
    # True: engine.compute_scores evaluates it by name (engine.POLICIES).
    # False: needs a dedicated engine carrying state (models/learned.py
    # LearnedEngine holds the scorer parameters) — host.Scheduler builds
    # it from config; sending the name to a stock engine raises.
    engine_schedulable: bool = True


HEURISTIC_POLICIES = {
    "balanced_cpu_diskio": PolicyInfo(
        name="balanced_cpu_diskio",
        description="CPU/disk-IO load balancing: S = 10 - 10|alpha.V - beta.U|",
        reference="pkg/yoda/score/algorithm.go:99-119",
        live_in_reference=True,
    ),
    "balanced_diskio": PolicyInfo(
        name="balanced_diskio",
        description="disk-IO variance minimization, min-max rescaled",
        reference="pkg/yoda/score/algorithm.go:121-176",
        live_in_reference=False,
    ),
    "free_capacity": PolicyInfo(
        name="free_capacity",
        description="weighted free capacity: 100(100-io) + 2(100-cpu) + 3(100-mem)",
        reference="pkg/yoda/score/algorithm.go:178-198",
        live_in_reference=False,
    ),
    "card": PolicyInfo(
        name="card",
        description="GPU-card weighted normalized metrics, summed per node",
        reference="pkg/yoda/score/algorithm.go:264-291",
        live_in_reference=False,
    ),
    "least_allocated": PolicyInfo(
        name="least_allocated",
        description="NodeResourcesLeastAllocated (k8s 1.22 default): mean "
        "free share of cpu/memory after placement",
        reference="k8s 1.22 default score plugin via go.mod:13 "
        "(deploy/yoda-scheduler.yaml:21-47 disables nothing)",
        live_in_reference=True,
    ),
    "balanced_allocation": PolicyInfo(
        name="balanced_allocation",
        description="NodeResourcesBalancedAllocation (k8s 1.22 default): "
        "(1 - |cpuFrac - memFrac|) * 100 after placement",
        reference="k8s 1.22 default score plugin via go.mod:13",
        live_in_reference=True,
    ),
    "image_locality": PolicyInfo(
        name="image_locality",
        description="ImageLocality (k8s 1.22 default): spread-scaled image "
        "footprint already present on the node, 23MB..1GB/container ramp",
        reference="k8s 1.22 default score plugin via go.mod:13",
        live_in_reference=True,
    ),
    "learned": PolicyInfo(
        name="learned",
        description="two-tower learned scorer (models/learned.py), distilled"
        " from any heuristic policy over the full advisor feature set",
        reference="beyond reference (SURVEY.md has no learned path)",
        live_in_reference=False,
        engine_schedulable=False,
    ),
}

# back-compat / clearer name: the registry holds every selectable policy,
# heuristic or learned
POLICY_REGISTRY = HEURISTIC_POLICIES


def get_policy(name: str) -> PolicyInfo:
    try:
        return HEURISTIC_POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown policy {name!r}; available: {sorted(HEURISTIC_POLICIES)}"
        ) from None
