"""Scheduling-policy "model families".

Two families:
- heuristic: the reference's four scoring formulas as selectable policies
  (the live BalancedCpuDiskIO plus the three dead/legacy alternates,
  pkg/yoda/score/algorithm.go) — zero parameters, pure kernels.
- learned: a trainable two-tower scorer (flax) over pod/node features,
  trained to imitate (or improve on) a heuristic teacher — the framework's
  flagship *model* in the ML sense, and the vehicle for the multi-chip
  dp x node training-step sharding.
"""

from kubernetes_scheduler_tpu.models.policy import HEURISTIC_POLICIES, get_policy
from kubernetes_scheduler_tpu.models.learned import (
    NodeScorer,
    TrainState,
    init_train_state,
    make_features,
    train_step,
)
