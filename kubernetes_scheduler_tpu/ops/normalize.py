"""Score normalization kernels.

Replaces the NormalizeScore extension point (pkg/yoda/scheduler.go:158-183):
min-max rescale of each pod's node scores to [0, MaxNodeScore], including the
reference's `highest == lowest` guard (scheduler.go:173-175: decrement lowest
by one, which maps every node to exactly MaxNodeScore). Also provides a
softmax variant for the batched engine (the north-star design's device-side
normalization, BASELINE.json).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# framework.MaxNodeScore in the upstream scheduler framework.
MAX_NODE_SCORE = 100.0


def score_bounds(
    scores: jnp.ndarray, node_mask: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-pod (highest, lowest) over valid nodes, with the reference's
    seeds: highest starts at 0 (scheduler.go:162) so an all-negative row
    still normalizes against 0; lowest is seeded from a real node's score.
    Shapes [p, 1] each. The sharded engine computes these locally and
    reduces with pmax/pmin before normalizing."""
    big = jnp.asarray(jnp.finfo(jnp.float32).max, jnp.float32)
    masked_hi = jnp.where(node_mask[None, :], scores, -big)
    masked_lo = jnp.where(node_mask[None, :], scores, big)
    highest = jnp.maximum(masked_hi.max(axis=1, keepdims=True), 0.0)
    lowest = masked_lo.min(axis=1, keepdims=True)
    return highest, lowest


def min_max_normalize(
    scores: jnp.ndarray,
    node_mask: jnp.ndarray,
    *,
    max_node_score: float = MAX_NODE_SCORE,
    integer_parity: bool = False,
    bounds: tuple[jnp.ndarray, jnp.ndarray] | None = None,
) -> jnp.ndarray:
    """Per-pod min-max rescale to [0, max_node_score] over valid nodes.

    scores:    [p, n] raw scores
    node_mask: [n] bool
    integer_parity: reproduce the reference exactly — upstream hands
        NormalizeScore int64 scores (already truncated at
        pkg/yoda/scheduler.go:154) and the rescale at scheduler.go:178 is
        integer division. With this flag the inputs are floored and the
        division truncated, matching the Go path bit-for-bit.
    bounds: optional precomputed (highest, lowest) [p, 1] pair — the
        sharded engine passes pmax/pmin-reduced global bounds here.

    Padded nodes get 0.
    """
    if integer_parity:
        scores = jnp.floor(scores)
    if bounds is not None:
        highest, lowest = bounds
    else:
        highest, lowest = score_bounds(scores, node_mask)
    lowest = jnp.where(highest == lowest, lowest - 1.0, lowest)
    out = (scores - lowest) * max_node_score / (highest - lowest)
    if integer_parity:
        out = jnp.trunc(out)
    return jnp.where(node_mask[None, :], out, 0.0)


def softmax_normalize(
    scores: jnp.ndarray,
    node_mask: jnp.ndarray,
    *,
    temperature: float = 1.0,
) -> jnp.ndarray:
    """Masked softmax over the node axis: scores become a placement
    distribution. Used by the learned policy head and as the batched
    engine's alternative to min-max (differentiable, scale-free)."""
    neg = jnp.asarray(-1e30, scores.dtype)
    logits = jnp.where(node_mask[None, :], scores / temperature, neg)
    return jax.nn.softmax(logits, axis=-1)
