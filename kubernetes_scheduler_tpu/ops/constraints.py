"""Taint/toleration, node-affinity and inter-pod-affinity mask kernels.

The reference has no implementation of these (its Filter passes every node,
pkg/yoda/scheduler.go:96-99; upstream kube-scheduler's TaintToleration and
InterPodAffinity plugins handled them outside the plugin) — but the
framework's benchmark matrix requires them as batch predicates
(BASELINE.md config 4: "5k pods x 5k nodes with inter-pod
affinity/anti-affinity + taints"). They are formulated from scratch as
tensor ops over integer-id-encoded labels, following upstream Kubernetes
semantics.

Encoding (host side interns strings to int32 ids; -1 is "absent"):

- taints[n, T, 3]: (key_id, value_id, effect), effect in {1=NoSchedule,
  2=PreferNoSchedule, 3=NoExecute}; taint_mask[n, T].
- tolerations[p, L, 4]: (key_id, value_id, op, effect); op in {0=Exists,
  1=Equal}; key_id = -1 means "empty key" (with Exists: tolerate
  everything); effect = 0 means "all effects"; tol_mask[p, L].
- node labels as (key_id, value_id) pairs: node_labels[n, Ln, 2] with
  node_label_mask[n, Ln].
- node-affinity requirements: one required nodeSelectorTerm of up to E
  matchExpressions (ANDed), each (key_id, op, values[V]); op in
  {0=In, 1=NotIn, 2=Exists, 3=DoesNotExist}.
- inter-pod (anti)affinity: the host resolves each distinct label selector
  in the batch against running pods and aggregates matches over each
  selector's topology domain, handing the device domain_counts[n, s] =
  "#running pods matching selector s in node n's topology domain". Pods
  carry selector indices (-1 padded). pod_affinity_fit below evaluates
  these counts statically (pre-window state); batch-internal interactions
  (pods of the same window affecting each other) are handled exactly by
  the greedy assigner, which threads live per-domain placement counts
  through its scan (ops/assign.py AffinityState).
"""

from __future__ import annotations

import jax.numpy as jnp

# taint effects
NO_SCHEDULE = 1
PREFER_NO_SCHEDULE = 2
NO_EXECUTE = 3
# toleration operators
TOL_EXISTS = 0
TOL_EQUAL = 1
# node-affinity expression operators
OP_IN = 0
OP_NOT_IN = 1
OP_EXISTS = 2
OP_NOT_EXISTS = 3


def _taints_tolerated(
    taints: jnp.ndarray,
    tolerations: jnp.ndarray,
    tol_mask: jnp.ndarray,
) -> jnp.ndarray:
    """[p, n, T] bool: taint t of node n is tolerated by some toleration of
    pod p — upstream v1.Toleration.ToleratesTaint semantics:
      (tol.key == -1 and tol.op == Exists) or
      (tol.key == taint.key and
       (tol.op == Exists or tol.value == taint.value))
    and (tol.effect == 0 or tol.effect == taint.effect).
    """
    t_key = taints[..., 0][None, :, :, None]    # [1, n, T, 1]
    t_val = taints[..., 1][None, :, :, None]
    t_eff = taints[..., 2][None, :, :, None]
    o_key = tolerations[..., 0][:, None, None, :]  # [p, 1, 1, L]
    o_val = tolerations[..., 1][:, None, None, :]
    o_op = tolerations[..., 2][:, None, None, :]
    o_eff = tolerations[..., 3][:, None, None, :]

    wildcard_key = (o_key == -1) & (o_op == TOL_EXISTS)
    key_ok = wildcard_key | (
        (o_key == t_key) & ((o_op == TOL_EXISTS) | (o_val == t_val))
    )
    eff_ok = (o_eff == 0) | (o_eff == t_eff)
    matches = key_ok & eff_ok & tol_mask[:, None, None, :]  # [p, n, T, L]
    return matches.any(-1)                                   # [p, n, T]


def taint_toleration_fit(
    taints: jnp.ndarray,
    taint_mask: jnp.ndarray,
    tolerations: jnp.ndarray,
    tol_mask: jnp.ndarray,
) -> jnp.ndarray:
    """F[p, n]: no untolerated NoSchedule/NoExecute taint.
    PreferNoSchedule taints never filter (scoring concern only — see
    prefer_no_schedule_penalty)."""
    tolerated = _taints_tolerated(taints, tolerations, tol_mask)
    hard = taint_mask[None, :, :] & (
        (taints[..., 2] == NO_SCHEDULE) | (taints[..., 2] == NO_EXECUTE)
    )[None, :, :]
    return ~(hard & ~tolerated).any(-1)


def prefer_no_schedule_penalty(
    taints: jnp.ndarray,
    taint_mask: jnp.ndarray,
    tolerations: jnp.ndarray,
    tol_mask: jnp.ndarray,
) -> jnp.ndarray:
    """[p, n] float32: count of untolerated PreferNoSchedule taints —
    upstream TaintToleration's scoring input (its score prefers nodes with
    fewer intolerable soft taints). Callers subtract a weighted multiple
    from the score matrix."""
    tolerated = _taints_tolerated(taints, tolerations, tol_mask)
    soft = taint_mask[None, :, :] & (taints[..., 2] == PREFER_NO_SCHEDULE)[None, :, :]
    return (soft & ~tolerated).sum(-1).astype(jnp.float32)


def node_affinity_fit(
    node_labels: jnp.ndarray,
    node_label_mask: jnp.ndarray,
    expr_key: jnp.ndarray,
    expr_op: jnp.ndarray,
    expr_vals: jnp.ndarray,
    expr_val_mask: jnp.ndarray,
    expr_mask: jnp.ndarray,
    expr_term: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """F[p, n]: required node affinity with full upstream OR-of-ANDs
    `nodeSelectorTerms` semantics — a node passes if it satisfies EVERY
    expression of SOME term (a pod with no expressions passes everywhere).

    node_labels: [n, Ln, 2] (key_id, value_id); node_label_mask: [n, Ln]
    expr_key:  [p, E] int32; expr_op: [p, E]
    expr_vals: [p, E, V] int32 value-id sets; expr_val_mask: [p, E, V]
    expr_mask: [p, E] (False = padding: expression ignored)
    expr_term: [p, E] int32 OR-group ids in [0, E) (None = all zeros, a
               single AND list — the pre-term behavior)

    Upstream per-expression semantics: In — label present with value in
    set; NotIn — label absent OR value not in set; Exists — label
    present; DoesNotExist — label absent. Terms are grouped by id, ANDed
    within a group, OR'd across groups, via one [p,E,G]x[p,E,n] batched
    contraction (G = E worst case; tiny next to the [p,E,n,Ln,V] match
    tensor _expressions_satisfied already builds).
    """
    ok = _expressions_satisfied(
        node_labels, node_label_mask, expr_key, expr_op, expr_vals, expr_val_mask
    )
    if expr_term is None:
        ok = ok | ~expr_mask[:, :, None]
        return ok.all(1)  # [p, n]
    e = expr_key.shape[1]
    member = (
        expr_term[:, :, None] == jnp.arange(e)[None, None, :]
    ) & expr_mask[:, :, None]                                   # [p, E, G]
    fail = expr_mask[:, :, None] & ~ok                          # [p, E, n]
    group_fail = (
        jnp.einsum(
            "peg,pen->pgn",
            member.astype(jnp.float32),
            fail.astype(jnp.float32),
        )
        > 0
    )                                                           # [p, G, n]
    group_has = member.any(1)                                   # [p, G]
    term_ok = group_has[:, :, None] & ~group_fail
    no_terms = ~group_has.any(1)                                # [p]
    return term_ok.any(1) | no_terms[:, None]                   # [p, n]


def _expressions_satisfied(
    node_labels: jnp.ndarray,
    node_label_mask: jnp.ndarray,
    expr_key: jnp.ndarray,
    expr_op: jnp.ndarray,
    expr_vals: jnp.ndarray,
    expr_val_mask: jnp.ndarray,
) -> jnp.ndarray:
    """[p, E, n] bool: node satisfies each matchExpression (no padding
    handling — callers apply their expr masks)."""
    n_key = node_labels[..., 0]  # [n, Ln]
    n_val = node_labels[..., 1]

    # key presence per (p, e, n): any node label with matching key
    key_eq = (
        n_key[None, None, :, :] == expr_key[:, :, None, None]
    ) & node_label_mask[None, None, :, :]                      # [p, E, n, Ln]
    has_key = key_eq.any(-1)                                   # [p, E, n]

    # value match: node's value for the key is in the expression's set
    val_in_set = (
        n_val[None, None, :, :, None] == expr_vals[:, :, None, None, :]
    ) & expr_val_mask[:, :, None, None, :]                     # [p, E, n, Ln, V]
    key_val_match = (key_eq[..., None] & val_in_set).any((-1, -2))  # [p, E, n]

    op = expr_op[:, :, None]
    return jnp.where(
        op == OP_IN,
        key_val_match,
        jnp.where(
            op == OP_NOT_IN,
            ~key_val_match,
            jnp.where(op == OP_EXISTS, has_key, ~has_key),
        ),
    )  # [p, E, n]


def node_affinity_preference(
    node_labels: jnp.ndarray,
    node_label_mask: jnp.ndarray,
    expr_key: jnp.ndarray,
    expr_op: jnp.ndarray,
    expr_vals: jnp.ndarray,
    expr_val_mask: jnp.ndarray,
    expr_mask: jnp.ndarray,
    expr_weight: jnp.ndarray,
    expr_term: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """[p, n] float32: PREFERRED node-affinity scoring with upstream
    weighted-term semantics (preferredDuringSchedulingIgnoredDuring-
    Execution): each term is an AND-list of expressions sharing a group
    id, and its weight is granted ONCE iff every expression matches.

    expr_term: [p, E] int32 group ids in [0, E). None = each expression
    its own term (the single-expression-per-term common case, where
    per-expression and per-term weighting coincide).
    """
    ok = _expressions_satisfied(
        node_labels, node_label_mask, expr_key, expr_op, expr_vals, expr_val_mask
    )
    if expr_term is None:
        w = jnp.where(expr_mask, expr_weight.astype(jnp.float32), 0.0)  # [p, E]
        return (ok * w[:, :, None]).sum(1)  # [p, n]
    e = expr_key.shape[1]
    member = (
        expr_term[:, :, None] == jnp.arange(e)[None, None, :]
    ) & expr_mask[:, :, None]                                   # [p, E, G]
    fail = expr_mask[:, :, None] & ~ok                          # [p, E, n]
    group_fail = (
        jnp.einsum(
            "peg,pen->pgn",
            member.astype(jnp.float32),
            fail.astype(jnp.float32),
        )
        > 0
    )                                                           # [p, G, n]
    group_has = member.any(1)                                   # [p, G]
    # weights are per-term (identical across a group's expressions)
    group_w = jnp.where(
        member, expr_weight.astype(jnp.float32)[:, :, None], 0.0
    ).max(1)                                                    # [p, G]
    sat = group_has[:, :, None] & ~group_fail
    return (sat * group_w[:, :, None]).sum(1)                   # [p, n]


def pod_affinity_preference(
    domain_counts: jnp.ndarray,
    pref_affinity_sel: jnp.ndarray,
    pref_affinity_weight: jnp.ndarray,
    pref_anti_sel: jnp.ndarray,
    pref_anti_weight: jnp.ndarray,
) -> jnp.ndarray:
    """[p, n] float32: weighted preferred inter-pod (anti)affinity —
    upstream InterPodAffinity scoring: +weight for each preferred selector
    with a match in the node's topology domain, −weight for each preferred
    anti selector with a match. Selector ids are -1 padded; out-of-range
    ids contribute nothing (unlike the hard path, a stale preference must
    not make a pod unschedulable)."""
    s = domain_counts.shape[1]

    def term(sel, weight, sign):
        ok = (sel >= 0) & (sel < s)                            # [p, K]
        idx = jnp.clip(sel, 0, max(s - 1, 0))
        present = domain_counts[:, idx] > 0                    # [n, p, K]
        w = jnp.where(ok, weight.astype(jnp.float32), 0.0)     # [p, K]
        return sign * (present * w[None, :, :]).sum(-1).T      # [p, n]

    return term(pref_affinity_sel, pref_affinity_weight, 1.0) + term(
        pref_anti_sel, pref_anti_weight, -1.0
    )


def node_name_fit(target_node: jnp.ndarray, n: int) -> jnp.ndarray:
    """F[p, n] for spec.nodeName pinning (upstream NodeName filter):
    target_node[p] int32 — -1 unpinned (every node ok), an index pins to
    that node, any value >= n (the host's encoding for a pinned-but-absent
    node name) matches nothing and the pod surfaces as unschedulable."""
    cols = jnp.arange(n)[None, :]
    return (target_node[:, None] < 0) | (cols == target_node[:, None])


def topology_spread_fit(
    domain_counts: jnp.ndarray,
    node_mask: jnp.ndarray,
    spread_sel: jnp.ndarray,
    spread_max: jnp.ndarray,
) -> jnp.ndarray:
    """F[p, n]: hard topologySpreadConstraints (upstream PodTopologySpread,
    DoNotSchedule): placing the pod in node n's domain must keep
        count(domain, selector) + 1 − min over schedulable domains <= maxSkew
    for every constraint. domain_counts[n, s] are per-node-replicated domain
    totals, so the min over valid nodes equals the min over present domains.
    Selector ids are -1 padded; out-of-range ids are unsatisfiable (stale
    pod batch — same stance as pod_affinity_fit)."""
    s = domain_counts.shape[1]
    big = jnp.asarray(jnp.finfo(jnp.float32).max, jnp.float32)
    dmin = jnp.where(node_mask[:, None], domain_counts, big).min(0)  # [S]
    sel = jnp.clip(spread_sel, 0, max(s - 1, 0))                     # [p, K]
    skew = (
        domain_counts[:, sel] + 1.0 - dmin[sel][None, :, :]
    )                                                                # [n, p, K]
    ok = (skew <= spread_max[None, :, :]) | (spread_sel < 0)[None, :, :]
    valid = ~(spread_sel >= s).any(-1)                               # [p]
    return ok.all(-1).T & valid[:, None]


def pod_affinity_fit(
    domain_counts: jnp.ndarray,
    affinity_sel: jnp.ndarray,
    anti_affinity_sel: jnp.ndarray,
) -> jnp.ndarray:
    """F[p, n] from pre-aggregated topology-domain match counts.

    domain_counts:     [n, S] float32 — running pods matching selector s in
                       node n's topology domain (host-aggregated)
    affinity_sel:      [p, K] int32 selector indices, -1 padding; every
                       listed selector must have a match in the domain
    anti_affinity_sel: [p, K] int32; every listed selector must have zero
                       matches in the domain

    A selector id >= S is a host-side bug (pod batch built against a
    different snapshot's selector table). Rather than silently aliasing
    another selector's counts, such ids are treated as unsatisfiable: the
    pod becomes infeasible everywhere and surfaces as unschedulable.
    """
    s = domain_counts.shape[1]
    invalid_aff = affinity_sel >= s                          # [p, K]
    invalid_anti = anti_affinity_sel >= s
    aff = jnp.clip(affinity_sel, 0, max(s - 1, 0))
    aff_counts = domain_counts[:, aff]                       # [n, p, K]
    aff_ok = (aff_counts > 0) | (affinity_sel[None, :, :] < 0)
    anti = jnp.clip(anti_affinity_sel, 0, max(s - 1, 0))
    anti_counts = domain_counts[:, anti]
    anti_ok = (anti_counts == 0) | (anti_affinity_sel[None, :, :] < 0)
    valid = ~(invalid_aff.any(-1) | invalid_anti.any(-1))    # [p]
    return (aff_ok & anti_ok).all(-1).transpose() & valid[:, None]  # [p, n]
