"""Resource-axis layout shared by every kernel and snapshot builder.

The reference scores five canonical resources (pkg/yoda/scheduler.go:55:
cpu, memory, pods, storage, ephemeral-storage) plus arbitrary scalar
("extended") resources (pkg/yoda/score/algorithm.go:224-228). We lay these
out as one dense resource axis: slots [0, N_CANONICAL) are canonical, slots
[N_CANONICAL, N_CANONICAL + n_extended) are extended resources whose meaning
is assigned per-snapshot by the host layer.

Units follow the reference:
  - CPU is in millicores (schedutil returns milli-values for cpu),
  - memory / storage / ephemeral-storage in bytes,
  - pods is a count,
  - extended resources are opaque integer quantities.
"""

from __future__ import annotations

RES_CPU = 0
RES_MEMORY = 1
RES_PODS = 2
RES_STORAGE = 3
RES_EPHEMERAL_STORAGE = 4
N_CANONICAL = 5

CANONICAL_NAMES = ("cpu", "memory", "pods", "storage", "ephemeral-storage")

# Non-zero defaults applied when a container specifies no request, matching
# k8s scheduler util semantics used by the reference's request math
# (pkg/yoda/score/algorithm.go:238-262 via schedutil.GetNonzeroRequestForResource).
DEFAULT_MILLI_CPU_REQUEST = 100            # 0.1 core
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024  # 200 MB


def total_slots(n_extended: int) -> int:
    return N_CANONICAL + int(n_extended)
