"""Cluster-wide utilization statistics as device reductions.

Replaces the reference's two O(N) host loops with Redis round-trips
(pkg/yoda/score/algorithm.go:67-89: U_i/V_i per node, u_avg, M_tmp variance,
each value SET/GET through Redis) with masked mean/variance reductions that
run in one pass on device. In the sharded engine these become `psum`s over
the node-axis mesh dimension.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

# Normalization divisors hard-coded in the reference
# (pkg/yoda/score/algorithm.go:71: Ui = DiskIO / 50.0, :73: Vi = Cpu / 100.0).
DISK_IO_DIVISOR = 50.0
CPU_DIVISOR = 100.0


class UtilizationStats(NamedTuple):
    u: jnp.ndarray       # [n] disk-IO utilization, DiskIO / 50
    v: jnp.ndarray       # [n] CPU utilization, Cpu% / 100
    u_avg: jnp.ndarray   # [] masked mean of u
    m_var: jnp.ndarray   # [] masked population variance of u ("M_tmp")
    n_valid: jnp.ndarray  # [] number of valid (unpadded) nodes


def utilization_stats(
    disk_io: jnp.ndarray,
    cpu_pct: jnp.ndarray,
    node_mask: jnp.ndarray,
    *,
    disk_io_divisor: float = DISK_IO_DIVISOR,
    cpu_divisor: float = CPU_DIVISOR,
) -> UtilizationStats:
    """Compute U, V, u_avg and M_tmp over the valid nodes.

    disk_io:   [n] float, MB/s per node (advisor's DiskIO series)
    cpu_pct:   [n] float, CPU%% per node (advisor's Cpu series)
    node_mask: [n] bool, True for real nodes, False for padding
    """
    mask = node_mask.astype(disk_io.dtype)
    n_valid = jnp.maximum(mask.sum(), 1.0)
    u = disk_io / disk_io_divisor
    v = cpu_pct / cpu_divisor
    u_avg = (u * mask).sum() / n_valid
    m_var = (((u - u_avg) ** 2) * mask).sum() / n_valid
    return UtilizationStats(u=u, v=v, u_avg=u_avg, m_var=m_var, n_valid=n_valid)
