"""Pure-JAX kernels: the device-side compute path of the framework.

Every kernel is a pure function over dense, statically-shaped arrays with an
explicit validity mask for padding, so it composes with `jax.jit`, `jax.vmap`
and `shard_map` without data-dependent Python control flow.
"""

from kubernetes_scheduler_tpu.ops import resources
from kubernetes_scheduler_tpu.ops.stats import utilization_stats
from kubernetes_scheduler_tpu.ops.score import (
    balanced_cpu_diskio,
    balanced_diskio,
    free_capacity,
    card_score,
)
from kubernetes_scheduler_tpu.ops.normalize import min_max_normalize, softmax_normalize
from kubernetes_scheduler_tpu.ops.feasibility import resource_fit, card_fit
from kubernetes_scheduler_tpu.ops.collect import collect_max_card_values
from kubernetes_scheduler_tpu.ops.assign import greedy_assign
from kubernetes_scheduler_tpu.ops.constraints import (
    node_affinity_fit,
    pod_affinity_fit,
    taint_toleration_fit,
)
