"""Scoring-policy kernels: batched pod × node score matrices.

Each kernel vectorizes one of the reference's scoring policies over the full
pending-pod × node batch, replacing the per-(pod, node) plugin invocations
(pkg/yoda/scheduler.go:116-156) and the Redis memoization they require
(pkg/yoda/score/algorithm.go:57-63,116). All kernels:

  - take a `node_mask` for padding and return raw scores with padded entries
    left in place (callers mask before reductions / normalization);
  - are elementwise + broadcast over [pods, nodes] — XLA fuses the whole
    policy into a handful of HBM-bandwidth-bound loops, and on TPU the
    matrix layout keeps the lanes full.
"""

from __future__ import annotations

import jax.numpy as jnp

from kubernetes_scheduler_tpu.ops.stats import UtilizationStats

# Legacy per-metric weights from the reference's scoring constants
# (pkg/yoda/score/algorithm.go:24-35).
BANDWIDTH_WEIGHT = 1.0
CLOCK_WEIGHT = 1.0
CORE_WEIGHT = 2.0
POWER_WEIGHT = 1.0
FREE_MEMORY_WEIGHT = 3.0
TOTAL_MEMORY_WEIGHT = 1.0
ACTUAL_WEIGHT = 2.0
DISK_IO_WEIGHT = 100.0
ALLOCATE_WEIGHT = 3.0

# Raw score range of the live policy (pkg/yoda/score/algorithm.go:111).
MAX_RAW_SCORE = 10.0


def alpha_beta(r_cpu: jnp.ndarray, r_io: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(alpha[p], beta[p]) pod weights of the live policy
    (algorithm.go:105-106): beta = 1/(1 + Rcpu/Rio), alpha = 1 - beta.
    A missing/zero diskIO annotation reproduces the Go Rcpu/0 = +Inf limit
    (beta = 0, alpha = 1) explicitly. Shared by the unfused kernel below
    and the fused Pallas kernel (ops/pallas_fused.py) so the two paths
    cannot drift."""
    r_cpu = r_cpu.astype(jnp.float32)
    r_io = r_io.astype(jnp.float32)
    safe_io = jnp.where(r_io > 0, r_io, 1.0)
    beta = jnp.where(r_io > 0, 1.0 / (1.0 + r_cpu / safe_io), 0.0)
    return 1.0 - beta, beta


def balanced_cpu_diskio(
    stats: UtilizationStats,
    r_cpu: jnp.ndarray,
    r_io: jnp.ndarray,
    *,
    truncate: bool = False,
) -> jnp.ndarray:
    """The live policy: CPU/disk-IO load balancing.

    Vectorizes BalancedCpuDiskIOPriority (pkg/yoda/score/algorithm.go:99-119):
        beta  = 1 / (1 + Rcpu / Rio)
        alpha = 1 - beta
        L[p,n] = |alpha[p] * V[n] - beta[p] * U[n]|
        S[p,n] = 10 - 10 * L[p,n]

    r_cpu: [p] pod CPU request in millicores (algorithm.go:104)
    r_io:  [p] pod disk-IO demand from the `diskIO` annotation in MB/s
           (algorithm.go:103). A missing/unparsable annotation is 0 in the
           reference (strconv returns 0); Go then computes Rcpu/0 = +Inf so
           beta = 0, alpha = 1 — we reproduce that limit explicitly instead
           of relying on float division by zero.
    truncate: reproduce the reference's `uint64(Si)` floor quantization to
           11 integer levels (algorithm.go:113). Off by default: the batch
           engine keeps full precision and documents the deviation.

    Returns S[p, n] float32.
    """
    alpha, beta = alpha_beta(r_cpu, r_io)
    load = jnp.abs(
        alpha[:, None] * stats.v[None, :] - beta[:, None] * stats.u[None, :]
    )
    s = MAX_RAW_SCORE - MAX_RAW_SCORE * load
    if truncate:
        # uint64() in Go truncates toward zero; scores here are >= 0 whenever
        # load <= 1, and the reference never guards load > 1, so mirror a
        # plain floor on the non-negative branch and clamp the rest to 0
        # (uint64 of a negative float is undefined behavior in Go; observed
        # behavior on amd64 is saturation — we choose 0 and document it).
        s = jnp.where(s >= 0, jnp.floor(s), 0.0)
    return s


def balanced_diskio(
    stats: UtilizationStats,
    disk_io: jnp.ndarray,
    r_io: jnp.ndarray,
    node_mask: jnp.ndarray,
) -> jnp.ndarray:
    """Legacy variance-minimization policy (dead in the reference main path).

    Vectorizes BalancedDiskIOPriority (pkg/yoda/score/algorithm.go:121-176):
        Tj = Dj + Rio;  Fj = Tj / 100
        F_avg = u_avg - (Uj - Fj) / N
        Mj = M_tmp - ((Uj - u_avg)^2 - (Fj - F_avg)^2) / N
        S  = 100 - 100 * (Mj - M_min) / (M_max - M_min)

    Reference quirk reproduced deliberately: M_max/M_min are seeded with
    0 and 1e6 before the node loop (algorithm.go:122-123), so the min/max
    used for rescaling includes those sentinels whenever every Mj is
    positive (resp. below 1e6). Golden tests pin this behavior.

    disk_io: [n] MB/s; r_io: [p]; returns S[p, n] float32.
    """
    m = balanced_diskio_m(stats, disk_io, r_io)
    m_hi, m_lo = balanced_diskio_local_bounds(m, node_mask)
    return balanced_diskio_from_m(m, m_hi, m_lo)


def balanced_diskio_m(
    stats: UtilizationStats, disk_io: jnp.ndarray, r_io: jnp.ndarray
) -> jnp.ndarray:
    """The per-(pod, node) Mj statistic (algorithm.go:138-151). Split out so
    the sharded engine can compute it locally and reduce the bounds with
    pmax/pmin across node shards."""
    n = stats.n_valid
    t = disk_io[None, :] + r_io[:, None].astype(jnp.float32)  # [p,n]
    f = t / 100.0
    u = stats.u[None, :]
    f_avg = stats.u_avg - (u - f) / n
    return stats.m_var - ((u - stats.u_avg) ** 2 - (f - f_avg) ** 2) / n


def balanced_diskio_local_bounds(
    m: jnp.ndarray, node_mask: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(m_max, m_min) [p, 1] over valid local nodes, including the
    reference's sentinel seeds (algorithm.go:122-123: M_max starts at 0,
    M_min at 1e6)."""
    big = jnp.asarray(jnp.finfo(jnp.float32).max, jnp.float32)
    m_masked_max = jnp.where(node_mask[None, :], m, -big)
    m_masked_min = jnp.where(node_mask[None, :], m, big)
    m_max = jnp.maximum(m_masked_max.max(axis=1, keepdims=True), 0.0)
    m_min = jnp.minimum(m_masked_min.min(axis=1, keepdims=True), 1.0e6)
    return m_max, m_min


def balanced_diskio_from_m(
    m: jnp.ndarray, m_max: jnp.ndarray, m_min: jnp.ndarray
) -> jnp.ndarray:
    """Finish the policy: min-max rescale of Mj to [0, 100]
    (algorithm.go:163-172)."""
    denom = m_max - m_min
    safe = jnp.where(denom != 0, denom, 1.0)
    return 100.0 - 100.0 * (m - m_min) / safe


def free_capacity(
    cpu_pct: jnp.ndarray,
    mem_pct: jnp.ndarray,
    disk_io: jnp.ndarray,
    *,
    disk_io_weight: float = DISK_IO_WEIGHT,
    cpu_weight: float = CORE_WEIGHT,
    memory_weight: float = FREE_MEMORY_WEIGHT,
) -> jnp.ndarray:
    """Legacy weighted free-capacity policy.

    Vectorizes CalculateBasicScore2 (pkg/yoda/score/algorithm.go:178-198):
        S[n] = 100*(100 - floor(DiskIO)) + 2*(100 - Cpu) + 3*(100 - Memory)
    (the reference truncates DiskIO to int64 before subtracting,
    algorithm.go:189 — reproduced with floor). Pod-independent: returns
    S[n] float32; callers broadcast over the pod axis.
    """
    disk_score = disk_io_weight * (100.0 - jnp.floor(disk_io))
    cpu_score = cpu_weight * (100.0 - cpu_pct)
    mem_score = memory_weight * (100.0 - mem_pct)
    return disk_score + cpu_score + mem_score


def card_score(
    cards: jnp.ndarray,
    card_mask: jnp.ndarray,
    fits: jnp.ndarray,
    max_values: jnp.ndarray,
    *,
    reference_clock_bug: bool = False,
    integer_parity: bool = False,
) -> jnp.ndarray:
    """GPU-card scoring: per-card weighted normalized metrics, summed per node.

    Vectorizes the reference's commented-out GPU path
    (pkg/yoda/score/algorithm.go:264-291): each fitting card contributes
        bandwidth*100/max_bw * 1 + clock*100/max_clock * 1 + core*100/max_core * 2
        + power*100/max_power * 1 + free_mem*100/max_free * 3
        + total_mem*100/max_total * 1

    cards:      [n, c, 6] float32, metric order
                (bandwidth, clock, core, power, free_memory, total_memory)
    card_mask:  [n, c] bool, real cards
    fits:       [p, n, c] bool, per-pod card feasibility (see feasibility.card_fit)
    max_values: [p, 6] per-pod maxima over fitting cards, exactly the shape
                collect.collect_max_card_values returns (the reference
                recollects maxima per pod, collection.go:30-55)
    reference_clock_bug: the reference normalizes clock by MaxBandwidth
                (algorithm.go:283: `clock = card.Clock * 100 / value.MaxBandwidth`)
                — almost certainly a typo. Default False normalizes clock by
                max clock; set True for value-parity with the commented code.
    integer_parity: reproduce the Go path's uint arithmetic — each
                `metric * 100 / max` is integer (floor) division
                (algorithm.go:282-287) before weighting. Off by default.

    Returns S[p, n] float32.
    """
    weights = jnp.asarray(
        [
            BANDWIDTH_WEIGHT,
            CLOCK_WEIGHT,
            CORE_WEIGHT,
            POWER_WEIGHT,
            FREE_MEMORY_WEIGHT,
            TOTAL_MEMORY_WEIGHT,
        ],
        jnp.float32,
    )
    denom = max_values  # [p, 6]
    if reference_clock_bug:
        denom = denom.at[:, 1].set(max_values[:, 0])
    denom = jnp.maximum(denom, 1.0)
    if integer_parity:
        # Go uint division is exact; float32 `floor(a*100/b)` can land one
        # off when a*100/b is an exact integer. Metric values are integers
        # < 2^24, so int32 arithmetic reproduces the Go path bit-for-bit.
        ratio = (
            cards[None, :, :, :].astype(jnp.int32) * 100
            // denom[:, None, None, :].astype(jnp.int32)
        ).astype(jnp.float32)
    else:
        ratio = cards[None, :, :, :] * 100.0 / denom[:, None, None, :]  # [p,n,c,6]
    per_card = (ratio * weights).sum(-1)  # [p, n, c]
    valid = fits & card_mask[None, :, :]
    return (per_card * valid).sum(-1)


# ---- upstream default resource-shape scorers --------------------------------
# The reference's deployed config enables yoda WITHOUT disabling the
# kube-scheduler defaults (/root/reference/deploy/yoda-scheduler.yaml:21-47
# has no `disabled: [{name: "*"}]`), so its production score is the
# framework's weighted sum of yoda + the k8s 1.22 default score plugins
# (via /root/reference/go.mod:13). These kernels vectorize the three
# defaults this engine did not already carry as soft terms:
# NodeResourcesLeastAllocated, NodeResourcesBalancedAllocation, and
# ImageLocality. All produce [0, 100] like the framework's MaxNodeScore.

MAX_NODE_SCORE = 100.0
# ImageLocality thresholds (upstream pkg/scheduler/.../image_locality.go):
# per-container min/max image footprint the linear ramp runs between
IMAGE_MIN_THRESHOLD = 23.0 * 1024 * 1024
IMAGE_MAX_THRESHOLD = 1000.0 * 1024 * 1024


def least_allocated(
    allocatable: jnp.ndarray,
    requested: jnp.ndarray,
    pod_request: jnp.ndarray,
    *,
    resource_cols: tuple = (0, 1),
) -> jnp.ndarray:
    """NodeResourcesLeastAllocated (k8s 1.22 default, weight 1): prefer
    nodes with the most free share AFTER placing the pod.

        frame_r = (alloc_r - req_r - pod_r) * 100 / alloc_r
        S = sum_r w_r * frame_r / sum_r w_r        (w_r = 1 for cpu, memory)

    A resource with alloc == 0 or req+pod > alloc contributes 0 (the
    upstream guards). resource_cols picks the cpu/memory columns of the
    [.., r] matrices (the 1.22 default resource set). Returns S[p, n].
    """
    cols = jnp.asarray(resource_cols, jnp.int32)
    alloc = allocatable[:, cols]                       # [n, 2]
    req = requested[:, cols][None] + pod_request[:, cols][:, None]  # [p,n,2]
    free = alloc[None] - req
    frac = jnp.where(
        (alloc[None] > 0) & (free >= 0),
        free * MAX_NODE_SCORE / jnp.maximum(alloc[None], 1e-9),
        0.0,
    )
    return frac.mean(-1)


def balanced_allocation(
    allocatable: jnp.ndarray,
    requested: jnp.ndarray,
    pod_request: jnp.ndarray,
    *,
    resource_cols: tuple = (0, 1),
) -> jnp.ndarray:
    """NodeResourcesBalancedAllocation (k8s 1.22 default, weight 1):
    prefer nodes whose cpu and memory utilization FRACTIONS stay close
    after placing the pod.

        cpuF = (req_cpu + pod_cpu) / alloc_cpu ; memF likewise
        any fraction >= 1 (or alloc == 0)  ->  S = 0
        else S = (1 - |cpuF - memF|) * 100

    (The 1.22 two-resource formula; the volume fraction rides a
    default-off feature gate upstream.) Returns S[p, n].
    """
    cols = jnp.asarray(resource_cols, jnp.int32)
    alloc = allocatable[:, cols]                       # [n, 2]
    req = requested[:, cols][None] + pod_request[:, cols][:, None]  # [p,n,2]
    frac = req / jnp.maximum(alloc[None], 1e-9)
    ok = (alloc[None] > 0).all(-1) & (frac < 1.0).all(-1)  # [p, n]
    diff = jnp.abs(frac[..., 0] - frac[..., 1])
    return jnp.where(ok, (1.0 - diff) * MAX_NODE_SCORE, 0.0)


def image_locality(
    image_scaled: jnp.ndarray,
    image_ids: jnp.ndarray,
    n_containers: jnp.ndarray,
) -> jnp.ndarray:
    """ImageLocality (k8s 1.22 default, weight 1): prefer nodes already
    holding the pod's container images, discounted by how widely each
    image is spread (so a ubiquitous image doesn't pin placement).

    image_scaled: [n, V] float32 — host-precomputed
        present(n, v) * sizeBytes(n, v) * (nodes holding v) / (total nodes)
        (the upstream scaledImageScore, with the spread ratio resolved
        host-side so the kernel shards along the node axis with no
        collective)
    image_ids:    [p, Ki] int32 image-vocabulary ids, -1 padded
    n_containers: [p] int32 — the per-pod threshold scale: upstream ramps
        between 23MB and 1000MB PER CONTAINER

        S = clip((sum - 23MB*c) / (1000MB*c - 23MB*c), 0, 1) * 100

    Returns S[p, n].
    """
    v = image_scaled.shape[1]
    ids = jnp.clip(image_ids, 0, max(v - 1, 0))        # [p, Ki]
    got = image_scaled[:, ids]                         # [n, p, Ki]
    summed = (got * (image_ids >= 0)[None]).sum(-1).T  # [p, n]
    c = jnp.maximum(n_containers.astype(jnp.float32), 1.0)[:, None]
    lo = IMAGE_MIN_THRESHOLD * c
    hi = IMAGE_MAX_THRESHOLD * c
    return jnp.clip((summed - lo) / (hi - lo), 0.0, 1.0) * MAX_NODE_SCORE
