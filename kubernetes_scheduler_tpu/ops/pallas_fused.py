"""Fused Pallas TPU kernel: policy score + resource feasibility in one pass.

The hot [p, n] pipeline of the batch engine is HBM-bandwidth-bound: the live
policy score (ops/score.balanced_cpu_diskio, vectorizing
pkg/yoda/score/algorithm.go:99-119) and the NodeResourcesFit mask
(ops/feasibility.resource_fit, vectorizing algorithm.go:209-262) each stream
a [p, n]-shaped intermediate through HBM, and the assignment step reads both
to build `where(feasible, score, NEG)`. This kernel fuses all three into ONE
tiled pass: each (TILE_P, TILE_N) block loads the per-pod and per-node
vectors once into VMEM, evaluates score + fit on the VPU, and writes only
the final masked-score block — one [p, n] HBM write instead of three
[p, n] round-trips.

Layout: per-pod and per-node feature vectors are passed transposed —
[k, p] and [k, n] with the batch axis in lanes — so every block's last
dimension is the 128-aligned tile axis and the tiny feature axis (2-8 rows)
sits in sublanes. The [p, n] output tiles map directly onto the VPU's
(8, 128) native shape.

On non-TPU backends the same kernel runs through the Pallas interpreter
(tests) — semantics, including padding behavior, are identical.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from kubernetes_scheduler_tpu.ops.assign import NEG
from kubernetes_scheduler_tpu.ops.score import MAX_RAW_SCORE, alpha_beta

TILE_P = 256
TILE_N = 1024


def _fused_kernel(pod_sc_ref, node_ft_ref, pod_req_ref, alloc_ref, reqd_ref,
                  out_ref, *, n_res: int):
    """One (TILE_P, TILE_N) block of masked scores.

    pod_sc_ref:  [3, TILE_P]  rows = (alpha, beta, pod_mask)
    node_ft_ref: [3, TILE_N]  rows = (u, v, node_mask)
    pod_req_ref: [n_res, TILE_P]   pod requests, resource-major
    alloc_ref:   [n_res, TILE_N]   node allocatable
    reqd_ref:    [n_res, TILE_N]   node requested (non-zero defaults applied)
    out_ref:     [TILE_P, TILE_N]  score where feasible else NEG
    """
    alpha = pod_sc_ref[0, :][:, None]      # [TILE_P, 1]
    beta = pod_sc_ref[1, :][:, None]
    pmask = pod_sc_ref[2, :][:, None] > 0.0
    u = node_ft_ref[0, :][None, :]         # [1, TILE_N]
    v = node_ft_ref[1, :][None, :]
    nmask = node_ft_ref[2, :][None, :] > 0.0

    # BalancedCpuDiskIOPriority (algorithm.go:105-111), one VPU expression
    score = MAX_RAW_SCORE - MAX_RAW_SCORE * jnp.abs(alpha * v - beta * u)

    # NodeResourcesFit with the unrequested-resource bypass
    # (algorithm.go:211-215): static unroll over the small resource axis
    fit = pmask & nmask
    for i in range(n_res):
        req = pod_req_ref[i, :][:, None]       # [TILE_P, 1]
        ok = (reqd_ref[i, :][None, :] + req <= alloc_ref[i, :][None, :]) | (
            req == 0.0
        )
        fit = fit & ok

    out_ref[:, :] = jnp.where(fit, score, NEG)


def _pad_axis(x: jnp.ndarray, axis: int, to: int) -> jnp.ndarray:
    size = x.shape[axis]
    rem = (-size) % to
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad)


@functools.partial(
    jax.jit, static_argnames=("tile_p", "tile_n", "interpret")
)
def fused_masked_score(
    u: jnp.ndarray,
    v: jnp.ndarray,
    node_mask: jnp.ndarray,
    alloc: jnp.ndarray,
    reqd: jnp.ndarray,
    r_cpu: jnp.ndarray,
    r_io: jnp.ndarray,
    pod_request: jnp.ndarray,
    pod_mask: jnp.ndarray,
    *,
    tile_p: int = TILE_P,
    tile_n: int = TILE_N,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Masked score matrix [p, n]: balanced_cpu_diskio where the pod fits
    the node (resource_fit & node_mask & pod_mask), NEG elsewhere.

    u, v:        [n] utilization (disk_io/50, cpu/100 — ops/stats.py)
    node_mask:   [n] bool
    alloc, reqd: [n, r] float32
    r_cpu, r_io: [p] pod CPU request (milli) and diskIO annotation (MB/s)
    pod_request: [p, r] float32 with non-zero defaults
    pod_mask:    [p] bool

    Semantically identical to
        where(resource_fit(...) & masks, balanced_cpu_diskio(...), NEG)
    (pinned by tests/test_pallas.py); padded rows/cols return NEG.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    p, n = pod_request.shape[0], alloc.shape[0]
    n_res = alloc.shape[1]

    alpha, beta = alpha_beta(r_cpu, r_io)

    pod_sc = _pad_axis(
        jnp.stack([alpha, beta, pod_mask.astype(jnp.float32)]), 1, tile_p
    )
    node_ft = _pad_axis(
        jnp.stack(
            [
                u.astype(jnp.float32),
                v.astype(jnp.float32),
                node_mask.astype(jnp.float32),
            ]
        ),
        1,
        tile_n,
    )
    pod_req_t = _pad_axis(pod_request.astype(jnp.float32).T, 1, tile_p)
    alloc_t = _pad_axis(alloc.astype(jnp.float32).T, 1, tile_n)
    reqd_t = _pad_axis(reqd.astype(jnp.float32).T, 1, tile_n)

    pp, nn = pod_sc.shape[1], node_ft.shape[1]
    grid = (pp // tile_p, nn // tile_n)
    pod_side = lambda i, j: (0, i)  # noqa: E731 — block index, node-invariant
    node_side = lambda i, j: (0, j)  # noqa: E731

    out = pl.pallas_call(
        functools.partial(_fused_kernel, n_res=n_res),
        out_shape=jax.ShapeDtypeStruct((pp, nn), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((3, tile_p), pod_side),
            pl.BlockSpec((3, tile_n), node_side),
            pl.BlockSpec((n_res, tile_p), pod_side),
            pl.BlockSpec((n_res, tile_n), node_side),
            pl.BlockSpec((n_res, tile_n), node_side),
        ],
        out_specs=pl.BlockSpec((tile_p, tile_n), lambda i, j: (i, j)),
        interpret=interpret,
    )(pod_sc, node_ft, pod_req_t, alloc_t, reqd_t)
    return out[:p, :n]
