"""Fused Pallas TPU kernels: the resident device step as tiled VMEM passes.

The hot [p, n] pipeline of the batch engine is HBM-bandwidth-bound. Three
kernels keep it resident:

1. `fused_masked_score` — the masked-score MEGAKERNEL. The live policy
   score (ops/score.balanced_cpu_diskio, vectorizing
   pkg/yoda/score/algorithm.go:99-119), the NodeResourcesFit mask
   (ops/feasibility.resource_fit, algorithm.go:209-262), spec.nodeName
   pinning (ops/constraints.node_name_fit), the count-based inter-pod
   (anti)affinity / reverse-avoider / topology-spread families
   (ops/constraints.pod_affinity_fit, assign.anti_reverse_bad,
   constraints.topology_spread_fit — previously three separate [p, n]
   passes ANDed on top), the remaining externally-computed constraint
   mask (cards/taints/node-affinity as one `other` operand), and an
   optional min-max normalize epilogue all run in ONE tiled pass: each
   (TILE_P, TILE_N) block loads the per-pod and per-node vectors once
   into VMEM and writes only the final masked (optionally normalized)
   score block — one [p, n] HBM write instead of up to seven [p, n]
   round-trips.

2. `fused_score_row_stats` — the tiny companion pass feeding the min-max
   epilogue: per-pod (max, min) of the raw score over valid nodes,
   computed from the [k, p]/[k, n] feature vectors alone (NO [p, n] HBM
   traffic; the score is recomputed per tile on the VPU, which is free
   next to one HBM round-trip of the full matrix).

3. `fused_auction_bid` — the auction's inner-loop bid kernel
   (ops/assign.auction_assign): per round, capacity mask + price
   subtraction + row argmax in one pass over the precomputed masked
   score matrix. The XLA round body materializes a [p, n, r] capacity
   broadcast plus a [p, n] bid row every round; this kernel reads sj
   once per tile and writes only three [p]-shaped vectors. Tie
   semantics replicate jnp.argmax exactly (first index of the row
   maximum), so auction decisions are bitwise identical.

Layout: per-pod and per-node feature vectors are passed transposed —
[k, p] and [k, n] with the batch axis in lanes — so every block's last
dimension is the 128-aligned tile axis and the tiny feature axis sits in
sublanes. The [p, n] tiles map directly onto the VPU's (8, 128) native
shape.

On non-TPU backends the same kernels run through the Pallas interpreter
(tests) — semantics, including padding behavior, are identical.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from kubernetes_scheduler_tpu.ops.assign import NEG
from kubernetes_scheduler_tpu.ops.normalize import MAX_NODE_SCORE
from kubernetes_scheduler_tpu.ops.score import MAX_RAW_SCORE, alpha_beta

TILE_P = 256
TILE_N = 1024

# selector-axis ceiling for folding the count-based constraint families
# into the kernel: the per-selector mask work unrolls statically, so a
# pathologically wide selector table (bucketed powers of two beyond
# this) falls back to the outside [p, n] composition instead of
# exploding kernel size. 32 selectors = 128 pod-side + 96 node-side
# sublane rows, ~0.5 MB of extra VMEM at the default tiles.
MAX_FUSED_SELECTORS = 32

_F32_BIG = 3.4028235e38  # jnp.finfo(jnp.float32).max, as a literal


def _score_block(pod_sc_ref, node_ft_ref):
    """One block's raw score + masks from the feature rows: the live
    BalancedCpuDiskIOPriority expression (algorithm.go:105-111), shared
    by the megakernel and the row-stats pass so the two cannot drift."""
    alpha = pod_sc_ref[0, :][:, None]      # [TILE_P, 1]
    beta = pod_sc_ref[1, :][:, None]
    pmask = pod_sc_ref[2, :][:, None] > 0.0
    u = node_ft_ref[0, :][None, :]         # [1, TILE_N]
    v = node_ft_ref[1, :][None, :]
    nmask = node_ft_ref[2, :][None, :] > 0.0
    score = MAX_RAW_SCORE - MAX_RAW_SCORE * jnp.abs(alpha * v - beta * u)
    return score, pmask, nmask


def _fused_kernel(pod_sc_ref, node_ft_ref, pod_req_ref, alloc_ref, reqd_ref,
                  *refs, n_res: int, n_sel: int, has_other: bool,
                  minmax: bool, tile_n: int):
    """One (TILE_P, TILE_N) block of masked (optionally normalized) scores.

    pod_sc_ref:  [4, TILE_P]  rows = (alpha, beta, pod_ok, target_node)
                 pod_ok folds pod_mask AND the selector-validity bits
                 (stale selector ids make a pod infeasible everywhere)
    node_ft_ref: [3, TILE_N]  rows = (u, v, node_mask)
    pod_req_ref: [n_res, TILE_P]   pod requests, resource-major
    alloc_ref:   [n_res, TILE_N]   node allocatable
    reqd_ref:    [n_res, TILE_N]   node requested (non-zero defaults applied)
    then, in order, the optional refs:
    aff_pod_ref:  [4*n_sel, TILE_P] rows = required-selector one-hot,
                  anti one-hot, label-match one-hot, spread threshold
                  (min maxSkew per selector, +big when unconstrained)
    aff_node_ref: [3*n_sel, TILE_N] rows = domain presence, avoider
                  presence, count+1-dmin per selector
    other_ref:    [TILE_P, TILE_N] externally-computed constraint mask
                  (cards/taints/node-affinity; > 0 = feasible)
    stats_ref:    [2, TILE_P] per-pod (highest, lowest) raw-score bounds
                  for the min-max epilogue (ops/normalize semantics)
    out_ref:      [TILE_P, TILE_N] score where feasible else NEG
    """
    i = 0
    aff_pod_ref = aff_node_ref = None
    if n_sel:
        aff_pod_ref, aff_node_ref = refs[0], refs[1]
        i = 2
    other_ref = None
    if has_other:
        other_ref = refs[i]
        i += 1
    stats_ref = refs[i] if minmax else None
    out_ref = refs[-1]

    score, pmask, nmask = _score_block(pod_sc_ref, node_ft_ref)
    fit = pmask & nmask

    # NodeResourcesFit with the unrequested-resource bypass
    # (algorithm.go:211-215): static unroll over the small resource axis
    for r in range(n_res):
        req = pod_req_ref[r, :][:, None]       # [TILE_P, 1]
        ok = (reqd_ref[r, :][None, :] + req <= alloc_ref[r, :][None, :]) | (
            req == 0.0
        )
        fit = fit & ok

    # spec.nodeName pinning (constraints.node_name_fit): target < 0 is
    # unpinned; otherwise only the matching GLOBAL column passes. Both
    # sides are small exact integers, so the f32 compare is exact.
    tgt = pod_sc_ref[3, :][:, None]
    cols = (pl.program_id(1) * tile_n).astype(jnp.float32) + (
        jax.lax.broadcasted_iota(jnp.float32, (1, tile_n), 1)
    )
    fit = fit & ((tgt < 0.0) | (cols == tgt))

    # count-based families, one statically-unrolled pass per selector:
    # required presence, anti absence, reverse avoiders, spread skew —
    # boolean-equivalent to pod_affinity_fit & ~anti_reverse_bad &
    # topology_spread_fit (tests/test_pallas.py pins the identity)
    if n_sel:
        bad = None
        for s in range(n_sel):
            a = aff_pod_ref[s, :][:, None] > 0.0
            t = aff_pod_ref[n_sel + s, :][:, None] > 0.0
            mm = aff_pod_ref[2 * n_sel + s, :][:, None] > 0.0
            th = aff_pod_ref[3 * n_sel + s, :][:, None]
            pres = aff_node_ref[s, :][None, :] > 0.0
            avo = aff_node_ref[n_sel + s, :][None, :] > 0.0
            cplus = aff_node_ref[2 * n_sel + s, :][None, :]
            b = (a & ~pres) | (t & pres) | (mm & avo) | (cplus > th)
            bad = b if bad is None else (bad | b)
        fit = fit & ~bad

    if has_other:
        fit = fit & (other_ref[:, :] > 0.0)

    # min-max epilogue (ops/normalize.min_max_normalize over node_mask
    # bounds): same expression, so feasible cells are bitwise equal to
    # the unfused normalize pass
    if minmax:
        hi = stats_ref[0, :][:, None]
        lo = stats_ref[1, :][:, None]
        score = (score - lo) * MAX_NODE_SCORE / (hi - lo)

    out_ref[:, :] = jnp.where(fit, score, NEG)


def _row_stats_kernel(pod_sc_ref, node_ft_ref, out_ref):
    """Accumulate per-pod (max, min) of the raw score over valid nodes
    across the node-tile axis — the bounds feed for the min-max
    epilogue. out_ref [2, TILE_P] is revisited for every node tile of a
    pod tile (the index map drops j), initialized on the first."""
    score, _, nmask = _score_block(pod_sc_ref, node_ft_ref)
    hi = jnp.where(nmask, score, -_F32_BIG).max(axis=1)
    lo = jnp.where(nmask, score, _F32_BIG).min(axis=1)

    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[0, :] = hi
        out_ref[1, :] = lo

    @pl.when(pl.program_id(1) != 0)
    def _fold():
        out_ref[0, :] = jnp.maximum(out_ref[0, :], hi)
        out_ref[1, :] = jnp.minimum(out_ref[1, :], lo)


def _pad_axis(x: jnp.ndarray, axis: int, to: int) -> jnp.ndarray:
    size = x.shape[axis]
    rem = (-size) % to
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad)


def _pad2(x: jnp.ndarray, tile_p: int, tile_n: int, value=0.0) -> jnp.ndarray:
    """Pad a [p, n] matrix to tile multiples with a constant."""
    pp = -(-x.shape[0] // tile_p) * tile_p
    nn = -(-x.shape[1] // tile_n) * tile_n
    if (pp, nn) == x.shape:
        return x
    return jnp.pad(
        x, ((0, pp - x.shape[0]), (0, nn - x.shape[1])),
        constant_values=value,
    )


def prep_node_operands(u, v, node_mask, alloc, reqd, *, tile_n: int = TILE_N):
    """The node-side kernel-layout buffers (node_ft [3, nn], alloc_t and
    reqd_t [r, nn]) — ONE definition shared by the per-call prep below
    and engine.build_fused_layout, so the resident-layout path cannot
    drift from the re-pad path (PARITY round 12)."""
    node_ft = _pad_axis(
        jnp.stack(
            [
                u.astype(jnp.float32),
                v.astype(jnp.float32),
                node_mask.astype(jnp.float32),
            ]
        ),
        1,
        tile_n,
    )
    alloc_t = _pad_axis(alloc.astype(jnp.float32).T, 1, tile_n)
    reqd_t = prep_requested(reqd, tile_n=tile_n)
    return node_ft, alloc_t, reqd_t


def prep_requested(reqd, *, tile_n: int = TILE_N) -> jnp.ndarray:
    """reqd_t alone — the one kernel-layout leaf that changes along a
    windows scan's capacity carry. The multi-window scan rebuilds just
    this leaf per window and reuses the retained node_ft/alloc_t
    (engine.schedule_windows with a layout); sharing the expression with
    prep_node_operands keeps the carried layout bitwise the re-prep."""
    return _pad_axis(reqd.astype(jnp.float32).T, 1, tile_n)


@functools.partial(
    jax.jit,
    static_argnames=("tile_p", "tile_n", "interpret", "normalizer"),
)
def fused_masked_score(
    u: jnp.ndarray,
    v: jnp.ndarray,
    node_mask: jnp.ndarray,
    alloc: jnp.ndarray,
    reqd: jnp.ndarray,
    r_cpu: jnp.ndarray,
    r_io: jnp.ndarray,
    pod_request: jnp.ndarray,
    pod_mask: jnp.ndarray,
    *,
    target_node: jnp.ndarray | None = None,
    other: jnp.ndarray | None = None,
    aff_pod: jnp.ndarray | None = None,
    aff_node: jnp.ndarray | None = None,
    node_prepped: tuple | None = None,
    normalizer: str = "none",
    tile_p: int = TILE_P,
    tile_n: int = TILE_N,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Masked score matrix [p, n]: balanced_cpu_diskio where the pod fits
    the node (resource_fit & node_mask & pod_mask & every folded
    constraint family), NEG elsewhere.

    u, v:        [n] utilization (disk_io/50, cpu/100 — ops/stats.py)
    node_mask:   [n] bool
    alloc, reqd: [n, r] float32
    r_cpu, r_io: [p] pod CPU request (milli) and diskIO annotation (MB/s)
    pod_request: [p, r] float32 with non-zero defaults
    pod_mask:    [p] bool — callers fold selector-validity bits in here
    target_node: optional [p] int32 spec.nodeName pinning (-1 unpinned;
                 out-of-range matches nothing — constraints.node_name_fit)
    other:       optional [p, n] float32 externally-computed constraint
                 mask (> 0 feasible): cards/taints/node-affinity, and the
                 count-based families when the selector axis exceeds
                 MAX_FUSED_SELECTORS
    aff_pod:     optional [4*S, p] float32 pod-side selector rows (see
                 _fused_kernel); engine._fused_masked_scores builds them
    aff_node:    optional [3*S, n] float32 node-side selector rows
    node_prepped: optional prepped (node_ft, alloc_t, reqd_t) kernel-
                 layout buffers (engine.FusedLayout): resident cycles
                 ship deltas straight into these instead of re-deriving
                 the transpose/pad/stack every step
    normalizer:  "none" (raw masked scores) or "min_max" — the
                 ops/normalize.min_max_normalize epilogue applied in the
                 kernel, with row bounds from the fused_score_row_stats
                 pass; feasible cells are bitwise equal to the unfused
                 normalize-then-mask composition

    Semantically identical to the unfused op composition (pinned by
    tests/test_pallas.py); padded rows/cols return NEG.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if normalizer not in ("none", "min_max"):
        raise ValueError(
            f"fused kernel epilogue supports normalizer 'none' or "
            f"'min_max', not {normalizer!r}"
        )
    p, n = pod_request.shape[0], node_mask.shape[0]
    n_res = pod_request.shape[1]

    alpha, beta = alpha_beta(r_cpu, r_io)
    if target_node is None:
        target = jnp.full((p,), -1.0, jnp.float32)
    else:
        target = target_node.astype(jnp.float32)
    pod_sc = _pad_axis(
        jnp.stack([alpha, beta, pod_mask.astype(jnp.float32), target]),
        1, tile_p,
    )
    if node_prepped is not None:
        node_ft, alloc_t, reqd_t = node_prepped
        if node_ft.shape[1] % tile_n:
            raise ValueError(
                f"prepped node operands ({node_ft.shape[1]} cols) do not "
                f"tile by tile_n={tile_n}"
            )
    else:
        node_ft, alloc_t, reqd_t = prep_node_operands(
            u, v, node_mask, alloc, reqd, tile_n=tile_n
        )
    pod_req_t = _pad_axis(pod_request.astype(jnp.float32).T, 1, tile_p)

    pp, nn = pod_sc.shape[1], node_ft.shape[1]
    grid = (pp // tile_p, nn // tile_n)
    pod_side = lambda i, j: (0, i)  # noqa: E731 — block index, node-invariant
    node_side = lambda i, j: (0, j)  # noqa: E731

    n_sel = 0
    operands = [pod_sc, node_ft, pod_req_t, alloc_t, reqd_t]
    in_specs = [
        pl.BlockSpec((4, tile_p), pod_side),
        pl.BlockSpec((3, tile_n), node_side),
        pl.BlockSpec((n_res, tile_p), pod_side),
        pl.BlockSpec((n_res, tile_n), node_side),
        pl.BlockSpec((n_res, tile_n), node_side),
    ]
    if aff_pod is not None:
        n_sel = aff_pod.shape[0] // 4
        operands.append(_pad_axis(aff_pod.astype(jnp.float32), 1, tile_p))
        in_specs.append(pl.BlockSpec((4 * n_sel, tile_p), pod_side))
        operands.append(_pad_axis(aff_node.astype(jnp.float32), 1, tile_n))
        in_specs.append(pl.BlockSpec((3 * n_sel, tile_n), node_side))
    has_other = other is not None
    if has_other:
        operands.append(_pad2(other.astype(jnp.float32), tile_p, tile_n))
        in_specs.append(pl.BlockSpec((tile_p, tile_n), lambda i, j: (i, j)))
    minmax = normalizer == "min_max"
    if minmax:
        operands.append(
            fused_score_row_stats(
                pod_sc, node_ft, tile_p=tile_p, tile_n=tile_n,
                interpret=interpret,
            )
        )
        in_specs.append(pl.BlockSpec((2, tile_p), pod_side))

    out = pl.pallas_call(
        functools.partial(
            _fused_kernel, n_res=n_res, n_sel=n_sel, has_other=has_other,
            minmax=minmax, tile_n=tile_n,
        ),
        out_shape=jax.ShapeDtypeStruct((pp, nn), jnp.float32),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((tile_p, tile_n), lambda i, j: (i, j)),
        interpret=interpret,
    )(*operands)
    return out[:p, :n]


def fused_score_row_stats(
    pod_sc: jnp.ndarray,
    node_ft: jnp.ndarray,
    *,
    tile_p: int = TILE_P,
    tile_n: int = TILE_N,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """[2, pp] per-pod (highest, lowest) bounds of the raw score, with
    ops/normalize.score_bounds semantics (highest floored at 0, the
    hi==lo guard applied) — the min-max epilogue's stats feed. Operands
    are the already-prepped [4, pp]/[3, nn] feature blocks; the raw
    score is recomputed per tile and reduced in VMEM, so this pass
    reads/writes NO [p, n] HBM intermediate."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    pp, nn = pod_sc.shape[1], node_ft.shape[1]
    raw = pl.pallas_call(
        _row_stats_kernel,
        out_shape=jax.ShapeDtypeStruct((2, pp), jnp.float32),
        grid=(pp // tile_p, nn // tile_n),
        in_specs=[
            pl.BlockSpec((4, tile_p), lambda i, j: (0, i)),
            pl.BlockSpec((3, tile_n), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((2, tile_p), lambda i, j: (0, i)),
        interpret=interpret,
    )(pod_sc, node_ft)
    # ops/normalize.score_bounds + min_max_normalize's hi==lo guard —
    # tiny [pp] ops, kept OUTSIDE the kernels so the expression is the
    # shared normalize module's, line for line
    highest = jnp.maximum(raw[0], 0.0)
    lowest = jnp.where(highest == raw[1], raw[1] - 1.0, raw[1])
    return jnp.stack([highest, lowest])


def _greedy_kernel(sj_ref, req_ref, free0_ref, picks_ref, free_ref,
                   *, n_res: int, pp: int):
    """One pod step of the greedy scan: capacity mask + row argmax +
    the per-pod capacity decrement, with free capacity CARRIED in the
    revisited free_ref output block across grid steps — the scan's
    whole [n, r] free matrix stays in VMEM for the entire window
    instead of round-tripping HBM once per pod (the XLA scan body
    additionally materializes a [n, r] one-hot delta per step).

    sj_ref:    [1, NN] this pod's feasibility-masked scores (NEG where
               infeasible — pod_mask and `feasible` folded by the host)
    req_ref:   [1, R_pad] this pod's request row (resource axis padded
               to the lane tile; only the first n_res lanes are read)
    free0_ref: [n_res, NN] initial free capacity, resource-major
    picks_ref: [1, PP] int32 — pod i's chosen GLOBAL column, -1 = none
               (revisited; initialized on the first step)
    free_ref:  [n_res, NN] — the carried free capacity AND the final
               free_after output

    Tie semantics replicate jnp.argmax(row) exactly (first column of
    the row maximum); the capacity update subtracts only the chosen
    column, which is bitwise the XLA body's `free - onehot(choice)*req`
    (x - 0 == x for every non-chosen cell, and the chosen column sees
    the identical single subtraction).
    """
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        free_ref[...] = free0_ref[...]
        picks_ref[...] = jnp.full(picks_ref.shape, -1, jnp.int32)

    sj = sj_ref[...]                                       # [1, NN]
    mask = sj > NEG * 0.5
    for r in range(n_res):
        req = req_ref[0, r]
        mask = mask & (
            (req <= free_ref[r, :][None, :]) | (req == 0.0)
        )
    row = jnp.where(mask, sj, NEG)
    mx = row.max()
    found = mask.any()
    iota = jax.lax.broadcasted_iota(jnp.int32, row.shape, 1)
    choice = jnp.where(row == mx, iota, jnp.int32(2**31 - 1)).min()
    pick = jnp.where(found, choice, jnp.int32(-1))
    pods = jax.lax.broadcasted_iota(jnp.int32, (1, pp), 1)
    picks_ref[...] = jnp.where(pods == i, pick, picks_ref[...])
    upd = mask & (iota == choice) & found                  # [1, NN]
    free = free_ref[...]
    req_col = jnp.stack(
        [req_ref[0, r] for r in range(n_res)]
    )[:, None]                                             # [n_res, 1]
    free_ref[...] = jnp.where(upd, free - req_col, free)


def fused_greedy_scan(
    sj: jnp.ndarray,
    pod_request: jnp.ndarray,
    node_free: jnp.ndarray,
    *,
    tile_n: int = 128,
    interpret: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(picks [p] int32, free_after [n, r]) — the sequential greedy
    scan (ops/assign.greedy_assign's no-affinity body) as ONE Pallas
    kernel with the free-capacity carry resident in VMEM.

    sj:          [p, n] feasibility-masked scores IN SCAN ORDER (the
                 caller permutes by priority and un-permutes the picks,
                 exactly like the lax.scan body's order/ordering)
    pod_request: [p, r] requests in the same order
    node_free:   [n, r] initial free capacity

    Bitwise-identical picks and free_after to the XLA scan body (pinned
    in tests/test_pallas.py); like fused_auction_bid this is a TPU
    bandwidth optimization — the CPU interpreter path exists for
    parity tests, not speed."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    p, n = sj.shape
    n_res = pod_request.shape[1]
    sj_pad = _pad2(sj, tile_n, tile_n, value=NEG)
    pp, nn = sj_pad.shape
    req_rows = _pad_axis(pod_request.astype(jnp.float32), 1, tile_n)
    req_rows = _pad_axis(req_rows, 0, tile_n)
    free_t = _pad_axis(node_free.astype(jnp.float32).T, 1, tile_n)
    picks, free_after_t = pl.pallas_call(
        functools.partial(_greedy_kernel, n_res=n_res, pp=pp),
        out_shape=(
            jax.ShapeDtypeStruct((1, pp), jnp.int32),
            jax.ShapeDtypeStruct((n_res, nn), jnp.float32),
        ),
        grid=(pp,),
        in_specs=[
            pl.BlockSpec((1, nn), lambda i: (i, 0)),
            pl.BlockSpec((1, req_rows.shape[1]), lambda i: (i, 0)),
            pl.BlockSpec((n_res, nn), lambda i: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, pp), lambda i: (0, 0)),
            pl.BlockSpec((n_res, nn), lambda i: (0, 0)),
        ),
        interpret=interpret,
    )(sj_pad, req_rows, free_t)
    return picks[0, :p], free_after_t[:, :n].T


def _bid_kernel(sj_ref, price_ref, act_ref, req_ref, free_ref,
                bid_ref, has_ref, best_ref, *, n_res: int, tile_n: int):
    """One (TILE_P, TILE_N) block of one auction round's bidding:
    capacity mask + price-adjusted value + running row argmax.

    sj_ref:    [TILE_P, TILE_N] feasibility-masked jittered scores (NEG
               where infeasible — round-invariant, precomputed once)
    price_ref: [1, TILE_N] current node prices
    act_ref:   [1, TILE_P] active (unassigned, real) pods as float
    req_ref:   [n_res, TILE_P] pod requests, resource-major
    free_ref:  [n_res, TILE_N] current free capacity, resource-major
    bid_ref:   [1, TILE_P] int32 — running argmax (global column id)
    has_ref:   [1, TILE_P] int32 — running any-feasible-bid flag
    best_ref:  [1, TILE_P] f32 — running row maximum

    Tie semantics replicate jnp.argmax(row) exactly: within a block the
    FIRST column attaining the block max wins; across blocks a later
    block replaces the running best only when STRICTLY greater.
    """
    j = pl.program_id(1)
    sj = sj_ref[:, :]
    price = price_ref[0, :][None, :]
    act = act_ref[0, :][:, None] > 0.0
    cap_ok = act
    for r in range(n_res):
        req = req_ref[r, :][:, None]
        cap_ok = cap_ok & (
            (req <= free_ref[r, :][None, :]) | (req == 0.0)
        )
    mask = (sj > NEG * 0.5) & cap_ok
    row = jnp.where(mask, sj - price, NEG)
    blk_max = row.max(axis=1)                                  # [TILE_P]
    iota = jax.lax.broadcasted_iota(jnp.int32, row.shape, 1)
    blk_arg = jnp.where(
        row == blk_max[:, None], iota + j * tile_n, jnp.int32(2**31 - 1)
    ).min(axis=1)
    anyb = mask.any(axis=1).astype(jnp.int32)

    @pl.when(j == 0)
    def _init():
        best_ref[0, :] = blk_max
        bid_ref[0, :] = blk_arg
        has_ref[0, :] = anyb

    @pl.when(j != 0)
    def _fold():
        prev = best_ref[0, :]
        better = blk_max > prev
        best_ref[0, :] = jnp.where(better, blk_max, prev)
        bid_ref[0, :] = jnp.where(better, blk_arg, bid_ref[0, :])
        has_ref[0, :] = has_ref[0, :] | anyb


def fused_auction_bid(
    sj_padded: jnp.ndarray,
    price: jnp.ndarray,
    active: jnp.ndarray,
    req_t_padded: jnp.ndarray,
    free: jnp.ndarray,
    *,
    p: int,
    tile_p: int = TILE_P,
    tile_n: int = TILE_N,
    interpret: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(bid [p] int32, has_bid [p] bool) for one auction round — the
    fused equivalent of ops/assign's XLA round head

        mask = (sj > NEG/2) & cap_ok & active[:, None]
        bid  = argmax(where(mask, sj - price, NEG), axis=1)

    without materializing the [p, n, r] capacity broadcast or the
    [p, n] bid row in HBM (at 1k pods x 4k nodes x 7 resources those
    were ~130 MB of HBM traffic PER ROUND).

    sj_padded:    [pp, nn] round-invariant masked scores, NEG-padded
                  (hoisted out of the round loop by the caller)
    price:        [n] current prices
    active:       [p] bool — pod_mask & unassigned
    req_t_padded: [r, pp] resource-major requests (round-invariant)
    free:         [n, r] current free capacity
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    pp, nn = sj_padded.shape
    n_res = free.shape[1]
    price_p = _pad_axis(price.astype(jnp.float32)[None, :], 1, tile_n)
    act_p = _pad_axis(active.astype(jnp.float32)[None, :], 1, tile_p)
    free_t = _pad_axis(free.astype(jnp.float32).T, 1, tile_n)
    bid, has, _best = pl.pallas_call(
        functools.partial(_bid_kernel, n_res=n_res, tile_n=tile_n),
        out_shape=(
            jax.ShapeDtypeStruct((1, pp), jnp.int32),
            jax.ShapeDtypeStruct((1, pp), jnp.int32),
            jax.ShapeDtypeStruct((1, pp), jnp.float32),
        ),
        grid=(pp // tile_p, nn // tile_n),
        in_specs=[
            pl.BlockSpec((tile_p, tile_n), lambda i, j: (i, j)),
            pl.BlockSpec((1, tile_n), lambda i, j: (0, j)),
            pl.BlockSpec((1, tile_p), lambda i, j: (0, i)),
            pl.BlockSpec((n_res, tile_p), lambda i, j: (0, i)),
            pl.BlockSpec((n_res, tile_n), lambda i, j: (0, j)),
        ],
        out_specs=(
            pl.BlockSpec((1, tile_p), lambda i, j: (0, i)),
            pl.BlockSpec((1, tile_p), lambda i, j: (0, i)),
            pl.BlockSpec((1, tile_p), lambda i, j: (0, i)),
        ),
        interpret=interpret,
    )(sj_padded, price_p, act_p, req_t_padded, free_t)
    return bid[0, :p], has[0, :p] > 0
