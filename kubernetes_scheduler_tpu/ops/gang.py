"""Gang co-scheduling: all-or-nothing pod groups (arXiv:2511.08373's
constraint-based packing, the upstream coscheduling plugin's PodGroup).

A gang is a set of pods that must start together (distributed training
workers, MPI ranks): binding a strict subset wastes capacity on members
that will spin waiting for the rest. Pods declare membership with the
`scv/gang` + `scv/gang-size` labels (host.queue.pod_gang); the snapshot
builder threads them into PodBatch as

    gang_id    [p] int32  window-local gang slot, -1 = not in a gang
    gang_size  [p] int32  the gang's declared total member count

and `gang_mask_assign` post-processes an assigner's node_idx ON DEVICE:
a gang whose assigned-member count falls short of gang_size has every
assigned member's placement rescinded before the result leaves the
engine (engine.finish_cycle), so a partial gang can never reach the
host's bind loop — and the windows scan's capacity/affinity carries
never see phantom placements.

Masked entries use the sentinel encoding

    node_idx' = GANG_MASKED_BASE - node_idx      (<= -2)

instead of the plain -1 so the would-have node stays decodable: the op
gives the rescinded members' capacity back to free_after, and the host
counts rescinded placements (CycleMetrics.gang_pods_masked) without a
second result surface. Any consumer that only asks `idx >= 0` keeps
treating masked rows as unassigned.

The op is BITWISE the identity when the window carries no gang pods
(every select keeps the original lane), which is what pins the
gang-off <-> no-gangs-in-traffic parity in PARITY.md.
"""

from __future__ import annotations

import jax.numpy as jnp

# masked placements encode as GANG_MASKED_BASE - node_idx; -1 stays the
# plain "no node found" value, so decode is its own inverse
GANG_MASKED_BASE = -2


def decode_masked(idx):
    """The node a masked (<= -2) entry would have been assigned to.
    Works on numpy and jnp arrays alike (pure arithmetic)."""
    return GANG_MASKED_BASE - idx


def gang_mask_assign(
    gang_id,
    gang_size,
    pod_mask,
    node_idx,
    request,
    free_after,
    n_assigned,
):
    """All-or-nothing post-pass over an assigner's result.

    Returns (node_idx', free_after', n_assigned'): members of gangs with
    fewer than gang_size assigned members have their placements rescinded
    (sentinel-encoded), their request rows handed back to free_after,
    and n_assigned recomputed. Everything is a lane-wise select, so a
    gang-free window passes through bit-identical.
    """
    p = node_idx.shape[0]
    has = (gang_id >= 0) & pod_mask
    assigned = node_idx >= 0
    # assigned members per gang slot (slot space = window rows; pad slot
    # p absorbs non-members)
    slot = jnp.where(has & assigned, jnp.clip(gang_id, 0, p - 1), p)
    cnt = jnp.zeros(p + 1, jnp.int32).at[slot].add(1)
    complete = cnt[jnp.clip(gang_id, 0, max(p - 1, 0))] >= gang_size
    mask_out = has & assigned & ~complete
    new_idx = jnp.where(mask_out, GANG_MASKED_BASE - node_idx, node_idx)
    any_masked = mask_out.any()
    # capacity give-back: the assigner consumed the masked members'
    # requests; the next window (windows-scan carry) must not
    rows = jnp.where(mask_out, node_idx, free_after.shape[0])
    freed = jnp.zeros_like(free_after).at[rows].add(
        jnp.where(mask_out[:, None], request, 0.0), mode="drop"
    )
    free_after = jnp.where(any_masked, free_after + freed, free_after)
    n_assigned = jnp.where(
        any_masked,
        ((new_idx >= 0) & pod_mask).sum().astype(jnp.int32),
        n_assigned,
    )
    return new_idx, free_after, n_assigned


def mask_partial_gangs_np(gang_id, gang_size, node_idx):
    """Host (numpy) mirror of the all-or-nothing rule, applied as the
    unconditional backstop in host.scheduler._resolve_gangs: against a
    gang-capable engine it is the identity (the device op already
    rescinded the placements), against an old sidecar that never saw the
    gang tensors (bridge capability downgrade) it produces the same
    masked vector the device op would have — bitwise, so degraded mode
    keeps binding parity. Returns (node_idx', newly_masked_count)."""
    import numpy as np

    idx = np.asarray(node_idx).copy()
    gid = np.asarray(gang_id)
    gsz = np.asarray(gang_size)
    n = min(idx.shape[0], gid.shape[0])
    newly = 0
    for g in np.unique(gid[:n]):
        if g < 0:
            continue
        rows = np.flatnonzero(gid[:n] == g)
        got = idx[rows]
        cnt = int((got >= 0).sum())
        # PER-LANE size check, exactly like the device op's
        # `cnt[gang] >= gang_size` select: members declaring
        # inconsistent sizes (malformed labels) mask lane-wise, so the
        # mirror stays bitwise-equal on any input
        bad = rows[(got >= 0) & (cnt < gsz[rows])]
        idx[bad] = GANG_MASKED_BASE - idx[bad]
        newly += int(bad.size)
    return idx, newly
