"""Feasibility-mask kernels: boolean F[p, n] over the full batch.

The reference's Filter extension point passes every node (log-only,
pkg/yoda/scheduler.go:96-99), but its capability surface includes real
resource-fit math (pkg/yoda/score/algorithm.go:209-262, used for scoring)
and GPU-card predicates (pkg/yoda/filter/filter.go:11-58, the legacy SCV
path). Here both become batched mask tensors, which is what the upstream
NodeResourcesFit filter computes per (pod, node) — evaluated for the whole
batch in one pass.
"""

from __future__ import annotations

import jax.numpy as jnp


def resource_fit(
    allocatable: jnp.ndarray,
    requested: jnp.ndarray,
    pod_request: jnp.ndarray,
    node_mask: jnp.ndarray,
) -> jnp.ndarray:
    """NodeResourcesFit as one broadcast compare-and-reduce.

    allocatable: [n, r] per-node allocatable quantities (A)
    requested:   [n, r] per-node already-requested quantities (Q); callers
                 build this with non-zero defaults applied, mirroring
                 NonZeroRequested in the reference's
                 CalculateResourceAllocatableRequest (algorithm.go:219-221)
    pod_request: [p, r] per-pod requests (R) with non-zero defaults
    node_mask:   [n] bool

    A resource the pod does not request never excludes a node — this covers
    the reference's extended-resource bypass (algorithm.go:211-215: if the
    pod requests 0 of a scalar resource, the resource is skipped) and is a
    no-op for canonical resources (0 <= anything).

    Returns F[p, n] bool: requested + pod_request <= allocatable on every
    requested resource.
    """
    fits = requested[None, :, :] + pod_request[:, None, :] <= allocatable[None, :, :]
    fits = fits | (pod_request[:, None, :] == 0)
    return fits.all(-1) & node_mask[None, :]


def card_fit(
    cards: jnp.ndarray,
    card_mask: jnp.ndarray,
    card_healthy: jnp.ndarray,
    want_number: jnp.ndarray,
    want_memory: jnp.ndarray,
    want_clock: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """GPU-card feasibility, vectorizing pkg/yoda/filter/filter.go:11-58.

    cards:        [n, c, 6] metric order (bandwidth, clock, core, power,
                  free_memory, total_memory)
    card_mask:    [n, c] bool, real (non-padded) cards
    card_healthy: [n, c] bool, card.Health == "Healthy"
    want_number:  [p] int32, pod label `scv/number`; a pod with no GPU
                  demand at all encodes want_number=0 (fits every node); a
                  GPU pod without an explicit number label wants 1 card
                  (filter.go:15: absent label => (CardNumber > 0, 1), which
                  is exactly want_number=1)
    want_memory:  [p] pod label `scv/memory`; -1 = label absent
                  (unconstrained, filter.go:32). The reference gates on
                  label *presence*, not value: a present-but-"0" (or
                  unparsable, strToUint => 0) label demands FreeMemory >= 0
                  from want_number healthy cards — encode that as 0, not -1
    want_clock:   [p] pod label `scv/clock`; -1 = label absent
                  (filter.go:49). A present "0" demands Clock == 0, which
                  no real card has — the reference then rejects every node

    Per-card predicates (filter.go:52-58): a card satisfies the memory demand
    iff healthy AND free_memory >= want; satisfies the clock demand iff
    healthy AND clock == want. A node fits iff
        want_number <= card_number                 (PodFitsNumber)
        AND #cards fitting memory >= want_number   (PodFitsMemory)
        AND #cards fitting clock  >= want_number   (PodFitsClock).
    Pods with want_number == 0 fit every node (no GPU demand).

    Returns (node_fits[p, n] bool, per_card_fits[p, n, c] bool); the latter
    feeds card_score (a card contributes iff it meets both demands,
    algorithm.go:270-273).
    """
    free_mem = cards[..., 4]  # [n, c]
    clock = cards[..., 1]
    healthy = card_healthy & card_mask
    mem_unconstrained = want_memory < 0  # [p] label absent
    clock_unconstrained = want_clock < 0

    mem_ok = healthy[None, :, :] & (free_mem[None, :, :] >= want_memory[:, None, None])
    clock_ok = healthy[None, :, :] & (clock[None, :, :] == want_clock[:, None, None])

    card_number = card_mask.sum(-1)  # [n]
    n_mem = mem_ok.sum(-1)  # [p, n]
    n_clock = clock_ok.sum(-1)

    number_fits = want_number[:, None] <= card_number[None, :]
    mem_fits = mem_unconstrained[:, None] | (n_mem >= want_number[:, None])
    clock_fits = clock_unconstrained[:, None] | (n_clock >= want_number[:, None])
    no_gpu_demand = (want_number == 0)[:, None]

    node_fits = no_gpu_demand | (number_fits & mem_fits & clock_fits)

    # A card "fits the pod" for scoring/collection when it meets both
    # demands: FreeMemory >= memory AND Clock >= clock (algorithm.go:270-272,
    # collection.go:45-49). Unlike the filter predicates, the reference does
    # NOT check health here, and scoring uses Clock >= want where filtering
    # used == — both quirks reproduced (real cards only, via card_mask).
    # For absent labels the reference's PodFits* return 0 demands
    # (filter.go:32,49), so clamp the -1 sentinels to 0 here.
    score_mem = jnp.maximum(want_memory, 0)
    score_clock = jnp.maximum(want_clock, 0)
    per_card = (
        card_mask[None, :, :]
        & (free_mem[None, :, :] >= score_mem[:, None, None])
        & (clock[None, :, :] >= score_clock[:, None, None])
    )
    return node_fits, per_card
