"""Batched pod→node assignment with capacity accounting.

The upstream scheduler binds one pod per scheduling cycle, decrementing node
capacity in its in-memory snapshot between cycles; the reference plugin just
rides that loop (pkg/yoda/scheduler.go:116-196). The batch engine instead
assigns a whole window of pending pods in one device program:

- `greedy_assign`: exact sequential-greedy semantics — pods in priority
  order (sort.go:8-18: higher `scv/priority` first), each takes its
  best-scoring feasible node that still has capacity, capacity is
  decremented before the next pod. Implemented as `lax.scan` over the pod
  axis, so it is O(P·N·R) of pure vector work with no host round-trips —
  equivalent to P upstream cycles but without P× (snapshot + plugin fan-out
  + HTTP/Redis traffic).

- `auction_assign`: a parallel relaxation — rounds of simultaneous
  argmax bidding with conflict resolution by priority, useful when P is
  large and strict greedy order is not required. Converges to a
  capacity-respecting assignment in <= rounds iterations.

Both return -1 for pods that fit nowhere (upstream: unschedulable, requeued
with backoff — deploy/yoda-scheduler.yaml:19-20).
"""

from __future__ import annotations

import os
from typing import NamedTuple

import jax
import jax.numpy as jnp

NEG = -1.0e30

# auction bid-kernel routing (ops/pallas_fused.fused_auction_bid):
# "auto" engages the fused bid kernel on TPU backends for no-affinity
# auctions (where the XLA round head materializes a [p, n, r] capacity
# broadcast per round), "on"/"off" force it either way — the escape
# hatch for shapes where per-round kernel-launch overhead outweighs the
# saved HBM traffic. Read once at import (never inside a trace).
_BID_KERNEL_MODE = os.environ.get("YODA_AUCTION_BID_KERNEL", "auto")

# greedy scan-kernel routing (ops/pallas_fused.fused_greedy_scan): the
# same dial for the greedy assigner's per-pod capacity update — the
# scan body's [n, r] capacity broadcast + one-hot delta per pod step
# round-trips HBM P times; the kernel carries free capacity in VMEM
# for the whole window. Auto-gated to TPU backends like the bid kernel
# (the CPU interpreter keeps the XLA scan); no-affinity windows only.
_GREEDY_KERNEL_MODE = os.environ.get("YODA_GREEDY_KERNEL", "auto")

# element budgets for trading dense compare-and-reduce formulations
# against scatter forms (TPU scatters serialize per update, dense forms
# vectorize but cost O(elements) work); overridable in tests to pin
# dense/scatter parity without huge arrays
DENSE_EVICT_BUDGET = 1 << 25   # [p, q, S] same-domain tensor in eviction
DENSE_FOLD_BUDGET = 1 << 27    # [p, n, S] carry fold in the round body


class AssignResult(NamedTuple):
    node_idx: jnp.ndarray      # [p] int32, assigned node or -1
    free_after: jnp.ndarray    # [n, r] remaining free capacity
    n_assigned: jnp.ndarray    # [] int32


class AffinityState(NamedTuple):
    """Inter-pod (anti)affinity state threaded through greedy assignment.

    The upstream scheduler re-snapshots between single-pod cycles, so pod B
    sees pod A's placement; a batched window must reproduce that
    incrementally or hard anti-affinity can be violated inside the window.
    greedy_assign maintains a running per-(domain, selector) count of
    window placements on top of the host-provided base counts.

    domain_counts:     [n, S] base counts (running pods, host-aggregated)
    domain_id:         [n, S] int32 — node n's topology-domain id for
                       selector s, encoded as a representative node index
                       in [0, n) (first node of the domain), so the
                       in-window counts array can be statically shaped [n, S]
    pod_matches:       [p, S] bool — pending pod p's labels match selector s
    affinity_sel:      [p, K] int32, -1 padded
    anti_affinity_sel: [p, K] int32, -1 padded
    avoid_counts:      [n, S] base counts of running AVOIDERS — pods whose
                       required anti-affinity terms use selector s — in
                       node n's domain. Gates the REVERSE direction: an
                       incoming pod matching s may not join a domain
                       holding an avoider of s (upstream InterPodAffinity
                       checks existing pods' anti terms too)
    pod_has_anti:      [p, S] bool — one-hot of each pod's anti selectors
                       (so placing a pod updates in-window avoid counts)
    """

    domain_counts: jnp.ndarray
    domain_id: jnp.ndarray
    pod_matches: jnp.ndarray
    affinity_sel: jnp.ndarray
    anti_affinity_sel: jnp.ndarray
    avoid_counts: jnp.ndarray
    pod_has_anti: jnp.ndarray
    # hard topologySpreadConstraints (upstream PodTopologySpread) — also
    # count-based, so they share the live-count machinery:
    spread_sel: jnp.ndarray   # [p, Ks] int32 selector ids, -1 pad
    spread_max: jnp.ndarray   # [p, Ks] int32 maxSkew
    node_mask: jnp.ndarray    # [n] bool (for the min-over-domains term)


def tie_jitter(
    p: int, n: int, scale, col_offset=0, dtype=jnp.float32
) -> jnp.ndarray:
    """[p, n] deterministic sub-step tie-break jitter in [0, scale).

    A counter-based per-element hash of (row, GLOBAL column) rather than a
    stateful PRNG draw, so a node-sharded caller can materialize just its
    own columns (`col_offset` = shard offset) and get bit-identical values
    to the dense [p, n_global] matrix — the property the sharded auction's
    decision parity with the dense auction rests on. Magnitude << the
    price step keeps it decision-neutral except between genuine near-ties.
    """
    r = jnp.arange(p, dtype=jnp.uint32)[:, None]
    c = (jnp.arange(n, dtype=jnp.uint32) + jnp.uint32(col_offset))[None, :]
    x = r * jnp.uint32(0x9E3779B9) + c * jnp.uint32(0x85EBCA6B) + jnp.uint32(1)
    # final avalanche of a murmur3-style mixer: every (row, col) bit
    # diffuses into the mantissa bits we keep
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    u = (x >> 8).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))
    return u.astype(dtype) * scale


def pod_has_anti_onehot(anti_affinity_sel: jnp.ndarray, s: int) -> jnp.ndarray:
    """[p, S] bool one-hot union of each pod's anti selectors."""
    p = anti_affinity_sel.shape[0]
    tc = jnp.clip(anti_affinity_sel, 0, max(s - 1, 0))
    return (
        jnp.zeros((p, s), bool)
        .at[jnp.arange(p)[:, None], tc]
        .max(anti_affinity_sel >= 0)
    )


def affinity_ok_from_counts(
    cnt: jnp.ndarray, a_sel: jnp.ndarray, t_sel: jnp.ndarray
) -> jnp.ndarray:
    """[n] bool from live domain counts cnt[n, S] and one pod's selector
    lists a_sel/t_sel[K] (-1 padded; ids >= S are unsatisfiable, see
    constraints.pod_affinity_fit)."""
    s = cnt.shape[1]
    a = jnp.clip(a_sel, 0, max(s - 1, 0))
    t = jnp.clip(t_sel, 0, max(s - 1, 0))
    aff_ok = ((cnt[:, a] > 0) | (a_sel[None, :] < 0)).all(-1)   # [n]
    anti_ok = ((cnt[:, t] == 0) | (t_sel[None, :] < 0)).all(-1)
    valid = ~((a_sel >= s).any() | (t_sel >= s).any())
    return aff_ok & anti_ok & valid


def spread_ok_from_counts(
    cnt: jnp.ndarray,
    node_mask: jnp.ndarray,
    spread_sel: jnp.ndarray,
    spread_max: jnp.ndarray,
) -> jnp.ndarray:
    """[n] bool: one pod's hard spread constraints hold on each node given
    live counts cnt[n, S]: count + 1 − min over schedulable domains <=
    maxSkew (ops/constraints.topology_spread_fit against live counts)."""
    s = cnt.shape[1]
    big = jnp.asarray(jnp.finfo(jnp.float32).max, jnp.float32)
    dmin = jnp.where(node_mask[:, None], cnt, big).min(0)         # [S]
    sel = jnp.clip(spread_sel, 0, max(s - 1, 0))                  # [K]
    skew = cnt[:, sel] + 1.0 - dmin[sel][None, :]                 # [n, K]
    ok = (skew <= spread_max[None, :]) | (spread_sel < 0)[None, :]
    valid = ~(spread_sel >= s).any()
    return ok.all(-1) & valid


def spread_ok_batched(
    cnt: jnp.ndarray,
    node_mask: jnp.ndarray,
    spread_sel: jnp.ndarray,
    spread_max: jnp.ndarray,
    dmin: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """[p, n] bool batched spread_ok_from_counts (spread_sel/max [p, K]).

    dmin: optional precomputed [S] per-selector minimum domain count over
    schedulable nodes — a node-sharded caller passes the GLOBAL (pmin'd)
    minimum; default computes it from the local cnt."""
    s = cnt.shape[1]
    big = jnp.asarray(jnp.finfo(jnp.float32).max, jnp.float32)
    if dmin is None:
        dmin = jnp.where(node_mask[:, None], cnt, big).min(0)     # [S]
    sel = jnp.clip(spread_sel, 0, max(s - 1, 0))                  # [p, K]
    skew = cnt[:, sel] + 1.0 - dmin[sel][None, :, :]              # [n, p, K]
    ok = (skew <= spread_max[None, :, :]) | (spread_sel < 0)[None, :, :]
    valid = ~(spread_sel >= s).any(-1)                            # [p]
    return ok.all(-1).T & valid[:, None]


def anti_reverse_ok(avoid_cnt: jnp.ndarray, matches: jnp.ndarray) -> jnp.ndarray:
    """[n] bool: node's domain holds no avoider of any selector the
    incoming pod matches. avoid_cnt[n, S] live avoider counts, matches[S]."""
    return ~((avoid_cnt > 0) & matches[None, :]).any(-1)


def anti_reverse_bad(matches: jnp.ndarray, avoid_cnt: jnp.ndarray) -> jnp.ndarray:
    """[p, n] bool: batched complement of anti_reverse_ok — pod p matches a
    selector some avoider holds in node n's domain. matches[p, S] bool,
    avoid_cnt[n, S] avoider counts. One small matmul over the selector
    axis."""
    return (
        matches.astype(jnp.float32) @ (avoid_cnt > 0).astype(jnp.float32).T
    ) > 0


def _affinity_row_ok(
    aff: AffinityState, added: jnp.ndarray, added_avoid: jnp.ndarray,
    i: jnp.ndarray,
) -> jnp.ndarray:
    """[n] bool: does every (anti)affinity constraint of pod i — its own
    selectors AND existing avoiders' reverse terms — hold on each node,
    counting both pre-existing and in-window placements."""
    s = aff.domain_counts.shape[1]
    cols = jnp.arange(s)[None, :]
    cnt = aff.domain_counts + added[aff.domain_id, cols]     # [n, S]
    own = affinity_ok_from_counts(cnt, aff.affinity_sel[i], aff.anti_affinity_sel[i])
    avoid_cnt = aff.avoid_counts + added_avoid[aff.domain_id, cols]
    return (
        own
        & anti_reverse_ok(avoid_cnt, aff.pod_matches[i])
        & spread_ok_from_counts(
            cnt, aff.node_mask, aff.spread_sel[i], aff.spread_max[i]
        )
    )


def _affinity_update(
    aff: AffinityState, added: jnp.ndarray, added_avoid: jnp.ndarray,
    i: jnp.ndarray, choice: jnp.ndarray, found: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Record pod i's placement on node `choice` into the in-window match
    and avoider counts."""
    s = aff.domain_counts.shape[1]
    cols = jnp.arange(s)
    inc = jnp.where(found, aff.pod_matches[i].astype(added.dtype), 0.0)
    inc_a = jnp.where(found, aff.pod_has_anti[i].astype(added.dtype), 0.0)
    return (
        added.at[aff.domain_id[choice], cols].add(inc),
        added_avoid.at[aff.domain_id[choice], cols].add(inc_a),
    )


def _priority_order(priority: jnp.ndarray, pod_mask: jnp.ndarray) -> jnp.ndarray:
    """Stable order: valid pods by descending priority, padding last.

    Mirrors sort.Less (pkg/yoda/sort/sort.go:8-10): higher `scv/priority`
    label schedules first; ties keep queue (index) order.
    """
    key = jnp.where(pod_mask, priority.astype(jnp.int32), jnp.int32(-(2**31) + 1))
    return jnp.argsort(-key, stable=True)


def greedy_assign(
    scores: jnp.ndarray,
    feasible: jnp.ndarray,
    pod_request: jnp.ndarray,
    node_free: jnp.ndarray,
    priority: jnp.ndarray,
    pod_mask: jnp.ndarray,
    affinity: AffinityState | None = None,
    greedy_kernel: bool | None = None,
) -> AssignResult:
    """Sequential-greedy assignment as a lax.scan.

    scores:      [p, n] (higher better; padded nodes may hold junk — they
                 are excluded via `feasible`)
    feasible:    [p, n] bool — all filter masks ANDed, False on padding
    pod_request: [p, r] requests with non-zero defaults
    node_free:   [n, r] free capacity (allocatable - requested)
    priority:    [p] int priority (sort.go semantics)
    pod_mask:    [p] bool

    greedy_kernel routes the no-affinity scan through the fused Pallas
    step kernel (ops/pallas_fused.fused_greedy_scan): the free-capacity
    carry stays resident in VMEM for the whole window instead of the
    scan body's per-step [n, r] HBM round-trip + one-hot delta.
    Decisions and free_after are bitwise identical (first-max ties —
    pinned in tests/test_pallas.py). None = auto (TPU backends only;
    YODA_GREEDY_KERNEL=on/off overrides). Affinity windows keep the
    XLA scan: their per-step masks depend on carried [n, S] count
    state the kernel does not fold.
    """
    order = _priority_order(priority, pod_mask)
    p = scores.shape[0]
    if greedy_kernel is None:
        greedy_kernel = _GREEDY_KERNEL_MODE == "on" or (
            _GREEDY_KERNEL_MODE == "auto" and jax.default_backend() == "tpu"
        )
    if greedy_kernel and affinity is None:
        from kubernetes_scheduler_tpu.ops.pallas_fused import (
            fused_greedy_scan,
        )

        sj = jnp.where(feasible & pod_mask[:, None], scores, NEG)
        picks, free_after = fused_greedy_scan(
            sj[order], pod_request[order].astype(jnp.float32), node_free
        )
        node_idx = jnp.full((p,), -1, jnp.int32).at[order].set(picks)
        return AssignResult(
            node_idx=node_idx,
            free_after=free_after.astype(node_free.dtype),
            n_assigned=(node_idx >= 0).sum().astype(jnp.int32),
        )
    added0 = (
        None if affinity is None else jnp.zeros_like(affinity.domain_counts)
    )
    added_avoid0 = (
        None if affinity is None else jnp.zeros_like(affinity.domain_counts)
    )

    def step(carry, i):
        free, added, added_avoid = carry
        req = pod_request[i]                      # [r]
        # Unrequested resources never exclude a node, matching
        # feasibility.resource_fit's extended-resource bypass
        # (algorithm.go:211-215) even when a slot is oversubscribed.
        cap_ok = ((req[None, :] <= free) | (req[None, :] == 0)).all(-1)  # [n]
        mask = feasible[i] & cap_ok & pod_mask[i]
        if affinity is not None:
            mask = mask & _affinity_row_ok(affinity, added, added_avoid, i)
        row = jnp.where(mask, scores[i], NEG)
        choice = jnp.argmax(row)
        found = mask.any()
        delta = jnp.zeros_like(free).at[choice].set(req)
        free = jnp.where(found, free - delta, free)
        if affinity is not None:
            added, added_avoid = _affinity_update(
                affinity, added, added_avoid, i, choice, found
            )
        return (free, added, added_avoid), jnp.where(
            found, choice.astype(jnp.int32), jnp.int32(-1)
        )

    (free_after, _, _), picks = jax.lax.scan(
        step, (node_free, added0, added_avoid0), order
    )
    node_idx = jnp.full((p,), -1, jnp.int32).at[order].set(picks)
    return AssignResult(
        node_idx=node_idx,
        free_after=free_after,
        n_assigned=(node_idx >= 0).sum().astype(jnp.int32),
    )


def _segmented_admission(
    bid: jnp.ndarray,
    has_bid: jnp.ndarray,
    pod_request: jnp.ndarray,
    free: jnp.ndarray,
    by_prio: jnp.ndarray,
) -> jnp.ndarray:
    """[p] bool: per node, admit bidders in (priority desc, index asc)
    order while the cumulative request including self fits the node's
    free capacity.

    O(p·log p + p·r): sort bidders by (node, -priority), segmented
    prefix-sum of requests within each node's group, compare against that
    node's capacity — no [p, n, r] intermediate.

    `by_prio` is the priority-descending (stable) pod order, computed
    ONCE outside the auction loop: device sorts are the expensive part of
    a round (a [p] sort lowers to ~log^2 p sorting-network passes), and
    priority never changes between rounds, so the only per-round sort is
    the node grouping — non-bidders are keyed past the last node instead
    of masked into the priority key.
    """
    p = bid.shape[0]
    n = free.shape[0]
    has_s = has_bid[by_prio]
    bid_p = jnp.where(has_s, bid[by_prio], jnp.int32(n))         # [p]
    by_node = jnp.argsort(bid_p, stable=True)
    order = by_prio[by_node]                                     # [p]
    bid_s = bid_p[by_node]
    req_s = jnp.where(has_bid[order][:, None], pod_request[order], 0.0)
    total = jnp.cumsum(req_s, axis=0)                            # [p, r]
    # segment start: running max of indices where the node id changes
    idx = jnp.arange(p)
    boundary = jnp.concatenate(
        [jnp.ones((1,), bool), bid_s[1:] != bid_s[:-1]]
    )
    start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(boundary, idx, 0)
    )                                                            # [p]
    base = jnp.where(
        (start > 0)[:, None], total[jnp.maximum(start - 1, 0)], 0.0
    )
    cum = total - base                                           # [p, r] incl. self
    cap = free[jnp.minimum(bid_s, n - 1)]                        # [p, r]
    # unrequested-resource bypass (cum==0 -> no admitted bidder needs it)
    fits = ((cum <= cap) | (cum == 0)).all(-1) & has_bid[order]
    return jnp.zeros((p,), bool).at[order].set(fits)


def _affinity_round_mask(
    aff: AffinityState,
    added: jnp.ndarray,
    added_avoid: jnp.ndarray,
    dmin: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """[p, n] bool: every (anti)affinity constraint of each pod — own
    selectors and existing avoiders' reverse terms — holds on each node
    against live counts (base + in-window). Batched _affinity_row_ok.

    `added`/`added_avoid` are per-node EXPANDED [n, S] tables (every
    member of a domain holds the domain's in-window total, matching the
    layout of snapshot.domain_counts itself), so live counts are a plain
    add — no representative-row gather.

    MXU formulation: presence is binarized at the tiny [n, S] count table
    and each pod's required/forbidden selector SET becomes a one-hot row,
    so the per-round [p, n] masks are two [p, S] x [S, n] matmuls instead
    of [n, p, K] gathers — the gathers were the dominant HBM traffic of
    the auction's dynamic-affinity rounds (~5x the static path at
    5k pods x 5k nodes). The one-hot operands are round-invariant; XLA's
    loop-invariant code motion hoists them out of the while_loop."""
    s = aff.domain_counts.shape[1]
    cnt = aff.domain_counts + added                                # [n, S]
    present = (cnt > 0).astype(jnp.float32)                       # [n, S]
    # required selectors: ALL present <=> presence count reaches the
    # pod's distinct-required count (one-hot union handles -1 padding
    # and duplicate ids identically to the gathered all())
    a_hot = pod_has_anti_onehot(aff.affinity_sel, s).astype(jnp.float32)
    n_req = a_hot.sum(-1, keepdims=True)                          # [p, 1]
    aff_ok = (a_hot @ present.T) >= n_req                         # [p, n]
    # forbidden selectors: ANY present violates
    t_hot = aff.pod_has_anti.astype(jnp.float32)
    anti_ok = (t_hot @ present.T) == 0.0                          # [p, n]
    valid = ~(
        (aff.affinity_sel >= s).any(-1) | (aff.anti_affinity_sel >= s).any(-1)
    )                                                              # [p]
    avoid_cnt = aff.avoid_counts + added_avoid
    rev_bad = anti_reverse_bad(aff.pod_matches, avoid_cnt)         # [p, n]
    spread = spread_ok_batched(
        cnt, aff.node_mask, aff.spread_sel, aff.spread_max, dmin=dmin
    )
    return aff_ok & anti_ok & valid[:, None] & ~rev_bad & spread


def _evict_conflicts_core(
    pod_matches: jnp.ndarray,
    anti_affinity_sel: jnp.ndarray,
    pod_has_anti: jnp.ndarray,
    spread_sel: jnp.ndarray,
    spread_max: jnp.ndarray,
    admitted: jnp.ndarray,
    dom_p: jnp.ndarray,
    prio_key: jnp.ndarray,
    base_at_bid: jnp.ndarray,
    added_at_bid: jnp.ndarray,
    dmin: jnp.ndarray,
    table_rows: int,
) -> jnp.ndarray:
    """[p] bool: admitted pods whose hard anti-affinity or spread skew is
    violated by OTHER same-round placements, minus one survivor per
    conflict group. Pure per-pod/replicated inputs — shared by the dense
    wrapper (_evict_round_conflicts) and the node-sharded auction, whose
    node-side state lives on other shards: a sharded caller psum-broadcasts
    the bid-node lookups (dom_p, base_at_bid, added_at_bid) and the global
    dmin, then runs this identically on every shard.

    dom_p:        [p, S] domain rep ids of each pod's bid node, in
                  [0, table_rows)
    base_at_bid:  [p, S] base (pre-window) domain counts at the bid node
    added_at_bid: [p, S] prior-round in-window totals of the bid domain
    dmin:         [S] min live (base + prior-round) count over schedulable
                  nodes — GLOBAL under sharding
    table_rows:   row count of the scatter-form aggregation tables

    The pre-bid mask guarantees no violation against base + previous
    rounds; only pods admitted in the SAME round can conflict. A pod p
    (anti selector t, placed in domain d) survives iff every matcher of t
    placed in d this round was itself an avoider of t and p holds the
    group's (priority desc, index asc) maximum — the spread-pods pattern
    (self-anti-affinity) keeps exactly one per domain, and a non-avoider
    matcher (not violated itself, so permanently placed) forces every
    avoider out. Evicted pods re-bid next round against counts that now
    include the survivors, so their masks strictly shrink — no livelock.
    """
    p, s = pod_matches.shape
    cols = jnp.arange(s)[None, :]
    contrib = jnp.where(
        admitted[:, None], pod_matches.astype(jnp.float32), 0.0
    )
    # No [n, S] scatters in here: TPU scatters serialize per update, and
    # four of them per auction round were ~45% of the constraint-config
    # backlog time. Per-(domain, selector) aggregates go through a dense
    # same-domain tensor when the window is small enough (a few MXU/VPU
    # passes), the scatter form otherwise.
    use_dense = p * p * s <= DENSE_EVICT_BUDGET
    if use_dense:
        same = dom_p[:, None, :] == dom_p[None, :, :]              # [p, q, S]
        samef = same.astype(jnp.float32)
        cnt_incl = jnp.einsum("pqs,qs->ps", samef, contrib)        # [p, S]
    else:
        adds = (
            jnp.zeros((table_rows, s), jnp.float32)
            .at[dom_p, cols]
            .add(contrib)
        )
        cnt_incl = adds[dom_p, cols]
    cnt_other = cnt_incl - contrib                                 # [p, S]

    t_sel = anti_affinity_sel                                      # [p, K]
    tc = jnp.clip(t_sel, 0, max(s - 1, 0))
    has_anti = pod_has_anti                                        # [p, S]
    viol_t = (t_sel >= 0) & (
        jnp.take_along_axis(cnt_other, tc, axis=1) > 0
    ) & admitted[:, None]                                          # [p, K]

    # non-avoider matchers: permanent this round; their presence hard-blocks
    contrib_nv = jnp.where(
        (admitted[:, None] & pod_matches & ~has_anti), 1.0, 0.0
    )
    if use_dense:
        blocked_full = jnp.einsum("pqs,qs->ps", samef, contrib_nv) > 0
    else:
        adds_nv = jnp.zeros((table_rows, s), jnp.float32).at[dom_p, cols].add(
            contrib_nv
        )
        blocked_full = adds_nv[dom_p, cols] > 0
    hard_blocked_t = jnp.take_along_axis(blocked_full, tc, axis=1)

    # avoider-matcher groups: keep the (priority desc, index asc) max.
    # prio_key = p - rank in priority order: always in [1, p], exact in
    # int32 (a direct (priority+1)*p - i encoding overflows int32 / loses
    # precision under a float cast for large p x priority, and goes
    # non-positive for negative priority labels). Computed ONCE outside
    # the auction loop — the rank argsort is round-invariant and device
    # sorts inside a while_loop were the auction's dominant round cost.
    key = prio_key                                                 # [p]
    member = admitted[:, None] & has_anti & pod_matches            # [p, S]
    keyf = jnp.where(member, key[:, None], 0)
    if use_dense:
        gmax_at = jnp.max(jnp.where(same, keyf[None, :, :], 0), axis=1)
    else:
        gmax = (
            jnp.zeros((table_rows, s), jnp.int32)
            .at[dom_p, cols]
            .max(keyf)
        )
        gmax_at = gmax[dom_p, cols]
    keep_s = member & (keyf == gmax_at)                            # [p, S]
    keep_t = jnp.take_along_axis(keep_s, tc, axis=1)               # [p, K]

    survive_t = keep_t & ~hard_blocked_t
    evict = (viol_t & ~survive_t).any(-1)                          # [p]

    # same-round SPREAD conflicts: each bid passed the pre-round skew mask,
    # but joint placements into one domain can exceed maxSkew together.
    # Keep the (priority desc, index asc) max among this round's admitted
    # CONTRIBUTORS (pods matching the selector and carrying the
    # constraint) per (domain, selector); everyone else violated re-bids
    # against counts that include the survivors — masks shrink, no
    # livelock. Violated non-contributors always re-bid (keeping them
    # blocks nothing).
    sp_sel = spread_sel                                            # [p, Kc]
    spc = jnp.clip(sp_sel, 0, max(s - 1, 0))
    # dmin from base + prior-round carry only (this round's adds can only
    # RAISE counts, so omitting them under-estimates dmin and the skew
    # check is conservative: a borderline pod may be over-evicted once and
    # re-bids next round against counts whose carry has absorbed the adds
    # — at most one extra round, never a missed violation. In exchange
    # the eviction path needs NO [n, S] scatter at all.)
    cnt_mine = base_at_bid + added_at_bid + cnt_incl                # [p, S]
    skew_t = (
        jnp.take_along_axis(cnt_mine, spc, axis=1)
        - dmin[spc]
    )                                                               # [p, Kc]
    viol_sp = admitted[:, None] & (sp_sel >= 0) & (
        skew_t > spread_max.astype(jnp.float32)
    )
    rows_sp = jnp.arange(p)[:, None]
    has_spread = (
        jnp.zeros((p, s), bool).at[rows_sp, spc].max(sp_sel >= 0)
    )                                                               # [p, S]
    member_sp = admitted[:, None] & has_spread & pod_matches        # [p, S]
    keyf_sp = jnp.where(member_sp, key[:, None], 0)
    if use_dense:
        gmax_sp_at = jnp.max(jnp.where(same, keyf_sp[None, :, :], 0), axis=1)
    else:
        gmax_sp = (
            jnp.zeros((table_rows, s), jnp.int32)
            .at[dom_p, cols]
            .max(keyf_sp)
        )
        gmax_sp_at = gmax_sp[dom_p, cols]
    keep_sp_s = member_sp & (keyf_sp == gmax_sp_at)                 # [p, S]
    survive_sp = jnp.take_along_axis(keep_sp_s, spc, axis=1)        # [p, Kc]
    return evict | (viol_sp & ~survive_sp).any(-1)


def _evict_round_conflicts(
    aff: AffinityState,
    admitted: jnp.ndarray,
    bid: jnp.ndarray,
    prio_key: jnp.ndarray,
    added: jnp.ndarray,
) -> jnp.ndarray:
    """Dense wrapper over _evict_conflicts_core: `added` [n, S] carries
    prior rounds' permanent placements in the per-node EXPANDED layout
    (see _affinity_round_mask), so the bid-node lookups are plain gathers.
    Spread skew is a TOTAL-count constraint, so the core must see base +
    added + this round's adds (anti-affinity needs only same-round adds —
    the pre-bid mask already rules out violations against base + added).
    """
    dom_p = aff.domain_id[bid]                                     # [p, S]
    live_cnt = aff.domain_counts + added
    big = jnp.asarray(jnp.finfo(jnp.float32).max, jnp.float32)
    dmin = jnp.where(aff.node_mask[:, None], live_cnt, big).min(0)  # [S]
    # expanded layout: added[bid] IS the prior-round total of bid's domain
    return _evict_conflicts_core(
        aff.pod_matches, aff.anti_affinity_sel, aff.pod_has_anti,
        aff.spread_sel, aff.spread_max, admitted, dom_p, prio_key,
        aff.domain_counts[bid], added[bid], dmin,
        aff.domain_counts.shape[0],
    )


def auction_assign(
    scores: jnp.ndarray,
    feasible: jnp.ndarray,
    pod_request: jnp.ndarray,
    node_free: jnp.ndarray,
    priority: jnp.ndarray,
    pod_mask: jnp.ndarray,
    *,
    rounds: int = 1024,
    price_frac: float = 1.0,
    affinity: AffinityState | None = None,
    bid_kernel: bool | None = None,
) -> AssignResult:
    """Price-guided parallel auction: rounds of bid → admit → reprice.

    Each round every unassigned pod bids on its argmax feasible node by
    *value* = score − price (scores first min-max'd per row, so pricing is
    invariant under per-row affine rescaling of the input); per node,
    bidders are admitted in priority order while their cumulative request
    fits remaining capacity (segmented prefix-sum admission, no [p,n,r]
    intermediate). Nodes that rejected bidders raise their price by
    `price_frac` (of the unit row range), so
    contending pods spread to their next-best nodes instead of re-bidding
    a full node (Bertsekas-auction ε-complementary slackness; without
    prices, P pods with similar preference orders fill one node per round
    and the fixed round budget strands schedulable pods).

    Terminates when no active pod has any feasible node with capacity —
    i.e. the assignment is *maximal* — or after `rounds` (stragglers
    return -1 and requeue next cycle, like upstream's backoff requeue).
    Within a round, the top-priority bidder of every contested node is
    always admitted (its own request passed the capacity pre-mask), so
    each round makes progress and `rounds >= p` guarantees maximality.
    Quality is within one price step of greedy; not bitwise-identical
    under adversarial ties.

    With `affinity`, inter-pod (anti)affinity is enforced EXACTLY against
    live counts (base + permanent in-window placements): the bid mask is
    recomputed per round from running domain counts, and same-round
    conflicts (two pods whose joint placement violates a hard anti
    selector) are resolved by _evict_round_conflicts before placements
    become permanent. This replaces the O(p)-sequential-step greedy scan
    for affinity windows with O(rounds) parallel rounds (~50x fewer
    device steps at 5k pods); placement ORDER differs from strict greedy
    (documented deviation), hard-constraint satisfaction does not.

    bid_kernel routes the no-affinity round head (capacity mask + price
    + row argmax) through the fused Pallas bid kernel instead of the
    XLA body — bitwise-identical bids (first-max tie semantics), no
    [p, n, r] capacity broadcast per round. None = auto (TPU backends
    only; YODA_AUCTION_BID_KERNEL=on/off overrides). The affinity path
    keeps the XLA body: its round mask depends on carried [n, S] count
    state the kernel does not fold.
    """
    p, n = scores.shape
    # Per-row min-max to [0, 1] over feasible entries before pricing. Bids
    # only compare within a row, but the price vector is SHARED across
    # pods — without this, a pod whose raw row spans [0, 1000] and one
    # spanning [0, 1] react to the same price bump wildly differently.
    # This also makes the auction invariant under any per-row monotone
    # normalization (min_max / softmax / none give identical decisions).
    row_hi = jnp.where(feasible, scores, -jnp.inf).max(axis=1, keepdims=True)
    row_lo = jnp.where(feasible, scores, jnp.inf).min(axis=1, keepdims=True)
    row_ok = jnp.isfinite(row_hi) & jnp.isfinite(row_lo)
    denom = jnp.where(row_ok, jnp.maximum(row_hi - row_lo, 1e-6), 1.0)
    scores = jnp.where(row_ok, (scores - jnp.where(row_ok, row_lo, 0.0)) / denom, 0.0)
    step = jnp.asarray(price_frac, scores.dtype)
    # Deterministic sub-step tie-break jitter: without it, pods with
    # identical score rows (homogeneous clusters) bid in lockstep — one
    # admission per round — and a round budget strands schedulable pods.
    jitter = tie_jitter(p, n, 0.01 * price_frac, dtype=scores.dtype)

    # priority order and its rank key are round-invariant; hoisted here so
    # each round pays ONE device sort (the node grouping in admission)
    # instead of three
    by_prio = _priority_order(priority, pod_mask)
    rank = jnp.zeros((p,), jnp.int32).at[by_prio].set(
        jnp.arange(p, dtype=jnp.int32)
    )
    prio_key = p - rank
    # the feasibility-masked jittered score matrix is round-invariant on
    # the no-affinity path — build it once outside the loop
    sj = jnp.where(feasible, scores + jitter, NEG) if affinity is None else None
    # bid kernel (ops/pallas_fused.fused_auction_bid): fold the round's
    # capacity mask + price + row argmax into one tiled pass over sj —
    # the XLA head's [p, n, r] capacity broadcast plus the [p, n] bid
    # row were the round's dominant HBM traffic. Decisions are bitwise
    # identical (the kernel replicates jnp.argmax's first-max ties).
    # Auto-gated to TPU backends: under the CPU interpreter the kernel
    # is a correctness path (tests pass bid_kernel=True), not a fast one.
    if bid_kernel is None:
        bid_kernel = _BID_KERNEL_MODE == "on" or (
            _BID_KERNEL_MODE == "auto" and jax.default_backend() == "tpu"
        )
    use_bid_kernel = bool(bid_kernel) and affinity is None
    if use_bid_kernel:
        from kubernetes_scheduler_tpu.ops.pallas_fused import (
            TILE_N,
            TILE_P,
            _pad2,
            _pad_axis,
            fused_auction_bid,
        )

        # round-invariant kernel operands, hoisted: NEG-padded sj and
        # the resource-major request block
        sj_pad = _pad2(sj, TILE_P, TILE_N, value=NEG)
        req_t_pad = _pad_axis(pod_request.astype(jnp.float32).T, 1, TILE_P)

    def round_body(state):
        assigned, free, price, added, added_avoid, _, _round = state
        active = pod_mask & (assigned < 0)
        if affinity is None:
            if use_bid_kernel:
                bid, has_bid = fused_auction_bid(
                    sj_pad, price, active, req_t_pad, free, p=p,
                )
            else:
                cap_ok = (
                    (pod_request[:, None, :] <= free[None, :, :])
                    | (pod_request[:, None, :] == 0)
                ).all(-1)
                mask = (sj > NEG * 0.5) & cap_ok & active[:, None]
                row = jnp.where(mask, sj - price[None, :], NEG)
                bid = jnp.argmax(row, axis=1).astype(jnp.int32)
                has_bid = mask.any(axis=1)
        else:
            cap_ok = (
                (pod_request[:, None, :] <= free[None, :, :])
                | (pod_request[:, None, :] == 0)
            ).all(-1)
            mask = feasible & cap_ok & active[:, None]
            mask = mask & _affinity_round_mask(affinity, added, added_avoid)
            row = jnp.where(mask, scores + jitter - price[None, :], NEG)
            bid = jnp.argmax(row, axis=1).astype(jnp.int32)      # [p]
            has_bid = mask.any(axis=1)
        admitted = _segmented_admission(
            bid, has_bid, pod_request, free, by_prio
        )
        if affinity is not None:
            admitted = admitted & ~_evict_round_conflicts(
                affinity, admitted, bid, prio_key, added
            )
            # Fold this round's placements into the per-node EXPANDED
            # carry tables: node j gains pod i's contribution iff j is in
            # the same (selector-s) domain as i's bid node. At window
            # sizes this is one fused compare-and-reduce over [p, n, S] —
            # NO [n, S] scatter: the two .at[dom, cols].add scatters here
            # were ~100% of the auction's marginal round cost on TPU
            # (scatters serialize per update; the reduction vectorizes).
            # Past the dense budget (mirroring _evict_round_conflicts's
            # use_dense guard) fall back to representative-row scatter +
            # member gather, whose cost is O(p·S) not O(p·n·S).
            dom_bid = affinity.domain_id[bid]                    # [p, S]
            inc_m = jnp.where(
                admitted[:, None],
                affinity.pod_matches.astype(added.dtype), 0.0,
            )
            inc_a = jnp.where(
                admitted[:, None],
                affinity.pod_has_anti.astype(added.dtype), 0.0,
            )
            s_dim = affinity.domain_counts.shape[1]
            if p * n * s_dim <= DENSE_FOLD_BUDGET:
                same = (
                    affinity.domain_id[None, :, :] == dom_bid[:, None, :]
                )                                                # [p, n, S]
                added = added + jnp.where(
                    same, inc_m[:, None, :], 0.0
                ).sum(0)
                added_avoid = added_avoid + jnp.where(
                    same, inc_a[:, None, :], 0.0
                ).sum(0)
            else:
                cols_s = jnp.arange(s_dim)[None, :]
                rep = jnp.zeros_like(added).at[dom_bid, cols_s].add(inc_m)
                rep_a = jnp.zeros_like(added).at[dom_bid, cols_s].add(inc_a)
                added = added + rep[affinity.domain_id, cols_s]
                added_avoid = added_avoid + rep_a[affinity.domain_id, cols_s]
        new_assigned = jnp.where(admitted, bid, assigned)
        used = jnp.zeros_like(free).at[bid].add(
            jnp.where(admitted[:, None], pod_request, 0.0)
        )
        rejected = (
            jnp.zeros((n,), bool)
            .at[bid]
            .max(has_bid & ~admitted)
        )
        return (
            new_assigned,
            free - used,
            price + jnp.where(rejected, step, 0.0),
            added,
            added_avoid,
            has_bid.any(),
            _round + 1,
        )

    def cond(state):
        # `can_bid` carried from the previous body evaluation (computed on
        # that round's pre-admission state) — at most one no-op extra round
        # instead of recomputing the O(p·n·r) capacity mask here.
        can_bid, r = state[-2], state[-1]
        return (r < rounds) & can_bid

    assigned0 = jnp.full((p,), -1, jnp.int32)
    added0 = (
        jnp.zeros((0, 0), scores.dtype)
        if affinity is None
        else jnp.zeros_like(affinity.domain_counts)
    )
    assigned, free_after, _, _, _, _, _ = jax.lax.while_loop(
        cond,
        round_body,
        (
            assigned0,
            node_free,
            jnp.zeros((n,), scores.dtype),
            added0,
            jnp.zeros_like(added0),
            jnp.asarray(True),
            jnp.int32(0),
        ),
    )
    return AssignResult(
        node_idx=assigned,
        free_after=free_after,
        n_assigned=(assigned >= 0).sum().astype(jnp.int32),
    )
