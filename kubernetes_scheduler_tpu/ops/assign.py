"""Batched pod→node assignment with capacity accounting.

The upstream scheduler binds one pod per scheduling cycle, decrementing node
capacity in its in-memory snapshot between cycles; the reference plugin just
rides that loop (pkg/yoda/scheduler.go:116-196). The batch engine instead
assigns a whole window of pending pods in one device program:

- `greedy_assign`: exact sequential-greedy semantics — pods in priority
  order (sort.go:8-18: higher `scv/priority` first), each takes its
  best-scoring feasible node that still has capacity, capacity is
  decremented before the next pod. Implemented as `lax.scan` over the pod
  axis, so it is O(P·N·R) of pure vector work with no host round-trips —
  equivalent to P upstream cycles but without P× (snapshot + plugin fan-out
  + HTTP/Redis traffic).

- `auction_assign`: a parallel relaxation — rounds of simultaneous
  argmax bidding with conflict resolution by priority, useful when P is
  large and strict greedy order is not required. Converges to a
  capacity-respecting assignment in <= rounds iterations.

Both return -1 for pods that fit nowhere (upstream: unschedulable, requeued
with backoff — deploy/yoda-scheduler.yaml:19-20).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

NEG = -1.0e30


class AssignResult(NamedTuple):
    node_idx: jnp.ndarray      # [p] int32, assigned node or -1
    free_after: jnp.ndarray    # [n, r] remaining free capacity
    n_assigned: jnp.ndarray    # [] int32


class AffinityState(NamedTuple):
    """Inter-pod (anti)affinity state threaded through greedy assignment.

    The upstream scheduler re-snapshots between single-pod cycles, so pod B
    sees pod A's placement; a batched window must reproduce that
    incrementally or hard anti-affinity can be violated inside the window.
    greedy_assign maintains a running per-(domain, selector) count of
    window placements on top of the host-provided base counts.

    domain_counts:     [n, S] base counts (running pods, host-aggregated)
    domain_id:         [n, S] int32 — node n's topology-domain id for
                       selector s, encoded as a representative node index
                       in [0, n) (first node of the domain), so the
                       in-window counts array can be statically shaped [n, S]
    pod_matches:       [p, S] bool — pending pod p's labels match selector s
    affinity_sel:      [p, K] int32, -1 padded
    anti_affinity_sel: [p, K] int32, -1 padded
    """

    domain_counts: jnp.ndarray
    domain_id: jnp.ndarray
    pod_matches: jnp.ndarray
    affinity_sel: jnp.ndarray
    anti_affinity_sel: jnp.ndarray


def affinity_ok_from_counts(
    cnt: jnp.ndarray, a_sel: jnp.ndarray, t_sel: jnp.ndarray
) -> jnp.ndarray:
    """[n] bool from live domain counts cnt[n, S] and one pod's selector
    lists a_sel/t_sel[K] (-1 padded; ids >= S are unsatisfiable, see
    constraints.pod_affinity_fit)."""
    s = cnt.shape[1]
    a = jnp.clip(a_sel, 0, max(s - 1, 0))
    t = jnp.clip(t_sel, 0, max(s - 1, 0))
    aff_ok = ((cnt[:, a] > 0) | (a_sel[None, :] < 0)).all(-1)   # [n]
    anti_ok = ((cnt[:, t] == 0) | (t_sel[None, :] < 0)).all(-1)
    valid = ~((a_sel >= s).any() | (t_sel >= s).any())
    return aff_ok & anti_ok & valid


def _affinity_row_ok(
    aff: AffinityState, added: jnp.ndarray, i: jnp.ndarray
) -> jnp.ndarray:
    """[n] bool: does every (anti)affinity selector of pod i hold on each
    node, counting both pre-existing and in-window placements."""
    s = aff.domain_counts.shape[1]
    cols = jnp.arange(s)[None, :]
    cnt = aff.domain_counts + added[aff.domain_id, cols]     # [n, S]
    return affinity_ok_from_counts(cnt, aff.affinity_sel[i], aff.anti_affinity_sel[i])


def _affinity_update(
    aff: AffinityState, added: jnp.ndarray, i: jnp.ndarray,
    choice: jnp.ndarray, found: jnp.ndarray
) -> jnp.ndarray:
    """Record pod i's placement on node `choice` into the in-window
    counts."""
    s = aff.domain_counts.shape[1]
    cols = jnp.arange(s)
    inc = jnp.where(found, aff.pod_matches[i].astype(added.dtype), 0.0)
    return added.at[aff.domain_id[choice], cols].add(inc)


def _priority_order(priority: jnp.ndarray, pod_mask: jnp.ndarray) -> jnp.ndarray:
    """Stable order: valid pods by descending priority, padding last.

    Mirrors sort.Less (pkg/yoda/sort/sort.go:8-10): higher `scv/priority`
    label schedules first; ties keep queue (index) order.
    """
    key = jnp.where(pod_mask, priority.astype(jnp.int32), jnp.int32(-(2**31) + 1))
    return jnp.argsort(-key, stable=True)


def greedy_assign(
    scores: jnp.ndarray,
    feasible: jnp.ndarray,
    pod_request: jnp.ndarray,
    node_free: jnp.ndarray,
    priority: jnp.ndarray,
    pod_mask: jnp.ndarray,
    affinity: AffinityState | None = None,
) -> AssignResult:
    """Sequential-greedy assignment as a lax.scan.

    scores:      [p, n] (higher better; padded nodes may hold junk — they
                 are excluded via `feasible`)
    feasible:    [p, n] bool — all filter masks ANDed, False on padding
    pod_request: [p, r] requests with non-zero defaults
    node_free:   [n, r] free capacity (allocatable - requested)
    priority:    [p] int priority (sort.go semantics)
    pod_mask:    [p] bool
    """
    order = _priority_order(priority, pod_mask)
    p = scores.shape[0]
    added0 = (
        None if affinity is None else jnp.zeros_like(affinity.domain_counts)
    )

    def step(carry, i):
        free, added = carry
        req = pod_request[i]                      # [r]
        # Unrequested resources never exclude a node, matching
        # feasibility.resource_fit's extended-resource bypass
        # (algorithm.go:211-215) even when a slot is oversubscribed.
        cap_ok = ((req[None, :] <= free) | (req[None, :] == 0)).all(-1)  # [n]
        mask = feasible[i] & cap_ok & pod_mask[i]
        if affinity is not None:
            mask = mask & _affinity_row_ok(affinity, added, i)
        row = jnp.where(mask, scores[i], NEG)
        choice = jnp.argmax(row)
        found = mask.any()
        delta = jnp.zeros_like(free).at[choice].set(req)
        free = jnp.where(found, free - delta, free)
        if affinity is not None:
            added = _affinity_update(affinity, added, i, choice, found)
        return (free, added), jnp.where(
            found, choice.astype(jnp.int32), jnp.int32(-1)
        )

    (free_after, _), picks = jax.lax.scan(step, (node_free, added0), order)
    node_idx = jnp.full((p,), -1, jnp.int32).at[order].set(picks)
    return AssignResult(
        node_idx=node_idx,
        free_after=free_after,
        n_assigned=(node_idx >= 0).sum().astype(jnp.int32),
    )


def auction_assign(
    scores: jnp.ndarray,
    feasible: jnp.ndarray,
    pod_request: jnp.ndarray,
    node_free: jnp.ndarray,
    priority: jnp.ndarray,
    pod_mask: jnp.ndarray,
    *,
    rounds: int = 8,
) -> AssignResult:
    """Parallel rounds of bid → resolve-by-priority → decrement.

    Each round every unassigned pod bids on its argmax feasible node; for
    every node, bidders are admitted in priority order while their summed
    requests fit the node's remaining capacity (prefix-sum admission). Not
    identical to greedy for adversarial score ties, but capacity-safe and
    typically within one round of greedy quality; O(rounds · P·N·R).
    """
    p, n = scores.shape

    def round_body(state):
        assigned, free, _round = state
        active = pod_mask & (assigned < 0)
        cap_ok = (
            (pod_request[:, None, :] <= free[None, :, :])
            | (pod_request[:, None, :] == 0)
        ).all(-1)
        mask = feasible & cap_ok & active[:, None]
        row = jnp.where(mask, scores, NEG)
        bid = jnp.argmax(row, axis=1).astype(jnp.int32)          # [p]
        has_bid = mask.any(axis=1)
        # Admission: per node, order bidders by (priority desc, index asc)
        # and admit while cumulative request fits.
        key = jnp.where(has_bid, priority.astype(jnp.int32), jnp.int32(-(2**31) + 1))
        order = jnp.argsort(-key, stable=True)                   # [p]
        bid_o = bid[order]
        req_o = pod_request[order]
        has_o = has_bid[order]
        onehot = (
            (bid_o[:, None] == jnp.arange(n)[None, :]) & has_o[:, None]
        ).astype(scores.dtype)                                   # [p, n]
        # cumulative requested per (node, resource) including self
        cum = jnp.cumsum(onehot[:, :, None] * req_o[:, None, :], axis=0)
        # cum == 0 on a slot means no admitted bidder requests it — apply
        # the same unrequested-resource bypass as above.
        fits = ((cum <= free[None, :, :]) | (cum == 0)).all(-1)  # [p, n]
        admit_o = has_o & jnp.take_along_axis(fits, bid_o[:, None], 1)[:, 0]
        admitted = jnp.zeros((p,), bool).at[order].set(admit_o)
        new_assigned = jnp.where(admitted, bid, assigned)
        used = (
            (onehot * admit_o[:, None].astype(scores.dtype))[:, :, None]
            * req_o[:, None, :]
        ).sum(0)
        return new_assigned, free - used, _round + 1

    def cond(state):
        assigned, free, r = state
        active = pod_mask & (assigned < 0)
        return (r < rounds) & active.any()

    assigned0 = jnp.full((p,), -1, jnp.int32)
    assigned, free_after, _ = jax.lax.while_loop(
        cond, round_body, (assigned0, node_free, jnp.int32(0))
    )
    return AssignResult(
        node_idx=assigned,
        free_after=free_after,
        n_assigned=(assigned >= 0).sum().astype(jnp.int32),
    )
