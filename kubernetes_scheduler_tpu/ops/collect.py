"""Cluster-wide max collection for score normalization.

Vectorizes pkg/yoda/collection/collection.go:30-76: the reference walks the
SCV list host-side accumulating per-metric maxima over every card that fits
the pod; here it is a masked max-reduction over the [node, card] axes. The
reference seeds every max with 1 (collection.go:31-38) so the later
`metric * 100 / max` never divides by zero — reproduced.
"""

from __future__ import annotations

import jax.numpy as jnp


def local_max_card_values(cards: jnp.ndarray, fits: jnp.ndarray) -> jnp.ndarray:
    """Unclamped max per metric over a pod's fitting (local) cards; the
    sharded engine pmax-reduces this across node shards before clamping.

    cards: [n, c, 6]; fits: [p, n, c] bool. Returns [p, 6] (0 where no card
    fits)."""
    masked = jnp.where(fits[..., None], cards[None, :, :, :], 0.0)
    return masked.max(axis=(1, 2))


def collect_max_card_values(
    cards: jnp.ndarray,
    fits: jnp.ndarray,
) -> jnp.ndarray:
    """Max per metric over a pod's fitting cards.

    cards: [n, c, 6]; fits: [p, n, c] bool (from feasibility.card_fit).
    Returns max_values[p, 6], each seeded at 1.0 (collection.go:31-38).
    """
    return jnp.maximum(local_max_card_values(cards, fits), 1.0)
