"""Batched preemption (upstream PostFilter parity): victim selection on
device.

The reference rides the upstream kube-scheduler, whose scheduling
framework runs a PostFilter phase when a pod fits nowhere: find a node
where evicting a minimal set of strictly-lower-priority pods makes the
pod feasible, preferring candidates whose victims matter least
(upstream's ordering: lowest highest-victim-priority first, then fewest
victims). The reference plugin itself never customizes this phase
(SURVEY.md L6 — the implicit upstream layer), so parity means
reproducing the framework behavior, batched.

TPU-first formulation: instead of upstream's per-node goroutine
simulation (clone snapshot, remove pods one by one, re-run filters),
victims are laid out ONCE into per-node prefix tables sorted by
priority — freed[n, k, r] = capacity released by evicting the k
lowest-priority victims of node n — and every (pending pod, node,
victim count) combination is evaluated as one [p, n, K] tensor op.
Priority eligibility ("only strictly lower priority may be evicted")
falls out of the sort: the k-th prefix is eligible iff its LAST
(= highest-priority) member is below the preemptor's priority.

Deviations from upstream, documented:
- PodDisruptionBudgets are consulted HOST-SIDE (host/scheduler:
  victims under an exhausted budget are excluded from the tables, and
  the apply loop never overdraws a budget), but strictly: upstream
  orders candidates by fewest PDB violations and may still preempt
  past a budget as a last resort; this framework never violates one.
- Constraint families (taints, node/pod affinity, spread) are checked
  against the CURRENT cluster state via the caller-supplied
  `static_ok` mask; the marginal effect of removing the victims
  themselves on (anti)affinity domain counts is not re-simulated.
  Upstream's RemovePod/AddPod accounting does simulate it; for count-
  based families this can only make a chosen node conservatively wrong
  in the pod's favor (victims leaving a domain free anti-affinity slots,
  never consume them), and the next cycle re-checks everything against
  real state before binding.

Candidate ordering reproduces upstream pickOneNodeForPreemption's
criteria 2-6 in order: lowest highest-victim priority, lowest sum of
victim priorities, fewest victims, LATEST start time of the
highest-priority victim, then first node index (upstream criterion 1,
fewest PDB violations, is superseded: budgets are enforced host-side and
never violated at all). Within a node, victims of equal priority are
evicted most-recently-started first (upstream util.MoreImportantPod:
earlier start = more important).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

PRIO_PAD = jnp.iinfo(jnp.int32).max  # padding sentinel: never evictable


class VictimTables(NamedTuple):
    """Per-node victim prefix tables, victims sorted by (priority asc,
    start time desc) — the k-th entry is the k-th least "important" pod
    in upstream util.MoreImportantPod order, so prefix k is always the
    upstream-minimal victim set of size k.

    prio:  [n, K] int32 — k-th victim priority on node n
           (PRIO_PAD past the node's victim count)
    freed: [n, K, r] f32 — capacity released by evicting victims 0..k
           (inclusive prefix sums)
    vid:   [n, K] int32 — index into the caller's victim arrays, -1 pad
    psum_hi/psum_lo: [n, K] int32 — inclusive prefix sum of victim
           priorities (padding contributes 0), upstream ordering
           criterion 3, as a two-limb value hi*2^16 + lo with lo in
           [0, 2^16): k8s priorities reach 2e9 and a K-victim prefix
           overflows int32 (this image has no int64 — jnp silently
           downgrades), so sums are compared lexicographically on the
           normalized limb pair instead
    start: [n, K] int32 — k-th victim's start time (relative seconds;
           larger = started later; 0 past the victim count) — upstream
           ordering criterion 5 reads it at the prefix end (the
           highest-priority victim)
    """

    prio: jnp.ndarray
    freed: jnp.ndarray
    vid: jnp.ndarray
    psum_hi: jnp.ndarray
    psum_lo: jnp.ndarray
    start: jnp.ndarray


class VictimArrays(NamedTuple):
    """Dense victim-side inputs to the preemption pass — the shape the
    host ships to the sidecar (bridge Preempt RPC) or feeds the local
    engine. Entries with node < 0 (PDB-protected, terminating, or
    nomination reservations) never enter the tables.

    node:  [m] int32 — victim's node index, -1 = not evictable
    prio:  [m] int32
    req:   [m, r] f32 — request vectors with non-zero defaults
    mask:  [m] bool
    start: [m] int32 — relative start seconds (larger = later)
    """

    node: jnp.ndarray
    prio: jnp.ndarray
    req: jnp.ndarray
    mask: jnp.ndarray
    start: jnp.ndarray


class PreemptResult(NamedTuple):
    """node:    [p] int32 — chosen node, -1 when no candidate exists
    victims: [p, K] int32 — victim indices to evict (-1 padded)
    n_victims: [p] int32
    """

    node: jnp.ndarray
    victims: jnp.ndarray
    n_victims: jnp.ndarray


def build_victim_tables(
    victim_node: jnp.ndarray,
    victim_prio: jnp.ndarray,
    victim_req: jnp.ndarray,
    victim_mask: jnp.ndarray,
    *,
    n_nodes: int,
    k_cap: int,
    victim_start: jnp.ndarray | None = None,
) -> VictimTables:
    """Lay running pods out into per-node prefix tables sorted by
    (priority asc, start time desc). victim_node [m] int32 (entries
    outside [0, n) ignored), victim_prio [m] int32, victim_req [m, r]
    f32, victim_mask [m] bool, victim_start [m] int32 relative seconds
    (None = all equal, reducing the tie-break to input order).

    One sort + one scatter over the m running pods, paid once per
    preemption pass (not per candidate)."""
    m, r = victim_req.shape
    ok = victim_mask & (victim_node >= 0) & (victim_node < n_nodes)
    if victim_start is None:
        victim_start = jnp.zeros((m,), jnp.int32)
    # lexicographic (node asc, prio asc, start desc) via stable argsorts,
    # innermost key first: equal-priority victims evict most-recently-
    # started first (upstream MoreImportantPod: earlier start = more
    # important, evicted later)
    ord0 = jnp.argsort(-victim_start, stable=True)
    ord1 = jnp.argsort(victim_prio[ord0], stable=True)
    ord01 = ord0[ord1]
    ord2 = jnp.argsort(
        jnp.where(ok, victim_node, n_nodes)[ord01], stable=True
    )
    order = ord01[ord2]                                          # [m]
    node_s = jnp.where(ok[order], victim_node[order], n_nodes)
    prio_s = victim_prio[order]
    start_s = victim_start[order]
    req_s = victim_req[order]
    # position within the node's segment
    idx = jnp.arange(m)
    boundary = jnp.concatenate(
        [jnp.ones((1,), bool), node_s[1:] != node_s[:-1]]
    )
    start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(boundary, idx, 0)
    )
    pos = idx - start                                            # [m]
    keep = (node_s < n_nodes) & (pos < k_cap)
    row = jnp.where(keep, node_s, n_nodes)                       # pad row
    prio = (
        jnp.full((n_nodes + 1, k_cap), PRIO_PAD, jnp.int32)
        .at[row, pos].set(jnp.where(keep, prio_s, PRIO_PAD))[:n_nodes]
    )
    steps = (
        jnp.zeros((n_nodes + 1, k_cap, r), req_s.dtype)
        .at[row, pos].set(jnp.where(keep[:, None], req_s, 0.0))[:n_nodes]
    )
    vid = (
        jnp.full((n_nodes + 1, k_cap), -1, jnp.int32)
        .at[row, pos].set(jnp.where(keep, order.astype(jnp.int32), -1))[
            :n_nodes
        ]
    )
    # priority prefix sums as two 16-bit limbs (see VictimTables.psum_hi):
    # arithmetic >> handles negative priorities (hi = floor division by
    # 2^16, lo in [0, 2^16)); the post-cumsum carry normalization restores
    # lo's range so lexicographic (hi, lo) ordering equals numeric
    # ordering of hi*2^16 + lo
    kept_prio = jnp.where(keep, prio_s, 0)
    hi_v = kept_prio >> 16
    lo_v = kept_prio - (hi_v << 16)
    hi_steps = (
        jnp.zeros((n_nodes + 1, k_cap), jnp.int32)
        .at[row, pos].set(hi_v)[:n_nodes]
    )
    lo_steps = (
        jnp.zeros((n_nodes + 1, k_cap), jnp.int32)
        .at[row, pos].set(lo_v)[:n_nodes]
    )
    psum_hi = jnp.cumsum(hi_steps, axis=1)
    psum_lo = jnp.cumsum(lo_steps, axis=1)
    carry = psum_lo >> 16
    psum_hi = psum_hi + carry
    psum_lo = psum_lo - (carry << 16)
    start = (
        jnp.zeros((n_nodes + 1, k_cap), jnp.int32)
        .at[row, pos].set(jnp.where(keep, start_s, 0))[:n_nodes]
    )
    return VictimTables(
        prio=prio,
        freed=jnp.cumsum(steps, axis=1),
        vid=vid,
        psum_hi=psum_hi,
        psum_lo=psum_lo,
        start=start,
    )


def preempt_candidates(
    pend_req: jnp.ndarray,
    pend_prio: jnp.ndarray,
    pend_mask: jnp.ndarray,
    static_ok: jnp.ndarray,
    free: jnp.ndarray,
    tables: VictimTables,
) -> PreemptResult:
    """Choose a preemption candidate per pending pod.

    pend_req [p, r], pend_prio [p] int32, pend_mask [p] bool,
    static_ok [p, n] bool (non-resource constraint families hold),
    free [n, r] current free capacity.

    Candidate (pod p, node n, count k) is valid iff all k victims have
    priority strictly below p's and p's request fits free + freed[k-1].
    Per pod the minimal k per node is kept, then nodes compete on
    upstream pickOneNodeForPreemption's ordering: lowest highest-victim
    priority, lowest sum of victim priorities, fewest victims, latest
    start time of the highest-priority victim, first node index."""
    p, r = pend_req.shape
    n, k_cap = tables.prio.shape
    cap = free[None, :, None, :] + tables.freed[None, :, :, :]  # [1,n,K,r]
    fits = (
        (pend_req[:, None, None, :] <= cap)
        | (pend_req[:, None, None, :] == 0)
    ).all(-1)                                                   # [p,n,K]
    # victims sorted ascending: prefix k eligible iff its last member is
    # below the preemptor (PRIO_PAD padding fails automatically)
    elig = tables.prio[None, :, :] < pend_prio[:, None, None]   # [p,n,K]
    ok = fits & elig & static_ok[:, :, None] & pend_mask[:, None, None]
    has_k = ok.any(-1)                                          # [p,n]
    kstar = jnp.argmax(ok, axis=-1)                             # first True

    def at_kstar(table):
        return jnp.take_along_axis(
            table[None], jnp.broadcast_to(kstar[:, :, None], (p, n, 1)),
            axis=2,
        )[..., 0]                                               # [p,n]

    maxprio = at_kstar(tables.prio)
    priosum_hi = at_kstar(tables.psum_hi)
    priosum_lo = at_kstar(tables.psum_lo)
    hp_start = at_kstar(tables.start)
    # lexicographic argmin over nodes:
    # (maxprio, priosum (hi then lo limb), kstar, -hp_start, node index)
    big = jnp.iinfo(jnp.int32).max
    mp = jnp.where(has_k, maxprio, big)
    best_mp = mp.min(axis=1, keepdims=True)
    tier1 = has_k & (mp == best_mp)
    ps_hi = jnp.where(tier1, priosum_hi, big)
    best_ps_hi = ps_hi.min(axis=1, keepdims=True)
    tier1b = tier1 & (ps_hi == best_ps_hi)
    ps_lo = jnp.where(tier1b, priosum_lo, big)
    best_ps_lo = ps_lo.min(axis=1, keepdims=True)
    tier2 = tier1b & (ps_lo == best_ps_lo)
    ks = jnp.where(tier2, kstar, big)
    best_k = ks.min(axis=1, keepdims=True)
    tier3 = tier2 & (ks == best_k)
    st = jnp.where(tier3, hp_start, -big)
    best_st = st.max(axis=1, keepdims=True)
    tier4 = tier3 & (st == best_st)
    node = jnp.where(
        tier4.any(-1), jnp.argmax(tier4, axis=-1), -1
    ).astype(jnp.int32)                                         # [p]
    safe = jnp.maximum(node, 0)
    nv = jnp.where(node >= 0, kstar[jnp.arange(p), safe] + 1, 0)
    vics = tables.vid[safe]                                     # [p, K]
    vics = jnp.where(
        (jnp.arange(k_cap)[None, :] < nv[:, None]) & (node >= 0)[:, None],
        vics, -1,
    )
    return PreemptResult(
        node=node, victims=vics, n_victims=nv.astype(jnp.int32)
    )
