"""Batched preemption (upstream PostFilter parity): victim selection on
device.

The reference rides the upstream kube-scheduler, whose scheduling
framework runs a PostFilter phase when a pod fits nowhere: find a node
where evicting a minimal set of strictly-lower-priority pods makes the
pod feasible, preferring candidates whose victims matter least
(upstream's ordering: lowest highest-victim-priority first, then fewest
victims). The reference plugin itself never customizes this phase
(SURVEY.md L6 — the implicit upstream layer), so parity means
reproducing the framework behavior, batched.

TPU-first formulation: instead of upstream's per-node goroutine
simulation (clone snapshot, remove pods one by one, re-run filters),
victims are laid out ONCE into per-node prefix tables sorted by
priority — freed[n, k, r] = capacity released by evicting the k
lowest-priority victims of node n — and every (pending pod, node,
victim count) combination is evaluated as one [p, n, K] tensor op.
Priority eligibility ("only strictly lower priority may be evicted")
falls out of the sort: the k-th prefix is eligible iff its LAST
(= highest-priority) member is below the preemptor's priority.

Deviations from upstream, documented:
- PodDisruptionBudgets are consulted HOST-SIDE (host/scheduler:
  victims under an exhausted budget are excluded from the tables, and
  the apply loop never overdraws a budget), but strictly: upstream
  orders candidates by fewest PDB violations and may still preempt
  past a budget as a last resort; this framework never violates one.

Count-based constraint families (inter-pod (anti)affinity, reverse
anti-affinity, topology spread) RE-SIMULATE the victims' removal, like
upstream's RemovePod/AddPod accounting: the victim prefix tables carry
per-(node, k) freed selector-match and freed-avoider counts
(cfreed/afreed, mirroring `freed`), and preempt_candidates evaluates
each (pod, node, k) against the counts as they would stand after the
evictions — so a preemptor whose required anti-affinity is satisfied
ONLY by evicting a victim finds the candidate, and one whose required
affinity depends on a victim staying does not waste an eviction.
Node-local families (taints, node affinity, resources vs full
allocatable) stay in the caller-supplied `static_ok` mask — victims
cannot change node labels or taints.

Candidate ordering reproduces upstream pickOneNodeForPreemption's
criteria 2-6 in order: lowest highest-victim priority, lowest sum of
victim priorities, fewest victims, LATEST start time of the
highest-priority victim, then first node index (upstream criterion 1,
fewest PDB violations, is superseded: budgets are enforced host-side and
never violated at all). Within a node, victims of equal priority are
evicted most-recently-started first (upstream util.MoreImportantPod:
earlier start = more important).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

PRIO_PAD = jnp.iinfo(jnp.int32).max  # padding sentinel: never evictable


class VictimTables(NamedTuple):
    """Per-node victim prefix tables, victims sorted by (priority asc,
    start time desc) — the k-th entry is the k-th least "important" pod
    in upstream util.MoreImportantPod order, so prefix k is always the
    upstream-minimal victim set of size k.

    prio:  [n, K] int32 — k-th victim priority on node n
           (PRIO_PAD past the node's victim count)
    freed: [n, K, r] f32 — capacity released by evicting victims 0..k
           (inclusive prefix sums)
    vid:   [n, K] int32 — index into the caller's victim arrays, -1 pad
    psum_hi/psum_lo: [n, K] int32 — inclusive prefix sum of victim
           priorities (padding contributes 0), upstream ordering
           criterion 3, as a two-limb value hi*2^16 + lo with lo in
           [0, 2^16): k8s priorities reach 2e9 and a K-victim prefix
           overflows int32 (this image has no int64 — jnp silently
           downgrades), so sums are compared lexicographically on the
           normalized limb pair instead
    start: [n, K] int32 — k-th victim's start time (relative seconds;
           larger = started later; 0 past the victim count) — upstream
           ordering criterion 5 reads it at the prefix end (the
           highest-priority victim)
    """

    prio: jnp.ndarray
    freed: jnp.ndarray
    vid: jnp.ndarray
    psum_hi: jnp.ndarray
    psum_lo: jnp.ndarray
    start: jnp.ndarray
    # [n, K, S] — selector-match counts (cfreed) and freed-avoider
    # counts (afreed) released by evicting victims 0..k of node n: the
    # RemovePod side of upstream's RemovePod/AddPod accounting, so
    # candidate evaluation can re-simulate the victims' effect on
    # (anti)affinity/spread domain counts. All-zero when the caller
    # supplies no victim selector data.
    cfreed: jnp.ndarray
    afreed: jnp.ndarray


class VictimArrays(NamedTuple):
    """Dense victim-side inputs to the preemption pass — the shape the
    host ships to the sidecar (bridge Preempt RPC) or feeds the local
    engine. Entries with node < 0 (PDB-protected, terminating, or
    nomination reservations) never enter the tables.

    node:  [m] int32 — victim's node index, -1 = not evictable
    prio:  [m] int32
    req:   [m, r] f32 — request vectors with non-zero defaults
    mask:  [m] bool
    start: [m] int32 — relative start seconds (larger = later)
    matches: [m, S] bool — victim's labels match selector s (the pod
           batch's pod_matches rows for the running set)
    anti:  [m, S] bool — victim carries a REQUIRED anti term using
           selector s (an avoider whose eviction frees the domain)
    """

    node: jnp.ndarray
    prio: jnp.ndarray
    req: jnp.ndarray
    mask: jnp.ndarray
    start: jnp.ndarray
    # None = no selector data (affinity evaluated against unadjusted
    # counts; a local-engine convenience — the host always fills these,
    # and the bridge codec requires real arrays on the wire)
    matches: jnp.ndarray | None = None
    anti: jnp.ndarray | None = None


class PreemptAffinity(NamedTuple):
    """Inputs for re-simulating the victims' effect on the count-based
    constraint families per candidate (pod, node, k) — the RemovePod
    half of upstream's accounting. Node-side tables come from the
    snapshot; pod-side selectors from the preemptors' PodBatch."""

    domain_counts: jnp.ndarray      # [n, S]
    avoid_counts: jnp.ndarray       # [n, S]
    domain_id: jnp.ndarray          # [n, S]
    node_mask: jnp.ndarray          # [n]
    affinity_sel: jnp.ndarray       # [p, Ka] required attract, -1 pad
    anti_affinity_sel: jnp.ndarray  # [p, Ka] required anti, -1 pad
    pod_matches: jnp.ndarray        # [p, S]
    spread_sel: jnp.ndarray         # [p, Ks] hard spread, -1 pad
    spread_max: jnp.ndarray         # [p, Ks]


def affinity_after_evictions(
    a: PreemptAffinity, tables: VictimTables
) -> jnp.ndarray:
    """OK[p, n, K]: do the count-based families hold at node n after
    evicting its k-prefix victims?

    The prefix victims all live on node n, and node n belongs to its own
    domain under every topology key, so the post-eviction counts AT THE
    CANDIDATE NODE are exactly counts - cfreed/afreed. For spread, the
    global minimum can only change through the candidate's own domain:
    min_after = min(min over OTHER domains, adjusted own count), with
    the other-domain minimum from the two-smallest-domains trick."""
    n, k_cap, s = tables.cfreed.shape
    dc = a.domain_counts[:, None, :] - tables.cfreed     # [n, K, S]
    av = a.avoid_counts[:, None, :] - tables.afreed

    inv_aff = a.affinity_sel >= s                        # [p, Ka]
    sel_a = jnp.clip(a.affinity_sel, 0, max(s - 1, 0))
    aff_ok = (
        (dc[:, :, sel_a] > 0) | (a.affinity_sel < 0)[None, None]
    ).all(-1)                                            # [n, K, p]
    inv_anti = a.anti_affinity_sel >= s
    sel_t = jnp.clip(a.anti_affinity_sel, 0, max(s - 1, 0))
    anti_ok = (
        (dc[:, :, sel_t] <= 0) | (a.anti_affinity_sel < 0)[None, None]
    ).all(-1)
    # reverse direction: bad iff the pod matches s and an AVOIDER of s
    # remains in the domain after the evictions
    rev_bad = (
        (av > 0)[:, :, None, :] & a.pod_matches[None, None]
    ).any(-1)                                            # [n, K, p]

    # hard topology spread: two-smallest-domains for the min excluding
    # the candidate's own domain (only it changes)
    big = jnp.asarray(jnp.finfo(jnp.float32).max, jnp.float32)
    cols = jnp.arange(s)
    masked = jnp.where(a.node_mask[:, None], a.domain_counts, big)
    min1 = masked.min(0)                                 # [S]
    rep1 = a.domain_id[masked.argmin(0), cols]           # [S] min domain rep
    same1 = a.domain_id == rep1[None, :]                 # [n, S]
    masked2 = jnp.where(
        a.node_mask[:, None] & ~same1, a.domain_counts, big
    )
    min2 = masked2.min(0)
    min_excl = jnp.where(same1, min2[None, :], min1[None, :])  # [n, S]
    new_min = jnp.minimum(min_excl[:, None, :], dc)      # [n, K, S]
    inv_sp = a.spread_sel >= s
    sel_s = jnp.clip(a.spread_sel, 0, max(s - 1, 0))
    sp_ok = (
        (dc[:, :, sel_s] + 1.0 - new_min[:, :, sel_s]
         <= a.spread_max[None, None].astype(jnp.float32))
        | (a.spread_sel < 0)[None, None]
    ).all(-1)                                            # [n, K, p]
    valid = ~(
        inv_aff.any(-1) | inv_anti.any(-1) | inv_sp.any(-1)
    )                                                    # [p]
    return (
        (aff_ok & anti_ok & ~rev_bad & sp_ok).transpose(2, 0, 1)
        & valid[:, None, None]
    )


class PreemptResult(NamedTuple):
    """node:    [p] int32 — chosen node, -1 when no candidate exists
    victims: [p, K] int32 — victim indices to evict (-1 padded)
    n_victims: [p] int32
    """

    node: jnp.ndarray
    victims: jnp.ndarray
    n_victims: jnp.ndarray


def build_victim_tables(
    victim_node: jnp.ndarray,
    victim_prio: jnp.ndarray,
    victim_req: jnp.ndarray,
    victim_mask: jnp.ndarray,
    *,
    n_nodes: int,
    k_cap: int,
    victim_start: jnp.ndarray | None = None,
    victim_matches: jnp.ndarray | None = None,
    victim_anti: jnp.ndarray | None = None,
) -> VictimTables:
    """Lay running pods out into per-node prefix tables sorted by
    (priority asc, start time desc). victim_node [m] int32 (entries
    outside [0, n) ignored), victim_prio [m] int32, victim_req [m, r]
    f32, victim_mask [m] bool, victim_start [m] int32 relative seconds
    (None = all equal, reducing the tie-break to input order).
    victim_matches/victim_anti [m, S] bool feed the cfreed/afreed
    count-freed prefix tables (None = [*, 1] zeros — no affinity
    re-simulation data).

    One sort + one scatter over the m running pods, paid once per
    preemption pass (not per candidate)."""
    m, r = victim_req.shape
    ok = victim_mask & (victim_node >= 0) & (victim_node < n_nodes)
    if victim_start is None:
        victim_start = jnp.zeros((m,), jnp.int32)
    # lexicographic (node asc, prio asc, start desc) via stable argsorts,
    # innermost key first: equal-priority victims evict most-recently-
    # started first (upstream MoreImportantPod: earlier start = more
    # important, evicted later)
    ord0 = jnp.argsort(-victim_start, stable=True)
    ord1 = jnp.argsort(victim_prio[ord0], stable=True)
    ord01 = ord0[ord1]
    ord2 = jnp.argsort(
        jnp.where(ok, victim_node, n_nodes)[ord01], stable=True
    )
    order = ord01[ord2]                                          # [m]
    node_s = jnp.where(ok[order], victim_node[order], n_nodes)
    prio_s = victim_prio[order]
    start_s = victim_start[order]
    req_s = victim_req[order]
    # position within the node's segment
    idx = jnp.arange(m)
    boundary = jnp.concatenate(
        [jnp.ones((1,), bool), node_s[1:] != node_s[:-1]]
    )
    start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(boundary, idx, 0)
    )
    pos = idx - start                                            # [m]
    keep = (node_s < n_nodes) & (pos < k_cap)
    row = jnp.where(keep, node_s, n_nodes)                       # pad row
    prio = (
        jnp.full((n_nodes + 1, k_cap), PRIO_PAD, jnp.int32)
        .at[row, pos].set(jnp.where(keep, prio_s, PRIO_PAD))[:n_nodes]
    )
    steps = (
        jnp.zeros((n_nodes + 1, k_cap, r), req_s.dtype)
        .at[row, pos].set(jnp.where(keep[:, None], req_s, 0.0))[:n_nodes]
    )
    vid = (
        jnp.full((n_nodes + 1, k_cap), -1, jnp.int32)
        .at[row, pos].set(jnp.where(keep, order.astype(jnp.int32), -1))[
            :n_nodes
        ]
    )
    # priority prefix sums as two 16-bit limbs (see VictimTables.psum_hi):
    # arithmetic >> handles negative priorities (hi = floor division by
    # 2^16, lo in [0, 2^16)); the post-cumsum carry normalization restores
    # lo's range so lexicographic (hi, lo) ordering equals numeric
    # ordering of hi*2^16 + lo
    kept_prio = jnp.where(keep, prio_s, 0)
    hi_v = kept_prio >> 16
    lo_v = kept_prio - (hi_v << 16)
    hi_steps = (
        jnp.zeros((n_nodes + 1, k_cap), jnp.int32)
        .at[row, pos].set(hi_v)[:n_nodes]
    )
    lo_steps = (
        jnp.zeros((n_nodes + 1, k_cap), jnp.int32)
        .at[row, pos].set(lo_v)[:n_nodes]
    )
    psum_hi = jnp.cumsum(hi_steps, axis=1)
    psum_lo = jnp.cumsum(lo_steps, axis=1)
    carry = psum_lo >> 16
    psum_hi = psum_hi + carry
    psum_lo = psum_lo - (carry << 16)
    start = (
        jnp.zeros((n_nodes + 1, k_cap), jnp.int32)
        .at[row, pos].set(jnp.where(keep, start_s, 0))[:n_nodes]
    )

    def count_table(per_victim: jnp.ndarray | None) -> jnp.ndarray:
        """[m, S] bool -> [n, K, S] inclusive prefix counts in victim
        order (mirrors `freed` for selector-match counts)."""
        if per_victim is None:
            return jnp.zeros((n_nodes, k_cap, 1), jnp.float32)
        sel_s = per_victim[order].astype(jnp.float32)          # [m, S]
        s_dim = sel_s.shape[1]
        sel_steps = (
            jnp.zeros((n_nodes + 1, k_cap, s_dim), jnp.float32)
            .at[row, pos].set(jnp.where(keep[:, None], sel_s, 0.0))[:n_nodes]
        )
        return jnp.cumsum(sel_steps, axis=1)

    return VictimTables(
        prio=prio,
        freed=jnp.cumsum(steps, axis=1),
        vid=vid,
        psum_hi=psum_hi,
        psum_lo=psum_lo,
        start=start,
        cfreed=count_table(victim_matches),
        afreed=count_table(victim_anti),
    )


def preempt_candidates(
    pend_req: jnp.ndarray,
    pend_prio: jnp.ndarray,
    pend_mask: jnp.ndarray,
    static_ok: jnp.ndarray,
    free: jnp.ndarray,
    tables: VictimTables,
    affinity: PreemptAffinity | None = None,
) -> PreemptResult:
    """Choose a preemption candidate per pending pod.

    pend_req [p, r], pend_prio [p] int32, pend_mask [p] bool,
    static_ok [p, n] bool (node-local constraint families hold —
    taints, node affinity, resources vs full allocatable),
    free [n, r] current free capacity.

    Candidate (pod p, node n, count k) is valid iff all k victims have
    priority strictly below p's, p's request fits free + freed[k-1],
    and — when `affinity` is given — the count-based families hold
    against the domain counts AS ADJUSTED by evicting those k victims
    (affinity_after_evictions; upstream RemovePod/AddPod parity).
    Per pod the minimal k per node is kept, then nodes compete on
    upstream pickOneNodeForPreemption's ordering: lowest highest-victim
    priority, lowest sum of victim priorities, fewest victims, latest
    start time of the highest-priority victim, first node index."""
    p, r = pend_req.shape
    n, k_cap = tables.prio.shape
    cap = free[None, :, None, :] + tables.freed[None, :, :, :]  # [1,n,K,r]
    fits = (
        (pend_req[:, None, None, :] <= cap)
        | (pend_req[:, None, None, :] == 0)
    ).all(-1)                                                   # [p,n,K]
    # victims sorted ascending: prefix k eligible iff its last member is
    # below the preemptor (PRIO_PAD padding fails automatically)
    elig = tables.prio[None, :, :] < pend_prio[:, None, None]   # [p,n,K]
    ok = fits & elig & static_ok[:, :, None] & pend_mask[:, None, None]
    if affinity is not None:
        ok = ok & affinity_after_evictions(affinity, tables)
    has_k = ok.any(-1)                                          # [p,n]
    kstar = jnp.argmax(ok, axis=-1)                             # first True

    def at_kstar(table):
        return jnp.take_along_axis(
            table[None], jnp.broadcast_to(kstar[:, :, None], (p, n, 1)),
            axis=2,
        )[..., 0]                                               # [p,n]

    maxprio = at_kstar(tables.prio)
    priosum_hi = at_kstar(tables.psum_hi)
    priosum_lo = at_kstar(tables.psum_lo)
    hp_start = at_kstar(tables.start)
    # lexicographic argmin over nodes:
    # (maxprio, priosum (hi then lo limb), kstar, -hp_start, node index)
    big = jnp.iinfo(jnp.int32).max
    mp = jnp.where(has_k, maxprio, big)
    best_mp = mp.min(axis=1, keepdims=True)
    tier1 = has_k & (mp == best_mp)
    ps_hi = jnp.where(tier1, priosum_hi, big)
    best_ps_hi = ps_hi.min(axis=1, keepdims=True)
    tier1b = tier1 & (ps_hi == best_ps_hi)
    ps_lo = jnp.where(tier1b, priosum_lo, big)
    best_ps_lo = ps_lo.min(axis=1, keepdims=True)
    tier2 = tier1b & (ps_lo == best_ps_lo)
    ks = jnp.where(tier2, kstar, big)
    best_k = ks.min(axis=1, keepdims=True)
    tier3 = tier2 & (ks == best_k)
    st = jnp.where(tier3, hp_start, -big)
    best_st = st.max(axis=1, keepdims=True)
    tier4 = tier3 & (st == best_st)
    node = jnp.where(
        tier4.any(-1), jnp.argmax(tier4, axis=-1), -1
    ).astype(jnp.int32)                                         # [p]
    safe = jnp.maximum(node, 0)
    nv = jnp.where(node >= 0, kstar[jnp.arange(p), safe] + 1, 0)
    vics = tables.vid[safe]                                     # [p, K]
    vics = jnp.where(
        (jnp.arange(k_cap)[None, :] < nv[:, None]) & (node >= 0)[:, None],
        vics, -1,
    )
    return PreemptResult(
        node=node, victims=vics, n_victims=nv.astype(jnp.int32)
    )
