"""Batched preemption (upstream PostFilter parity): victim selection on
device.

The reference rides the upstream kube-scheduler, whose scheduling
framework runs a PostFilter phase when a pod fits nowhere: find a node
where evicting a minimal set of strictly-lower-priority pods makes the
pod feasible, preferring candidates whose victims matter least
(upstream's ordering: lowest highest-victim-priority first, then fewest
victims). The reference plugin itself never customizes this phase
(SURVEY.md L6 — the implicit upstream layer), so parity means
reproducing the framework behavior, batched.

TPU-first formulation: instead of upstream's per-node goroutine
simulation (clone snapshot, remove pods one by one, re-run filters),
victims are laid out ONCE into per-node prefix tables sorted by
priority — freed[n, k, r] = capacity released by evicting the k
lowest-priority victims of node n — and every (pending pod, node,
victim count) combination is evaluated as one [p, n, K] tensor op.
Priority eligibility ("only strictly lower priority may be evicted")
falls out of the sort: the k-th prefix is eligible iff its LAST
(= highest-priority) member is below the preemptor's priority.

Deviations from upstream, documented:
- PodDisruptionBudgets are consulted HOST-SIDE (host/scheduler:
  victims under an exhausted budget are excluded from the tables, and
  the apply loop never overdraws a budget), but strictly: upstream
  orders candidates by fewest PDB violations and may still preempt
  past a budget as a last resort; this framework never violates one.
- Constraint families (taints, node/pod affinity, spread) are checked
  against the CURRENT cluster state via the caller-supplied
  `static_ok` mask; the marginal effect of removing the victims
  themselves on (anti)affinity domain counts is not re-simulated.
  Upstream's RemovePod/AddPod accounting does simulate it; for count-
  based families this can only make a chosen node conservatively wrong
  in the pod's favor (victims leaving a domain free anti-affinity slots,
  never consume them), and the next cycle re-checks everything against
  real state before binding.
- Victim start-time tie-breaking (upstream's final ordering criterion)
  is replaced by deterministic node-index order: start times are not
  part of the snapshot.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

PRIO_PAD = jnp.iinfo(jnp.int32).max  # padding sentinel: never evictable


class VictimTables(NamedTuple):
    """Per-node victim prefix tables, victims sorted by priority asc.

    prio:  [n, K] int32 — k-th lowest victim priority on node n
           (PRIO_PAD past the node's victim count)
    freed: [n, K, r] f32 — capacity released by evicting victims 0..k
           (inclusive prefix sums)
    vid:   [n, K] int32 — index into the caller's victim arrays, -1 pad
    """

    prio: jnp.ndarray
    freed: jnp.ndarray
    vid: jnp.ndarray


class PreemptResult(NamedTuple):
    """node:    [p] int32 — chosen node, -1 when no candidate exists
    victims: [p, K] int32 — victim indices to evict (-1 padded)
    n_victims: [p] int32
    """

    node: jnp.ndarray
    victims: jnp.ndarray
    n_victims: jnp.ndarray


def build_victim_tables(
    victim_node: jnp.ndarray,
    victim_prio: jnp.ndarray,
    victim_req: jnp.ndarray,
    victim_mask: jnp.ndarray,
    *,
    n_nodes: int,
    k_cap: int,
) -> VictimTables:
    """Lay running pods out into per-node priority-ascending prefix
    tables. victim_node [m] int32 (entries outside [0, n) ignored),
    victim_prio [m] int32, victim_req [m, r] f32, victim_mask [m] bool.

    One sort + one scatter over the m running pods, paid once per
    preemption pass (not per candidate)."""
    m, r = victim_req.shape
    ok = victim_mask & (victim_node >= 0) & (victim_node < n_nodes)
    # lexicographic (node asc, prio asc) via two stable argsorts
    ord1 = jnp.argsort(victim_prio, stable=True)
    ord2 = jnp.argsort(
        jnp.where(ok, victim_node, n_nodes)[ord1], stable=True
    )
    order = ord1[ord2]                                           # [m]
    node_s = jnp.where(ok[order], victim_node[order], n_nodes)
    prio_s = victim_prio[order]
    req_s = victim_req[order]
    # position within the node's segment
    idx = jnp.arange(m)
    boundary = jnp.concatenate(
        [jnp.ones((1,), bool), node_s[1:] != node_s[:-1]]
    )
    start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(boundary, idx, 0)
    )
    pos = idx - start                                            # [m]
    keep = (node_s < n_nodes) & (pos < k_cap)
    row = jnp.where(keep, node_s, n_nodes)                       # pad row
    prio = (
        jnp.full((n_nodes + 1, k_cap), PRIO_PAD, jnp.int32)
        .at[row, pos].set(jnp.where(keep, prio_s, PRIO_PAD))[:n_nodes]
    )
    steps = (
        jnp.zeros((n_nodes + 1, k_cap, r), req_s.dtype)
        .at[row, pos].set(jnp.where(keep[:, None], req_s, 0.0))[:n_nodes]
    )
    vid = (
        jnp.full((n_nodes + 1, k_cap), -1, jnp.int32)
        .at[row, pos].set(jnp.where(keep, order.astype(jnp.int32), -1))[
            :n_nodes
        ]
    )
    return VictimTables(prio=prio, freed=jnp.cumsum(steps, axis=1), vid=vid)


def preempt_candidates(
    pend_req: jnp.ndarray,
    pend_prio: jnp.ndarray,
    pend_mask: jnp.ndarray,
    static_ok: jnp.ndarray,
    free: jnp.ndarray,
    tables: VictimTables,
) -> PreemptResult:
    """Choose a preemption candidate per pending pod.

    pend_req [p, r], pend_prio [p] int32, pend_mask [p] bool,
    static_ok [p, n] bool (non-resource constraint families hold),
    free [n, r] current free capacity.

    Candidate (pod p, node n, count k) is valid iff all k victims have
    priority strictly below p's and p's request fits free + freed[k-1].
    Per pod the minimal k per node is kept, then nodes compete
    lexicographically on (highest victim priority, victim count, node
    index) — upstream's dominant two criteria with a deterministic tie
    break."""
    p, r = pend_req.shape
    n, k_cap = tables.prio.shape
    cap = free[None, :, None, :] + tables.freed[None, :, :, :]  # [1,n,K,r]
    fits = (
        (pend_req[:, None, None, :] <= cap)
        | (pend_req[:, None, None, :] == 0)
    ).all(-1)                                                   # [p,n,K]
    # victims sorted ascending: prefix k eligible iff its last member is
    # below the preemptor (PRIO_PAD padding fails automatically)
    elig = tables.prio[None, :, :] < pend_prio[:, None, None]   # [p,n,K]
    ok = fits & elig & static_ok[:, :, None] & pend_mask[:, None, None]
    has_k = ok.any(-1)                                          # [p,n]
    kstar = jnp.argmax(ok, axis=-1)                             # first True
    maxprio = jnp.take_along_axis(
        tables.prio[None], jnp.broadcast_to(kstar[:, :, None], (p, n, 1)),
        axis=2,
    )[..., 0]                                                   # [p,n]
    # lexicographic argmin over nodes: (maxprio, kstar, node index)
    big = jnp.iinfo(jnp.int32).max
    mp = jnp.where(has_k, maxprio, big)
    best_mp = mp.min(axis=1, keepdims=True)
    tier1 = has_k & (mp == best_mp)
    ks = jnp.where(tier1, kstar, big)
    best_k = ks.min(axis=1, keepdims=True)
    tier2 = tier1 & (ks == best_k)
    node = jnp.where(
        tier2.any(-1), jnp.argmax(tier2, axis=-1), -1
    ).astype(jnp.int32)                                         # [p]
    safe = jnp.maximum(node, 0)
    nv = jnp.where(node >= 0, kstar[jnp.arange(p), safe] + 1, 0)
    vics = tables.vid[safe]                                     # [p, K]
    vics = jnp.where(
        (jnp.arange(k_cap)[None, :] < nv[:, None]) & (node >= 0)[:, None],
        vics, -1,
    )
    return PreemptResult(
        node=node, victims=vics, n_victims=nv.astype(jnp.int32)
    )
