"""Out-of-tree plugin registration gate.

The reference's entire pkg/register/register.go is a one-call shim that
registers the yoda plugin with the embedded upstream scheduler
(app.NewSchedulerCommand(app.WithPlugin(yoda.Name, yoda.New)),
register.go:9-13). This is the same gate for this framework: named
factories for the scalar extension-point plugin surface
(host/plugins.SchedulerPlugin) so alternative plugins can be dropped in
without touching the scheduler loop, plus the feature-gate check that
decides batch-on-device vs. scalar per cycle.
"""

from __future__ import annotations

from typing import Callable

from kubernetes_scheduler_tpu.host.plugins import ScalarYodaPlugin, SchedulerPlugin

YODA = "yoda-tpu"

_REGISTRY: dict[str, Callable[..., SchedulerPlugin]] = {}


def register_plugin(name: str, factory: Callable[..., SchedulerPlugin]) -> None:
    """app.WithPlugin(name, factory) analog; later registrations win so an
    embedder can shadow the built-in."""
    _REGISTRY[name] = factory


def make_plugin(name: str, /, **kwargs) -> SchedulerPlugin:
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown plugin {name!r}; registered: {sorted(_REGISTRY)}"
        ) from None
    return factory(**kwargs)


def registered_plugins() -> list[str]:
    return sorted(_REGISTRY)


register_plugin(YODA, ScalarYodaPlugin)
