from kubernetes_scheduler_tpu.utils.padding import bucket_size, pad_axis, pad_to_bucket
