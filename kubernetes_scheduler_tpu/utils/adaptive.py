"""Adaptive scalar-vs-device dispatch model.

The right `min_device_work` threshold is deployment-dependent: the device
path's fixed cost is ~20ms against a tunneled dev chip but ~1ms against a
colocated sidecar, while the C++ scalar path is ~1-2ns per pod x node
cell — so any STATIC pods*nodes threshold is wrong somewhere (ADVICE r1
#4: the shipped 1<<20 default was never validated). Instead the scheduler
can learn both paths' latency models online and route each cycle to the
predicted-faster path.

Model: per path, t(cells) = overhead + rate * cells, fitted by recursive
least squares on (cells, seconds) observations from real cycles. The
scalar path's overhead is ~0 and the device path's is its dispatch
round-trip, so two parameters per path capture exactly the regime split
the static threshold approximates. Until a path has enough observations
the caller falls back to the static threshold, and a periodic exploration
cycle keeps the underdog path's estimate fresh.
"""

from __future__ import annotations

CELL_SCALE = 1.0e6  # cells normalized to millions: keeps the fit well-conditioned

# Tikhonov floor for the normal-equation solve. Under CONSTANT cycle
# shape — steady-state full windows on a fixed cluster, the common case —
# the decayed Gram matrix is rank-1 and the affine fit is unidentifiable;
# the ridge pins the unexcited direction to the zero prior while the
# excited direction (the only one predictions at observed shapes use)
# fits the data exactly. It also bounds the condition number, which is
# what classic forgetting-RLS lacks: there the covariance grows 1/forget
# per step along unexcited directions and overflows to inf after ~35k
# constant-shape observations, turning the fit to NaN and wedging
# dispatch permanently (found by review; pinned in test_adaptive).
RIDGE = 1e-9


class PathModel:
    """Exponentially-weighted least-squares fit of
    t = overhead + rate * (cells / CELL_SCALE).

    Kept as decayed normal-equation sums (Gram matrix + moment vector),
    solved with a ridge floor at prediction time. Same effective
    ~1/(1-forget)-observation window as forgetting-RLS, but every state
    component is a decayed sum of bounded inputs, so the estimator is
    bounded by construction — no covariance windup, no divergence, full
    adaptivity after arbitrarily long constant-excitation stretches.
    """

    def __init__(self, forget: float = 0.98):
        self.forget = forget
        # decayed sums: Gram [[1,x],[x,x2]] and moments [y, xy]
        self.s11 = 0.0
        self.s1x = 0.0
        self.sxx = 0.0
        self.sy = 0.0
        self.sxy = 0.0
        self.n_obs = 0

    def observe(self, cells: int, seconds: float) -> None:
        if cells <= 0 or seconds <= 0:
            return
        x = cells / CELL_SCALE
        lam = self.forget
        self.s11 = lam * self.s11 + 1.0
        self.s1x = lam * self.s1x + x
        self.sxx = lam * self.sxx + x * x
        self.sy = lam * self.sy + seconds
        self.sxy = lam * self.sxy + x * seconds
        self.n_obs += 1

    def _theta(self) -> tuple[float, float]:
        a, b, c = self.s11 + RIDGE, self.s1x, self.sxx + RIDGE
        det = a * c - b * b
        if det <= 0.0:
            return 0.0, 0.0
        t0 = (c * self.sy - b * self.sxy) / det
        t1 = (a * self.sxy - b * self.sy) / det
        return t0, t1

    def predict(self, cells: int) -> float:
        t0, t1 = self._theta()
        t = t0 + t1 * (cells / CELL_SCALE)
        # an extrapolating or partially-fitted model can dip negative;
        # clamp to "free"
        return max(t, 0.0)


class AdaptiveDispatch:
    """Route a cycle to the path with the lower predicted latency.

    decide(cells) -> True for the device path. Falls back to the static
    pods*nodes threshold until BOTH paths have >= min_obs observations;
    every `explore_every`-th decision routes to the other path so a
    path that lost early never starves of fresh observations (latency
    regimes shift: sidecar restarts, thermal throttling, host load).
    """

    def __init__(
        self,
        static_threshold: int,
        *,
        min_obs: int = 3,
        explore_every: int = 32,
        explore_ratio_cap: float = 10.0,
    ):
        self.static_threshold = static_threshold
        self.scalar = PathModel()
        self.device = PathModel()
        self.min_obs = min_obs
        self.explore_every = explore_every
        # exploration is bounded: flip to the underdog only when its
        # predicted time is within this factor of the winner's — a path
        # predicted 100x slower (e.g. a Python scalar rescore of a
        # 10M-cell window) is a latency spike, not an experiment
        self.explore_ratio_cap = explore_ratio_cap
        self._decisions = 0
        self._device_warmups = 0
        self._device_outliers = 0

    def observe(self, used_device: bool, cells: int, seconds: float) -> None:
        if used_device and self._device_warmups < 1:
            # the first device cycle pays the jit compile (seconds, vs a
            # ~ms steady-state dispatch); fitting it would poison the
            # overhead estimate for hundreds of cycles under forget=0.98
            self._device_warmups += 1
            return
        if used_device and self.device.n_obs >= self.min_obs:
            # later XLA retraces (window/node bucket changes) pay the
            # compile again: a sample far above the fitted prediction is
            # a compile spike, not steady-state latency — but THREE in a
            # row is a real regime shift and must be believed, or a
            # genuinely degraded device path would never be re-modeled
            pred = self.device.predict(cells)
            if seconds > 10.0 * max(pred, 1e-4):
                self._device_outliers += 1
                if self._device_outliers < 3:
                    return
            else:
                self._device_outliers = 0
        (self.device if used_device else self.scalar).observe(cells, seconds)

    def decide(self, cells: int) -> bool:
        self._decisions += 1
        fitted = (
            self.scalar.n_obs >= self.min_obs
            and self.device.n_obs >= self.min_obs
        )
        if not fitted:
            # cold start: static threshold, but force early samples of the
            # un-observed path so the model can take over. Forced SCALAR
            # samples are bounded to near-threshold sizes — a scalar pass
            # over a 25M-cell window is a multi-second spike, the exact
            # thing explore_ratio_cap forbids post-fit (the device side
            # needs no such bound: its cost is overhead-dominated)
            if self.scalar.n_obs < self.min_obs <= self.device.n_obs:
                return not (cells <= 4 * max(self.static_threshold, 1))
            if self.device.n_obs < self.min_obs <= self.scalar.n_obs:
                return True
            return cells >= self.static_threshold
        t_dev = self.device.predict(cells)
        t_sca = self.scalar.predict(cells)
        choice = t_dev <= t_sca
        if self._decisions % self.explore_every == 0:
            worse, better = max(t_dev, t_sca), min(t_dev, t_sca)
            if worse <= self.explore_ratio_cap * max(better, 1e-6):
                return not choice
        return choice
