"""Typed configuration.

Collects the reference's three config tiers into one structure (SURVEY.md
§5): upstream KubeSchedulerConfiguration knobs (backoffs, extension-point
toggles), plugin args (the demo fields at pkg/yoda/scheduler.go:36-40),
and — most importantly — everything the reference hard-codes that should
have been config: Prometheus host (advisor.go:15), Redis address
(cache/cache.go:18, gone entirely here), score weights
(score/algorithm.go:24-35), and the normalization divisors
(algorithm.go:71,73). Plus the TPU-era knobs: policy/assigner selection,
batch window, mesh devices, and feature gates.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field


@dataclass
class FeatureGates:
    """TPUBatchScore gates batch-on-device vs. the scalar fallback path
    (the north-star design's `--feature-gates=TPUBatchScore=false`,
    BASELINE.json)."""

    tpu_batch_score: bool = True
    # use the C++ host runtime (native/) for the queue and the scalar
    # fallback cycle; off -> pure-Python equivalents, same decisions
    native_host: bool = True
    # route the device step through the fused Pallas megakernel
    # (ops/pallas_fused.py) when policy/normalizer permit: score,
    # resource fit, nodeName pinning, the count-based constraint
    # families, the remaining constraint mask, and the min-max epilogue
    # in ONE tiled [p, n] pass instead of up to seven HBM round-trips.
    # Engages for policy="balanced_cpu_diskio" with normalizer "none"
    # OR — for local TPU-backend engines — "min_max" (the deployed
    # default); softmax configurations, CPU engines under min_max, and
    # remote sidecars under min_max (no capability bit yet) run
    # unfused (decisions identical either way — PARITY round 12)
    fused_kernel: bool = True


@dataclass
class AdvisorConfig:
    prometheus_host: str = "prometheus.monitoring:9090"
    # normalization divisors (algorithm.go:71,73)
    disk_io_divisor: float = 50.0
    cpu_divisor: float = 100.0
    # background refresh (host.advisor.BackgroundAdvisor): a daemon
    # thread scrapes every refresh_interval_seconds so the scheduling
    # cycle never blocks on the five Prometheus round-trips (the
    # reference pays them inside PreScore, advisor.go:149-265). 0 =
    # fetch directly inside the cycle. Snapshots older than
    # max_staleness_seconds are not served — fetch falls back to one
    # synchronous scrape whose failure requeues the window, the direct
    # wiring's outage behavior.
    refresh_interval_seconds: float = 5.0
    max_staleness_seconds: float = 60.0


@dataclass
class SchedulerConfig:
    scheduler_name: str = "yoda-tpu"
    policy: str = "balanced_cpu_diskio"
    # weighted multi-plugin scoring (upstream framework RunScorePlugins):
    # a non-empty list of {"name": <policy>, "weight": N} replaces the
    # single `policy` with the framework's weighted sum — the combination
    # the reference's deployed config produces by enabling yoda BESIDE
    # the k8s 1.22 defaults (deploy/yoda-scheduler.yaml:21-47 disables
    # nothing; example/config:25-27 weights yoda at 2). E.g.:
    #   [{"name": "balanced_cpu_diskio", "weight": 2},
    #    {"name": "least_allocated", "weight": 1},
    #    {"name": "balanced_allocation", "weight": 1},
    #    {"name": "image_locality", "weight": 1}]
    # Empty = single-policy scoring (engine.compute_scores on `policy`).
    score_plugins: list = field(default_factory=list)
    # auction is the deployed default since round 5: it enforces hard
    # (anti)affinity exactly (per-round dynamic masks + same-round
    # conflict eviction), its measured placement quality matches greedy
    # on every BENCH_SUITE config at the default price step (PARITY.md
    # round-4 table: assigned counts and mean chosen scores equal or
    # better), and its parallel rounds are ~90x faster than the
    # sequential greedy scan at scale — the greedy path remains for
    # strict upstream-order semantics (assigner="greedy")
    assigner: str = "auction"
    normalizer: str = "min_max"
    batch_window: int = 1024
    # auction assigner knobs (ops/assign.auction_assign). price_frac is
    # the quality/throughput dial: rounds-to-converge scales
    # ~1/price_frac. Default 1.0: with the counter-hash tie-break jitter
    # (round 4) the measured mean placement score at 1.0 matches 1/16 to
    # <0.3% on every BENCH_SUITE config and never trails the greedy
    # oracle (PARITY.md round-4 table), so the fast step is no longer a
    # quality trade. Lower it for workloads with fine-grained score
    # distinctions worth extra rounds. The knobs ride the gRPC wire too
    # (ScheduleRequest.auction_*), so remote engines honor them.
    auction_rounds: int = 1024
    auction_price_frac: float = 1.0
    # resource -> weight, all 1 like the reference (scheduler.go:75-77)
    resource_weights: dict = field(
        default_factory=lambda: {
            "cpu": 1, "memory": 1, "pods": 1, "storage": 1,
            "ephemeral-storage": 1,
        }
    )
    extended_resources: list = field(default_factory=list)
    # queue backoffs (deploy/yoda-scheduler.yaml:19-20)
    initial_backoff_seconds: float = 1.0
    max_backoff_seconds: float = 10.0
    mesh_devices: int | None = None  # None = single device
    # policy="learned": restore the two-tower scorer from this orbax
    # checkpoint (models/learned.py); None = fresh (untrained) parameters
    learned_checkpoint: str | None = None
    # adaptive dispatch: below this pods x nodes product a cycle runs the
    # host scalar path (C++ when native_host) instead of the device — tiny
    # problems are device-dispatch-latency-bound (a 1-pod x 3-node cycle
    # is ~25us in C++ vs ~20ms of device round-trip). With
    # adaptive_dispatch=True this is only the COLD-START prior: the
    # scheduler fits per-path latency models (overhead + rate x cells,
    # utils/adaptive.py) from its own cycles and routes each cycle to the
    # predicted-faster path, because the true crossover is
    # deployment-dependent (tunneled dev chip ~20ms dispatch vs colocated
    # sidecar ~1ms — a 20x shift in the break-even point).
    min_device_work: int = 1 << 20
    adaptive_dispatch: bool = True
    # deep-queue batching: a cycle may pop up to this many windows and
    # schedule them in ONE engine dispatch (engine.schedule_windows /
    # the ScheduleWindows RPC) with capacity + affinity carried between
    # windows on device. 1 = one window per cycle (the upstream shape).
    # Throughput/latency dial: 16 amortizes the engine round-trip over
    # twice the pods (~+35% loop throughput on a tunneled chip,
    # host_loop_4000nodes_deep16w in bench.py) at ~1.5x cycle latency;
    # remote sidecars see the biggest gains, colocated engines pay ~ms
    # round-trips and gain little.
    max_windows_per_cycle: int = 8
    # pipelined host loop (host/scheduler.py): with depth 1 the cycle
    # dispatches the engine asynchronously and overlaps the wait with
    # next-cycle host work (queue pop, pod-batch build, record warming),
    # folding this cycle's binds into the snapshot accumulator with
    # SnapshotBuilder.apply_assignment_deltas instead of a full rebuild.
    # 0 restores the strictly alternating host/device loop. Bindings are
    # bit-identical to serial mode for the same arrival order; pods that
    # become ready mid-flight (backoff expiry, informer submits) join
    # the NEXT dispatch instead of the prefetched window. Values > 1
    # behave as 1 (the pipeline is one deep — a deeper pipeline would
    # score stale capacity).
    pipeline_depth: int = 0
    # device-resident cluster state (engine.ResidentState): the engine
    # retains the snapshot leaves on device after the first full upload
    # in a bucket shape, and subsequent single-window cycles ship only a
    # SnapshotDelta (changed requested/utilization/domain rows by value + the
    # node mask), applied by a jitted donated-buffer scatter — no full
    # [n, r] matrix crosses the host<->device boundary in the common
    # case. Flushes to a full upload on epoch mismatch, layout/
    # fingerprint churn, engine failure, or preemption. Off by default:
    # the default-off path is bit-identical to the pre-resident loop,
    # and delta mode itself is binding-parity-pinned against full-upload
    # mode (PARITY.md; tests/test_resident.py). Multi-window backlog
    # cycles (max_windows_per_cycle > 1 with a deep queue) always upload
    # in full — only the schedule_batch surface is resident.
    resident_state: bool = False
    # mesh-sharded engine (parallel/engine.ShardedEngine): shard the
    # snapshot's node axis across every visible device (the largest
    # divisor of 8 the host has — node buckets are multiples of 8) and
    # run each cycle shard-local with the budgeted collectives
    # (COLLECTIVE_BUDGET.json). Composes with resident_state: each shard
    # retains ITS snapshot slice (and kernel-layout slice on fused
    # paths), and every SnapshotDelta is routed to the shards owning its
    # rows (host.snapshot.shard_snapshot_delta) — per-cycle host->device
    # bytes scale with the change, flat as the cluster grows (the
    # 100k-node scale step). Decisions are bitwise the dense engine's
    # (PARITY.md round 15); only in-process engines are built from this
    # knob — a remote sidecar's mesh is its own --mesh-devices flag.
    sharded_engine: bool = False
    # fleet-shared device engine (host/engine_pool.SharedEnginePool):
    # every replica of a ReplicaFleet multiplexes its engine traffic
    # onto ONE Local/Remote engine holding ONE device-resident snapshot
    # — a churn event uploads once per fleet instead of once per
    # replica (pool-held base + per-dispatch deltas, epoch-fenced so a
    # replica that raced a flush transparently re-syncs with a full
    # upload), and dispatches queued while the device is busy stack
    # into one coalesced invocation (schedule_batch_fleet) with results
    # de-multiplexed per replica. Decisions are bit-identical to
    # private-engine replicas: every stacked window is scored against
    # ITS OWN snapshot content (base + that replica's functional
    # delta), so first-bind-wins semantics and union parity are
    # unchanged (PARITY.md round 20). Only consulted by ReplicaFleet;
    # a single scheduler ignores it.
    shared_engine: bool = False
    # how long a THREADED shared-engine dispatch with no companions yet
    # waits for other replicas' windows to arrive before dispatching
    # alone (milliseconds). Only consulted when several fleet threads
    # are inside the pool concurrently — single-threaded/round-robin
    # drains never wait, so sequential harnesses pay zero latency.
    coalesce_window_ms: float = 2.0
    # pre-size the snapshot/mirror selector bucket (warm restart):
    # selector tables grow by power-of-two crossings, and every
    # crossing is a mirror flush-to-full rebuild. `yoda-tpu trace stats`
    # reports the journal's peak selector count; plumbing it back here
    # lets a restart allocate the steady-state bucket up front and skip
    # the early crossing rebuilds entirely. 0 = grow from scratch.
    mirror_initial_selectors: int = 0
    # streaming state ingestion (host/mirror.SnapshotMirror): informer
    # pod/node/utilization events apply directly to a persistent
    # host-side numpy mirror of the snapshot arrays, and each cycle
    # emits a ready-made SnapshotDelta in O(events since last cycle)
    # instead of rebuilding from the full lists (snapshot_build) and
    # row-diffing whole matrices (delta_derive) — an idle cluster costs
    # ~0 and the 100k-node host ceiling moves off the cycle path.
    # build_snapshot remains the flush-to-full path (node churn,
    # selector/port layout drift) and the verification path:
    # mirror_verify_interval > 0 cross-checks the mirror against a full
    # rebuild every N emits, BITWISE, resyncing loudly on mismatch
    # (mirror_verify_failures_total). ON by default since the in-place
    # extension paths absorbed the recurring flush classes (selector
    # drift within the power-of-two bucket, same-width hostPort remaps
    # — mirror_incremental_extensions_total{kind}): mirror-on and
    # mirror-off bindings are bit-identical (PARITY.md rounds 16/19 and
    # tests/test_mirror.py's default-config pin), so the flip changes
    # host-side cost, never decisions. Turn off to fall back to the
    # per-cycle rebuild loop.
    snapshot_mirror: bool = True
    mirror_verify_interval: int = 256
    # cycle triggering: "event" (default since the flip pinned by
    # tests/test_trigger.py's default-config parity test) arms a
    # CycleTrigger the loops sleep on — queue pushes and mirror events
    # wake a cycle immediately, the poll interval degrades to a
    # watchdog timeout (no lost wakeups: the trigger latches notifies
    # that land between the work check and the wait). "tick" restores
    # the fixed-poll idle waits of the host loops. Scheduling decisions
    # are unaffected — only WHEN cycles run changes (tick↔event
    # bindings are bitwise identical under the default config).
    cycle_trigger: str = "event"
    # gang co-scheduling (ops/gang.py, arXiv:2511.08373): pods labeled
    # scv/gang + scv/gang-size bind all-or-nothing — the engine rescinds
    # every placement of a gang that did not fully fit, and the host
    # requeues the whole gang atomically to the FRONT of the queue
    # (queue.restore_window: order preserved, re-pops next cycle).
    # gang_max_defers bounds the front-of-queue retries; a gang that
    # exhausts them is resolved per gang_defer_policy:
    #   "split"  members lose their gang identity and schedule as
    #            individuals with ordinary retry backoff (the default —
    #            capacity eventually flows)
    #   "drop"   members requeue with ordinary backoff but KEEP the gang,
    #            retrying all-or-nothing at backoff cadence
    # Off: gang labels are ignored entirely — bit-identical to the
    # pre-gang scheduler (PARITY.md pins gang-off == no-gangs-in-traffic)
    gang_scheduling: bool = True
    gang_max_defers: int = 4
    gang_defer_policy: str = "split"
    # preemption (upstream PostFilter parity, ops/preempt.py): when a pod
    # fits nowhere, evict <= preemption_max_victims strictly-lower-
    # priority pods from the least-disruptive node. Requires an evictor
    # wired into the Scheduler (RecordingEvictor for sims, kube.
    # KubeEvictor live); without one the pass is inert.
    # cycle flight recorder (trace/): when set, every scheduling cycle
    # appends one length-prefixed, CRC-guarded record (window pod
    # identity, the snapshot arrays or the SnapshotDelta actually
    # shipped, engine options, resident epoch, path taken, bindings,
    # CycleMetrics) to a rotating journal under this DIRECTORY, bounded
    # by trace_max_bytes total (oldest files dropped; every file opens
    # with a full snapshot so a head-rotated journal still replays).
    # trace/replay.py re-executes a journal through any engine mode
    # combination and diffs bindings bitwise. None = off (zero cost).
    trace_path: str | None = None
    trace_file_bytes: int = 32 * 1024 * 1024
    trace_max_bytes: int = 256 * 1024 * 1024
    # per-cycle span telemetry (host/observe.SpanRecorder): when set,
    # every completed cycle appends Chrome-trace-event JSON spans
    # (queue pop, state fetch, snapshot build, delta derivation, engine
    # step, bind fan-out, recorder write) under a monotonically-assigned
    # trace id to a rotating journal-style directory (trace/spans.py).
    # The id rides gRPC metadata to the sidecar so `yoda-tpu spans
    # merge` joins host and sidecar spans into one Perfetto-loadable
    # timeline; each span also carries the cycle's flight-recorder seq
    # when trace_path is set. None = off (zero cost); encoding happens
    # in the completion stage, off the device-dispatch critical path.
    span_path: str | None = None
    span_file_bytes: int = 32 * 1024 * 1024
    span_max_bytes: int = 128 * 1024 * 1024
    # on-demand device profiling (/debug/profile?cycles=N): where the
    # jax.profiler dumps land. None = derive (<span_path>/profiles when
    # spans are on, else a tempdir)
    profile_path: str | None = None
    # /metrics bind host (host/observe exporters): the deploy manifests
    # bind all interfaces for the Prometheus scrape; tests bind loopback
    metrics_bind_host: str = "0.0.0.0"
    # live SLO watchdog (host/scheduler._check_slo, run from the cycle
    # completion stage — never the dispatch path): a cycle slower than
    # cycle_slo_ms logs its trace id + flight-recorder seq and bumps
    # slo_breaches_total{path} on /metrics, so a slow production cycle
    # leaves an addressable record instead of a vague p99 drift. With
    # slo_profile_cycles > 0 a breach also self-arms the on-demand
    # jax.profiler hook (the /debug/profile machinery) for the next N
    # engine calls — the next slow cycle leaves a journal seq, a span
    # timeline, AND a profile dump that `spans report` joins into one
    # story. 0 = watchdog off (zero cost); the watchdog only reads
    # clocks, so watchdog-on/off bindings are bit-identical (PARITY.md).
    cycle_slo_ms: float = 0.0
    slo_profile_cycles: int = 0
    # resilience layer (host/resilience.py). advisor_stale_ttl_s: on an
    # advisor/cluster-source fetch failure, cycles are served the
    # LAST-GOOD cluster state (marked CycleMetrics.advisor_stale,
    # counted advisor_stale_cycles_total) for up to this many seconds
    # before the window-requeue outage path engages — scheduling keeps
    # flowing on slightly stale utilization instead of stalling. 0 =
    # off; with the TTL never firing the loop is bit-identical to the
    # pre-grace scheduler (PARITY round 17). Advisor retry attempts
    # during an outage follow the shared deterministic-jitter
    # exponential BackoffPolicy instead of hammering every cycle.
    advisor_stale_ttl_s: float = 0.0
    # circuit breakers (closed -> open -> half-open with recovery
    # probes) guarding the engine dispatch and the advisor fetch:
    # after breaker_failure_threshold consecutive failures the
    # dependency is skipped outright for breaker_recovery_window_s
    # seconds, then ONE probe per window until it succeeds — an outage
    # costs one probe per window instead of a timeout per call. While
    # the engine breaker is open, cycles route to the scalar fallback
    # directly (the degradation ladder records engine->local with the
    # breaker as the reason; degradation_rung{subsystem} on /metrics).
    breaker_failure_threshold: int = 3
    breaker_recovery_window_s: float = 8.0
    preemption: bool = True
    preemption_max_victims: int = 8
    # preemptors evaluated per pass, highest priority first: the
    # RemovePod re-simulation's candidate tensors scale with
    # nodes x k_cap x preemptors x selectors, and the host applies at
    # most one proposal per node per cycle anyway — a mass-unschedulable
    # event must not feed the whole backlog into one device pass
    preemption_max_candidates: int = 128
    # how long a preemptor's nominated-node capacity reservation survives
    # if the preemptor never comes back to bind (deleted while pending)
    preemption_nomination_ttl_seconds: float = 120.0
    feature_gates: FeatureGates = field(default_factory=FeatureGates)
    advisor: AdvisorConfig = field(default_factory=AdvisorConfig)

    @classmethod
    def from_dict(cls, d: dict) -> "SchedulerConfig":
        d = dict(d)
        if "feature_gates" in d and isinstance(d["feature_gates"], dict):
            d["feature_gates"] = FeatureGates(**d["feature_gates"])
        if "advisor" in d and isinstance(d["advisor"], dict):
            d["advisor"] = AdvisorConfig(**d["advisor"])
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown config keys: {sorted(unknown)}")
        cfg = cls(**d)
        for entry in cfg.score_plugins:
            if not isinstance(entry, dict) or "name" not in entry:
                raise ValueError(
                    "score_plugins entries must be {'name': ..., "
                    "'weight': N} dicts; got " + repr(entry)
                )
            extra = set(entry) - {"name", "weight"}
            if extra:
                raise ValueError(
                    f"unknown score_plugins keys: {sorted(extra)}"
                )
            # weight 0 is ambiguous on the proto wire (proto3 zero =
            # unset) and silently disables the plugin locally — a
            # disabled plugin should be REMOVED from the list instead
            if float(entry.get("weight", 1)) <= 0:
                raise ValueError(
                    f"score_plugins weight must be > 0 (drop the entry "
                    f"to disable a plugin): {entry!r}"
                )
            # fail fast on typo'd names: a bad plugin would otherwise
            # error every cycle into the yoda-formula fallback forever.
            # SCALAR_POLICIES is the jax-free mirror of engine.POLICIES
            # (test-pinned equal)
            from kubernetes_scheduler_tpu.host.plugins import SCALAR_POLICIES

            if entry["name"] not in SCALAR_POLICIES:
                raise ValueError(
                    f"unknown score plugin {entry['name']!r}; "
                    f"expected one of {SCALAR_POLICIES}"
                )
        return cfg

    def score_plugins_tuple(self) -> tuple | None:
        """The engine's static score_plugins encoding: ((name, weight),
        ...) or None when single-policy scoring is configured."""
        if not self.score_plugins:
            return None
        return tuple(
            (e["name"], float(e.get("weight", 1))) for e in self.score_plugins
        )

    @classmethod
    def from_json(cls, path: str) -> "SchedulerConfig":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)
