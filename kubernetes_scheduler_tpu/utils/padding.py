"""Bucketed padding: dynamic cluster sizes vs. XLA static shapes.

The pending-pod count P and node count N vary every cycle, but everything
under `jit` must be statically shaped. We round each axis up to a bucket
(powers of two by default, with a floor) so recompilation only happens when
a cluster crosses a bucket boundary, and carry boolean masks for the
padding. Buckets are also kept multiples of 8 so the node axis divides the
TPU sublane tiling and any mesh size up to 8.
"""

from __future__ import annotations

import numpy as np


def bucket_size(n: int, *, floor: int = 8, multiple: int = 8) -> int:
    """Smallest power-of-two bucket >= n, at least `floor`, a multiple of
    `multiple`."""
    b = floor
    while b < n:
        b *= 2
    return int(np.ceil(b / multiple) * multiple)


def pad_axis(arr: np.ndarray, size: int, axis: int = 0, fill=0) -> np.ndarray:
    """Pad `axis` of `arr` with `fill` up to `size`."""
    cur = arr.shape[axis]
    if cur == size:
        return arr
    if cur > size:
        raise ValueError(f"axis {axis} has {cur} > bucket {size}")
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, size - cur)
    return np.pad(arr, widths, constant_values=fill)


def pad_to_bucket(arr: np.ndarray, axis: int = 0, *, floor: int = 8, fill=0):
    """Pad `axis` up to its bucket; returns (padded, mask) where mask is a
    bool array over the padded axis marking real entries."""
    size = bucket_size(arr.shape[axis], floor=floor)
    mask = np.zeros(size, bool)
    mask[: arr.shape[axis]] = True
    return pad_axis(arr, size, axis, fill), mask


# Fields whose "absent" encoding is -1, not 0 (make_pod_batch defaults):
# selector ids, the NodeName pin, and the card wants. Zero-filled padding
# would read as "selector 0" / "pinned to node 0" / "wants 0-memory
# card"; decisions stay correct (pod_mask gates the engine) but any
# consumer inspecting the raw fields — e.g. the host's affinity_aware
# heuristic — would see phantom constraints.
_NEG_SENTINEL_FIELDS = frozenset({
    "affinity_sel", "anti_affinity_sel", "spread_sel", "target_node",
    "pref_affinity_sel", "pref_anti_sel", "want_memory", "want_clock",
    "gang_id",
})


def pad_pod_batch(pods, size: int):
    """Pad every array of a PodBatch along the pod axis to `size`, with
    pod_mask False on the padding and each field's own absent sentinel
    (-1 for selector/pin/card-want fields, 0 elsewhere)."""
    p = pods.request.shape[0]
    if p > size:
        raise ValueError(f"pod count {p} > target {size}")
    if p == size:
        return pods
    return type(pods)(
        *[
            pad_axis(
                np.asarray(f), size, 0,
                fill=-1 if name in _NEG_SENTINEL_FIELDS else 0,
            )
            for name, f in zip(pods._fields, pods)
        ]
    )._replace(pod_mask=np.concatenate([np.asarray(pods.pod_mask),
                                        np.zeros(size - p, bool)]))
