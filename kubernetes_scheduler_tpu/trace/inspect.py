"""Journal inspection: dump / stats / diff (the `trace` CLI's backends).

These read journals without touching an engine (importing this module
never pulls in jax) — safe to run against a production trace on a
laptop. `diff` pairs two journals' records by seq and compares their
DECISION content (path, window identity, node_idx), which is what "two
identical replays report zero differences" means: metrics, timestamps,
and bind-time outcomes (a live binder's 404/409 drops ride `bindings`)
legitimately differ between runs and are never part of the comparison.
"""

from __future__ import annotations

import numpy as np

from kubernetes_scheduler_tpu.trace.recorder import journal_files, read_journal


def record_summary(rec: dict) -> dict:
    """One journal record as a compact JSON-able summary (tensor payloads
    reduced to shapes)."""
    assign = (rec.get("assign") or {}).get("node_idx")
    out = {
        "seq": rec.get("seq"),
        "path": rec.get("path"),
        "pods_in": len(rec.get("pod_keys") or []),
        "bound": len(rec.get("bindings") or []),
        "assigned": int((np.asarray(assign) >= 0).sum())
        if assign is not None
        else None,
        "resident_epoch": rec.get("resident_epoch", 0),
        "delta_sent": bool(rec.get("delta_sent", 0)),
        "carries": (
            "delta" if "delta" in rec
            else "snapshot" if "snapshot" in rec
            else "none"
        ),
    }
    snap = rec.get("snapshot") or rec.get("delta")
    if snap:
        out["tensor_bytes"] = int(
            sum(a.nbytes for a in snap.values())
            + sum(a.nbytes for a in (rec.get("pods") or {}).values())
        )
    if rec.get("batch_window"):
        out["batch_window"] = rec["batch_window"]
    return out


def dump(path: str, *, limit: int | None = None):
    """Yield per-record summaries, oldest first."""
    for i, rec in enumerate(read_journal(path)):
        if limit is not None and i >= limit:
            return
        yield record_summary(rec)


def stats(path: str) -> dict:
    """Whole-journal aggregates: what a pilot reads before a replay."""
    import os

    files = journal_files(path)
    by_path: dict[str, int] = {}
    records = 0
    bound = 0
    assigned = 0
    delta_records = 0
    full_records = 0
    peak_sel = 0
    first_seq = last_seq = None
    for rec in read_journal(path):
        records += 1
        by_path[rec.get("path", "?")] = by_path.get(rec.get("path", "?"), 0) + 1
        bound += len(rec.get("bindings") or [])
        a = (rec.get("assign") or {}).get("node_idx")
        if a is not None:
            assigned += int((np.asarray(a) >= 0).sum())
        if "delta" in rec:
            delta_records += 1
            dv = rec["delta"].get("dom_vals")
            if dv is not None and np.asarray(dv).ndim == 3:
                peak_sel = max(peak_sel, int(np.asarray(dv).shape[1]))
        elif "snapshot" in rec:
            full_records += 1
            dc = rec["snapshot"].get("domain_counts")
            if dc is not None and np.asarray(dc).ndim == 2:
                peak_sel = max(peak_sel, int(np.asarray(dc).shape[1]))
        if first_seq is None:
            first_seq = rec.get("seq")
        last_seq = rec.get("seq")
    return {
        "files": len(files),
        "bytes": sum(os.path.getsize(fp) for fp in files),
        "records": records,
        "by_path": by_path,
        "first_seq": first_seq,
        "last_seq": last_seq,
        "pods_bound": bound,
        "pods_assigned": assigned,
        "snapshot_records": full_records,
        "delta_records": delta_records,
        # the selector-table width the run peaked at (the snapshot's
        # domain tables are sized to the power-of-two selector bucket):
        # feed this to config.mirror_initial_selectors on a warm restart
        # so the restarted mirror skips the early bucket-crossing
        # rebuilds the original run already paid for
        "peak_selector_slots": peak_sel,
    }


def _compare_decisions(ra: dict, rb: dict) -> list:
    """The DECISION identity of a cycle record: path, window pod
    identity, and the engine's node_idx. Bindings are deliberately NOT
    compared — they record bind-time outcomes (a live binder's 404/409
    drops), which are environment, not decisions: a recorded production
    journal and its replay legitimately differ there while agreeing on
    every assignment."""
    problems = []
    if ra.get("path") != rb.get("path"):
        problems.append(f"path {ra.get('path')!r} != {rb.get('path')!r}")
    if (ra.get("pod_keys") or []) != (rb.get("pod_keys") or []):
        problems.append("window pod identity differs")
    ia = np.asarray((ra.get("assign") or {}).get("node_idx", ()))
    ib = np.asarray((rb.get("assign") or {}).get("node_idx", ()))
    if ia.shape != ib.shape or not np.array_equal(ia, ib):
        n = (
            int((ia != ib).sum())
            if ia.shape == ib.shape
            else max(ia.size, ib.size)
        )
        problems.append(f"node_idx differs on {n} rows")
    return problems


def diff(path_a: str, path_b: str, *, limit: int | None = None) -> dict:
    """Record-by-record decision diff of two journals. Zero differences
    means the two runs decided identically — the acceptance check for
    replaying the same journal twice.

    Records pair by `seq` (a two-pointer merge — seq is monotonic
    within a run), so a journal whose head was rotated or pruned away
    diffs against the surviving overlap instead of misaligning every
    record positionally; records only one side has count as extra, not
    as differences. Records without seq fall back to positional
    pairing."""
    it_a = read_journal(path_a)
    it_b = read_journal(path_b)
    compared = 0
    differing = []
    extra_a = extra_b = 0
    truncated = False
    ra = next(it_a, None)
    rb = next(it_b, None)
    while ra is not None and rb is not None:
        if limit is not None and compared >= limit:
            # a limited diff is NOT a verdict on the uncompared tail —
            # flag it, so "differences: 0" cannot be mistaken for
            # "the journals agree" (cmd_trace never passes a limit)
            truncated = True
            ra = rb = None
            break
        sa, sb = ra.get("seq"), rb.get("seq")
        if sa is not None and sb is not None and sa != sb:
            if sa < sb:
                extra_a += 1
                ra = next(it_a, None)
            else:
                extra_b += 1
                rb = next(it_b, None)
            continue
        compared += 1
        problems = _compare_decisions(ra, rb)
        if problems:
            differing.append({"seq": sa, "problems": problems})
        ra = next(it_a, None)
        rb = next(it_b, None)
    if ra is not None:
        extra_a += 1 + sum(1 for _ in it_a)
    if rb is not None:
        extra_b += 1 + sum(1 for _ in it_b)
    return {
        "records_compared": compared,
        "differences": len(differing),
        "differing": differing[:32],
        "extra_records_a": extra_a,
        "extra_records_b": extra_b,
        "truncated": truncated,
    }
