"""Soak-length trend and leak detection: windowed regression slopes
over span series and journal metrics, with CI-able exit codes.

A pairwise `spans diff` answers "is the candidate slower than the
baseline"; a soak run asks a different question — "is ANYTHING slowly
getting worse": p99 creep from a resident-state leak, delta hit-rate
decay from layout churn accumulating, queue depth ratcheting because
drain never quite catches arrivals. One threshold comparison cannot see
those; a monotone slope over a windowed series can.

The gate: a series regresses when its least-squares slope points the
wrong way, the end-to-end rise clears the absolute floor (the `spans
diff --min-ms` floor reused — sub-tick jitter must not fail builds) AND
the relative threshold, and the movement is MONOTONE enough
(`monotone_frac` of consecutive deltas in the trend direction) — noise
is jagged, leaks are not. Everything here is engine/jax-free, like the
rest of the journal/span read tooling.

Three front ends share the gate:
- `trend_over_reports`: N `spans report` snapshots in time order
  (`spans diff --trend base cand more...`).
- `build_trend`: ONE span source split into equal-time windows
  (`spans report --trend` — the soak gate).
- `journal_trend`: leak signals straight from a journal's per-cycle
  metrics (`trace trend`): delta hit-rate decay, cycle p99 creep,
  queue-depth runaway, resident-state byte growth.
"""

from __future__ import annotations

import json
import os

import numpy as np

from kubernetes_scheduler_tpu.trace.analyze import (
    AnalyzeError,
    _dist,
    _load_events,
)

TREND_METRICS = ("p50_ms", "p99_ms")


class TrendError(RuntimeError):
    """Unusable trend input (too few points/windows to fit a slope)."""


def _fit(values: list[float]) -> dict:
    """Least-squares slope per step plus the monotonicity of the raw
    series (fraction of consecutive deltas that do not move against
    the fitted direction)."""
    v = np.asarray(values, dtype=float)
    n = v.shape[0]
    x = np.arange(n, dtype=float)
    slope = float(np.polyfit(x, v, 1)[0]) if n >= 2 else 0.0
    deltas = np.diff(v)
    if deltas.size and slope != 0.0:
        agree = (deltas >= 0) if slope > 0 else (deltas <= 0)
        monotone = float(agree.mean())
    else:
        monotone = 0.0
    return {
        "slope": round(slope, 6),
        "rise": round(float(v[-1] - v[0]), 4) if n else 0.0,
        "monotone_frac": round(monotone, 4),
    }


def gate_series(
    name: str,
    values: list[float],
    *,
    direction: str = "up",
    min_abs: float = 0.05,
    threshold_pct: float = 25.0,
    monotone_frac: float = 0.6,
    min_points: int = 3,
) -> dict:
    """One series through the regression gate. direction="up" flags
    growth (latency creep, queue runaway, byte leaks); "down" flags
    decay (delta hit-rate). Returns the row; `regression` is the CI
    bit."""
    row: dict = {
        "series": name,
        "direction": direction,
        "points": len(values),
        "values": [round(float(v), 4) for v in values],
        "regression": False,
    }
    if len(values) < min_points:
        row["reason"] = f"too few points (<{min_points})"
        return row
    fit = _fit(values)
    row.update(fit)
    sign = 1.0 if direction == "up" else -1.0
    move = sign * fit["rise"]
    base = abs(values[0])
    pct = 100.0 * move / base if base > 0 else (
        float("inf") if move > 0 else 0.0
    )
    row["rise_pct"] = round(pct, 2) if pct != float("inf") else None
    row["regression"] = bool(
        sign * fit["slope"] > 0
        and move > min_abs
        and pct > threshold_pct
        and fit["monotone_frac"] >= monotone_frac
    )
    return row


def trend_over_reports(
    reports: list[dict],
    *,
    metrics: tuple = TREND_METRICS,
    threshold_pct: float = 25.0,
    min_ms: float = 0.05,
    monotone_frac: float = 0.6,
) -> dict:
    """Monotone-slope gate over N report snapshots in time order: every
    stage's p50/p99 series, plus the whole-cycle series. A stage absent
    from some snapshots is skipped (a contract question for the
    span-hygiene lint, not a latency trend)."""
    if len(reports) < 3:
        raise TrendError(
            f"trend needs >= 3 report snapshots in time order, got "
            f"{len(reports)}"
        )
    rows: list[dict] = []
    stage_names = sorted(
        set().union(*(r.get("stages", {}).keys() for r in reports))
    )
    for metric in metrics:
        # a per-window p99 is estimated from few samples and behaves
        # like a max — give the tail series a 10x wider absolute floor
        # so micro-stage jitter cannot fail a soak
        floor = min_ms * (10.0 if metric == "p99_ms" else 1.0)
        if all(r.get("cycle_ms") for r in reports):
            rows.append(
                gate_series(
                    f"cycle.{metric}",
                    [r["cycle_ms"][metric] for r in reports],
                    min_abs=floor,
                    threshold_pct=threshold_pct,
                    monotone_frac=monotone_frac,
                )
            )
        for stage in stage_names:
            dists = [r.get("stages", {}).get(stage) for r in reports]
            if any(d is None or not d.get("count") for d in dists):
                continue
            rows.append(
                gate_series(
                    f"{stage}.{metric}",
                    [d[metric] for d in dists],
                    min_abs=floor,
                    threshold_pct=threshold_pct,
                    monotone_frac=monotone_frac,
                )
            )
    regressions = [r["series"] for r in rows if r["regression"]]
    return {
        "points": len(reports),
        "threshold_pct": threshold_pct,
        "min_ms": min_ms,
        "monotone_frac": monotone_frac,
        "rows": rows,
        "regressions": regressions,
        "clean": not regressions,
    }


def _window_reports(events: list[dict], windows: int) -> list[dict]:
    """Split one span stream into `windows` time-ordered, equal-
    POPULATION slices (quantile edges over event start ts) and build a
    per-slice stage/cycle distribution table — the report shape
    trend_over_reports expects. Equal-population beats equal-duration
    here: a smoke-scale soak's wall clock is dominated by compile
    pauses, which would leave most equal-duration windows empty and
    the survivors unevenly filled."""
    complete = [ev for ev in events if ev.get("ph") == "X"]
    if not complete:
        raise AnalyzeError("span source holds no complete spans")
    ts = np.asarray([float(ev.get("ts", 0.0)) for ev in complete])
    t0, t1 = float(ts.min()), float(ts.max())
    if t1 <= t0:
        raise TrendError(
            "span source covers a single instant — cannot window a trend"
        )
    edges = np.quantile(ts, np.linspace(0.0, 1.0, windows + 1))
    out = []
    for w in range(windows):
        lo, hi = edges[w], edges[w + 1]
        sel = (ts >= lo) & ((ts < hi) | (w == windows - 1))
        by_name: dict[str, list[float]] = {}
        for ev in (complete[i] for i in np.flatnonzero(sel)):
            by_name.setdefault(ev.get("name", "?"), []).append(
                float(ev.get("dur", 0.0)) / 1e3
            )
        cyc = by_name.get("cycle", [])
        rep: dict = {
            "cycles": len(cyc),
            "stages": {
                n: _dist(v) for n, v in by_name.items() if n != "cycle"
            },
        }
        if cyc:
            rep["cycle_ms"] = _dist(cyc)
        out.append(rep)
    return out


def build_trend(
    source: str,
    *,
    windows: int = 8,
    warmup: int = 1,
    threshold_pct: float = 25.0,
    min_ms: float = 0.05,
    monotone_frac: float = 0.6,
) -> dict:
    """The soak gate: one span directory/trace, windowed in time,
    through the monotone-slope trend. A window with no samples for a
    stage drops that stage's series (short soaks), never errors.

    The first `warmup` non-empty windows are excluded when enough
    points remain: the opening slice carries one-time costs (JIT
    compilation, cold caches) orders of magnitude above steady state,
    which would mask any genuine upward drift behind a huge falling
    first step."""
    events, _ = _load_events(source)
    reports = _window_reports(events, windows)
    # windows with no cycles at all (a paused soak) would poison every
    # series with zeros; keep only windows that saw work
    reports = [r for r in reports if r["cycles"] or r["stages"]]
    dropped = min(max(warmup, 0), max(len(reports) - 3, 0))
    reports = reports[dropped:]
    if len(reports) < 3:
        raise TrendError(
            f"{source}: fewer than 3 non-empty windows — soak too short "
            "for a trend"
        )
    out = trend_over_reports(
        reports,
        threshold_pct=threshold_pct,
        min_ms=min_ms,
        monotone_frac=monotone_frac,
    )
    out["source"] = source
    out["windows"] = windows
    out["warmup_windows_dropped"] = dropped
    return out


def journal_trend(
    path: str,
    *,
    windows: int = 6,
    threshold_pct: float = 25.0,
    min_ms: float = 0.05,
    monotone_frac: float = 0.6,
) -> dict:
    """Leak signals straight from the journal's per-cycle metrics,
    windowed by record order:

    - delta_hit_ratio (DOWN gate): delta/(delta+full) uploads decaying
      means resident-state churn is accumulating.
    - cycle_p99_ms (UP gate): end-to-end latency creep.
    - queue_depth_mean (UP gate): pods_in per cycle ratcheting — drain
      never catching arrivals.
    - state_bytes_mean (UP gate): mean snapshot/delta tensor payload
      growing — the resident-state memory-leak proxy.
    """
    from kubernetes_scheduler_tpu.trace.recorder import read_journal

    recs = [r for r in read_journal(path) if r.get("metrics")]
    if len(recs) < windows * 2:
        raise TrendError(
            f"{path}: {len(recs)} records for {windows} windows — journal "
            "too short for a trend"
        )
    slices = np.array_split(np.arange(len(recs)), windows)
    delta_hit, p99, depth, nbytes = [], [], [], []
    for sl in slices:
        ms, du, fu, pods, sizes = [], 0, 0, [], []
        for i in sl:
            m = recs[i].get("metrics") or {}
            ms.append(float(m.get("cycle_seconds", 0.0)) * 1e3)
            du += int(m.get("delta_uploads", 0))
            fu += int(m.get("full_uploads", 0))
            pods.append(float(m.get("pods_in", 0)))
            for key in ("snapshot", "delta"):
                t = recs[i].get(key)
                if t:
                    sizes.append(
                        float(sum(np.asarray(a).nbytes for a in t.values()))
                    )
        if du + fu:
            delta_hit.append(du / (du + fu))
        p99.append(float(np.percentile(ms, 99)) if ms else 0.0)
        depth.append(float(np.mean(pods)) if pods else 0.0)
        nbytes.append(float(np.mean(sizes)) if sizes else 0.0)
    rows = [
        gate_series(
            "cycle_p99_ms", p99, min_abs=min_ms,
            threshold_pct=threshold_pct, monotone_frac=monotone_frac,
        ),
        gate_series(
            "queue_depth_mean", depth, min_abs=1.0,
            threshold_pct=threshold_pct, monotone_frac=monotone_frac,
        ),
        gate_series(
            "state_bytes_mean", nbytes, min_abs=1024.0,
            threshold_pct=threshold_pct, monotone_frac=monotone_frac,
        ),
    ]
    if len(delta_hit) >= 3:
        rows.append(
            gate_series(
                "delta_hit_ratio", delta_hit, direction="down",
                min_abs=0.05, threshold_pct=threshold_pct,
                monotone_frac=monotone_frac,
            )
        )
    regressions = [r["series"] for r in rows if r["regression"]]
    return {
        "source": path,
        "windows": windows,
        "records": len(recs),
        "rows": rows,
        "regressions": regressions,
        "clean": not regressions,
    }


def perturb_trend(
    src: str, dst: str, *, stage: str = "engine_step", factor: float = 3.0
) -> int:
    """Copy span directory `src` to `dst` with `stage` durations grown
    by a LINEAR DRIFT from +0 (earliest event) to +(factor-1)x the
    stage's median duration (latest) — the seeded-leak harness for the
    trend gate, the way perturb_spans seeds the pairwise diff gate.
    The drift is additive and linear in POPULATION RANK (the event's
    position in the ts-sorted stage stream), not multiplicative or
    wall-clock: the gate windows by equal population, a wall-clock ramp
    collapses to a flat step when one JIT compile eats most of the
    run's duration, and multiplying a noisy baseline (a mid-run
    recompile hump) yields a non-monotone product the gate rightly
    rejects. Owning cycle spans stretch by the added time so the
    directory stays self-consistent. Returns events perturbed."""
    from kubernetes_scheduler_tpu.trace.spans import (
        read_span_file,
        span_files,
    )

    files = span_files(src)
    if not files:
        raise AnalyzeError(f"{src}: no span files (spans-*.trace.json)")
    per_file = [read_span_file(fp) for fp in files]
    hits = sorted(
        (float(ev.get("ts", 0.0)), float(ev.get("dur", 0.0)))
        for events in per_file
        for ev in events
        if ev.get("ph") == "X" and ev.get("name") == stage
    )
    if not hits:
        raise AnalyzeError(f"{src}: no {stage!r} spans to perturb")
    rank = {ts: j for j, (ts, _) in enumerate(hits)}
    denom = max(len(hits) - 1, 1)
    base = sorted(d for _, d in hits)[len(hits) // 2]
    os.makedirs(dst, exist_ok=True)
    touched = 0
    for i, events in enumerate(per_file):
        added: dict = {}
        for ev in events:
            if ev.get("ph") != "X" or ev.get("name") != stage:
                continue
            frac = rank[float(ev.get("ts", 0.0))] / denom
            extra = base * (factor - 1.0) * frac
            ev["dur"] = float(ev.get("dur", 0.0)) + extra
            tid = (ev.get("args") or {}).get("trace_id")
            if tid is not None:
                added[tid] = added.get(tid, 0.0) + extra
            touched += 1
        for ev in events:
            if ev.get("ph") != "X" or ev.get("name") != "cycle":
                continue
            tid = (ev.get("args") or {}).get("trace_id")
            if tid in added:
                ev["dur"] = float(ev.get("dur", 0.0)) + added[tid]
        out = os.path.join(dst, "spans-%08d.trace.json" % i)
        with open(out, "w", encoding="utf-8") as f:
            f.write("[\n")
            for ev in events:
                f.write(json.dumps(ev, separators=(",", ":")) + ",\n")
    return touched
