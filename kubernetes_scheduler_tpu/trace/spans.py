"""Span telemetry files: rotating Chrome-trace-event JSON + the merge.

Writers emit the Chrome trace "JSON Array Format": every file opens
with ``[`` and holds one complete ("ph": "X") event object per line,
comma-terminated. The closing ``]`` is deliberately absent — the format
specifies it as optional precisely so a crashed writer's file stays
loadable — which gives span files the same crash-consistency contract
as the flight-recorder journal (trace/recorder.py): a torn tail costs
at most the last line, and every file loads independently in Perfetto
(ui.perfetto.dev) or chrome://tracing.

Rotation rides the same machinery as the journal: numbered files under
one directory, a per-file size bound, and a whole-directory disk budget
enforced by `recorder.enforce_disk_budget` (oldest files dropped).

Host and sidecar each write their own span directory; `merge_spans`
joins them on the `args.trace_id` every event carries (the host's
monotonically-assigned cycle id, propagated to the sidecar over gRPC
metadata) into one timeline. Timestamps are epoch microseconds on both
sides, so same-machine processes need no clock alignment and
cross-machine skew shows up honestly instead of being hidden.
"""

from __future__ import annotations

import json
import logging
import os
import threading

from kubernetes_scheduler_tpu.trace.recorder import enforce_disk_budget

log = logging.getLogger("yoda_tpu.trace.spans")

_FILE_PATTERN = "spans-%08d.trace.json"


def span_files(path: str) -> list[str]:
    """The span directory's data files, oldest first."""
    if not os.path.isdir(path):
        return []
    return [
        os.path.join(path, n)
        for n in sorted(os.listdir(path))
        if n.startswith("spans-") and n.endswith(".trace.json")
    ]


class SpanWriter:
    """Rotating, disk-budgeted Chrome-trace-event file writer.

    `append` takes fully-formed event dicts; encoding cost is paid by
    the caller's completion stage, never a dispatch path. Each fresh
    file opens with a process_name metadata event so a merged timeline
    labels the host and sidecar tracks."""

    def __init__(
        self,
        path: str,
        *,
        file_bytes: int = 32 << 20,
        max_bytes: int = 128 << 20,
        process_name: str = "host",
    ):
        self.path = path
        self.file_bytes = int(file_bytes)
        self.max_bytes = int(max_bytes)
        self.process_name = process_name
        self.pid = os.getpid()
        os.makedirs(path, exist_ok=True)
        existing = span_files(path)
        self._next_index = len(existing) and (
            int(os.path.basename(existing[-1])[6:14]) + 1
        )
        self._f = None
        self._file_size = 0
        # the sidecar serves more than one worker thread; appends must
        # never interleave two events on one line
        self._lock = threading.Lock()
        self.events_written = 0
        self.bytes_written = 0
        # EAGER first file: a configured span directory always holds at
        # least the process_name metadata track, so "files exist but no
        # events joined" is distinguishable from "spans were never
        # configured" — the signal `spans merge` uses to flag broken
        # trace-id propagation instead of silently tolerating it
        self._open_next()

    def _open_next(self) -> None:
        if self._f is not None:
            self._f.close()
        fp = os.path.join(self.path, _FILE_PATTERN % self._next_index)
        self._next_index += 1
        # graftlint: disable=lock-discipline -- called only from append, which holds self._lock
        self._f = open(fp, "w", encoding="utf-8")
        meta = json.dumps(
            {
                "ph": "M",
                "name": "process_name",
                "pid": self.pid,
                "tid": 0,
                "args": {"name": self.process_name},
            },
            separators=(",", ":"),
        )
        head = "[\n" + meta + ",\n"
        self._f.write(head)
        # graftlint: disable=lock-discipline -- called only from append, which holds self._lock
        self._file_size = len(head)
        enforce_disk_budget(
            span_files(self.path), self.max_bytes, keep=self._f.name
        )

    def append(self, events: list[dict]) -> None:
        """Append events (one JSON object per line). Rotates when the
        current file would exceed file_bytes."""
        if not events:
            return
        lines = [
            json.dumps(ev, separators=(",", ":")) + ",\n" for ev in events
        ]
        blob = "".join(lines)
        with self._lock:
            if self._f is None or self._file_size + len(blob) > self.file_bytes:
                self._open_next()
            self._f.write(blob)
            self._f.flush()
            self._file_size += len(blob)
            self.bytes_written += len(blob)
            self.events_written += len(events)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


def read_span_file(fp: str) -> list[dict]:
    """Decode one span file, tolerant of a torn tail: unparseable lines
    end the file at the last good event (the crash contract)."""
    out: list[dict] = []
    with open(fp, encoding="utf-8") as f:
        first = f.readline()
        if not first.startswith("["):
            log.warning("spans: %s is not a span file; skipping", fp)
            return out
        for line in f:
            line = line.strip().rstrip(",").rstrip("]").strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                log.warning(
                    "spans: %s torn line; recovered to last good event", fp
                )
                break
    return out


def read_spans(path: str) -> list[dict]:
    """Every event across the directory's span files, oldest first."""
    out: list[dict] = []
    for fp in span_files(path):
        out.extend(read_span_file(fp))
    return out


def _trace_ids(events: list[dict]) -> set:
    return {
        ev["args"]["trace_id"]
        for ev in events
        if ev.get("ph") == "X" and "trace_id" in ev.get("args", {})
    }


def merge_spans(host_path: str, sidecar_path: str, out_path: str) -> dict:
    """Join host and sidecar span files on trace id into ONE Chrome
    trace (JSON Object Format — a plain `{"traceEvents": [...]}` that
    Perfetto loads directly). Every event rides through; the report
    counts the trace ids seen on each side and the ids present on BOTH
    (the join — zero joined ids on non-empty inputs means the metadata
    propagation is broken, and callers should fail loudly)."""
    host_files = len(span_files(host_path))
    sidecar_files = len(span_files(sidecar_path))
    host_events = read_spans(host_path)
    sidecar_events = read_spans(sidecar_path)
    host_ids = _trace_ids(host_events)
    sidecar_ids = _trace_ids(sidecar_events)
    joined = host_ids & sidecar_ids
    merged = host_events + sidecar_events
    merged.sort(key=lambda ev: ev.get("ts", 0))
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(
            {
                "traceEvents": merged,
                "otherData": {
                    "joined_trace_ids": len(joined),
                    "host_trace_ids": len(host_ids),
                    "sidecar_trace_ids": len(sidecar_ids),
                },
            },
            f,
        )
    return {
        "host_events": len(host_events),
        "sidecar_events": len(sidecar_events),
        "host_files": host_files,
        "sidecar_files": sidecar_files,
        "host_trace_ids": len(host_ids),
        "sidecar_trace_ids": len(sidecar_ids),
        "joined_trace_ids": len(joined),
        "merged_events": len(merged),
        "out": out_path,
    }
