"""Cycle flight recorder: append-only journal of scheduling cycles.

Journal layout (trace/schema.py is the field contract):

    file     := MAGIC "YTRJ" + u16 schema version + record*
    record   := u32 payload_len + u32 crc32(payload) + payload
    payload  := field*
    field    := u16 tag + u8 kind + value

Every record is length-prefixed and CRC-guarded, so a crash mid-write
(power cut, SIGKILL, full disk) costs at most the tail record: readers
stop a file at the first short or CRC-failing frame and keep everything
before it — the flight-recorder property. Journals rotate across
numbered files under one directory with a bounded total disk budget
(oldest files dropped); each file opens with a FULL snapshot record, so
a journal whose head was rotated away still replays.

The recorder sits OFF the device-dispatch critical path: the scheduler
appends from the cycle's completion stage (host/scheduler._finish_cycle),
after the engine result was forced and the binds applied, and the write
itself is a buffered memcpy — no device sync, no RPC, no lock shared
with the dispatch path.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import time
import zlib

import numpy as np

from kubernetes_scheduler_tpu.trace.schema import (
    FIELD_BY_NAME,
    FIELD_BY_TAG,
    KIND_F64,
    KIND_JSON,
    KIND_STR,
    KIND_TENSORS,
    KIND_U64,
    KINDS,
    MAGIC,
    SCHEMA_VERSION,
    TENSOR_DTYPES,
)

log = logging.getLogger("yoda_tpu.trace")

_HEADER = struct.Struct("<4sH")     # magic + version
_FRAME = struct.Struct("<II")       # payload_len + crc32
_FIELD = struct.Struct("<HB")       # tag + kind
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_F64 = struct.Struct("<d")
_FILE_PATTERN = "journal-%08d.ytrj"


class TraceError(RuntimeError):
    """Malformed journal content (beyond a recoverable truncated tail)."""


class TraceVersionError(TraceError):
    """The journal speaks a schema version this reader does not."""


# ---- record encoding -------------------------------------------------------


# dtype object -> canonical name (dtype.name walks numpy internals —
# ~0.4 ms/record over ~60 leaves without this)
_DTYPE_NAMES: dict = {}


def _put_tensor(out: list, field_name: str, name: str, arr) -> None:
    a = np.asarray(arr)
    want = TENSOR_DTYPES.get(f"{field_name}.{name}")
    if want is None:
        raise TraceError(
            f"tensor {field_name}.{name} has no pinned dtype in "
            "trace/schema.py — journals cannot carry unclassified leaves"
        )
    have = _DTYPE_NAMES.get(a.dtype)
    if have is None:
        have = "bool" if a.dtype == np.bool_ else a.dtype.name
        _DTYPE_NAMES[a.dtype] = have
    if have != want:
        raise TraceError(
            f"tensor {field_name}.{name} is {have}, schema pins {want} "
            "(never silently cast: replay parity is bitwise)"
        )
    a = np.ascontiguousarray(a)
    nb = name.encode()
    db = want.encode()
    out.append(
        struct.pack(
            f"<H{len(nb)}sB{len(db)}sB", len(nb), nb, len(db), db, a.ndim
        )
    )
    for d in a.shape:
        out.append(_U32.pack(d))
    out.append(_U32.pack(a.nbytes))
    # zero-copy view — the one copy happens in the payload join (the
    # builders' arrays are not mutated between dispatch and record)
    out.append(a.data.cast("B"))


def encode_record(rec: dict, extra: list | None = None) -> bytes:
    """dict (schema field name -> value) -> one framed record payload.
    Unknown names fail loudly — the schema table is the contract.
    `extra` carries pre-encoded field blobs (the recorder's cached
    node_names field) appended verbatim; field order is immaterial to
    the decoder."""
    out: list[bytes] = list(extra or ())
    for name, value in rec.items():
        f = FIELD_BY_NAME.get(name)
        if f is None:
            raise TraceError(f"unknown journal field {name!r}")
        kind = KINDS[f.kind]
        out.append(_FIELD.pack(f.tag, kind))
        if kind == KIND_U64:
            out.append(_U64.pack(int(value)))
        elif kind == KIND_F64:
            out.append(_F64.pack(float(value)))
        elif kind == KIND_STR:
            b = str(value).encode()
            out.append(_U32.pack(len(b)))
            out.append(b)
        elif kind == KIND_JSON:
            b = json.dumps(value, separators=(",", ":")).encode()
            out.append(_U32.pack(len(b)))
            out.append(b)
        else:  # KIND_TENSORS
            items = value.items() if isinstance(value, dict) else value
            items = list(items)
            out.append(struct.pack("<H", len(items)))
            for tname, arr in items:
                _put_tensor(out, name, tname, arr)
    return b"".join(out)


def decode_record(payload: bytes) -> dict:
    """Inverse of encode_record; unknown tags are skipped (forward
    compatibility — new fields under fresh tags must not break old
    readers), malformed framing raises TraceError."""
    rec: dict = {}
    view = memoryview(payload)
    pos = 0
    end = len(payload)

    def need(n: int):
        nonlocal pos
        if pos + n > end:
            raise TraceError("record payload truncated mid-field")
        chunk = view[pos : pos + n]
        pos += n
        return chunk

    while pos < end:
        tag, kind = _FIELD.unpack(need(_FIELD.size))
        if kind == KIND_U64:
            value = _U64.unpack(need(8))[0]
        elif kind == KIND_F64:
            value = _F64.unpack(need(8))[0]
        elif kind in (KIND_STR, KIND_JSON):
            (ln,) = _U32.unpack(need(4))
            raw = bytes(need(ln))
            value = raw.decode() if kind == KIND_STR else json.loads(raw)
        elif kind == KIND_TENSORS:
            (count,) = struct.unpack("<H", need(2))
            tensors = {}
            for _ in range(count):
                (nlen,) = struct.unpack("<H", need(2))
                tname = bytes(need(nlen)).decode()
                (dlen,) = struct.unpack("<B", need(1))
                dtype = bytes(need(dlen)).decode()
                (ndim,) = struct.unpack("<B", need(1))
                shape = tuple(
                    _U32.unpack(need(4))[0] for _ in range(ndim)
                )
                (nbytes,) = _U32.unpack(need(4))
                raw = need(nbytes)
                np_dtype = np.bool_ if dtype == "bool" else np.dtype(dtype)
                arr = np.frombuffer(raw, np_dtype)
                expect = 1
                for d in shape:
                    expect *= d
                if arr.size != expect:
                    raise TraceError(
                        f"tensor {tname}: {arr.size} elements for shape "
                        f"{shape}"
                    )
                tensors[tname] = arr.reshape(shape)
            value = tensors
        else:
            raise TraceError(f"unknown field kind {kind}")
        f = FIELD_BY_TAG.get(tag)
        if f is not None:
            rec[f.name] = value
    return rec


# ---- journal files ---------------------------------------------------------


def journal_files(path: str) -> list[str]:
    """The journal's data files under `path`, oldest first."""
    if not os.path.isdir(path):
        return []
    return [
        os.path.join(path, n)
        for n in sorted(os.listdir(path))
        if n.startswith("journal-") and n.endswith(".ytrj")
    ]


def enforce_disk_budget(
    files: list[str], max_bytes: int, *, keep: str | None = None
) -> None:
    """Drop the OLDEST of `files` (given oldest first) until the total
    size fits `max_bytes`; `keep` (the file being written) is never
    dropped. Shared by the journal writer and the span writer
    (trace/spans.py) — one disk-budget policy for every telemetry
    artifact the scheduler rotates."""
    total = 0
    sizes = {}
    for fp in files:
        try:
            sizes[fp] = os.path.getsize(fp)
        except OSError:
            sizes[fp] = 0
        total += sizes[fp]
    for fp in files:
        if total <= max_bytes or fp == keep:
            break
        total -= sizes[fp]
        try:
            os.remove(fp)
            log.info("trace: dropped %s (disk budget)", fp)
        except OSError:
            log.warning("trace: could not drop %s", fp, exc_info=True)


def read_journal_file(fp: str, *, strict_version: bool = True):
    """Yield decoded records from ONE journal file, with truncated-tail
    recovery: a short final frame, a failing CRC, or a payload cut
    mid-field ends the file at the last good record — the crash-
    consistency contract. A schema-version mismatch raises
    TraceVersionError (clear error, never a guessed parse)."""
    with open(fp, "rb") as f:
        head = f.read(_HEADER.size)
        if len(head) < _HEADER.size:
            log.warning("trace: %s too short for a header; skipping", fp)
            return
        magic, version = _HEADER.unpack(head)
        if magic != MAGIC:
            raise TraceError(f"{fp}: not a journal file (bad magic)")
        if version != SCHEMA_VERSION:
            if strict_version:
                raise TraceVersionError(
                    f"{fp}: journal schema version {version}, this "
                    f"reader speaks {SCHEMA_VERSION} — re-record or "
                    "replay with a matching build"
                )
            log.warning("trace: %s version %d skipped", fp, version)
            return
        while True:
            frame = f.read(_FRAME.size)
            if len(frame) < _FRAME.size:
                if frame:
                    log.warning(
                        "trace: %s truncated frame header; recovered "
                        "to last good record", fp,
                    )
                break
            ln, crc = _FRAME.unpack(frame)
            payload = f.read(ln)
            if len(payload) < ln:
                log.warning(
                    "trace: %s truncated record payload; recovered "
                    "to last good record", fp,
                )
                break
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                log.warning(
                    "trace: %s CRC mismatch; recovered to last good "
                    "record", fp,
                )
                break
            try:
                yield decode_record(payload)
            except TraceError:
                log.warning(
                    "trace: %s undecodable record; recovered to last "
                    "good record", fp,
                )
                break


def read_journal(path: str, *, strict_version: bool = True):
    """Yield decoded records across every journal file, oldest first."""
    for fp in journal_files(path):
        yield from read_journal_file(fp, strict_version=strict_version)


class JournalTailer:
    """Incremental reader over a journal that is STILL BEING WRITTEN.

    read_journal assumes a closed file set: it treats the first short or
    CRC-failing frame as end-of-file, which is exactly right post-mortem
    and exactly wrong mid-flight — a tail that is short because the
    writer's buffered append has not landed yet must be re-polled, not
    abandoned. The tailer keeps, per file, the byte offset after the
    last GOOD frame and distinguishes the three tail states a live
    journal can be in:

    - short/garbled tail on the NEWEST file: bytes may still be
      arriving (or the writer truncated a torn frame and will overwrite
      them) — hold position and retry next poll. A tail that was short
      and then decoded counts a truncated-tail-then-grew recovery.
    - short/garbled tail on a file with a SUCCESSOR: the writer only
      appends to the newest file, so that tail is final torn garbage
      (the ENOSPC poison path) — skip it and follow the rotation.
    - rotation: the next numbered file opens with a full snapshot
      record (CycleRecorder re-anchors the delta chain on every
      rotation), so following a boundary never strands the consumer's
      reconstruction.

    `resume_seq` filters records at or below an already-applied seq —
    the restart contract for a shadow consumer: re-open the tailer at
    its last applied seq and the delta chain re-anchors at the next
    full-snapshot record. The tailer never writes; it shares nothing
    with the writer but the directory."""

    def __init__(
        self,
        path: str,
        *,
        resume_seq: int | None = None,
        strict_version: bool = True,
    ):
        self.path = path
        self.strict_version = strict_version
        self.last_seq = None if resume_seq is None else int(resume_seq)
        self._file: str | None = None
        self._offset = 0            # byte offset after the last good frame
        self._short_tail = False    # last poll stopped mid-frame at _offset
        self._skip_file = False     # version-skipped file (non-strict mode)
        self.rotations_followed = 0
        self.truncations_recovered = 0
        self.dead_tails_skipped = 0
        self.records_yielded = 0
        self.records_filtered = 0   # skipped by the resume_seq watermark

    def stats(self) -> dict:
        return {
            "file": self._file,
            "offset": self._offset,
            "last_seq": self.last_seq,
            "records_yielded": self.records_yielded,
            "records_filtered": self.records_filtered,
            "rotations_followed": self.rotations_followed,
            "truncations_recovered": self.truncations_recovered,
            "dead_tails_skipped": self.dead_tails_skipped,
        }

    def poll(self, *, max_records: int | None = None) -> list[dict]:
        """Decode every record that became readable since the last poll
        (bounded by `max_records`); empty when the writer has not
        progressed. Never blocks, never raises on a recoverable tail —
        only on bad magic or (strict) schema-version mismatch."""
        out: list[dict] = []
        while True:
            files = journal_files(self.path)
            if not files:
                return out
            if self._file is None:
                self._enter(files[0], first=True)
            elif self._file not in files:
                # the file we were reading was dropped by the disk
                # budget — resume at the oldest survivor newer than it
                base = os.path.basename(self._file)
                newer = [
                    f for f in files if os.path.basename(f) > base
                ]
                if not newer:
                    return out
                self._enter(newer[0])
            self._drain(out, max_records)
            if max_records is not None and len(out) >= max_records:
                return out
            # current file exhausted: follow the rotation only when a
            # successor exists — the writer appends solely to the
            # newest file, so an older file's tail is final
            files = journal_files(self.path)
            try:
                i = files.index(self._file)
            except ValueError:
                continue  # dropped between listings; re-resolve
            if i + 1 >= len(files):
                return out
            if self._short_tail:
                log.warning(
                    "trace: %s rotated away with a torn tail; skipping "
                    "to %s", self._file, files[i + 1],
                )
                self.dead_tails_skipped += 1
            self._enter(files[i + 1])

    def _enter(self, fp: str, *, first: bool = False) -> None:
        self._file = fp
        self._offset = 0
        self._short_tail = False
        self._skip_file = False
        if not first:
            self.rotations_followed += 1

    def _drain(self, out: list, max_records: int | None) -> None:
        """Decode frames from the current file starting at _offset."""
        if self._skip_file:
            return
        try:
            f = open(self._file, "rb")
        except OSError:
            return
        with f:
            if self._offset == 0:
                head = f.read(_HEADER.size)
                if len(head) < _HEADER.size:
                    return  # header still being written; retry next poll
                magic, version = _HEADER.unpack(head)
                if magic != MAGIC:
                    raise TraceError(
                        f"{self._file}: not a journal file (bad magic)"
                    )
                if version != SCHEMA_VERSION:
                    if self.strict_version:
                        raise TraceVersionError(
                            f"{self._file}: journal schema version "
                            f"{version}, this reader speaks "
                            f"{SCHEMA_VERSION}"
                        )
                    log.warning(
                        "trace: %s version %d skipped", self._file, version
                    )
                    self._skip_file = True
                    return
                self._offset = _HEADER.size
            else:
                f.seek(self._offset)
            while max_records is None or len(out) < max_records:
                frame = f.read(_FRAME.size)
                if len(frame) < _FRAME.size:
                    self._short_tail = self._short_tail or bool(frame)
                    return
                ln, crc = _FRAME.unpack(frame)
                payload = f.read(ln)
                if len(payload) < ln:
                    self._short_tail = True
                    return
                if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                    # a torn frame the writer may truncate and overwrite
                    # — hold position; rotation supersedes if it never
                    # heals
                    self._short_tail = True
                    return
                try:
                    rec = decode_record(payload)
                except TraceError:
                    self._short_tail = True
                    return
                if self._short_tail:
                    # the bytes we previously stopped on completed
                    self.truncations_recovered += 1
                    self._short_tail = False
                self._offset += _FRAME.size + ln
                seq = rec.get("seq")
                if (
                    seq is not None
                    and self.last_seq is not None
                    and int(seq) <= self.last_seq
                ):
                    self.records_filtered += 1
                    continue
                if seq is not None:
                    self.last_seq = int(seq)
                self.records_yielded += 1
                out.append(rec)


def last_journal_seq(path: str) -> int | None:
    """The highest `seq` in the journal, or None when empty — scanned
    newest file backwards so a restarting recorder's startup cost is
    one file, not the whole journal."""
    for fp in reversed(journal_files(path)):
        last = None
        try:
            for rec in read_journal_file(fp):
                if "seq" in rec:
                    last = int(rec["seq"])
        except TraceError:
            continue
        if last is not None:
            return last
    return None


class JournalWriter:
    """Rotating, disk-budgeted journal writer.

    `file_bytes` bounds one file; `max_bytes` bounds the whole journal
    directory — exceeding it drops the OLDEST file(s). rotated() flips
    True whenever a rotation (or drop) happened since the last
    full-snapshot record, so the recorder can re-anchor the delta chain:
    every file must open with a full snapshot or it cannot replay after
    its predecessors are gone."""

    def __init__(
        self,
        path: str,
        *,
        file_bytes: int = 32 << 20,
        max_bytes: int = 256 << 20,
    ):
        self.path = path
        self.file_bytes = int(file_bytes)
        self.max_bytes = int(max_bytes)
        os.makedirs(path, exist_ok=True)
        existing = journal_files(path)
        self._next_index = len(existing) and (
            int(os.path.basename(existing[-1])[8:16]) + 1
        )
        self._f = None
        self._file_size = 0
        # a failed write may have left a torn frame we could not
        # truncate away: the file is poisoned (readers would stop at the
        # torn frame and lose everything after it), so the next append
        # must rotate to a fresh file
        self._torn = False
        self.bytes_written = 0
        self.records_written = 0

    def _open_next(self) -> None:
        if self._f is not None:
            self._f.close()
        fp = os.path.join(self.path, _FILE_PATTERN % self._next_index)
        self._next_index += 1
        self._f = open(fp, "wb")
        self._f.write(_HEADER.pack(MAGIC, SCHEMA_VERSION))
        self._file_size = _HEADER.size
        self._torn = False
        self._enforce_budget()

    def _enforce_budget(self) -> None:
        # never drop the file being written
        enforce_disk_budget(
            journal_files(self.path),
            self.max_bytes,
            keep=self._f.name if self._f is not None else None,
        )

    def needs_rotation(self, payload_len: int) -> bool:
        return (
            self._f is None
            or self._file_size + _FRAME.size + payload_len > self.file_bytes
        )

    def append(self, payload: bytes, *, rotate: bool = False) -> None:
        if rotate or self._f is None or self._torn:
            self._open_next()
        frame = _FRAME.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF)
        pos = self._file_size
        try:
            self._f.write(frame)
            self._f.write(payload)
            self._f.flush()
        except OSError:
            # a partial frame may be on disk (ENOSPC mid-payload):
            # readers stop a file at the first bad frame, so good
            # records appended after it would be unreachable. Truncate
            # the torn bytes away; if even that fails, poison the file
            # so the next append rotates instead of appending past them.
            try:
                self._f.seek(pos)
                self._f.truncate()
            except OSError:
                self._torn = True
            raise
        self._file_size += len(frame) + len(payload)
        self.bytes_written += len(frame) + len(payload)
        self.records_written += 1

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


# ---- the scheduler-facing recorder ----------------------------------------


class CycleRecorder:
    """One length-prefixed, CRC-guarded record per scheduling cycle.

    Owns the full-vs-delta choice: a cycle that shipped a SnapshotDelta
    is recorded as that delta ONLY while the chain is anchored — the
    previous device-path record lives in the same file (every file opens
    with a full snapshot so rotation never strands a delta against a
    dropped predecessor). The full host build is always available at
    record time, so re-anchoring costs bytes, never information."""

    def __init__(
        self,
        path: str,
        *,
        file_bytes: int = 32 << 20,
        max_bytes: int = 256 << 20,
    ):
        from kubernetes_scheduler_tpu.trace.schema import check_engine_coverage

        # fail loudly HERE (the write path) on engine-struct drift; the
        # read-only journal tooling stays engine/jax-free
        check_engine_coverage()
        self._writer = JournalWriter(
            path, file_bytes=file_bytes, max_bytes=max_bytes
        )
        self.path = path
        self.cycles_recorded = 0
        self.records_dropped = 0
        # cumulative encode+write wall time: the recorder's cost is kept
        # OUT of CycleMetrics.cycle_seconds (it runs after the cycle's
        # bookkeeping), so this is the number the <5%-overhead bench
        # gate reads directly
        self.seconds_spent = 0.0
        # seq RESUMES across restarts into the same directory (the way
        # JournalWriter resumes file numbering): a seq reset to 0 would
        # break `trace diff`'s merge-by-seq pairing on any journal that
        # spans a scheduler restart
        last = last_journal_seq(path)
        self._seq = 0 if last is None else last + 1
        # is there a reconstructible device-path snapshot earlier in the
        # CURRENT file for a delta record to chain from?
        self._chain_anchored = False
        # IDENTITY of the last device record's full snapshot: a delta is
        # recorded only when its base IS that object — a non-resident
        # dispatch in between (ephemeral build, engine fallback) moves
        # the reader's reconstruction off the delta's base, and applying
        # the delta there would reconstruct garbage silently
        self._last_snapshot_obj = None

    @property
    def bytes_written(self) -> int:
        return self._writer.bytes_written

    def record_cycle(
        self,
        *,
        path: str,
        metrics,
        node_names: list[str] | None = None,
        pod_keys: list | None = None,
        bindings: list | None = None,
        snapshot=None,
        delta=None,
        delta_base=None,
        pods=None,
        engine_kw: dict | None = None,
        node_idx=None,
        resident_epoch: int = 0,
        delta_sent: bool = False,
        batch_window: int = 0,
        fingerprint: dict | None = None,
        seq: int | None = None,
    ) -> None:
        """Append one cycle. Never raises into the scheduling loop — an
        encode/IO failure logs, counts a drop, and de-anchors the chain
        (the next delta record re-anchors with a full snapshot).

        `seq` overrides the recorder's own counter — the replayer
        re-records each cycle under its SOURCE record's seq, so a
        replay of a head-pruned journal still pairs with the original
        in `trace diff`'s merge-by-seq."""
        t0 = time.perf_counter()
        try:
            self._record(
                path=path, metrics=metrics, node_names=node_names,
                pod_keys=pod_keys, bindings=bindings, snapshot=snapshot,
                delta=delta, delta_base=delta_base, pods=pods,
                engine_kw=engine_kw,
                node_idx=node_idx, resident_epoch=resident_epoch,
                delta_sent=delta_sent, batch_window=batch_window,
                fingerprint=fingerprint, seq=seq,
            )
        except Exception:
            log.exception("trace: cycle record failed; dropping record")
            self.records_dropped += 1
            self._chain_anchored = False
            self._last_snapshot_obj = None
        finally:
            self.seconds_spent += time.perf_counter() - t0

    def _record(
        self, *, path, metrics, node_names, pod_keys, bindings, snapshot,
        delta, delta_base, pods, engine_kw, node_idx, resident_epoch,
        delta_sent, batch_window, fingerprint, seq=None,
    ) -> None:
        import dataclasses

        if seq is not None:
            self._seq = int(seq)
        rec: dict = {
            "seq": self._seq,
            "path": path,
            "wall_time": time.time(),
            "metrics": (
                dataclasses.asdict(metrics)
                if dataclasses.is_dataclass(metrics)
                else dict(metrics or {})
            ),
        }
        if fingerprint is not None:
            rec["fingerprint"] = fingerprint
        extra = []
        if node_names is not None:
            # the node-name list is identical cycle after cycle on a
            # quiet cluster; re-encoding 4k names cost ~1 ms/record, the
            # equality probe costs ~0.1 ms
            extra.append(self._names_field(node_names))
        if pod_keys is not None:
            rec["pod_keys"] = [list(k) for k in pod_keys]
        if bindings is not None:
            rec["bindings"] = [list(b) for b in bindings]
        if engine_kw is not None:
            rec["engine_kw"] = _jsonable_kw(engine_kw)
        rec["resident_epoch"] = int(resident_epoch)
        rec["delta_sent"] = int(bool(delta_sent))
        if batch_window:
            rec["batch_window"] = int(batch_window)
        device_record = pods is not None and (
            snapshot is not None or delta is not None
        )
        use_delta = (
            delta is not None
            and device_record
            and self._chain_anchored
            # the chain rule: the reader reconstructs by folding this
            # delta into the PREVIOUS device record's snapshot, so the
            # delta's base must BE that snapshot (object identity — a
            # non-resident dispatch in between breaks it)
            and delta_base is not None
            and delta_base is self._last_snapshot_obj
        )
        if device_record:
            if use_delta:
                rec["delta"] = _tensor_items(delta)
            else:
                if snapshot is None:
                    raise TraceError(
                        "delta record with no anchor and no full snapshot"
                    )
                rec["snapshot"] = _tensor_items(snapshot)
            rec["pods"] = _tensor_items(pods)
        if node_idx is not None:
            rec["assign"] = {
                "node_idx": np.asarray(node_idx, np.int32).reshape(-1)
            }
        payload = encode_record(rec, extra)
        rotate = self._writer.needs_rotation(len(payload))
        if rotate and use_delta:
            # a fresh file must open with a full snapshot: re-encode this
            # record as the full build (always in hand at record time)
            if snapshot is None:
                raise TraceError("rotation needs a full snapshot to anchor")
            del rec["delta"]
            rec["snapshot"] = _tensor_items(snapshot)
            use_delta = False
            payload = encode_record(rec, extra)
        self._writer.append(payload, rotate=rotate)
        if rotate:
            self._chain_anchored = False
        if device_record:
            # a delta record extends the chain; a full record anchors it
            self._chain_anchored = True
            self._last_snapshot_obj = snapshot
        self._seq += 1
        self.cycles_recorded += 1

    def _names_field(self, node_names) -> bytes:
        """The node_names field pre-encoded, cached by list equality."""
        names = list(node_names)
        c = self.__dict__.get("_names_cache")
        if c is not None and c[0] == names:
            return c[1]
        f = FIELD_BY_NAME["node_names"]
        b = json.dumps(names, separators=(",", ":")).encode()
        blob = _FIELD.pack(f.tag, KIND_JSON) + _U32.pack(len(b)) + b
        self.__dict__["_names_cache"] = (names, blob)
        return blob

    def stats(self) -> dict:
        return {
            "cycles_recorded": self.cycles_recorded,
            "trace_bytes": self.bytes_written,
            "records_dropped": self.records_dropped,
        }

    def close(self) -> None:
        self._writer.close()


def _tensor_items(nt) -> list:
    """(name, host ndarray) pairs of a NamedTuple of arrays. Leaves must
    already be host numpy (the builders' output); a device array here
    would force a sync on the record path, so convert explicitly."""
    return [(name, np.asarray(a)) for name, a in zip(type(nt)._fields, nt)]


def _jsonable_kw(kw: dict) -> dict:
    out = dict(kw)
    sp = out.get("score_plugins")
    if sp is not None:
        out["score_plugins"] = [[n, float(w)] for n, w in sp]
    return out
