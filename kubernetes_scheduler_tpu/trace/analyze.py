"""Span analytics: per-stage cycle budgets and a CI-able regression gate.

PR 5 made the scheduler and sidecar EMIT spans (trace/spans.py); this
module CONSUMES them. `build_report` turns a span directory (or a merged
Chrome trace, or a saved report) into per-stage latency percentiles and
a per-cycle budget attribution table — which stage owns what fraction of
the median cycle, keyed by the driver path label every `cycle` span
carries and bracketed by the flight-recorder seq range, so a report row
points straight back at journal records and Perfetto bookmarks.
`diff_reports` compares two reports with per-stage relative thresholds
and an absolute-delta floor; the `spans diff` CLI exits non-zero on any
regression, which makes a span directory a perf gate: capture a
baseline, run the candidate, diff.

Everything here is engine/jax-free — safe to run against production
span files on a laptop, like trace/inspect.py for journals.

Attribution semantics: the host stages in ATTRIBUTION_STAGES nest
inside their cycle's `cycle` span and are mutually exclusive in time,
so their totals partition the cycle wall time and the residual
("other") is genuinely unattributed host work. `host_overlap` is
deliberately NOT in the table — it runs CONCURRENTLY with the in-flight
engine step (it is the pipelined driver's hidden work, not a cycle
cost), and counting it would double-book the overlap window. Sidecar
stages (deserialize/device_step/serialize/delta_apply) nest inside
`engine_step` on the other side of the bridge; they get percentiles but
never attribution rows, for the same no-double-counting reason.
"""

from __future__ import annotations

import json
import os

import numpy as np

from kubernetes_scheduler_tpu.trace.spans import (
    read_span_file,
    read_spans,
    span_files,
)

# host stages that partition the cycle span's wall time (the budget
# table); names are registry-pinned (observe.SHIPPED_SPANS + the
# graftlint span-hygiene family)
ATTRIBUTION_STAGES = (
    "queue_pop",
    "state_fetch",
    "snapshot_build",
    "delta_derive",
    "engine_step",
    "bind",
    "recorder_write",
    "scalar_cycle",
    "reconstruct",
)
# reported (percentiles) but never attributed: concurrent with the
# engine step, or nested inside it across the bridge
NON_ATTRIBUTED_STAGES = (
    "host_overlap",
    "deserialize",
    "delta_apply",
    "device_step",
    "serialize",
)


class AnalyzeError(RuntimeError):
    """Unusable span input (no files, no events, not span data)."""


def _load_events(path: str) -> tuple[list[dict], int]:
    """(complete events, file count) from a span DIRECTORY, a merged
    Chrome trace JSON (`spans merge --out`), or one span file."""
    if os.path.isdir(path):
        files = span_files(path)
        if not files:
            raise AnalyzeError(f"{path}: no span files (spans-*.trace.json)")
        return read_spans(path), len(files)
    if not os.path.exists(path):
        raise AnalyzeError(f"{path}: no such file or directory")
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except json.JSONDecodeError:
        # a bare span file: the writer's crash-tolerant JSON-array
        # format has no closing bracket, so json.load refuses it
        return read_span_file(path), 1
    if isinstance(data, dict) and "traceEvents" in data:
        return list(data["traceEvents"]), 1
    raise AnalyzeError(
        f"{path}: not span data (expected a span directory, a merged "
        "Chrome trace, or a spans-*.trace.json file)"
    )


def _pctl(vals: list[float], q: float) -> float:
    return round(float(np.percentile(vals, q)), 4)


def _dist(vals: list[float]) -> dict:
    return {
        "count": len(vals),
        "p50_ms": _pctl(vals, 50),
        "p95_ms": _pctl(vals, 95),
        "p99_ms": _pctl(vals, 99),
        "total_ms": round(float(np.sum(vals)), 4),
    }


def build_report(path: str) -> dict:
    """Aggregate a span source into the analytics report `spans report`
    prints and `spans diff` consumes. Raises AnalyzeError when there is
    nothing to report on (no files / no complete spans) — an empty
    report exiting 0 would let a silently-dead telemetry pipeline pass
    a perf gate."""
    events, n_files = _load_events(path)
    complete = [ev for ev in events if ev.get("ph") == "X"]
    if not complete:
        raise AnalyzeError(f"{path}: span files hold no complete spans")
    by_name: dict[str, list[float]] = {}
    cycles_by_path: dict[str, list[float]] = {}
    seqs: list[int] = []
    cycles_with_seq = 0
    for ev in complete:
        dur_ms = float(ev.get("dur", 0.0)) / 1e3
        name = ev.get("name", "?")
        by_name.setdefault(name, []).append(dur_ms)
        args = ev.get("args") or {}
        if name == "cycle":
            cycles_by_path.setdefault(
                str(args.get("path", "?")), []
            ).append(dur_ms)
            if "seq" in args:
                cycles_with_seq += 1
        if "seq" in args:
            seqs.append(int(args["seq"]))
    cycle_durs = by_name.get("cycle", [])
    report: dict = {
        "source": path,
        "files": n_files,
        "events": len(complete),
        "cycles": len(cycle_durs),
        "by_path": {
            p: _dist(v) for p, v in sorted(cycles_by_path.items())
        },
        "stages": {
            name: _dist(v)
            for name, v in sorted(by_name.items())
            if name != "cycle"
        },
    }
    if cycle_durs:
        report["cycle_ms"] = _dist(cycle_durs)
        # the budget table: each attributed stage's share of total cycle
        # wall time, residual as "other" — the row set sums to 100 by
        # construction, so a reader can trust the table is exhaustive
        cycle_total = float(np.sum(cycle_durs))
        attribution: dict[str, float] = {}
        accounted = 0.0
        for stage in ATTRIBUTION_STAGES:
            vals = by_name.get(stage)
            if not vals:
                continue
            pct = 100.0 * float(np.sum(vals)) / max(cycle_total, 1e-12)
            attribution[stage] = round(pct, 2)
            accounted += pct
        attribution["other"] = round(100.0 - accounted, 2)
        report["attribution_pct"] = attribution
    if seqs:
        report["seq"] = {
            "first": int(min(seqs)),
            "last": int(max(seqs)),
            "cycles_with_seq": cycles_with_seq,
        }
    return report


def load_report(path: str) -> dict:
    """A report for `spans diff`'s sides: a saved `spans report` JSON
    passes through; span directories / trace files build fresh."""
    if os.path.isfile(path):
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except json.JSONDecodeError:
            return build_report(path)
        if isinstance(data, dict) and "stages" in data and "cycles" in data:
            return data
    return build_report(path)


def diff_reports(
    base: dict,
    cand: dict,
    *,
    threshold_pct: float = 25.0,
    min_ms: float = 0.05,
    stage_thresholds: dict | None = None,
) -> dict:
    """Per-stage p50 regression check: candidate vs baseline. A stage
    regresses when its p50 grew by MORE than min_ms (absolute floor —
    sub-tick jitter on micro-stages must not fail builds) AND by more
    than its relative threshold (stage_thresholds[stage], default
    threshold_pct; the whole-cycle row uses the "cycle" key). `clean`
    is the gate: the CLI exits non-zero when it is False."""
    stage_thresholds = stage_thresholds or {}
    rows = []
    regressions = []

    def compare(stage: str, b_p50: float, c_p50: float) -> None:
        thr = float(stage_thresholds.get(stage, threshold_pct))
        delta = c_p50 - b_p50
        pct = 100.0 * delta / b_p50 if b_p50 > 0 else (
            float("inf") if delta > 0 else 0.0
        )
        bad = delta > min_ms and pct > thr
        rows.append(
            {
                "stage": stage,
                "base_p50_ms": b_p50,
                "cand_p50_ms": c_p50,
                "delta_ms": round(delta, 4),
                "delta_pct": round(pct, 2) if pct != float("inf") else None,
                "threshold_pct": thr,
                "regression": bad,
            }
        )
        if bad:
            regressions.append(stage)

    if base.get("cycle_ms") and cand.get("cycle_ms"):
        compare(
            "cycle", base["cycle_ms"]["p50_ms"], cand["cycle_ms"]["p50_ms"]
        )
    missing = []
    for stage, b in sorted(base.get("stages", {}).items()):
        c = cand.get("stages", {}).get(stage)
        if c is None or not c.get("count"):
            # absent stages are a CONTRACT question (span-hygiene lint,
            # SHIPPED_SPANS), not a latency regression — surfaced, never
            # silently ignored, but they do not fail the perf gate
            missing.append(stage)
            continue
        compare(stage, b["p50_ms"], c["p50_ms"])
    # stages only the CANDIDATE has (e.g. delta_derive appearing when
    # the resident variant is the candidate): no baseline to diff
    # against, but a new cost center must be visible in the report —
    # its weight shows in the candidate's attribution table
    new_stages = sorted(
        stage
        for stage, c in cand.get("stages", {}).items()
        if c.get("count") and stage not in base.get("stages", {})
    )
    return {
        "baseline": base.get("source"),
        "candidate": cand.get("source"),
        "baseline_cycles": base.get("cycles", 0),
        "candidate_cycles": cand.get("cycles", 0),
        "threshold_pct": threshold_pct,
        "min_ms": min_ms,
        "compared": rows,
        "missing_stages": missing,
        "new_stages": new_stages,
        "regressions": regressions,
        "clean": not regressions,
    }


def perturb_spans(
    src: str, dst: str, *, stage: str = "engine_step", factor: float = 2.0
) -> int:
    """Copy span directory `src` to `dst` with every `stage` span's
    duration scaled by `factor`, and the owning cycle span stretched by
    the added time (so the perturbed directory stays self-consistent).
    This is the smoke/test harness for the diff gate — "a synthetically
    slowed stage trips the threshold" — NOT a production tool. Returns
    the number of events perturbed."""
    os.makedirs(dst, exist_ok=True)
    touched = 0
    for i, fp in enumerate(span_files(src)):
        events = read_span_file(fp)
        added: dict = {}  # trace_id -> extra us from slowed stages
        for ev in events:
            if ev.get("ph") != "X" or ev.get("name") != stage:
                continue
            extra = float(ev.get("dur", 0.0)) * (factor - 1.0)
            ev["dur"] = float(ev.get("dur", 0.0)) * factor
            tid = (ev.get("args") or {}).get("trace_id")
            if tid is not None:
                added[tid] = added.get(tid, 0.0) + extra
            touched += 1
        for ev in events:
            if ev.get("ph") != "X" or ev.get("name") != "cycle":
                continue
            tid = (ev.get("args") or {}).get("trace_id")
            if tid in added:
                ev["dur"] = float(ev.get("dur", 0.0)) + added[tid]
        out = os.path.join(dst, "spans-%08d.trace.json" % i)
        with open(out, "w", encoding="utf-8") as f:
            f.write("[\n")
            for ev in events:
                f.write(json.dumps(ev, separators=(",", ":")) + ",\n")
    return touched
