"""Deterministic journal replay: re-execute recorded cycles and diff
bindings bitwise against what production decided.

The replayer works at the engine boundary — the recorded PodBatch and
the reconstructed SnapshotArrays are bit-exact copies of what the live
cycle dispatched, so replaying them through ANY engine mode combination
(Local/Remote x serial/pipelined x full/resident) must reproduce the
recorded node_idx exactly; that is precisely the set of guarantees
PARITY.md pins, and this module is what turns those pins from promises
into a tool you can run against a production journal.

Snapshot reconstruction: records carry either the full snapshot or the
SnapshotDelta the cycle actually shipped; deltas fold into the previous
device record's snapshot with engine.apply_snapshot_delta_np, which is
bitwise the full build by construction. Resident-mode replay re-derives
its OWN deltas (host.snapshot.snapshot_delta against the previously
uploaded snapshot), so the replayed engine exercises the same delta
machinery the live host did rather than trusting the recorded bytes.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from kubernetes_scheduler_tpu.engine import (
    PodBatch,
    SnapshotArrays,
    SnapshotDelta,
    apply_snapshot_delta_np,
    stack_windows,
)
from kubernetes_scheduler_tpu.trace.recorder import TraceError, read_journal

MODES = ("serial", "pipelined")


@dataclass
class CycleDiff:
    seq: int
    mismatches: int
    detail: str = ""


@dataclass
class ReplayReport:
    cycles: int = 0
    replayed: int = 0
    skipped: int = 0            # scalar/mixed cycles (no engine dispatch)
    pods_recorded: int = 0      # assignments in the journal
    pods_replayed: int = 0      # assignments the replay produced
    seconds: float = 0.0
    diffs: list = field(default_factory=list)

    @property
    def binding_diffs(self) -> int:
        return sum(d.mismatches for d in self.diffs)

    def to_dict(self) -> dict:
        return {
            "cycles": self.cycles,
            "replayed": self.replayed,
            "skipped": self.skipped,
            "pods_recorded": self.pods_recorded,
            "pods_replayed": self.pods_replayed,
            "seconds": round(self.seconds, 3),
            "binding_diffs": self.binding_diffs,
            "diff_cycles": [
                {"seq": d.seq, "mismatches": d.mismatches, "detail": d.detail}
                for d in self.diffs
            ],
        }


def pod_batch_from_record(tensors: dict) -> PodBatch:
    """PodBatch from a record's `pods` tensors, backfilling leaves added
    to the struct AFTER the journal was written (schema tags are
    append-only, so old records simply lack them). Only leaves with a
    semantically-neutral default may be backfilled — currently the gang
    fields (gang_id=-1 / gang_size=0 is exactly "no gangs", and the gang
    mask is bitwise the identity then); any other absence is drift."""
    missing = set(PodBatch._fields) - set(tensors)
    if missing - {"gang_id", "gang_size"}:
        raise TraceError(
            f"record's pods tensors lack {sorted(missing)} and no neutral "
            "default exists — journal/struct drift"
        )
    if missing:
        tensors = dict(tensors)
        shape = np.asarray(tensors["request"]).shape[:-1]
        if "gang_id" in missing:
            tensors["gang_id"] = np.full(shape, -1, np.int32)
        if "gang_size" in missing:
            tensors["gang_size"] = np.zeros(shape, np.int32)
    return PodBatch(**tensors)


def engine_kw_from_record(rec: dict) -> dict:
    """The cycle options as the engine call expects them (JSON round-
    trips tuples to lists; score_plugins is static under jit and must be
    a tuple of tuples again)."""
    kw = dict(rec.get("engine_kw") or {})
    sp = kw.get("score_plugins")
    if sp is not None:
        kw["score_plugins"] = tuple((n, float(w)) for n, w in sp)
    return kw


def bindings_from_idx(pod_keys, node_names, idx) -> list:
    """(namespace, name, node_name) triples for assigned window rows —
    the human-facing form of a node_idx vector."""
    out = []
    for i, key in enumerate(pod_keys):
        j = int(idx[i]) if i < len(idx) else -1
        if 0 <= j < len(node_names):
            out.append((key[0], key[1], node_names[j]))
    return out


def reconstruct_cycles(path: str):
    """Yield (record, full SnapshotArrays | None) across the journal,
    folding recorded deltas into the previous device snapshot. A delta
    with no predecessor means a broken chain (hand-truncated journal) —
    fail loudly rather than replay against garbage."""
    prev: SnapshotArrays | None = None
    for rec in read_journal(path):
        snapshot = None
        if "snapshot" in rec:
            snapshot = SnapshotArrays(**rec["snapshot"])
        elif "delta" in rec:
            if prev is None:
                raise TraceError(
                    f"record seq={rec.get('seq')} carries a delta but no "
                    "prior snapshot anchors it (journal head missing?)"
                )
            snapshot = apply_snapshot_delta_np(
                prev, SnapshotDelta(**rec["delta"])
            )
        if snapshot is not None:
            prev = snapshot
        yield rec, snapshot


def _dispatch(engine, snapshot, pods, kw, *, mode, resident, state) -> np.ndarray:
    """One replayed engine call -> flat node_idx. `state` carries the
    resident replay bookkeeping (previously uploaded snapshot + epoch)."""
    if resident:
        from kubernetes_scheduler_tpu.host.snapshot import snapshot_delta

        delta = None
        if state.get("prev") is not None:
            delta = snapshot_delta(state["prev"], snapshot)
        epoch = state.get("epoch", 0) + 1
        submit = (
            getattr(engine, "schedule_resident_async", None)
            if mode == "pipelined"
            else None
        )
        if submit is not None:
            res = submit(snapshot, pods, delta=delta, epoch=epoch, **kw).result()
        else:
            res = engine.schedule_resident(
                snapshot, pods, delta=delta, epoch=epoch, **kw
            )
        state["prev"] = snapshot
        state["epoch"] = epoch
        return np.asarray(res.node_idx)
    submit = (
        getattr(engine, "schedule_batch_async", None)
        if mode == "pipelined"
        else None
    )
    if submit is not None:
        return np.asarray(submit(snapshot, pods, **kw).result().node_idx)
    return np.asarray(engine.schedule_batch(snapshot, pods, **kw).node_idx)


def _dispatch_windows(
    engine, snapshot, pods, kw, bw: int, *, resident, state
) -> np.ndarray:
    windows = stack_windows(pods, bw)
    if resident and hasattr(engine, "schedule_windows_resident"):
        from kubernetes_scheduler_tpu.host.snapshot import snapshot_delta

        delta = None
        if state.get("prev") is not None:
            delta = snapshot_delta(state["prev"], snapshot)
        epoch = state.get("epoch", 0) + 1
        res = engine.schedule_windows_resident(
            snapshot, windows, delta=delta, epoch=epoch, **kw
        )
        state["prev"] = snapshot
        state["epoch"] = epoch
    else:
        res = engine.schedule_windows(snapshot, windows, **kw)
    return np.asarray(res.node_idx).reshape(-1)


def replay_journal(
    path: str,
    *,
    engine=None,
    mode: str = "serial",
    resident: bool = False,
    limit: int | None = None,
    record_path: str | None = None,
    span_path: str | None = None,
) -> ReplayReport:
    """Re-execute a journal and diff every replayed cycle's node_idx
    bitwise against the recording. `engine` defaults to a fresh
    LocalEngine; pass a bridge RemoteEngine to replay through a live
    sidecar. mode="pipelined" drives the async dispatch surface;
    resident=True drives the delta-upload surface with re-derived
    deltas. record_path re-records the replayed cycles as a new journal
    (same inputs, the REPLAYED decisions), so `trace diff` can compare
    two replays record-for-record.

    span_path turns the replay into a POST-HOC attribution run: every
    journal record re-emits a span set (observe.SpanRecorder, process
    "replay") — `reconstruct` (journal decode + delta fold),
    `engine_step` (the replayed dispatch, resident delta re-derivation
    included), and one `cycle` span per record, each carrying the
    SOURCE record's flight-recorder seq. A journal captured with
    telemetry off becomes a Perfetto-loadable timeline after the fact,
    and the same journal replayed through different engine/driver
    combinations becomes a deterministic A/B measurement harness
    (`spans report`/`spans diff` over the per-variant directories)."""
    if mode not in MODES:
        raise ValueError(f"unknown replay mode {mode!r}; expected {MODES}")
    if engine is None:
        from kubernetes_scheduler_tpu.engine import LocalEngine

        engine = LocalEngine()
    out_rec = None
    if record_path is not None:
        from kubernetes_scheduler_tpu.trace.recorder import CycleRecorder

        # effectively unbounded budget: the replayed journal carries one
        # FULL snapshot per device record (deltas are an online-recording
        # optimization), so the production default budget could silently
        # drop its head — and a `trace diff` against the original must
        # see every record the operator asked to re-record
        out_rec = CycleRecorder(
            record_path, file_bytes=256 << 20, max_bytes=1 << 60
        )
    spans = None
    if span_path is not None:
        from kubernetes_scheduler_tpu.host.observe import SpanRecorder

        spans = SpanRecorder(span_path, process="replay")
    report = ReplayReport()
    state: dict = {}
    t0 = time.perf_counter()
    it = reconstruct_cycles(path)
    try:
        while True:
            if limit is not None and report.cycles >= limit:
                break
            t_cycle = time.perf_counter()
            # the reconstruction cost (journal decode + delta fold)
            # lives inside the generator's next() — timed around it so
            # the replay timeline attributes it as its own stage
            try:
                rec, snapshot = next(it)
            except StopIteration:
                break
            ss = spans.begin() if spans is not None else None
            if ss is not None:
                ss.add("reconstruct", t_cycle, time.perf_counter())
            report.cycles += 1
            recorded_idx = np.asarray(
                (rec.get("assign") or {}).get("node_idx", np.zeros(0, np.int32))
            )
            report.pods_recorded += int((recorded_idx >= 0).sum())
            pod_keys = rec.get("pod_keys") or []
            node_names = rec.get("node_names") or []
            if (
                snapshot is None
                or "pods" not in rec
                or rec.get("path") not in ("device", "backlog")
            ):
                report.skipped += 1
                if out_rec is not None:
                    out_rec.record_cycle(
                        path=rec.get("path", "scalar"),
                        metrics=rec.get("metrics") or {},
                        node_names=node_names or None,
                        pod_keys=pod_keys or None,
                        bindings=rec.get("bindings"),
                        node_idx=recorded_idx if recorded_idx.size else None,
                        seq=rec.get("seq"),
                    )
                if ss is not None:
                    # skipped cycles still own a timeline slot: the
                    # span count must match the journal's cycle count,
                    # and a scalar cycle's absence would read as a gap
                    ss.add(
                        "cycle", t_cycle, time.perf_counter(),
                        path=rec.get("path", "scalar"), replayed=False,
                    )
                    spans.flush(ss, seq=rec.get("seq"))
                continue
            pods = pod_batch_from_record(rec["pods"])
            kw = engine_kw_from_record(rec)
            t_eng = time.perf_counter()
            if rec["path"] == "backlog":
                bw = int(rec.get("batch_window") or 0)
                if bw <= 0:
                    raise TraceError(
                        f"backlog record seq={rec.get('seq')} lacks "
                        "batch_window"
                    )
                idx = _dispatch_windows(
                    engine, snapshot, pods, kw, bw,
                    resident=resident, state=state,
                )
            else:
                idx = _dispatch(
                    engine, snapshot, pods, kw,
                    mode=mode, resident=resident, state=state,
                )
            if ss is not None:
                ss.add(
                    "engine_step", t_eng, time.perf_counter(),
                    backlog=rec["path"] == "backlog", resident=resident,
                )
            n_real = len(pod_keys) if pod_keys else recorded_idx.shape[0]
            replay_idx = np.asarray(idx).reshape(-1)[:n_real].astype(np.int32)
            report.replayed += 1
            report.pods_replayed += int((replay_idx >= 0).sum())
            want = recorded_idx[:n_real]
            if want.shape != replay_idx.shape or not np.array_equal(
                want, replay_idx
            ):
                bad = (
                    int((want != replay_idx).sum())
                    if want.shape == replay_idx.shape
                    else n_real
                )
                rows = (
                    np.flatnonzero(want != replay_idx)[:5].tolist()
                    if want.shape == replay_idx.shape
                    else []
                )
                report.diffs.append(
                    CycleDiff(
                        seq=int(rec.get("seq", report.cycles - 1)),
                        mismatches=bad,
                        detail=f"first differing rows: {rows}",
                    )
                )
            if out_rec is not None:
                out_rec.record_cycle(
                    path=rec["path"],
                    metrics={"pods_bound": int((replay_idx >= 0).sum())},
                    node_names=node_names or None,
                    pod_keys=pod_keys or None,
                    bindings=bindings_from_idx(
                        pod_keys, node_names, replay_idx
                    ),
                    snapshot=snapshot,
                    pods=pods,
                    engine_kw=kw,
                    node_idx=replay_idx,
                    batch_window=int(rec.get("batch_window") or 0),
                    fingerprint=rec.get("fingerprint"),
                    seq=rec.get("seq"),
                )
            if ss is not None:
                ss.add(
                    "cycle", t_cycle, time.perf_counter(),
                    path=rec["path"], replayed=True,
                )
                spans.flush(ss, seq=rec.get("seq"))
    finally:
        if out_rec is not None:
            out_rec.close()
        if spans is not None:
            spans.close()
    report.seconds = time.perf_counter() - t0
    return report
