"""Cycle flight recorder: deterministic capture & replay of scheduling
cycles (ISSUE 4).

- trace/schema.py    journal record schema (tags + pinned dtypes)
- trace/recorder.py  CRC-framed rotating journal writer + reader
- trace/replay.py    re-execute a journal through any engine mode and
                     diff bindings bitwise against the recording
- trace/inspect.py   dump / stats / diff backends for the `trace` CLI
"""

from kubernetes_scheduler_tpu.trace.recorder import (  # noqa: F401
    CycleRecorder,
    TraceError,
    TraceVersionError,
    read_journal,
)

# replay exports resolve lazily: replay.py imports the engine (and so
# jax), which the read-only journal tooling (dump/stats/diff) must not
# pull in just for the package import
_REPLAY_EXPORTS = ("ReplayReport", "replay_journal")


def __getattr__(name):
    if name in _REPLAY_EXPORTS:
        from kubernetes_scheduler_tpu.trace import replay

        return getattr(replay, name)
    raise AttributeError(name)
