"""Journal record schema: the wire contract of the cycle flight recorder.

One declarative table — like bridge/schedule.proto for the gRPC bridge —
so the record layout has an explicit, lintable identity instead of being
implied by whatever the encoder happens to write. graftlint's wire-schema
family checks this module the way it checks the .proto: field tags must
be unique and stable (a tag is wire identity — renumbering breaks every
journal on disk), and every tensor leaf must pin its dtype (a dtype
drift would make "bitwise replay parity" silently meaningless).

Versioning: SCHEMA_VERSION rides every journal file's header. Readers
reject a version they do not speak with a clear error (never a best-
effort parse of an unknown layout); ADDING fields under fresh tags is
backward-compatible — old records simply lack them and decode to the
field's absence — so the version only moves on layout-breaking changes.
"""

from __future__ import annotations

from typing import NamedTuple

# file header: magic + u16 schema version (little-endian)
MAGIC = b"YTRJ"
SCHEMA_VERSION = 1

# field kinds (u8 on the wire)
KIND_U64 = 0
KIND_F64 = 1
KIND_STR = 2
KIND_JSON = 3
KIND_TENSORS = 4

KINDS = {
    "u64": KIND_U64,
    "f64": KIND_F64,
    "str": KIND_STR,
    "json": KIND_JSON,
    "tensors": KIND_TENSORS,
}


class Field(NamedTuple):
    tag: int    # wire identity; append-only, never renumbered or reused
    name: str
    kind: str   # one of KINDS


# One record per scheduling cycle. Tags are APPEND-ONLY: a retired field
# keeps its tag reserved (readers skip unknown tags), exactly like proto
# field numbers.
JOURNAL_FIELDS = (
    Field(1, "seq", "u64"),             # cycle sequence within the run
    Field(2, "path", "str"),            # device | backlog | scalar | mixed
    Field(3, "wall_time", "f64"),       # recorder wall clock (epoch s)
    Field(4, "fingerprint", "json"),    # config + layout identity summary
    Field(5, "engine_kw", "json"),      # the exact engine cycle options
    Field(6, "node_names", "json"),     # snapshot row -> node name
    Field(7, "pod_keys", "json"),       # batch row -> [namespace, name]
    Field(8, "bindings", "json"),       # [[namespace, name, node_name]]
    Field(9, "metrics", "json"),        # CycleMetrics as a dict
    Field(10, "resident_epoch", "u64"),
    Field(11, "delta_sent", "u64"),     # 1 = the cycle shipped a delta
    Field(12, "batch_window", "u64"),   # backlog records: window stride
    Field(13, "snapshot", "tensors"),   # full SnapshotArrays leaves
    Field(14, "delta", "tensors"),      # SnapshotDelta leaves (delta recs)
    Field(15, "pods", "tensors"),       # PodBatch leaves
    Field(16, "assign", "tensors"),     # node_idx over the real window
)

FIELD_BY_NAME = {f.name: f for f in JOURNAL_FIELDS}
FIELD_BY_TAG = {f.tag: f for f in JOURNAL_FIELDS}

# Pinned dtypes for every tensor leaf a record may carry, keyed
# "<field>.<leaf>". The recorder REJECTS an array whose dtype disagrees
# (never silently casts): replay parity is bitwise, so an upstream dtype
# drift must fail at record time, not surface as a mysterious diff.
TENSOR_DTYPES = {
    # SnapshotArrays
    "snapshot.allocatable": "float32",
    "snapshot.requested": "float32",
    "snapshot.disk_io": "float32",
    "snapshot.cpu_pct": "float32",
    "snapshot.mem_pct": "float32",
    "snapshot.net_up": "float32",
    "snapshot.net_down": "float32",
    "snapshot.node_mask": "bool",
    "snapshot.cards": "float32",
    "snapshot.card_mask": "bool",
    "snapshot.card_healthy": "bool",
    "snapshot.taints": "int32",
    "snapshot.taint_mask": "bool",
    "snapshot.node_labels": "int32",
    "snapshot.node_label_mask": "bool",
    "snapshot.domain_counts": "float32",
    "snapshot.domain_id": "int32",
    "snapshot.avoid_counts": "float32",
    "snapshot.pref_attract": "float32",
    "snapshot.pref_avoid": "float32",
    "snapshot.image_scaled": "float32",
    # SnapshotDelta
    "delta.req_rows": "int32",
    "delta.req_vals": "float32",
    "delta.util_rows": "int32",
    "delta.util_vals": "float32",
    "delta.dom_rows": "int32",
    "delta.dom_vals": "float32",
    "delta.node_mask": "bool",
    # PodBatch
    "pods.request": "float32",
    "pods.r_io": "float32",
    "pods.priority": "int32",
    "pods.pod_mask": "bool",
    "pods.want_number": "int32",
    "pods.want_memory": "float32",
    "pods.want_clock": "float32",
    "pods.tolerations": "int32",
    "pods.tol_mask": "bool",
    "pods.na_key": "int32",
    "pods.na_op": "int32",
    "pods.na_vals": "int32",
    "pods.na_val_mask": "bool",
    "pods.na_mask": "bool",
    "pods.na_term": "int32",
    "pods.affinity_sel": "int32",
    "pods.anti_affinity_sel": "int32",
    "pods.pod_matches": "bool",
    "pods.pna_key": "int32",
    "pods.pna_op": "int32",
    "pods.pna_vals": "int32",
    "pods.pna_val_mask": "bool",
    "pods.pna_mask": "bool",
    "pods.pna_weight": "float32",
    "pods.pna_term": "int32",
    "pods.pref_affinity_sel": "int32",
    "pods.pref_affinity_weight": "float32",
    "pods.pref_anti_sel": "int32",
    "pods.pref_anti_weight": "float32",
    "pods.target_node": "int32",
    "pods.spread_sel": "int32",
    "pods.spread_max": "int32",
    "pods.soft_spread_sel": "int32",
    "pods.image_ids": "int32",
    "pods.n_containers": "int32",
    # gang co-scheduling (ops/gang.py): window-local gang slot + size
    "pods.gang_id": "int32",
    "pods.gang_size": "int32",
    # replay comparison target: the engine's node_idx over the real
    # (unpadded) window rows — "bitwise binding parity" reduces to an
    # array_equal on this
    "assign.node_idx": "int32",
}


def _leaves(prefix: str) -> set:
    return {
        k.split(".", 1)[1] for k in TENSOR_DTYPES if k.startswith(prefix + ".")
    }


_engine_coverage_checked = False


def check_engine_coverage() -> None:
    """Every engine-struct leaf MUST carry a pinned dtype: a leaf added
    to SnapshotArrays/PodBatch/SnapshotDelta without a schema entry
    would be silently dropped from records — replay would re-execute
    with a default-valued leaf and the parity guarantee would be a lie.
    Same stance as host/snapshot.py's delta-leaf classification assert.

    Called lazily from the WRITE/replay paths (CycleRecorder, replay),
    never at import: the read-only inspection path (`trace dump/stats/
    diff`) must stay engine-free — importing engine initializes jax,
    which a laptop reading a production journal need not have."""
    global _engine_coverage_checked
    if _engine_coverage_checked:
        return
    from kubernetes_scheduler_tpu.engine import (
        PodBatch,
        SnapshotArrays,
        SnapshotDelta,
    )

    for prefix, cls in (
        ("snapshot", SnapshotArrays),
        ("delta", SnapshotDelta),
        ("pods", PodBatch),
    ):
        have, want = _leaves(prefix), set(cls._fields)
        assert have == want, (
            f"trace schema drift for {prefix!r}: TENSOR_DTYPES covers "
            f"{sorted(have ^ want)} differently than {cls.__name__} — pin "
            "the new leaf's dtype (or retire the stale entry) before "
            "journals can be trusted"
        )
    _engine_coverage_checked = True


def _check_tables() -> None:
    """Import-time sanity on the tables themselves (engine-free)."""
    assert len({f.tag for f in JOURNAL_FIELDS}) == len(JOURNAL_FIELDS), (
        "duplicate journal field tag"
    )
    assert len(FIELD_BY_NAME) == len(JOURNAL_FIELDS), (
        "duplicate journal field name"
    )
    assert all(f.kind in KINDS for f in JOURNAL_FIELDS)


_check_tables()
