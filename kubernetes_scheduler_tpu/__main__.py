import sys

from kubernetes_scheduler_tpu.cli import main

sys.exit(main())
