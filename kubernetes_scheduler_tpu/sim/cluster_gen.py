"""kwok-style synthetic cluster/pod generators for the benchmark matrix.

The reference ships only five example pods (example/test-pod*.yaml) and no
benchmark harness; BASELINE.md defines the five configs every measurement
runs on. These generators produce those shapes hermetically (no kind/kwok
cluster needed): dense SnapshotArrays/PodBatch pairs with realistic
utilization distributions, optional GPU cards, taints and affinity
selectors.
"""

from __future__ import annotations

import numpy as np

from kubernetes_scheduler_tpu.engine import PodBatch, SnapshotArrays, make_pod_batch, make_snapshot
from kubernetes_scheduler_tpu.ops.constraints import NO_SCHEDULE, OP_IN, TOL_EQUAL
from kubernetes_scheduler_tpu.ops.resources import (
    DEFAULT_MEMORY_REQUEST,
    DEFAULT_MILLI_CPU_REQUEST,
)

# The five BASELINE.md configs: (name, n_pods, n_nodes, features)
BENCH_CONFIGS = {
    "single-pod": dict(n_pods=1, n_nodes=3),
    "deployment-50": dict(n_pods=100, n_nodes=50),
    "resources-5kx1k": dict(n_pods=5000, n_nodes=1000),
    "constraints-5kx5k": dict(n_pods=5000, n_nodes=5000, constraints=True),
    "gpu-10kx10k": dict(n_pods=10000, n_nodes=10000, gpu=True),
}


def gen_cluster(
    n_nodes: int,
    *,
    seed: int = 0,
    n_resources: int = 3,
    gpu: bool = False,
    cards_per_node: int = 4,
    constraints: bool = False,
    n_taint_keys: int = 4,
    n_label_keys: int = 8,
    n_selectors: int = 8,
    images: bool = False,
    n_images: int = 64,
) -> SnapshotArrays:
    """A cluster snapshot: allocatable/requested resources, utilization
    series (what the advisor would scrape), optional GPU cards, taints on
    ~20%% of nodes, zone-style labels, and selector match counts."""
    rng = np.random.default_rng(seed)
    # resource axis: (cpu milli, memory bytes, pods) [+ extended]
    alloc = np.stack(
        [
            rng.choice([4000, 8000, 16000, 32000], n_nodes).astype(np.float32),
            rng.choice([8, 16, 32, 64], n_nodes).astype(np.float32) * 2**30,
            np.full(n_nodes, 110, np.float32),
        ]
        + [
            rng.choice([0, 0, 4, 8], n_nodes).astype(np.float32)
            for _ in range(n_resources - 3)
        ],
        axis=1,
    )
    util_frac = rng.beta(2, 3, (n_nodes, alloc.shape[1])).astype(np.float32)
    requested = (alloc * util_frac).astype(np.float32)

    kwargs: dict = {}
    if gpu:
        cards = np.stack(
            [
                rng.integers(16, 64, (n_nodes, cards_per_node)),          # bandwidth
                rng.choice([1000, 1500, 2000], (n_nodes, cards_per_node)),  # clock
                rng.integers(1024, 8192, (n_nodes, cards_per_node)),      # core
                rng.integers(100, 400, (n_nodes, cards_per_node)),        # power
                rng.integers(0, 32_000, (n_nodes, cards_per_node)),       # free mem
                np.full((n_nodes, cards_per_node), 32_000),               # total mem
            ],
            axis=-1,
        ).astype(np.float32)
        kwargs.update(
            cards=cards,
            card_mask=rng.random((n_nodes, cards_per_node)) < 0.9,
            card_healthy=rng.random((n_nodes, cards_per_node)) < 0.95,
        )
    if constraints:
        t_max = 2
        taint_key = rng.integers(0, n_taint_keys, (n_nodes, t_max))
        taints = np.stack(
            [taint_key, rng.integers(0, 2, (n_nodes, t_max)),
             np.full((n_nodes, t_max), NO_SCHEDULE)],
            axis=-1,
        ).astype(np.int32)
        taint_mask = rng.random((n_nodes, t_max)) < 0.1
        l_max = 3
        labels = np.stack(
            [rng.integers(0, n_label_keys, (n_nodes, l_max)),
             rng.integers(0, 4, (n_nodes, l_max))],
            axis=-1,
        ).astype(np.int32)
        kwargs.update(
            taints=taints,
            taint_mask=taint_mask,
            node_labels=labels,
            node_label_mask=np.ones((n_nodes, l_max), bool),
            domain_counts=(rng.random((n_nodes, n_selectors)) < 0.3).astype(
                np.float32
            ) * rng.integers(1, 5, (n_nodes, n_selectors)),
            # sparse running avoiders exercising the reverse anti direction
            avoid_counts=(rng.random((n_nodes, n_selectors)) < 0.03).astype(
                np.float32
            ),
        )
    if images:
        # ImageLocality signal (host/snapshot precomputes the same form
        # from node.status.images): presence ~30%, sizes 50MB..2GB,
        # scaled by each image's cross-node spread ratio
        present = rng.random((n_nodes, n_images)) < 0.3
        sizes = rng.uniform(50, 2000, n_images).astype(np.float32) * 2**20
        ratio = present.sum(0).astype(np.float32) / max(n_nodes, 1)
        kwargs["image_scaled"] = (
            present * (sizes * ratio)[None, :]
        ).astype(np.float32)
    return make_snapshot(
        allocatable=alloc,
        requested=requested,
        disk_io=rng.gamma(2.0, 8.0, n_nodes).clip(0, 50),
        cpu_pct=(util_frac[:, 0] * 100).clip(0, 100),
        mem_pct=(util_frac[:, 1] * 100).clip(0, 100),
        net_up=rng.gamma(2.0, 2.0, n_nodes),
        net_down=rng.gamma(2.0, 2.0, n_nodes),
        **kwargs,
    )


def gen_pods(
    n_pods: int,
    *,
    seed: int = 1,
    n_resources: int = 3,
    gpu: bool = False,
    constraints: bool = False,
    n_taint_keys: int = 4,
    n_label_keys: int = 8,
    n_selectors: int = 8,
    images: bool = False,
    n_images: int = 64,
) -> PodBatch:
    """A pending-pod window shaped like example/test-pod.yaml at scale:
    CPU/memory requests (with the k8s non-zero defaults for the ~10%% of
    pods that specify nothing), a diskIO annotation, scv/priority labels,
    and optionally GPU demands / tolerations / affinity."""
    rng = np.random.default_rng(seed)
    cpu = rng.choice([0, 100, 250, 500, 1000, 2000], n_pods).astype(np.float32)
    cpu[cpu == 0] = DEFAULT_MILLI_CPU_REQUEST
    mem = rng.choice([0, 0.25, 0.5, 1, 2, 4], n_pods).astype(np.float32) * 2**30
    mem[mem == 0] = DEFAULT_MEMORY_REQUEST
    request = np.stack(
        [cpu, mem, np.ones(n_pods, np.float32)]
        + [
            (rng.random(n_pods) < (0.5 if gpu else 0.0)).astype(np.float32)
            * rng.integers(1, 3, n_pods)
            for _ in range(n_resources - 3)
        ],
        axis=1,
    )
    kwargs: dict = {}
    if gpu:
        kwargs.update(
            want_number=rng.choice([0, 1, 1, 2, 4], n_pods),
            want_memory=rng.choice([-1, -1, 8000, 16000], n_pods).astype(np.float32),
            want_clock=rng.choice([-1, -1, -1, 1500], n_pods).astype(np.float32),
        )
    if constraints:
        l_max = 2
        tols = np.stack(
            [
                rng.integers(0, n_taint_keys, (n_pods, l_max)),
                rng.integers(0, 2, (n_pods, l_max)),
                np.full((n_pods, l_max), TOL_EQUAL),
                np.zeros((n_pods, l_max)),
            ],
            axis=-1,
        ).astype(np.int32)
        e_max, v_max = 1, 2
        kwargs.update(
            tolerations=tols,
            tol_mask=rng.random((n_pods, l_max)) < 0.3,
            na_key=rng.integers(0, n_label_keys, (n_pods, e_max)),
            na_op=np.full((n_pods, e_max), OP_IN),
            na_vals=rng.integers(0, 4, (n_pods, e_max, v_max)),
            na_val_mask=np.ones((n_pods, e_max, v_max), bool),
            na_mask=rng.random((n_pods, e_max)) < 0.2,
            affinity_sel=np.where(
                rng.random((n_pods, 1)) < 0.15,
                rng.integers(0, n_selectors, (n_pods, 1)),
                -1,
            ),
            anti_affinity_sel=np.where(
                rng.random((n_pods, 1)) < 0.15,
                rng.integers(0, n_selectors, (n_pods, 1)),
                -1,
            ),
            # pending pods themselves match selectors, so placements inside
            # one window interact (the hard case for batched assignment)
            pod_matches=rng.random((n_pods, n_selectors)) < 0.15,
        )
    if images:
        # 1-3 container images per pod from the shared vocabulary
        ki = 3
        ids = rng.integers(0, n_images, (n_pods, ki)).astype(np.int32)
        n_c = rng.integers(1, ki + 1, n_pods).astype(np.int32)
        ids[np.arange(ki)[None, :] >= n_c[:, None]] = -1
        kwargs.update(image_ids=ids, n_containers=n_c)
    return make_pod_batch(
        request=request,
        r_io=rng.gamma(2.0, 5.0, n_pods).clip(0.1, 45),
        priority=rng.integers(0, 10, n_pods),
        **kwargs,
    )


def gen_config(name: str, *, seed: int = 0):
    """(snapshot, pods) for one of the five BASELINE.md configs."""
    cfg = dict(BENCH_CONFIGS[name])
    n_pods = cfg.pop("n_pods")
    n_nodes = cfg.pop("n_nodes")
    snap = gen_cluster(n_nodes, seed=seed, **cfg)
    pods = gen_pods(n_pods, seed=seed + 1, **cfg)
    return snap, pods
