"""graftchaos: seeded, deterministic fault injection at every boundary.

A `FaultPlan` is a small program of tick-scheduled `FaultWindow`s on the
scenario VIRTUAL clock (sim/scenarios/base.SimClock): each window names
a subsystem boundary ("advisor", "informer", "engine", "journal",
"mirror"), a fault kind (error / latency / timeout / corruption /
flapping / partition), and a [start, end) tick range. Everything is
derived from the window table and the clock — no RNG — so the same
(scenario, seed, plan) always produces the same failures at the same
ticks, the same degradation events, and the same journal: chaos runs
are REPLAY-PINNED exactly like clean ones.

Injection happens through thin wrappers around objects the Scheduler
and CLI already own, never through monkey-patched internals:

- `FaultyAdvisor` wraps the advisor's `fetch()` (host/advisor.py) —
  the scheduler's fetch-failure/stale-grace path is the consumer;
- `FaultyEngine` wraps any engine's call surface (bridge RPCs for a
  RemoteEngine, the local/sharded device step otherwise), including
  the async dispatch handles and the health probes, and simulates a
  sidecar crash-restart by dropping retained resident state when a
  `crash`-tagged window closes;
- `FaultInjector.wrap_journal` wraps the flight recorder's
  `JournalWriter.append` (trace/recorder.py) with disk-full faults —
  the recorder's never-raise-into-the-loop contract absorbs them as
  `trace_records_dropped_total`;
- informer-stream faults gate ScenarioWorld's event delivery into the
  snapshot mirror (partition = buffered then flushed, error = dropped
  until RESYNC semantics reseed);
- mirror corruption goes through `SnapshotMirror.inject_corruption`
  (host/mirror.py) — the periodic bitwise verify cross-check must
  detect and resync it.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable

BOUNDARIES = ("advisor", "informer", "engine", "journal", "mirror")
FAULT_KINDS = (
    "error", "latency", "timeout", "corrupt", "flap", "partition",
)


class FaultError(RuntimeError):
    """Injected hard failure at a boundary."""


class FaultTimeout(TimeoutError):
    """Injected deadline expiry at a boundary."""


class FaultPartition(ConnectionError):
    """Injected network partition: the peer is unreachable."""


class FaultDiskFull(OSError):
    """Injected ENOSPC on a journal/span write."""


@dataclass(frozen=True)
class FaultWindow:
    """One scheduled fault: `kind` at `boundary` over virtual-clock
    [start, end). `flap` alternates per whole tick with `period` (fails
    on phase 0); `latency` adds `latency_s` of (bounded) real delay;
    `detail` tags windows for wrapper-specific behavior (e.g. "crash"
    on an engine window drops retained resident state at close)."""

    boundary: str
    kind: str
    start: float
    end: float
    latency_s: float = 0.0
    period: int = 2
    detail: str = ""

    def __post_init__(self):
        if self.boundary not in BOUNDARIES:
            raise ValueError(f"unknown fault boundary {self.boundary!r}")
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if not self.end > self.start:
            raise ValueError("fault window must have end > start")

    def active(self, now: float) -> bool:
        if not (self.start <= now < self.end):
            return False
        if self.kind == "flap":
            return int(now - self.start) % max(1, self.period) == 0
        return True


@dataclass(frozen=True)
class FaultPlan:
    """The program: a tuple of windows, queried by (boundary, now)."""

    windows: tuple = ()

    def active(self, boundary: str, now: float) -> list[FaultWindow]:
        return [
            w for w in self.windows
            if w.boundary == boundary and w.active(now)
        ]

    def last_end(self) -> float:
        return max((w.end for w in self.windows), default=0.0)


_RAISES = {
    "error": FaultError,
    "flap": FaultError,
    "timeout": FaultTimeout,
    "partition": FaultPartition,
}

# real-sleep ceiling for latency faults: the DECISIONS are unaffected
# either way (replay parity holds), so the wall delay only needs to be
# visible in latency telemetry, not realistic
_MAX_REAL_SLEEP_S = 0.05


@dataclass
class FaultInjector:
    """Evaluates a FaultPlan against the scenario clock and injects at
    each boundary. `injected` counts every fault fired, keyed
    (boundary, kind) — the scenario summary's audit surface."""

    plan: FaultPlan
    clock: Callable[[], float]
    sleep: Callable[[float], None] = time.sleep
    injected: dict = field(default_factory=dict)

    def _count(self, boundary: str, kind: str) -> None:
        key = (boundary, kind)
        self.injected[key] = self.injected.get(key, 0) + 1

    def check(self, boundary: str, *, error_cls=None) -> None:
        """Apply the active windows at `boundary`: latency sleeps (and
        counts), every failing kind raises its exception class
        (`error_cls` overrides for boundary-specific types, e.g. the
        journal's OSError)."""
        now = self.clock()
        for w in self.plan.active(boundary, now):
            if w.kind == "latency":
                self._count(boundary, "latency")
                if w.latency_s > 0:
                    self.sleep(min(w.latency_s, _MAX_REAL_SLEEP_S))
            elif w.kind in _RAISES:
                self._count(boundary, w.kind)
                cls = error_cls or _RAISES[w.kind]
                raise cls(
                    f"injected {w.kind} at {boundary} "
                    f"(window [{w.start}, {w.end}) @ t={now})"
                )

    def blocked(self, boundary: str) -> bool:
        """Would check() raise right now (latency/corrupt excluded)?"""
        now = self.clock()
        return any(
            w.kind in _RAISES for w in self.plan.active(boundary, now)
        )

    def quiesced(self) -> bool:
        """Past every window — the recovery tail has begun."""
        return self.clock() >= self.plan.last_end()

    def summary(self) -> dict:
        return {f"{b}:{k}": n for (b, k), n in sorted(self.injected.items())}

    def check_health_observed(self) -> None:
        """Count a health probe that observed an injected outage (no
        raise — health probes report, they don't fail)."""
        self._count("engine", "health-observed")

    # -- journal boundary -------------------------------------------------

    def wrap_journal(self, recorder) -> None:
        """Wrap the flight recorder's JournalWriter.append with
        disk-full faults (raised BEFORE any bytes hit the file, so no
        torn frames — the injected failure mode is a full disk
        rejecting the write, and the recorder's catch-count-drop
        contract absorbs it)."""
        if recorder is None:
            return
        writer = recorder._writer
        orig = writer.append
        inj = self

        def append(payload, *, rotate: bool = False):
            inj.check("journal", error_cls=FaultDiskFull)
            return orig(payload, rotate=rotate)

        writer.append = append


# ---- boundary wrappers -----------------------------------------------------


class FaultyAdvisor:
    """Advisor-fetch boundary wrapper: `fetch()` (and the coalescing
    `fetch_changed` when the inner advisor has one) raises/delays per
    the plan; everything else delegates. The scheduler's consumer side
    is the fetch-failure path (requeue + backoff hold) and the
    stale-utilization grace mode (config.advisor_stale_ttl_s)."""

    def __init__(self, inner, injector: FaultInjector):
        self.inner = inner
        self._inj = injector
        self._last: dict = {}

    def fetch(self):
        self._inj.check("advisor")
        return self.inner.fetch()

    def fetch_changed(self):
        """Changed-node coalescing surface, faulted: delegates when the
        inner advisor coalesces, otherwise diffs like CoalescingAdvisor
        (host/advisor.util_delta) — either way the injected failure
        fires BEFORE any data moves."""
        self._inj.check("advisor")
        fc = getattr(self.inner, "fetch_changed", None)
        if fc is not None:
            return fc()
        from kubernetes_scheduler_tpu.host.advisor import util_delta

        return util_delta(self._last, self.inner.fetch())

    def __getattr__(self, name):
        return getattr(self.inner, name)


class FaultyEngine:
    """Engine/bridge boundary wrapper: every schedule/preempt dispatch
    checks the plan first (so an in-window call fails the way a dead or
    partitioned sidecar would), the async surfaces check at dispatch
    time, and the health probes report the injected outage instead of
    raising (a health check's job is to OBSERVE the failure). A window
    tagged detail="crash" simulates a sidecar crash-restart: when it
    closes, the retained resident state is dropped (the restarted
    process never had it), forcing the epoch-mismatch full-resend
    recovery path."""

    def __init__(self, inner, injector: FaultInjector):
        self.inner = inner
        self._inj = injector
        self._crash_armed = False
        # engines without the async surface must not grow one through
        # the wrapper (the scheduler feature-probes with getattr)
        if not hasattr(inner, "schedule_batch_async"):
            self.schedule_batch_async = None
        if not hasattr(inner, "schedule_resident_async"):
            self.schedule_resident_async = None

    def _gate(self) -> None:
        now = self._inj.clock()
        crashing = any(
            w.detail == "crash"
            for w in self._inj.plan.active("engine", now)
        )
        if crashing:
            self._crash_armed = True
        elif self._crash_armed:
            # the crash window closed: the "restarted" engine lost its
            # session-retained state exactly once per crash
            self._crash_armed = False
            inval = getattr(self.inner, "invalidate_resident", None)
            if inval is not None:
                inval()
        self._inj.check("engine")

    # -- dispatch surfaces ------------------------------------------------

    def schedule_batch(self, snapshot, pods, **kw):
        self._gate()
        return self.inner.schedule_batch(snapshot, pods, **kw)

    def schedule_resident(self, snapshot, pods, **kw):
        self._gate()
        return self.inner.schedule_resident(snapshot, pods, **kw)

    def schedule_batch_async(self, snapshot, pods, **kw):
        self._gate()
        return self.inner.schedule_batch_async(snapshot, pods, **kw)

    def schedule_resident_async(self, snapshot, pods, **kw):
        self._gate()
        return self.inner.schedule_resident_async(snapshot, pods, **kw)

    def schedule_windows(self, snapshot, pods_windows, **kw):
        self._gate()
        return self.inner.schedule_windows(snapshot, pods_windows, **kw)

    def schedule_windows_resident(self, snapshot, pods_windows, **kw):
        self._gate()
        return self.inner.schedule_windows_resident(
            snapshot, pods_windows, **kw
        )

    def preempt(self, snapshot, pods, victims, **kw):
        self._gate()
        return self.inner.preempt(snapshot, pods, victims, **kw)

    # -- health -----------------------------------------------------------

    def healthy(self, **kw) -> bool:
        if self._inj.blocked("engine"):
            self._inj.check_health_observed()
            return False
        h = getattr(self.inner, "healthy", None)
        return bool(h(**kw)) if h is not None else True

    def health_info(self, **kw):
        if self._inj.blocked("engine"):
            self._inj.check_health_observed()
            return None
        hi = getattr(self.inner, "health_info", None)
        return hi(**kw) if hi is not None else None

    def __getattr__(self, name):
        return getattr(self.inner, name)


# ---- informer-stream gating (ScenarioWorld -> mirror) ----------------------


class InformerGate:
    """The informer-event boundary for scenario runs: ScenarioWorld
    routes its mirror deliveries (node/pod events) through here. During
    a partition window, events BUFFER (the watch stream is cut, the
    world keeps moving); `flush()` — called at each tick boundary —
    delivers the backlog in arrival order once the window closes, the
    same late-but-ordered delivery a re-established watch gives. An
    `error` window DROPS events (a crashed informer misses them
    outright); the consumer's RESYNC/verify machinery is what must
    absorb that."""

    def __init__(self, injector: FaultInjector):
        self._inj = injector
        self._buffer: list[tuple] = []
        self.dropped = 0

    def deliver(self, apply: Callable, *args) -> None:
        now = self._inj.clock()
        wins = self._inj.plan.active("informer", now)
        for w in wins:
            if w.kind == "partition":
                self._inj._count("informer", "partition")
                self._buffer.append((apply, args))
                return
            if w.kind in ("error", "flap"):
                self._inj._count("informer", w.kind)
                self.dropped += 1
                return
        apply(*args)

    def flush(self) -> int:
        """Deliver buffered events if no partition window is active;
        returns how many were delivered."""
        now = self._inj.clock()
        if any(
            w.kind == "partition"
            for w in self._inj.plan.active("informer", now)
        ):
            return 0
        buffered, self._buffer = self._buffer, []
        for apply, args in buffered:
            apply(*args)
        return len(buffered)
