from kubernetes_scheduler_tpu.sim.cluster_gen import (
    BENCH_CONFIGS,
    gen_cluster,
    gen_config,
    gen_pods,
)
