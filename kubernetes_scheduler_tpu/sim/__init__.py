from kubernetes_scheduler_tpu.sim.cluster_gen import (
    BENCH_CONFIGS,
    gen_cluster,
    gen_config,
    gen_pods,
)
from kubernetes_scheduler_tpu.sim.host_gen import (
    gen_host_cluster,
    gen_host_pods,
)
