"""Object-level (host API) cluster generators.

sim/cluster_gen.py produces dense device arrays directly; these produce
the host layer's Node/Pod objects + a StaticAdvisor, so the FULL pipeline
— queue, snapshot builder, engine, binder — can run against a kwok-style
simulated cluster (the hermetic stand-in for the reference's de-facto
integration test of applying example/test-pod*.yaml to a live cluster,
SURVEY.md §4).
"""

from __future__ import annotations

import numpy as np

from kubernetes_scheduler_tpu.host.advisor import NodeUtil, StaticAdvisor
from kubernetes_scheduler_tpu.host.types import (
    Card,
    Container,
    MatchExpression,
    Pod,
    PodAffinityTerm,
    Taint,
    Toleration,
)
from kubernetes_scheduler_tpu.host.types import Node

ZONES = ("zone-a", "zone-b", "zone-c", "zone-d")


def gen_host_cluster(
    n_nodes: int,
    *,
    seed: int = 0,
    gpu: bool = False,
    cards_per_node: int = 4,
    constraints: bool = False,
) -> tuple[list[Node], StaticAdvisor]:
    """Nodes + a StaticAdvisor with matching utilization series."""
    rng = np.random.default_rng(seed)
    nodes, utils = [], {}
    for i in range(n_nodes):
        name = f"node-{i}"
        kw: dict = {}
        if gpu:
            kw["cards"] = [
                Card(
                    bandwidth=float(rng.integers(16, 64)),
                    clock=float(rng.choice([1000, 1500, 2000])),
                    core=float(rng.integers(1024, 8192)),
                    power=float(rng.integers(100, 400)),
                    free_memory=float(rng.integers(0, 32_000)),
                    total_memory=32_000.0,
                    health="Healthy" if rng.random() < 0.95 else "Unhealthy",
                )
                for _ in range(cards_per_node)
            ]
        if constraints:
            kw["labels"] = {"topology.kubernetes.io/zone": ZONES[i % len(ZONES)]}
            if rng.random() < 0.1:
                kw["taints"] = [
                    Taint(key="dedicated", value="infra", effect="NoSchedule")
                ]
        nodes.append(
            Node(
                name=name,
                allocatable={
                    "cpu": float(rng.choice([4000, 8000, 16000, 32000])),
                    "memory": float(rng.choice([8, 16, 32, 64])) * 2**30,
                    "pods": 110.0,
                },
                **kw,
            )
        )
        utils[name] = NodeUtil(
            cpu_pct=float(rng.uniform(0, 100)),
            mem_pct=float(rng.uniform(0, 100)),
            disk_io=float(min(rng.gamma(2.0, 8.0), 50.0)),
            net_up=float(rng.gamma(2.0, 2.0)),
            net_down=float(rng.gamma(2.0, 2.0)),
        )
    return nodes, StaticAdvisor(utils)


def gen_host_pods(
    n_pods: int,
    *,
    seed: int = 1,
    gpu: bool = False,
    constraints: bool = False,
) -> list[Pod]:
    """Pending pods shaped like example/test-pod.yaml at scale: diskIO
    annotation, scv/priority label, optional GPU demands / tolerations /
    zone anti-affinity."""
    rng = np.random.default_rng(seed)
    pods = []
    for i in range(n_pods):
        labels = {"scv/priority": str(int(rng.integers(0, 10)))}
        kw: dict = {}
        if gpu and rng.random() < 0.5:
            labels["scv/number"] = str(int(rng.choice([1, 1, 2, 4])))
            if rng.random() < 0.5:
                labels["scv/memory"] = str(int(rng.choice([8000, 16000])))
        if constraints:
            if rng.random() < 0.3:
                kw["tolerations"] = [
                    Toleration(key="dedicated", value="infra", operator="Equal")
                ]
            if rng.random() < 0.2:
                kw["node_affinity"] = [
                    MatchExpression(
                        key="topology.kubernetes.io/zone",
                        operator="In",
                        values=[ZONES[int(rng.integers(0, len(ZONES)))]],
                    )
                ]
            if rng.random() < 0.1:
                kw["pod_affinity"] = [
                    PodAffinityTerm(
                        match_labels={"app": f"svc-{i % 16}"},
                        topology_key="topology.kubernetes.io/zone",
                        anti=True,
                    )
                ]
        pods.append(
            Pod(
                name=f"pod-{i}",
                labels={**labels, "app": f"svc-{i % 16}"},
                annotations={"diskIO": f"{min(max(rng.gamma(2.0, 5.0), 0.1), 45.0):.1f}"},
                containers=[
                    Container(
                        requests={
                            "cpu": float(rng.choice([100, 250, 500, 1000, 2000])),
                            "memory": float(rng.choice([1, 2, 4])) * 2**28,
                        }
                    )
                ],
                **kw,
            )
        )
    return pods
