"""Scenario harness: registry + runner (see base.py for the contract).

    from kubernetes_scheduler_tpu.sim.scenarios import SCENARIOS, run
    summary = run("burst", n_nodes=64, seed=0, trace_path="/tmp/j")
"""

from kubernetes_scheduler_tpu.sim.scenarios.base import (
    FleetScenarioWorld,
    Scenario,
    ScenarioWorld,
    SimClock,
    run_scenario,
    run_scenario_replicated,
    scenario_config,
)
from kubernetes_scheduler_tpu.sim.scenarios.library import SCENARIOS


def run(
    name: str,
    *,
    n_nodes: int = 64,
    intensity: float = 1.0,
    seed: int = 0,
    trace_path: str | None = None,
    span_path: str | None = None,
    config=None,
    faults: bool = True,
) -> dict:
    """Instantiate and run a registered scenario by name. faults=False
    runs a chaos program's traffic WITHOUT its fault plan (the clean
    A/B twin). Scenarios declaring `replicas` > 1 run through the
    replicated-fleet runner (N schedulers over a partitioned queue,
    per-replica journals under <trace_path>/r<i>)."""
    cls = SCENARIOS.get(name)
    if cls is None:
        raise ValueError(
            f"unknown scenario {name!r}; expected one of {sorted(SCENARIOS)}"
        )
    scenario = cls(n_nodes=n_nodes, intensity=intensity)
    runner = (
        run_scenario_replicated
        if getattr(scenario, "replicas", 1) > 1
        else run_scenario
    )
    return runner(
        scenario,
        seed=seed,
        trace_path=trace_path,
        span_path=span_path,
        config=config,
        faults=faults,
    )
