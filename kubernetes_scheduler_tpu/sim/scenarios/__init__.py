"""Scenario harness: registry + runner (see base.py for the contract).

    from kubernetes_scheduler_tpu.sim.scenarios import SCENARIOS, run
    summary = run("burst", n_nodes=64, seed=0, trace_path="/tmp/j")
"""

from kubernetes_scheduler_tpu.sim.scenarios.base import (
    Scenario,
    ScenarioWorld,
    SimClock,
    run_scenario,
    scenario_config,
)
from kubernetes_scheduler_tpu.sim.scenarios.library import SCENARIOS


def run(
    name: str,
    *,
    n_nodes: int = 64,
    intensity: float = 1.0,
    seed: int = 0,
    trace_path: str | None = None,
    span_path: str | None = None,
    config=None,
    faults: bool = True,
) -> dict:
    """Instantiate and run a registered scenario by name. faults=False
    runs a chaos program's traffic WITHOUT its fault plan (the clean
    A/B twin)."""
    cls = SCENARIOS.get(name)
    if cls is None:
        raise ValueError(
            f"unknown scenario {name!r}; expected one of {sorted(SCENARIOS)}"
        )
    return run_scenario(
        cls(n_nodes=n_nodes, intensity=intensity),
        seed=seed,
        trace_path=trace_path,
        span_path=span_path,
        config=config,
        faults=faults,
    )
