"""The shipped scenario programs (sim/scenarios/base.py is the harness).

Each scenario is a seeded adversarial traffic shape the static
generators (sim/cluster_gen, sim/host_gen) cannot express: load that
CHANGES over time, nodes that vanish mid-run, gangs whose members
straggle in. Every one is registered by name in SCENARIOS and runnable
via `yoda-tpu scenario run <name>`; all randomness flows from the single
rng the runner seeds, so a (name, seed, scale) triple pins the journal.
"""

from __future__ import annotations

import math

import numpy as np

from kubernetes_scheduler_tpu.host.types import Container, Pod, PodAffinityTerm
from kubernetes_scheduler_tpu.sim.scenarios.base import Scenario, ScenarioWorld

ZONES = ("zone-a", "zone-b", "zone-c", "zone-d")
_ZONE_KEY = "topology.kubernetes.io/zone"


def _mk_pod(
    rng,
    name: str,
    *,
    labels: dict | None = None,
    cpu: float | None = None,
    anti_group: str | None = None,
) -> Pod:
    """One simulated pod, everything drawn from the scenario rng."""
    lab = {"scv/priority": str(int(rng.integers(0, 10)))}
    if labels:
        lab.update(labels)
    kw: dict = {}
    if anti_group is not None:
        lab["app"] = anti_group
        kw["pod_affinity"] = [
            PodAffinityTerm(
                match_labels={"app": anti_group},
                topology_key=_ZONE_KEY,
                anti=True,
            )
        ]
    return Pod(
        name=name,
        labels=lab,
        annotations={
            "diskIO": f"{min(max(rng.gamma(2.0, 5.0), 0.1), 45.0):.1f}"
        },
        containers=[
            Container(
                requests={
                    "cpu": float(
                        cpu
                        if cpu is not None
                        else rng.choice([100, 250, 500, 1000])
                    ),
                    "memory": float(rng.choice([1, 2, 4])) * 2**28,
                }
            )
        ],
        **kw,
    )


class DiurnalScenario(Scenario):
    """A day compressed into `ticks`: arrivals follow a sinusoidal load
    curve (trough ~20% of peak), the steady-state shape a production
    scheduler actually sees. The baseline every adversarial scenario is
    judged against."""

    name = "diurnal"
    description = "sinusoidal arrival curve: compressed day/night load"
    ticks = 12
    smoke = True

    def tick(self, t: int, world: ScenarioWorld, rng) -> None:
        base = max(2, int(self.n_nodes * self.intensity))
        phase = 2.0 * math.pi * t / self.ticks
        n = max(1, int(base * (0.6 - 0.4 * math.cos(phase))))
        for i in range(n):
            world.submit(_mk_pod(rng, f"diurnal-t{t}-{i}"))


class BurstScenario(Scenario):
    """A quiet trickle, then one tick delivers a backlog ~8x the
    steady state (a controller rollout, a namespace un-pause): the
    deep-window pop, bucket-padding recompiles, and queue ordering all
    get exercised at once."""

    name = "burst"
    description = "arrival burst: ~8x backlog lands in one tick"
    ticks = 10
    smoke = True

    def tick(self, t: int, world: ScenarioWorld, rng) -> None:
        base = max(2, int(self.n_nodes * self.intensity / 4))
        n = base * 8 if t == self.ticks // 2 else base
        for i in range(n):
            world.submit(_mk_pod(rng, f"burst-t{t}-{i}"))


class NodeFlapScenario(Scenario):
    """Nodes vanish and return mid-run: each flap kills the node's
    running pods (resubmitted by their controllers) and churns the
    snapshot layout — with resident state on, every flap forces the
    delta chain to flush to a full upload; the pipelined driver's
    speculative batches discard on the fingerprint change."""

    name = "node-flap"
    description = "nodes vanish/return mid-run; resident state flushes"
    ticks = 14

    def tick(self, t: int, world: ScenarioWorld, rng) -> None:
        n = max(2, int(self.n_nodes * self.intensity / 2))
        for i in range(n):
            world.submit(_mk_pod(rng, f"flap-t{t}-{i}"))
        if t >= 2 and t % 3 == 2 and world.nodes:
            k = max(1, len(world.nodes) // 16)
            names = [
                world.nodes[int(j)].name
                for j in rng.choice(
                    len(world.nodes), size=min(k, len(world.nodes)),
                    replace=False,
                )
            ]
            for name in names:
                world.fail_node(name)
        if t % 3 == 1:
            for name in list(world.downed):
                world.restore_node(name)


class ZoneFailureScenario(Scenario):
    """A whole zone dies at once: every node in it is gone in one tick
    and every pod that ran there floods back into the queue — the mass-
    rescheduling spike. The zone returns (empty) near the end."""

    name = "zone-failure"
    description = "whole-zone outage -> mass rescheduling flood"
    ticks = 12

    def tick(self, t: int, world: ScenarioWorld, rng) -> None:
        n = max(2, int(self.n_nodes * self.intensity))
        for i in range(n):
            world.submit(_mk_pod(rng, f"zone-t{t}-{i}"))
        if t == self.ticks // 2:
            zone = ZONES[int(rng.integers(0, len(ZONES)))]
            for name in [
                nd.name
                for nd in world.nodes
                if nd.labels.get(_ZONE_KEY) == zone
            ]:
                world.fail_node(name)
        if t == self.ticks - 2:
            for name in list(world.downed):
                world.restore_node(name)


class AntiAffinityPackScenario(Scenario):
    """Adversarial packing: waves of pods whose REQUIRED zone-level
    anti-affinity admits at most one per zone per group — more members
    than zones, so every wave leaves a deterministic unschedulable
    remainder churning through retry backoff while plain filler traffic
    must keep flowing around it."""

    name = "anti-affinity-pack"
    description = "zone anti-affinity groups larger than the zone count"
    ticks = 10

    def tick(self, t: int, world: ScenarioWorld, rng) -> None:
        groups = max(1, int(self.n_nodes * self.intensity / 16))
        for g in range(groups):
            size = len(ZONES) + 2  # two can never place per wave
            for i in range(size):
                world.submit(
                    _mk_pod(
                        rng,
                        f"anti-t{t}-g{g}-{i}",
                        anti_group=f"spread-{t}-{g}",
                    )
                )
        for i in range(max(2, int(self.n_nodes * self.intensity / 4))):
            world.submit(_mk_pod(rng, f"anti-fill-t{t}-{i}"))


class GangMixScenario(Scenario):
    """Gang-heavy traffic (ops/gang.py): complete gangs of mixed sizes,
    straggler gangs whose last member arrives a tick late (deferral +
    reunite via restore_window), one oversize gang that must resolve by
    policy, and plain filler — the all-or-nothing machinery end to end."""

    name = "gang-mix"
    description = "gangs of mixed sizes, stragglers, one oversize gang"
    ticks = 10
    smoke = True

    def _gang_pod(self, rng, gang: str, size: int, i: int) -> Pod:
        return _mk_pod(
            rng,
            f"{gang}-m{i}",
            labels={"scv/gang": gang, "scv/gang-size": str(size)},
            cpu=float(rng.choice([100, 250, 500])),
        )

    def tick(self, t: int, world: ScenarioWorld, rng) -> None:
        scale = max(1, int(self.n_nodes * self.intensity / 32))
        for g in range(scale):
            size = int(rng.choice([2, 3, 4, 8]))
            gang = f"gang-t{t}-{g}"
            for i in range(size):
                world.submit(self._gang_pod(rng, gang, size, i))
        # straggler: all but one member now, the last one next tick
        if t % 2 == 0:
            size = int(rng.choice([3, 4]))
            gang = f"straggler-t{t}"
            for i in range(size - 1):
                world.submit(self._gang_pod(rng, gang, size, i))
            self._pending = (gang, size)
        elif getattr(self, "_pending", None) is not None:
            gang, size = self._pending
            self._pending = None
            world.submit(self._gang_pod(rng, gang, size, size - 1))
        # one gang no window can hold: exercises the oversize policy
        if t == 1:
            size = 2048 + 2
            # only a handful of members actually submitted — the
            # declared size alone makes it unschedulable as a gang
            for i in range(4):
                world.submit(self._gang_pod(rng, f"oversize-t{t}", size, i))
        for i in range(max(2, int(self.n_nodes * self.intensity / 8))):
            world.submit(_mk_pod(rng, f"gangfill-t{t}-{i}"))


# ---- chaos programs (sim/faults.py) ---------------------------------------
#
# Each chaos scenario is steady traffic PLUS a deterministic FaultPlan
# on the virtual clock: the run must keep scheduling through the fault
# windows (bounded degraded cycles, never a stall) and END fully
# recovered — every degradation-ladder rung back at top, both breakers
# closed — with the journal replay-pinned like every clean scenario.
# The breaker knobs are tightened so open -> half-open -> closed fits
# inside a handful of virtual ticks.

_CHAOS_BREAKER = {
    "breaker_failure_threshold": 2,
    "breaker_recovery_window_s": 3.0,
}


class ChaosScenario(Scenario):
    """Shared chaos shape: a steady half-intensity trickle every tick
    (including the calm recovery tail — recovery probes need traffic
    to ride), with the fault program declared in `windows()`."""

    chaos = True
    ticks = 18

    def windows(self) -> tuple:
        raise NotImplementedError

    def fault_plan(self):
        from kubernetes_scheduler_tpu.sim.faults import FaultPlan

        return FaultPlan(self.windows())

    def tick(self, t: int, world: ScenarioWorld, rng) -> None:
        n = max(2, int(self.n_nodes * self.intensity / 2))
        for i in range(n):
            world.submit(_mk_pod(rng, f"{self.name}-t{t}-{i}"))


def _w(boundary, kind, start, end, **kw):
    from kubernetes_scheduler_tpu.sim.faults import FaultWindow

    return FaultWindow(
        boundary=boundary, kind=kind, start=float(start), end=float(end),
        **kw,
    )


class AdvisorOutageScenario(ChaosScenario):
    """Prometheus dies for 8 virtual seconds: the stale-TTL grace mode
    serves last-good utilization (marked) until the TTL expires, then
    the window-requeue outage path takes over with backoff-paced
    retries; the advisor breaker opens and recovers by probe."""

    name = "advisor-outage"
    description = "advisor down past the stale TTL; grace then requeue"
    ticks = 18
    smoke = True
    config_overrides = {"advisor_stale_ttl_s": 4.0, **_CHAOS_BREAKER}

    def windows(self):
        return (_w("advisor", "error", 3, 11),)


class SidecarCrashRestartScenario(ChaosScenario):
    """The engine process crashes and restarts: in-window dispatches
    fail to the scalar path, the restarted engine lost its retained
    resident state (full-resend recovery), and the ladder walks
    engine->local->remote and resident->full->resident."""

    name = "sidecar-crash-restart"
    description = "engine crash-restart; resident state re-learned"
    ticks = 16
    config_overrides = {
        "resident_state": True, "pipeline_depth": 1, **_CHAOS_BREAKER,
    }

    def windows(self):
        return (_w("engine", "error", 4, 6, detail="crash"),)


class RpcFlapScenario(ChaosScenario):
    """The engine path flaps (fails every other virtual second): the
    pipelined driver alternates device cycles with scalar fallbacks,
    the breaker opens on failing phases and recovers by half-open
    probe on good ones — the retry-storm shape the unified backoff
    exists to de-phase."""

    name = "rpc-flap"
    description = "engine RPCs flap; breaker + fallback churn"
    ticks = 18
    smoke = True
    config_overrides = {"pipeline_depth": 1, **_CHAOS_BREAKER}

    def windows(self):
        return (_w("engine", "flap", 3, 11, period=2),)


class DiskFullJournalScenario(ChaosScenario):
    """The flight-recorder disk fills for 6 virtual seconds: journal
    appends fail, the recorder counts drops and keeps the loop
    unharmed (never raises into a cycle), the delta chain re-anchors
    with a full snapshot after the gap, and the journal still
    replay-pins."""

    name = "disk-full-journal"
    description = "journal writes ENOSPC; recorder drops, loop unharmed"
    ticks = 14
    config_overrides = dict(_CHAOS_BREAKER)

    def windows(self):
        return (_w("journal", "error", 3, 9),)


class MirrorCorruptionScenario(ChaosScenario):
    """Silent mirror drift, injected: one cell of a mirror leaf is
    perturbed without dirtying its row — the bitwise verify cross-check
    (pinned to every emit here) must detect it, count
    mirror_verify_failures_total, resync with a full rebuild, and climb
    the mirror rung back."""

    name = "mirror-corruption"
    description = "mirror cell corrupted; verify detects and resyncs"
    ticks = 14
    corrupt_ticks = (4, 8)
    config_overrides = {
        "snapshot_mirror": True, "mirror_verify_interval": 1,
        **_CHAOS_BREAKER,
    }

    def windows(self):
        # the corruption itself goes through SnapshotMirror.
        # inject_corruption (tick below); the plan carries a marker
        # window so the run is audited as chaos
        return (_w("mirror", "corrupt", min(self.corrupt_ticks),
                   max(self.corrupt_ticks) + 1),)

    def tick(self, t: int, world: ScenarioWorld, rng) -> None:
        super().tick(t, world, rng)
        if t in self.corrupt_ticks:
            mirror = world.scheduler.mirror
            if mirror is not None:
                mirror.inject_corruption(leaf="net_up", row=t)


class CompoundStormScenario(ChaosScenario):
    """Everything at once: advisor flapping past the stale TTL, an
    engine crash-restart, an informer partition over a node failure,
    a full journal disk, added engine latency, and a mirror corruption
    — the composed-degradation case none of the single-fault paths
    exercise together. The gate: bounded degraded cycles, zero binding
    diffs on replay, and FULL recovery (every rung top, breakers
    closed) by scenario end."""

    name = "compound-storm"
    description = "advisor+engine+informer+journal+mirror faults at once"
    ticks = 22
    config_overrides = {
        "resident_state": True, "pipeline_depth": 1,
        "snapshot_mirror": True, "mirror_verify_interval": 1,
        "advisor_stale_ttl_s": 4.0, **_CHAOS_BREAKER,
    }

    def windows(self):
        return (
            _w("advisor", "flap", 3, 9, period=2),
            _w("engine", "error", 5, 7, detail="crash"),
            _w("informer", "partition", 6, 9),
            _w("journal", "error", 5, 8),
            _w("engine", "latency", 9, 11, latency_s=0.005),
            _w("mirror", "corrupt", 10, 11),
        )

    def tick(self, t: int, world: ScenarioWorld, rng) -> None:
        super().tick(t, world, rng)
        if t == 6 and world.nodes:
            # node failure INSIDE the informer partition: the mirror
            # learns about it only when the buffered events flush
            world.fail_node(world.nodes[0].name)
        if t == 12:
            for name in list(world.downed):
                world.restore_node(name)
        if t == 10:
            mirror = world.scheduler.mirror
            if mirror is not None:
                mirror.inject_corruption(leaf="net_up", row=3)


class ReplicaConflictStormScenario(Scenario):
    """2-replica fleet over the partitioned queue: partition-skew plus
    conflict storms (host/replica.py, the shipped replica-bind protocol).

    Two tenant namespaces are picked to land on partitions 0 and 1
    (queue.namespace_partition), with skewed traffic — 75% of arrivals
    on replica 0's partition, 25% on replica 1's — so the fleet drains
    an UNBALANCED workload. Every third tick is a conflict storm: a
    filler window of high-priority pods occupies replica 0's current
    cycle while mid-priority OVERLAP pods are submitted to BOTH
    replicas (FleetScenarioWorld.submit_overlap — the partition-handoff
    race). With pipeline_depth=1, replica 0 prefetches the overlap
    window while binding filler, replica 1 binds its overlap copies in
    the same round-robin round, and replica 0's prefetched binds then
    LOSE the bind-table CAS — bind_lose requeues, the 409 lands in the
    binder's drop arm, and the requeued copies retire via drop_bound on
    the next pop. Deterministic, so the per-replica journals replay-pin;
    the evidence gate is bind_conflicts > 0 with double_binds == 0 and
    every pod bound exactly once."""

    name = "replica-conflict-storm"
    description = (
        "2-replica partitioned fleet: skewed tenants + overlap-pod "
        "conflict storms resolved first-bind-wins"
    )
    ticks = 10
    smoke = True
    replicas = 2
    # small windows so a storm's filler fills exactly one cycle, ONE
    # window per cycle (deep-queue batching would swallow filler AND
    # overlap in one backlog pop), and the pipelined prefetch slot to
    # hold the overlap window across the round-robin round
    config_overrides = {
        "batch_window": 32,
        "pipeline_depth": 1,
        "max_windows_per_cycle": 1,
    }

    def __init__(self, **kw):
        super().__init__(**kw)
        from kubernetes_scheduler_tpu.host.queue import namespace_partition

        # first tenant names landing on each partition, deterministically
        self.ns_by_partition = {}
        i = 0
        while len(self.ns_by_partition) < 2:
            ns = f"tenant-{i}"
            part = namespace_partition(ns, self.replicas)
            self.ns_by_partition.setdefault(part, ns)
            i += 1

    def _pod(self, rng, name, ns, prio):
        pod = _mk_pod(rng, name, labels={"scv/priority": str(prio)}, cpu=100)
        pod.namespace = ns
        return pod

    def tick(self, t: int, world: ScenarioWorld, rng) -> None:
        ns0 = self.ns_by_partition[0]
        ns1 = self.ns_by_partition[1]
        # partition skew: 75% of steady traffic on replica 0's tenant
        for i in range(12):
            world.submit(self._pod(rng, f"skew0-{t}-{i}", ns0, 0))
        for i in range(4):
            world.submit(self._pod(rng, f"skew1-{t}-{i}", ns1, 0))
        if t % 3 == 1:
            # conflict storm: filler occupies r0's current window so the
            # overlap pods land in its PREFETCHED window...
            for i in range(32):
                world.submit(self._pod(rng, f"filler-{t}-{i}", ns0, 10))
            # ...while the same overlap pods also enter r1's queue (the
            # handoff race) and bind there first — r0's prefetched copy
            # then loses the CAS
            for i in range(8):
                world.submit_overlap(
                    self._pod(rng, f"overlap-{t}-{i}", ns0, 5)
                )


class SoakScenario(Scenario):
    """A multi-hour soak compressed onto the virtual clock: TWO diurnal
    day cycles composed with periodic arrival bursts and node flaps —
    the traffic shape a long-lived deployment actually survives, run in
    minutes. This is what the trend gate (trace/trend.py) and the
    shadow scorer chew on: long enough for leak/drift slopes to mean
    something, rotated enough (config_overrides pins a small journal
    file size) that a live tailer crosses real file boundaries, and
    SLO-armed so the watchdog staying quiet is an assertable outcome
    (`make soak-smoke` checks slo_breaches == 0).
    """

    name = "soak"
    description = "compressed soak: diurnal x2 + bursts + node flaps"
    ticks = 48
    smoke = True
    config_overrides = {
        # force journal rotation during even a smoke-scale soak so the
        # shadow tailer's boundary-following is exercised end-to-end
        "trace_file_bytes": 1 << 16,
        # the watchdog is ARMED (not off) and expected to stay clean on
        # the virtual clock; a breach in a soak run is a finding. The
        # bound must clear the first-cycle JIT compile even on a loaded
        # smoke machine (a colocated shadow doubles wall time) while
        # still catching a genuinely wedged cycle
        "cycle_slo_ms": 15000.0,
    }

    def tick(self, t: int, world: ScenarioWorld, rng) -> None:
        base = max(2, int(self.n_nodes * self.intensity / 2))
        # two compressed day cycles across the run
        phase = 2.0 * math.pi * t / max(1, self.ticks // 2)
        n = max(1, int(base * (0.6 - 0.4 * math.cos(phase))))
        # the final eighth is a COOL-DOWN: diurnal tail only, no bursts
        # or flaps, so the trend gate's queue-depth series measures
        # drain health (a backlog surviving the cool-down is a real
        # runaway) instead of aliasing the injection schedule
        cooldown = t >= self.ticks - max(2, self.ticks // 8)
        if t % 12 == 6 and not cooldown:
            n *= 6  # rollout-style burst on top of the curve
        for i in range(n):
            world.submit(_mk_pod(rng, f"soak-t{t}-{i}"))
        if cooldown:
            for name in list(world.downed):
                world.restore_node(name)
            return
        if t >= 4 and t % 8 == 4 and world.nodes:
            k = max(1, len(world.nodes) // 16)
            names = [
                world.nodes[int(j)].name
                for j in rng.choice(
                    len(world.nodes), size=min(k, len(world.nodes)),
                    replace=False,
                )
            ]
            for name in names:
                world.fail_node(name)
        if t % 8 == 6:
            for name in list(world.downed):
                world.restore_node(name)


SCENARIOS = {
    s.name: s
    for s in (
        DiurnalScenario,
        BurstScenario,
        NodeFlapScenario,
        SoakScenario,
        ZoneFailureScenario,
        AntiAffinityPackScenario,
        GangMixScenario,
        AdvisorOutageScenario,
        SidecarCrashRestartScenario,
        RpcFlapScenario,
        DiskFullJournalScenario,
        MirrorCorruptionScenario,
        CompoundStormScenario,
        ReplicaConflictStormScenario,
    )
}
