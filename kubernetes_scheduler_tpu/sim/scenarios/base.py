"""Scenario harness: seeded, event-driven traffic programs over the host
loop.

A Scenario is a small deterministic program: the runner builds a
simulated cluster, then advances virtual time tick by tick — each tick
the scenario injects events (pod arrivals, node failures/returns,
utilization shifts) into the ScenarioWorld and the runner drains the
scheduler until it stops making progress. Everything downstream of the
seed is deterministic: the RNG is a single `np.random.default_rng(seed)`
stream, the queue runs on a virtual clock the runner advances one second
per tick (retry backoffs resolve in ticks, not wall time), and the
scheduler itself is single-threaded — so the same (scenario, seed,
scale) always produces the same journal, which is what lets every
scenario be REPLAY-PINNED: run it with `trace_path` set and
`trace replay` over the emitted journal must report zero binding diffs
(the scenario-smoke gate, and the diverse-traffic generator the
learned-policy ROADMAP item trains from).

Scenarios register by name in sim.scenarios.SCENARIOS (library.py) and
run via `yoda-tpu scenario run <name>` or run_scenario() directly.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from kubernetes_scheduler_tpu.host.advisor import NodeUtil, StaticAdvisor
from kubernetes_scheduler_tpu.host.scheduler import RecordingBinder, Scheduler
from kubernetes_scheduler_tpu.host.types import Node, Pod
from kubernetes_scheduler_tpu.utils.config import SchedulerConfig


class SimClock:
    """Deterministic stand-in for time.monotonic on the scheduling
    queue: the runner advances it one second per tick, so retry backoffs
    (initial 1s) resolve on the NEXT tick regardless of how fast the
    host machine drained the previous one."""

    def __init__(self):
        self.now = 0.0

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float = 1.0) -> None:
        self.now += dt


@dataclass
class ScenarioWorld:
    """The mutable cluster a scenario program acts on. All state changes
    go through these methods so the summary counters stay truthful."""

    nodes: list
    utils: dict
    scheduler: Scheduler
    running: list = field(default_factory=list)
    downed: dict = field(default_factory=dict)   # name -> Node
    submitted: int = 0
    resubmitted: int = 0
    node_failures: int = 0
    node_restores: int = 0
    # chaos runs (sim/faults.InformerGate): mirror-bound event delivery
    # routed through the informer-stream fault boundary — partitions
    # buffer, errors drop, everything else passes through
    informer_gate: object = None
    _seen_bindings: int = 0

    def submit(self, pod: Pod) -> None:
        self.submitted += 1
        self.scheduler.submit(pod)

    def _deliver(self, apply, *args) -> None:
        """One informer-style event delivery, through the fault gate
        when a chaos plan installed one."""
        if self.informer_gate is not None:
            self.informer_gate.deliver(apply, *args)
        else:
            apply(*args)

    def _mirror(self):
        """The scheduler's snapshot mirror when streaming ingestion is
        on (config.snapshot_mirror) — ScenarioWorld plays the informer's
        role then, delivering node/pod events instead of relying on the
        per-cycle list reads the mirror replaced. Bind events need no
        delivery: the scheduler self-applies its own binds."""
        return getattr(self.scheduler, "mirror", None)

    def fail_node(self, name: str) -> int:
        """Remove a node mid-run; its running pods are killed and
        resubmitted (the informer would deliver exactly this as a node
        delete + pod deletes + controller re-creates). Returns how many
        pods went back to the queue."""
        nd = next((n for n in self.nodes if n.name == name), None)
        if nd is None:
            return 0
        self.nodes.remove(nd)
        self.downed[name] = nd
        self.node_failures += 1
        mirror = self._mirror()
        if mirror is not None:
            self._deliver(mirror.apply_node_event, "DELETED", nd)
        displaced = [p for p in self.running if p.node_name == name]
        for pod in displaced:
            self.running.remove(pod)
            if mirror is not None:
                # the pod DELETE the informer would stream; the
                # controller's re-create is the submit below
                self._deliver(mirror.apply_pod_event, "DELETED", pod)
            pod.node_name = None
            self.resubmitted += 1
            self.scheduler.submit(pod)
        return len(displaced)

    def restore_node(self, name: str) -> bool:
        nd = self.downed.pop(name, None)
        if nd is None:
            return False
        self.nodes.append(nd)
        self.node_restores += 1
        mirror = self._mirror()
        if mirror is not None:
            self._deliver(mirror.apply_node_event, "ADDED", nd)
        return True

    def absorb_bindings(self) -> None:
        """Fold this drain's binds into the running set (what the
        informer's pod cache would reflect next cycle)."""
        binder = self.scheduler.binder
        for b in binder.bindings[self._seen_bindings:]:
            self.running.append(b.pod)
        self._seen_bindings = len(binder.bindings)


class Scenario:
    """One registered traffic program. Subclasses set `name`,
    `description`, optionally `smoke` (cheap enough for the
    scenario-smoke gate) and override build_cluster()/tick(). Chaos
    programs additionally set `chaos = True`, declare their
    SchedulerConfig knobs in `config_overrides`, and return a
    sim/faults.FaultPlan from fault_plan() — the runner then wraps the
    advisor/engine/journal boundaries and gates informer delivery, and
    the summary grows the recovery audit (degraded cycle counts,
    breaker states, ladder rungs, injected-fault counts, `recovered`).
    Replicated programs set `replicas` > 1 — scenarios.run then routes
    to run_scenario_replicated (a ReplicaFleet over the partitioned
    queue) and ticks receive a FleetScenarioWorld."""

    name = "?"
    description = ""
    ticks = 12
    smoke = False
    # chaos programs: deterministic fault injection rides this run
    chaos = False
    # replicated programs: N schedulers over a partitioned queue
    replicas = 1
    # SchedulerConfig overrides merged into scenario_config() when the
    # caller passes no explicit config (chaos programs pin the modes
    # their fault plan targets: mirror on, resident on, stale TTL, ...)
    config_overrides: dict = {}

    def fault_plan(self):
        """The sim/faults.FaultPlan for this program (None = no
        injection — every pre-chaos scenario)."""
        return None

    def __init__(self, *, n_nodes: int = 64, intensity: float = 1.0):
        self.n_nodes = int(n_nodes)
        self.intensity = float(intensity)

    # -- cluster -------------------------------------------------------

    def build_cluster(self, rng) -> tuple[list, dict]:
        """(nodes, utils) — zone-labeled by default so zone/affinity
        scenarios work against any cluster this base builds."""
        from kubernetes_scheduler_tpu.sim.scenarios.library import ZONES

        nodes, utils = [], {}
        for i in range(self.n_nodes):
            name = f"node-{i}"
            nodes.append(
                Node(
                    name=name,
                    labels={
                        "topology.kubernetes.io/zone": ZONES[i % len(ZONES)]
                    },
                    allocatable={
                        "cpu": float(rng.choice([4000, 8000, 16000])),
                        "memory": float(rng.choice([8, 16, 32])) * 2**30,
                        "pods": 110.0,
                    },
                )
            )
            utils[name] = NodeUtil(
                cpu_pct=float(rng.uniform(5, 70)),
                mem_pct=float(rng.uniform(5, 70)),
                disk_io=float(min(rng.gamma(2.0, 8.0), 50.0)),
                net_up=float(rng.gamma(2.0, 2.0)),
                net_down=float(rng.gamma(2.0, 2.0)),
            )
        return nodes, utils

    # -- per-tick program ----------------------------------------------

    def tick(self, t: int, world: ScenarioWorld, rng) -> None:
        raise NotImplementedError


def scenario_config(overrides: dict | None = None) -> SchedulerConfig:
    """The harness's SchedulerConfig: the device path pinned (tiny
    simulated cycles must not route to the scalar fallback — scalar
    cycles record decisions but are not replayable, and the whole point
    of a scenario is a replayable journal)."""
    base = dict(
        batch_window=256,
        normalizer="none",
        min_device_work=1,
        adaptive_dispatch=False,
    )
    base.update(overrides or {})
    return SchedulerConfig(**base)


def run_scenario(
    scenario: Scenario,
    *,
    seed: int = 0,
    trace_path: str | None = None,
    span_path: str | None = None,
    config: SchedulerConfig | None = None,
    max_cycles_per_tick: int = 64,
    faults: bool = True,
) -> dict:
    """Drive `scenario` through the host loop; returns the summary dict
    (one JSON-able line). With `trace_path`, every cycle lands in a
    flight-recorder journal replay-pinnable via `trace replay`; with
    `span_path`, every cycle emits its span timeline too, so an
    adversarial program produces attribution data (`spans report`) the
    same way a production run does."""
    rng = np.random.default_rng(seed)
    nodes, utils = scenario.build_cluster(rng)
    cfg = (
        config
        if config is not None
        else scenario_config(dict(scenario.config_overrides))
    )
    if (trace_path is not None and cfg.trace_path is None) or (
        span_path is not None and cfg.span_path is None
    ):
        import dataclasses

        cfg = dataclasses.replace(
            cfg,
            trace_path=cfg.trace_path or trace_path,
            span_path=cfg.span_path or span_path,
        )
    clock = SimClock()
    # chaos plan (sim/faults.py): wrap the boundaries the Scheduler/CLI
    # already own — advisor fetch, engine dispatch, journal writes —
    # and gate the world's informer-style event delivery. Everything
    # keys off the virtual clock, so the same (scenario, seed) injects
    # the same faults at the same ticks and the journal replay-pins.
    plan = scenario.fault_plan() if faults else None
    injector = None
    gate = None
    advisor = StaticAdvisor(utils)
    engine = None
    if plan is not None and plan.windows:
        from kubernetes_scheduler_tpu.engine import LocalEngine
        from kubernetes_scheduler_tpu.sim.faults import (
            FaultInjector,
            FaultyAdvisor,
            FaultyEngine,
            InformerGate,
        )

        injector = FaultInjector(plan, clock)
        advisor = FaultyAdvisor(advisor, injector)
        engine = FaultyEngine(LocalEngine(), injector)
        gate = InformerGate(injector)
    world = ScenarioWorld(nodes=nodes, utils=utils, scheduler=None)
    sched = Scheduler(
        cfg,
        advisor=advisor,
        binder=RecordingBinder(),
        engine=engine,
        list_nodes=lambda: world.nodes,
        list_running_pods=lambda: world.running,
        queue_clock=clock,
    )
    world.scheduler = sched
    world.informer_gate = gate
    if injector is not None:
        injector.wrap_journal(sched.recorder)

    t0 = time.perf_counter()
    cycles = 0
    try:
        for t in range(scenario.ticks):
            if gate is not None:
                # a closed partition window flushes its buffered events
                # at the tick boundary (the re-established watch)
                gate.flush()
            scenario.tick(t, world, rng)
            clock.advance(1.0)
            for _ in range(max_cycles_per_tick):
                if len(sched.queue) == 0 and sched._prefetched is None:
                    break
                m = sched.run_cycle()
                cycles += 1
                world.absorb_bindings()
                if m.pods_bound == 0:
                    # no progress: everything left is backoff-parked or
                    # a deferred gang waiting for members — both need
                    # the clock to advance, i.e. the next tick
                    break
        if gate is not None:
            gate.flush()
        sched.drain_pipeline()
    finally:
        if sched.recorder is not None:
            sched.recorder.close()
        if sched.spans is not None:
            sched.spans.close()
    dt = time.perf_counter() - t0
    totals = sched.totals
    out = {
        "scenario": scenario.name,
        "seed": seed,
        "n_nodes": scenario.n_nodes,
        "ticks": scenario.ticks,
        "cycles": cycles,
        "pods_submitted": world.submitted,
        "pods_resubmitted": world.resubmitted,
        "pods_bound": totals["pods_bound"],
        "pods_unschedulable": totals["pods_unschedulable"],
        "node_failures": world.node_failures,
        "node_restores": world.node_restores,
        "fallback_cycles": totals["fallback_cycles"],
        "gangs_admitted": totals["gangs_admitted"],
        "gangs_deferred": totals["gangs_deferred"],
        "gang_pods_masked": totals["gang_pods_masked"],
        "delta_uploads": totals["delta_uploads"],
        "full_uploads": totals["full_uploads"],
        "seconds": round(dt, 3),
        "pods_per_sec": round(totals["pods_bound"] / max(dt, 1e-9), 1),
        # resilience audit (host/resilience.py): how degraded the run
        # got, whether it climbed all the way back, and what the
        # breakers did — the chaos-scenario recovery gate reads these
        "fetch_failures": totals["fetch_failures"],
        "advisor_stale_cycles": totals["advisor_stale_cycles"],
        "degraded_cycles": totals["degraded_cycles"],
        "breaker_state": sched.engine_breaker.state(),
        "breaker_transitions": dict(sched.engine_breaker.transition_counts),
        "advisor_breaker_state": sched.advisor_breaker.state(),
        "degradation_rungs": {
            sub: info["rung"]
            for sub, info in sched.ladder.snapshot().items()
            if info["depth"] > 0
        },
        "recovered": (
            sched.ladder.fully_recovered()
            and sched.engine_breaker.state() == "closed"
            and sched.advisor_breaker.state() == "closed"
        ),
        # SLO watchdog verdict (config.cycle_slo_ms): a soak run asserts
        # the armed watchdog stayed QUIET — "watchdog clean" is an
        # outcome, not the absence of instrumentation
        "slo_breaches": int(getattr(sched, "slo_breaches", 0)),
    }
    if sched.recorder is not None:
        out["trace_records_dropped"] = sched.recorder.records_dropped
    if sched.mirror is not None:
        out["mirror_full_rebuilds"] = int(sched.mirror.ctr_rebuilds.total())
        out["mirror_rebuild_reasons"] = {
            key[0]: int(n)
            for key, n in sorted(sched.mirror.ctr_rebuilds.breakdown().items())
        }
        out["mirror_verify_failures"] = int(
            sched.mirror.ctr_verify_failures.value()
        )
    if injector is not None:
        out["faults_injected"] = injector.summary()
        if gate is not None:
            out["informer_events_dropped"] = gate.dropped
    if trace_path is not None:
        out["journal"] = trace_path
    if span_path is not None:
        out["spans"] = span_path
    return out


class FleetScenarioWorld(ScenarioWorld):
    """ScenarioWorld over a ReplicaFleet: submissions route to their
    partition's replica (or to SEVERAL replicas via submit_overlap —
    the partition-handoff race the replica-bind protocol resolves), and
    absorb_bindings folds every replica's recorded binds. Node-failure
    chaos is not wired for fleets yet (`scheduler` stays None so a
    fleet scenario reaching for it fails loudly, not silently)."""

    def __init__(self, *, nodes, utils, fleet=None):
        super().__init__(nodes=nodes, utils=utils, scheduler=None)
        self.fleet = fleet
        self._seen_per: list[int] = []

    def attach(self, fleet) -> None:
        self.fleet = fleet
        self._seen_per = [0] * fleet.n_replicas

    def submit(self, pod: Pod) -> None:
        self.submitted += 1
        self.fleet.submit(pod)

    def submit_overlap(self, pod: Pod, replicas=None) -> None:
        """The conflict generator: the SAME pod lands in several
        replicas' queues (membership churn re-homing a namespace while
        the old owner still holds queued copies). Counted once — it is
        one pod, however many queues transiently hold it."""
        self.submitted += 1
        self.fleet.submit_overlap(pod, replicas)

    def absorb_bindings(self) -> None:
        for i, sched in enumerate(self.fleet.schedulers):
            bindings = sched.binder.bindings
            for b in bindings[self._seen_per[i]:]:
                self.running.append(b.pod)
            self._seen_per[i] = len(bindings)


def run_scenario_replicated(
    scenario: Scenario,
    *,
    seed: int = 0,
    trace_path: str | None = None,
    span_path: str | None = None,
    config: SchedulerConfig | None = None,
    max_cycles_per_tick: int = 64,
    faults: bool = True,
) -> dict:
    """run_scenario for `scenario.replicas` > 1: N full Schedulers over
    one PartitionedQueue + BindTable (host/replica.ReplicaFleet), drained
    in deterministic ROUND-ROBIN — one cycle per live replica per round,
    single-threaded on the shared virtual clock, so the same (scenario,
    seed, scale) produces the same per-replica journals every run and
    each journal replay-pins independently (`trace replay <dir>/r0`).

    Round-robin at cycle granularity plus the pipelined prefetch slot is
    what makes conflicts REAL here: with pipeline_depth=1 a replica pops
    its next window while its current one binds, so an overlap pod can
    sit popped-but-unbound on replica A across the round in which
    replica B binds its copy — A's bind then loses the CAS (bind_lose:
    requeue + 409-drop), and A's next pop retires the requeued copy via
    drop_bound. The exact interleaving the model checks, produced
    deterministically."""
    del faults  # fleet scenarios carry no fault plan yet
    rng = np.random.default_rng(seed)
    nodes, utils = scenario.build_cluster(rng)
    cfg = (
        config
        if config is not None
        else scenario_config(dict(scenario.config_overrides))
    )
    if (trace_path is not None and cfg.trace_path is None) or (
        span_path is not None and cfg.span_path is None
    ):
        import dataclasses

        cfg = dataclasses.replace(
            cfg,
            trace_path=cfg.trace_path or trace_path,
            span_path=cfg.span_path or span_path,
        )
    from kubernetes_scheduler_tpu.host.replica import ReplicaFleet

    clock = SimClock()
    advisor = StaticAdvisor(utils)
    world = FleetScenarioWorld(nodes=nodes, utils=utils)
    fleet = ReplicaFleet(
        cfg,
        n_replicas=scenario.replicas,
        advisor_factory=lambda i: advisor,
        list_nodes=lambda: world.nodes,
        list_running_pods=lambda: world.running,
        queue_clock=clock,
    )
    world.attach(fleet)

    t0 = time.perf_counter()
    cycles = 0
    try:
        for t in range(scenario.ticks):
            scenario.tick(t, world, rng)
            clock.advance(1.0)
            for _ in range(max_cycles_per_tick):
                progressed = False
                active = False
                if fleet.engine_pool is not None:
                    # shared engine: split-phase round — dispatch EVERY
                    # live replica before the first force, so the whole
                    # round's windows coalesce into one device
                    # invocation (the deterministic round-robin
                    # equivalent of the timing a threaded fleet gets)
                    live = [
                        s for s in fleet.schedulers
                        if len(s.queue) > 0 or s._prefetched is not None
                    ]
                    if live:
                        active = True
                        handles = [s.run_cycle_split() for s in live]
                        for h in handles:
                            m = h.complete()
                            cycles += 1
                            world.absorb_bindings()
                            if m.pods_bound > 0 or m.pods_dropped > 0:
                                progressed = True
                else:
                    for sched in fleet.schedulers:
                        if len(sched.queue) == 0 and sched._prefetched is None:
                            continue
                        active = True
                        m = sched.run_cycle()
                        cycles += 1
                        world.absorb_bindings()
                        # a conflict cycle binds 0 but DROPS its fenced
                        # copies — that is progress (the queue shrank)
                        if m.pods_bound > 0 or m.pods_dropped > 0:
                            progressed = True
                if not active or not progressed:
                    break
        for sched in fleet.schedulers:
            sched.drain_pipeline()
            world.absorb_bindings()
    finally:
        for sched in fleet.schedulers:
            if sched.recorder is not None:
                sched.recorder.close()
            if sched.spans is not None:
                sched.spans.close()
    dt = time.perf_counter() - t0

    def _total(key):
        return sum(s.totals[key] for s in fleet.schedulers)

    evidence = fleet.evidence()
    out = {
        "scenario": scenario.name,
        "seed": seed,
        "n_nodes": scenario.n_nodes,
        "ticks": scenario.ticks,
        "replicas": scenario.replicas,
        "cycles": cycles,
        "pods_submitted": world.submitted,
        "pods_resubmitted": world.resubmitted,
        "pods_bound": _total("pods_bound"),
        "pods_unschedulable": _total("pods_unschedulable"),
        "pods_dropped": _total("pods_dropped"),
        "fallback_cycles": _total("fallback_cycles"),
        "gangs_admitted": _total("gangs_admitted"),
        "gangs_deferred": _total("gangs_deferred"),
        "seconds": round(dt, 3),
        "pods_per_sec": round(_total("pods_bound") / max(dt, 1e-9), 1),
        # the replica-bind evidence: conflicts RESOLVED, zero double
        # binds, every overlap pod bound exactly once somewhere
        "binds_per_replica": evidence["binds_per_replica"],
        "bind_conflicts": evidence["bind_conflicts_total"],
        "pods_discarded": evidence["pods_discarded"],
        "double_binds": evidence["double_binds"],
        "requeue_latency_mean_s": round(
            evidence["requeue_latency_mean_s"], 3
        ),
        "recovered": all(
            s.ladder.fully_recovered() for s in fleet.schedulers
        ),
    }
    if "shared_engine" in evidence:
        # fleet-shared engine evidence: dispatch coalescing + upload
        # dedupe (the replica-smoke --shared-engine leg asserts on these)
        out["shared_engine"] = evidence["shared_engine"]
    if trace_path is not None:
        out["journal"] = trace_path
        out["journals"] = [
            f"{trace_path}/r{i}" for i in range(scenario.replicas)
        ]
    if span_path is not None:
        out["spans"] = span_path
    return out
