"""Adaptive scalar-vs-device dispatch: the learned crossover."""

import pytest

from kubernetes_scheduler_tpu.host import NodeUtil
from kubernetes_scheduler_tpu.utils.adaptive import AdaptiveDispatch, PathModel
from tests.test_host import make_node, make_pod, make_sched


def test_path_model_fits_affine_latency():
    m = PathModel()
    # device-like: 20ms dispatch + 2ns/cell
    for cells in (1_000, 50_000, 2_000_000, 8_000_000, 300, 5_000_000):
        m.observe(cells, 0.020 + 2e-9 * cells)
    assert abs(m.predict(0) - 0.020) < 0.002
    assert abs(m.predict(10_000_000) - 0.040) < 0.004


def test_dispatch_learns_deployment_specific_crossover():
    """Same static prior, two deployments: against a tunneled chip (20ms
    dispatch) the crossover sits ~10M cells; against a colocated sidecar
    (1ms) it sits ~0.5M. The model must find both from observations."""
    for overhead, crossover_cells in ((0.020, 10_000_000), (0.001, 500_000)):
        d = AdaptiveDispatch(1 << 20, explore_every=10**9)
        scalar_rate = 2e-9   # ~C++ scalar ns/cell
        device_rate = 1e-11  # device compute amortized
        d.observe(True, 1_000, 5.0)   # jit-compile warmup, discarded
        for cells in (1_000, 100_000, 3_000_000, 20_000_000, 40_000):
            d.observe(False, cells, scalar_rate * cells)
            d.observe(True, cells, overhead + device_rate * cells)
        # well below crossover -> scalar; well above -> device
        assert not d.decide(crossover_cells // 20), overhead
        assert d.decide(crossover_cells * 20), overhead


def test_dispatch_cold_start_uses_threshold_then_samples_both():
    d = AdaptiveDispatch(1 << 20, min_obs=2)
    assert not d.decide(100)          # below threshold
    assert d.decide(1 << 21)          # above threshold
    # feed only scalar observations: it must force device samples
    d.observe(False, 1000, 1e-5)
    d.observe(False, 1000, 1e-5)
    assert d.decide(100)              # forced device exploration
    d.observe(True, 1000, 3.0)        # first device cycle = jit compile
    d.observe(True, 1000, 2e-2)
    d.observe(True, 1000, 2e-2)
    # both fitted: tiny cycle -> scalar (20ms device overhead dominates);
    # the 3s compile warmup was discarded, not fitted
    assert not d.decide(1000)
    assert d.device.predict(1000) < 0.5


def test_dispatch_periodic_exploration_flips_choice_within_cap():
    d = AdaptiveDispatch(0, min_obs=1, explore_every=5)
    d.observe(True, 1000, 9.0)        # warmup discard
    d.observe(False, 1000, 1e-3)
    d.observe(True, 1000, 2e-3)       # underdog within the 10x cap
    choices = [d.decide(1000) for _ in range(10)]
    assert choices.count(True) == 2   # every 5th flips to the underdog
    assert choices.count(False) == 8


def test_dispatch_exploration_suppressed_beyond_cap():
    """A path predicted 1000x slower is never 'explored' into — that
    would be a recurring latency spike, not an experiment."""
    d = AdaptiveDispatch(0, min_obs=1, explore_every=5)
    d.observe(True, 1000, 9.0)        # warmup discard
    d.observe(False, 1_000_000, 2.0)  # scalar: 2s (python rescore loop)
    d.observe(True, 1_000_000, 2e-3)
    choices = [d.decide(1_000_000) for _ in range(20)]
    assert all(choices)               # device always, no scalar spikes


def test_cold_start_forced_scalar_bounded():
    """Forced cold-start scalar sampling must not route a huge window
    through the scalar path (the unbounded-latency-spike case)."""
    d = AdaptiveDispatch(1 << 20, min_obs=2)
    d.observe(True, 1 << 22, 9.0)     # warmup discard
    d.observe(True, 1 << 22, 2e-2)
    d.observe(True, 1 << 22, 2e-2)
    # device fitted, scalar unobserved: force scalar only near threshold
    assert not d.decide(1 << 20)      # forced scalar sample (bounded size)
    assert d.decide(1 << 26)          # 64x threshold: stays on device


def test_rls_no_covariance_windup_under_constant_excitation():
    """Steady state means a CONSTANT cycle shape: with exponential
    forgetting the covariance grows without bound in the unexcited
    direction and (untreated) overflows to inf after ~35k observations,
    wedging dispatch with NaN predictions. The trace ceiling must keep
    theta finite and predictions sane through 100k identical cycles."""
    import math

    m = PathModel()
    for _ in range(100_000):
        m.observe(4096, 2e-3)
    assert math.isfinite(m.predict(4096))
    assert m.predict(4096) == pytest.approx(2e-3, rel=0.05)
    # still adapts after the long constant stretch (exponential window:
    # 100 fresh samples carry weight 1 - 0.98^100 ~ 0.87 of the fit)
    for _ in range(100):
        m.observe(4096, 8e-3)
    assert m.predict(4096) == pytest.approx(8e-3, rel=0.15)


def test_fast_failing_device_path_priced_at_full_cycle_cost():
    """A sidecar that fails in ~1ms must not be learned as a ~1ms device
    path: the scheduler prices a failed device cycle at failed attempt +
    scalar fallback, so the model routes away from a broken path."""
    nodes = [make_node(f"n{i}", cpu=8000) for i in range(3)]
    utils = {f"n{i}": NodeUtil(cpu_pct=10, disk_io=5) for i in range(3)}
    s = make_sched(nodes, [], utils, adaptive_dispatch=True)

    def boom(*a, **k):
        raise RuntimeError("connect refused")

    s._run_batched = boom
    s._dispatch.observe(True, 10, 0.5)  # burn warmup discard

    # instrument AFTER the warmup discard: pair every device-path
    # observation with the scalar fallback time measured INSIDE that same
    # cycle — the priced duration brackets the fallback, so the invariant
    # (price >= its own fallback work) holds under any machine load,
    # unlike a cross-model predict() comparison of two real-time fits
    # (flaky under parallel test runners)
    import time as _time

    fallback_time: list[float] = []
    orig_scalar = s._run_scalar

    def timed_scalar(*a, **k):
        t0 = _time.perf_counter()
        r = orig_scalar(*a, **k)
        fallback_time.append(_time.perf_counter() - t0)
        return r

    s._run_scalar = timed_scalar
    priced: list[tuple[float, float]] = []
    orig_obs = s._dispatch.observe

    def spy_obs(is_device, cells, dur):
        if is_device and fallback_time:
            priced.append((dur, fallback_time[-1]))
        return orig_obs(is_device, cells, dur)

    s._dispatch.observe = spy_obs
    for i in range(8):
        s.submit(make_pod(f"p{i}", cpu=10, annotations={"diskIO": "1"}))
        m = s.run_cycle()
        assert m.pods_bound == 1 and m.used_fallback
    # at least one cycle attempted (and failed) the device path, and every
    # failed attempt was priced at >= the fallback work it had to invoke
    assert priced
    assert all(dur >= fb for dur, fb in priced)


def test_retrace_compile_spike_filtered_but_regime_shift_believed():
    d = AdaptiveDispatch(0, min_obs=2)
    d.observe(True, 1000, 9.0)        # first-compile warmup
    for _ in range(3):
        d.observe(True, 1000, 2e-2)
        d.observe(False, 1000, 1e-3)
    base = d.device.predict(1000)
    d.observe(True, 1000, 5.0)        # retrace spike: filtered
    assert abs(d.device.predict(1000) - base) < 1e-3
    # three consecutive slow samples = the device really got slower
    d.observe(True, 1000, 5.0)
    d.observe(True, 1000, 5.0)
    assert d.device.predict(1000) > 0.5
