"""Preemption kernel (upstream PostFilter parity): victim selection."""

import numpy as np
import jax.numpy as jnp
import pytest

from kubernetes_scheduler_tpu.ops.preempt import (
    PRIO_PAD,
    build_victim_tables,
    preempt_candidates,
)


def run(
    pend_req, pend_prio, static_ok, free, vnode, vprio, vreq, k_cap=4,
    vstart=None,
):
    p = len(pend_prio)
    m = len(vprio)
    tables = build_victim_tables(
        jnp.asarray(vnode, jnp.int32), jnp.asarray(vprio, jnp.int32),
        jnp.asarray(vreq, jnp.float32), jnp.ones(m, bool),
        n_nodes=free.shape[0], k_cap=k_cap,
        victim_start=(
            None if vstart is None else jnp.asarray(vstart, jnp.int32)
        ),
    )
    return preempt_candidates(
        jnp.asarray(pend_req, jnp.float32), jnp.asarray(pend_prio, jnp.int32),
        jnp.ones(p, bool), jnp.asarray(static_ok), jnp.asarray(free, jnp.float32),
        tables,
    )


def oracle_one(
    req, prio, static_ok_row, free, vnode, vprio, vreq, k_cap, vstart=None
):
    """Reference semantics, brute force: per node, evict least-important
    victims (strictly below prio; importance = priority asc, start desc)
    one at a time until the pod fits (up to k_cap); among feasible nodes
    pick by upstream pickOneNodeForPreemption order: (highest victim
    priority, sum of victim priorities, count, LATEST highest-victim
    start, node index)."""
    if vstart is None:
        vstart = [0] * len(vprio)
    best = None
    for n in range(free.shape[0]):
        if not static_ok_row[n]:
            continue
        vics = sorted(
            [i for i in range(len(vprio)) if vnode[i] == n and vprio[i] < prio],
            key=lambda i: (vprio[i], -vstart[i]),
        )
        for k in range(1, min(k_cap, len(vics)) + 1):
            cap = free[n] + sum(vreq[i] for i in vics[:k])
            if all(req[j] <= cap[j] or req[j] == 0 for j in range(len(req))):
                cand = (
                    vprio[vics[k - 1]],
                    sum(vprio[i] for i in vics[:k]),
                    k,
                    -vstart[vics[k - 1]],
                    n,
                    [int(i) for i in vics[:k]],
                )
                if best is None or cand[:5] < best[:5]:
                    best = cand
                break
    return best


def test_minimal_victims_lowest_priority_first():
    # node 0 hosts victims prio 1, 2, 5; pod prio 4, needs 2 units freed
    free = np.array([[0.0], [0.0]])
    vnode = [0, 0, 0]
    vprio = [2, 1, 5]
    vreq = np.array([[1.0], [1.0], [10.0]])
    res = run(
        pend_req=[[2.0]], pend_prio=[4], static_ok=[[True, True]],
        free=free, vnode=vnode, vprio=vprio, vreq=vreq,
    )
    assert int(res.node[0]) == 0
    assert int(res.n_victims[0]) == 2
    vics = set(int(v) for v in np.asarray(res.victims[0]) if v >= 0)
    assert vics == {0, 1}  # the two low-priority victims, never prio-5


def test_never_evicts_equal_or_higher_priority():
    free = np.array([[0.0]])
    res = run(
        pend_req=[[1.0]], pend_prio=[3], static_ok=[[True]],
        free=free, vnode=[0, 0], vprio=[3, 7], vreq=np.array([[5.0], [5.0]]),
    )
    assert int(res.node[0]) == -1
    assert int(res.n_victims[0]) == 0
    assert (np.asarray(res.victims[0]) == -1).all()


def test_prefers_node_with_lowest_max_victim_priority():
    # both nodes feasible with one victim; node 1's victim has lower prio
    free = np.array([[0.0], [0.0]])
    res = run(
        pend_req=[[1.0]], pend_prio=[9], static_ok=[[True, True]],
        free=free, vnode=[0, 1], vprio=[5, 2], vreq=np.array([[1.0], [1.0]]),
    )
    assert int(res.node[0]) == 1


def test_prefers_fewer_victims_at_equal_max_priority():
    # node 0: one prio-2 victim frees enough; node 1: two prio-(1,2) needed
    free = np.array([[0.0], [0.0]])
    res = run(
        pend_req=[[2.0]], pend_prio=[9], static_ok=[[True, True]],
        free=free, vnode=[0, 1, 1], vprio=[2, 1, 2],
        vreq=np.array([[2.0], [1.0], [1.0]]),
    )
    assert int(res.node[0]) == 0
    assert int(res.n_victims[0]) == 1


def test_static_infeasible_node_excluded():
    free = np.array([[0.0], [0.0]])
    res = run(
        pend_req=[[1.0]], pend_prio=[9], static_ok=[[False, True]],
        free=free, vnode=[0, 1], vprio=[1, 5], vreq=np.array([[9.0], [9.0]]),
    )
    assert int(res.node[0]) == 1


def test_k_cap_bounds_victim_count():
    # four prio-1 victims each freeing 1; pod needs 4 but k_cap=2
    free = np.array([[0.0]])
    res = run(
        pend_req=[[4.0]], pend_prio=[9], static_ok=[[True]],
        free=free, vnode=[0] * 4, vprio=[1] * 4,
        vreq=np.ones((4, 1)), k_cap=2,
    )
    assert int(res.node[0]) == -1


def test_free_capacity_counts_toward_fit():
    # node already has 3 free; evicting one prio-1 victim (1 unit) fits a 4
    free = np.array([[3.0]])
    res = run(
        pend_req=[[4.0]], pend_prio=[9], static_ok=[[True]],
        free=free, vnode=[0], vprio=[1], vreq=np.array([[1.0]]),
    )
    assert int(res.node[0]) == 0 and int(res.n_victims[0]) == 1


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_matches_bruteforce_oracle(seed):
    rng = np.random.default_rng(seed)
    p, n, m, r, k_cap = 6, 5, 18, 2, 4
    pend_req = rng.uniform(0.5, 6.0, (p, r)).astype(np.float32)
    pend_prio = rng.integers(0, 10, p).astype(np.int32)
    static_ok = rng.random((p, n)) > 0.2
    free = rng.uniform(0.0, 2.0, (n, r)).astype(np.float32)
    vnode = rng.integers(0, n, m).astype(np.int32)
    vprio = rng.integers(0, 10, m).astype(np.int32)
    vreq = rng.uniform(0.2, 3.0, (m, r)).astype(np.float32)
    # coarse start times so (priority, start) ties actually occur
    vstart = rng.integers(0, 3, m).astype(np.int32)

    res = run(pend_req, pend_prio, static_ok, free, vnode, vprio, vreq,
              k_cap=k_cap, vstart=vstart)
    for i in range(p):
        want = oracle_one(
            pend_req[i], int(pend_prio[i]), static_ok[i], free,
            vnode, vprio, vreq, k_cap, vstart=vstart,
        )
        got_node = int(res.node[i])
        if want is None:
            assert got_node == -1, (seed, i)
        else:
            assert got_node == want[4], (seed, i, want, got_node)
            assert int(res.n_victims[i]) == want[2]
            got_v = sorted(int(v) for v in np.asarray(res.victims[i]) if v >= 0)
            # same victim SET by (priority, start); ties may reorder —
            # compare multisets of sort keys
            assert sorted((vprio[j], vstart[j]) for j in got_v) == sorted(
                (vprio[j], vstart[j]) for j in want[5]
            )


def test_equal_priority_victims_evict_latest_started_first():
    """Upstream MoreImportantPod: among equal-priority victims the most
    recently started is least important and evicted first."""
    free = np.array([[0.0]])
    # two prio-1 victims on node 0; victim 1 started LATER (t=100)
    res = run(
        pend_req=[[1.0]], pend_prio=[9], static_ok=[[True]],
        free=free, vnode=[0, 0], vprio=[1, 1],
        vreq=np.array([[1.0], [1.0]]), vstart=[10, 100],
    )
    assert int(res.node[0]) == 0 and int(res.n_victims[0]) == 1
    vics = [int(v) for v in np.asarray(res.victims[0]) if v >= 0]
    assert vics == [1], "the later-started equal-priority victim goes first"


def test_node_tie_broken_by_latest_highest_victim_start():
    """Upstream pickOneNodeForPreemption criterion 5: with equal highest
    victim priority, priority sum and count, pick the node whose
    highest-priority victim started LATEST."""
    free = np.array([[0.0], [0.0]])
    res = run(
        pend_req=[[1.0]], pend_prio=[9], static_ok=[[True, True]],
        free=free, vnode=[0, 1], vprio=[3, 3],
        vreq=np.array([[1.0], [1.0]]), vstart=[50, 200],
    )
    assert int(res.node[0]) == 1


def test_priority_sum_no_int32_overflow():
    """k8s PriorityClass values reach 2e9; a 3-victim prefix sum
    overflows int32. The two-limb psum must still order criterion 3
    correctly (review finding r4: a wrapped-negative sum beat a valid
    smaller one)."""
    big_prio = 1_000_000_000
    free = np.array([[0.0], [0.0]])
    # pod needs 3 units. node 0: three victims at 1e9 (sum 3e9 — wraps
    # int32). node 1: three victims at (1e9, 1e9, 0) — sum 2e9 (also
    # past int32 max). maxprio ties at 1e9; node 1's TRUE sum is lower.
    res = run(
        pend_req=[[3.0]], pend_prio=[2_000_000_000],
        static_ok=[[True, True]], free=free,
        vnode=[0, 0, 0, 1, 1, 1],
        vprio=[big_prio] * 3 + [big_prio, big_prio, 0],
        vreq=np.ones((6, 1)),
    )
    assert int(res.node[0]) == 1


def test_node_tie_broken_by_lower_priority_sum():
    """Upstream criterion 3: equal highest victim priority, lower SUM of
    victim priorities wins even with MORE victims."""
    free = np.array([[0.0], [0.0]])
    # pod needs 2 units. node 0: victims prio (4, 4) — sum 8, count 2.
    # node 1: victims prio (0, 4) — sum 4, count 2. Equal maxprio 4 and
    # count; node 1's sum is lower.
    res = run(
        pend_req=[[2.0]], pend_prio=[9], static_ok=[[True, True]],
        free=free, vnode=[0, 0, 1, 1], vprio=[4, 4, 0, 4],
        vreq=np.array([[1.0], [1.0], [1.0], [1.0]]),
    )
    assert int(res.node[0]) == 1


# ---- host integration: the PostFilter pass in the scheduling loop ------


def _cluster():
    from kubernetes_scheduler_tpu.host import NodeUtil
    from tests.test_host import make_node, make_pod

    nodes = [make_node("n0", cpu=1000), make_node("n1", cpu=1000)]
    utils = {n.name: NodeUtil(cpu_pct=10, disk_io=5) for n in nodes}
    low0 = make_pod("low0", cpu=900, labels={"scv/priority": "1"})
    low0.node_name = "n0"
    low1 = make_pod("low1", cpu=900, labels={"scv/priority": "2"})
    low1.node_name = "n1"
    return nodes, utils, [low0, low1]


def _sched(nodes, utils, running, evictor=None, controller_replicas=None, **cfg):
    from kubernetes_scheduler_tpu.host import RecordingEvictor, Scheduler, StaticAdvisor
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    cfg.setdefault("batch_window", 8)
    cfg.setdefault("min_device_work", 0)
    cfg.setdefault("adaptive_dispatch", False)
    return Scheduler(
        SchedulerConfig(**cfg),
        advisor=StaticAdvisor(utils),
        evictor=evictor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
        controller_replicas=controller_replicas,
    )


def test_host_preempts_lowest_priority_victim_then_binds():
    from kubernetes_scheduler_tpu.host import RecordingEvictor
    from tests.test_host import make_pod

    nodes, utils, running = _cluster()
    ev = RecordingEvictor()
    s = _sched(nodes, utils, running, evictor=ev)
    pend = make_pod("urgent", cpu=800, labels={"scv/priority": "9"},
                    annotations={"diskIO": "5"})
    s.submit(pend)
    m = s.run_cycle()
    assert m.pods_bound == 0 and m.pods_unschedulable == 1
    assert m.pods_preempted == 1 and m.victims_evicted == 1
    assert len(ev.evictions) == 1
    # lowest priority victim goes (prio 1 on n0, not prio 2 on n1)
    assert ev.evictions[0].victim.name == "low0"
    assert ev.evictions[0].preemptor.name == "urgent"

    # victim terminates; capacity frees; the requeued preemptor binds
    running.remove(ev.evictions[0].victim)
    s.queue._clock = lambda: 1e9  # jump past the retry backoff
    m2 = s.run_cycle()
    assert m2.pods_bound == 1
    assert s.binder.bindings[-1].node_name == "n0"


def test_host_preemption_routes_through_engine_surface():
    """The preemption pass runs on self.engine (the sidecar's Preempt RPC
    in a bridged deployment); a version-skewed engine without the surface
    degrades to the in-host evaluation with identical evictions."""
    from kubernetes_scheduler_tpu.engine import LocalEngine
    from kubernetes_scheduler_tpu.host import RecordingEvictor
    from tests.test_host import make_pod

    calls = []

    class SpyEngine(LocalEngine):
        def preempt(self, snapshot, pods, victims, *, k_cap):
            calls.append(k_cap)
            return super().preempt(snapshot, pods, victims, k_cap=k_cap)

    nodes, utils, running = _cluster()
    ev = RecordingEvictor()
    s = _sched(nodes, utils, running, evictor=ev)
    s.engine = SpyEngine()
    s.submit(make_pod("urgent", cpu=800, labels={"scv/priority": "9"},
                      annotations={"diskIO": "5"}))
    m = s.run_cycle()
    assert calls, "preemption did not route through the engine surface"
    assert m.pods_preempted == 1 and ev.evictions[0].victim.name == "low0"

    class SkewedEngine(LocalEngine):
        def preempt(self, *a, **k):
            raise NotImplementedError("old sidecar")

    nodes2, utils2, running2 = _cluster()
    ev2 = RecordingEvictor()
    s2 = _sched(nodes2, utils2, running2, evictor=ev2)
    s2.engine = SkewedEngine()
    s2.submit(make_pod("urgent", cpu=800, labels={"scv/priority": "9"},
                       annotations={"diskIO": "5"}))
    m2 = s2.run_cycle()
    assert m2.pods_preempted == 1 and ev2.evictions[0].victim.name == "low0"


def test_host_preemption_over_live_bridge():
    """Full integration of the Preempt RPC: a host Scheduler wired to a
    RemoteEngine runs its preemption pass on the sidecar (no in-host
    fallback), and the evictions match the local-engine decisions."""
    from kubernetes_scheduler_tpu.bridge.client import RemoteEngine
    from kubernetes_scheduler_tpu.bridge.server import make_server
    from kubernetes_scheduler_tpu.host import RecordingEvictor
    from tests.test_host import make_pod

    server, port, service = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=120.0)
    try:
        nodes, utils, running = _cluster()
        ev = RecordingEvictor()
        s = _sched(nodes, utils, running, evictor=ev)
        s.engine = client
        s.submit(make_pod("urgent", cpu=800, labels={"scv/priority": "9"},
                          annotations={"diskIO": "5"}))
        before = service.cycles_served
        m = s.run_cycle()
        assert m.pods_preempted == 1
        assert ev.evictions[0].victim.name == "low0"
        # the sidecar served BOTH the schedule cycle and the preempt pass
        assert service.cycles_served >= before + 2
    finally:
        client.close()
        server.stop(grace=None)


def test_host_no_preemption_without_higher_priority():
    from kubernetes_scheduler_tpu.host import RecordingEvictor
    from tests.test_host import make_pod

    nodes, utils, running = _cluster()
    ev = RecordingEvictor()
    s = _sched(nodes, utils, running, evictor=ev)
    s.submit(make_pod("peer", cpu=800, labels={"scv/priority": "1"}))
    m = s.run_cycle()
    assert m.pods_unschedulable == 1 and m.pods_preempted == 0
    assert not ev.evictions


def test_host_preemption_disabled_by_config_or_missing_evictor():
    from kubernetes_scheduler_tpu.host import RecordingEvictor
    from tests.test_host import make_pod

    nodes, utils, running = _cluster()
    ev = RecordingEvictor()
    s = _sched(nodes, utils, running, evictor=ev, preemption=False)
    s.submit(make_pod("urgent", cpu=800, labels={"scv/priority": "9"}))
    assert s.run_cycle().pods_preempted == 0 and not ev.evictions

    s2 = _sched(nodes, utils, running)  # no evictor wired
    s2.submit(make_pod("urgent2", cpu=800, labels={"scv/priority": "9"}))
    assert s2.run_cycle().pods_preempted == 0


def test_host_one_preemptor_per_node_per_cycle():
    from kubernetes_scheduler_tpu.host import RecordingEvictor
    from tests.test_host import make_pod

    nodes, utils, running = _cluster()
    ev = RecordingEvictor()
    s = _sched(nodes, utils, running, evictor=ev)
    s.submit(make_pod("u1", cpu=800, labels={"scv/priority": "9"}))
    s.submit(make_pod("u2", cpu=800, labels={"scv/priority": "8"}))
    m = s.run_cycle()
    # both independently choose n0 (lowest victim priority); only the
    # higher-priority preemptor is served — a second proposal for the
    # same node was computed assuming the first's victims still hold
    # their capacity, so it must wait for a recomputed pass
    assert m.pods_preempted == 1 and m.victims_evicted == 1
    assert ev.evictions[0].victim.name == "low0"
    assert ev.evictions[0].preemptor.name == "u1"

    # victim gone -> u1 binds on n0; u2's fresh pass preempts n1
    running.remove(ev.evictions[0].victim)
    s.queue._clock = lambda: 1e9
    m2 = s.run_cycle()
    assert m2.pods_bound == 1 and m2.pods_preempted == 1
    assert ev.evictions[-1].victim.name == "low1"
    assert ev.evictions[-1].preemptor.name == "u2"


def test_host_same_cycle_bindings_count_against_preemption_capacity():
    """A pod bound EARLIER IN THE SAME CYCLE consumes capacity the
    preemption pass must see: computing against the cycle-start running
    list would kill a victim for a preemptor that still cannot fit."""
    from kubernetes_scheduler_tpu.host import NodeUtil, RecordingEvictor
    from tests.test_host import make_node, make_pod

    nodes = [make_node("n0", cpu=1000)]
    utils = {"n0": NodeUtil(cpu_pct=10, disk_io=5)}
    low = make_pod("low", cpu=100, labels={"scv/priority": "1"})
    low.node_name = "n0"
    running = [low]
    ev = RecordingEvictor()
    s = _sched(nodes, utils, running, evictor=ev)
    # peer priority: the just-bound pod is NOT itself evictable by big
    # (strictly-lower-priority rule), isolating the capacity model
    s.submit(make_pod("mid", cpu=900, labels={"scv/priority": "9"}))
    s.submit(make_pod("big", cpu=950, labels={"scv/priority": "9"}))
    m = s.run_cycle()
    # mid binds (900 <= 900 free); big is unschedulable. Computed against
    # the cycle-START running list, evicting the 100-cpu victim would
    # "free" 900+100 >= 950 and kill it for nothing; with the same-cycle
    # binding counted, 0+100 < 950: NO eviction
    assert m.pods_bound == 1 and m.pods_unschedulable == 1
    assert m.pods_preempted == 0 and not ev.evictions


def test_host_terminating_victim_not_reevicted_and_node_reserved():
    """While a victim terminates (DELETE issued but still in the running
    list), it must not be proposed again and its node's promised capacity
    must not be handed to a second preemptor."""
    from kubernetes_scheduler_tpu.host import NodeUtil, RecordingEvictor
    from tests.test_host import make_node, make_pod

    nodes = [make_node("n0", cpu=1000)]
    utils = {"n0": NodeUtil(cpu_pct=10, disk_io=5)}
    low = make_pod("low", cpu=900, labels={"scv/priority": "1"})
    low.node_name = "n0"
    running = [low]
    ev = RecordingEvictor()
    s = _sched(nodes, utils, running, evictor=ev)
    s.submit(make_pod("urgent", cpu=800, labels={"scv/priority": "9"}))
    m1 = s.run_cycle()
    assert m1.pods_preempted == 1 and len(ev.evictions) == 1

    # victim still terminating: same preemptor retries, nothing new fires
    s.queue._clock = lambda: 1e9
    m2 = s.run_cycle()
    assert m2.pods_preempted == 0 and len(ev.evictions) == 1

    # a second preemptor arrives while n0's capacity is still promised:
    # it must not trigger another eviction on the reserved node either
    s.submit(make_pod("urgent2", cpu=800, labels={"scv/priority": "8"}))
    m3 = s.run_cycle()
    assert m3.pods_preempted == 0 and len(ev.evictions) == 1

    # victim finally dies: pending eviction record clears; preemptors bind
    # (the mirror owns running state once seeded — play the informer event)
    running.remove(low)
    s.mirror.apply_pod_event("DELETED", low)
    s.queue._clock = lambda: 2e9  # past the retry backoff from cycle 2/3
    m4 = s.run_cycle()
    assert m4.pods_bound >= 1
    assert not s._pending_evictions


def test_host_nominated_preemptor_does_not_evict_elsewhere():
    """After triggering evictions, a preemptor waits for its nominated
    node's capacity instead of killing more victims on other nodes every
    retry cycle (upstream nominatedNodeName semantics)."""
    from kubernetes_scheduler_tpu.host import NodeUtil, RecordingEvictor
    from tests.test_host import make_node, make_pod

    nodes = [make_node("n0", cpu=1000), make_node("n1", cpu=1000)]
    utils = {n.name: NodeUtil(cpu_pct=10, disk_io=5) for n in nodes}
    v0 = make_pod("v0", cpu=900, labels={"scv/priority": "1"})
    v0.node_name = "n0"
    v1 = make_pod("v1", cpu=900, labels={"scv/priority": "2"})
    v1.node_name = "n1"
    running = [v0, v1]
    ev = RecordingEvictor()
    s = _sched(nodes, utils, running, evictor=ev)
    s.submit(make_pod("urgent", cpu=800, labels={"scv/priority": "9"}))
    m1 = s.run_cycle()
    assert m1.pods_preempted == 1 and len(ev.evictions) == 1
    assert ev.evictions[0].victim.name == "v0"

    # v0 still terminating: urgent retries but must NOT evict v1 on n1
    s.queue._clock = lambda: 1e9
    m2 = s.run_cycle()
    assert m2.pods_preempted == 0 and len(ev.evictions) == 1
    assert s._nominations  # urgent holds its nomination for n0


def test_host_nominated_capacity_not_stolen_by_lower_priority_arrival():
    """After the victim terminates, the freed capacity is reserved for
    the nominated preemptor: a lower-priority pod arriving during the
    preemptor's retry backoff must not bind into it (otherwise the
    preemptor evicts again and again under a low-priority trickle)."""
    from kubernetes_scheduler_tpu.host import NodeUtil, RecordingEvictor
    from tests.test_host import make_node, make_pod

    nodes = [make_node("n0", cpu=1000)]
    utils = {"n0": NodeUtil(cpu_pct=10, disk_io=5)}
    low = make_pod("low", cpu=900, labels={"scv/priority": "1"})
    low.node_name = "n0"
    running = [low]
    ev = RecordingEvictor()
    # backoff far above any cold-compile time: cycle 1 jit-compiles the
    # preemption program (~seconds solo, warm in full-suite runs), and
    # the default 1s backoff could expire DURING it, popping the
    # preemptor alongside sneaky in cycle 2 and flipping the verdict
    # with JAX cache temperature
    s = _sched(
        nodes, utils, running, evictor=ev,
        initial_backoff_seconds=3600.0, max_backoff_seconds=3600.0,
    )
    s.submit(make_pod("urgent", cpu=800, labels={"scv/priority": "9"}))
    assert s.run_cycle().pods_preempted == 1

    # victim terminates while urgent sits in backoff; a fresh low-prio
    # pod arrives and is popped immediately (no backoff)
    running.remove(low)
    s.mirror.apply_pod_event("DELETED", low)
    s.submit(make_pod("sneaky", cpu=800, labels={"scv/priority": "1"}))
    m2 = s.run_cycle()
    assert m2.pods_bound == 0  # reservation holds n0: sneaky can't fit
    assert m2.pods_preempted == 0  # and sneaky can't evict a reservation

    # urgent's backoff expires: it consumes its nominated capacity
    s.queue._clock = lambda: 1e9
    m3 = s.run_cycle()
    bound = {b.pod.name for b in s.binder.bindings}
    assert "urgent" in bound
    assert not s._nominations  # nomination cleared on bind


def test_pdb_allowed_math():
    from kubernetes_scheduler_tpu.host.types import PodDisruptionBudget

    assert PodDisruptionBudget("a", min_available=2).allowed(5) == 3
    assert PodDisruptionBudget("a", min_available="50%").allowed(5) == 2
    assert PodDisruptionBudget("a", max_unavailable=1).allowed(5) == 1
    assert PodDisruptionBudget("a", max_unavailable="20%").allowed(5) == 1
    # server-computed status wins over spec math
    assert PodDisruptionBudget(
        "a", min_available=0, disruptions_allowed=0
    ).allowed(5) == 0
    assert PodDisruptionBudget("a").allowed(5) == 5  # unconstrained


def test_pdb_match_expressions_semantics():
    """k8s label-selector operators: In/NotIn/Exists/DoesNotExist, with
    a missing key satisfying NotIn; unknown operators fail closed."""
    from kubernetes_scheduler_tpu.host.types import (
        MatchExpression,
        PodDisruptionBudget,
    )
    from tests.test_host import make_pod

    db = make_pod("db", labels={"app": "db", "tier": "prod"})
    web = make_pod("web", labels={"app": "web"})
    bare = make_pod("bare")

    def pdb(*exprs):
        return PodDisruptionBudget("x", match_expressions=list(exprs))

    e_in = MatchExpression("app", "In", ["db", "cache"])
    assert pdb(e_in).selects(db) and not pdb(e_in).selects(web)
    e_notin = MatchExpression("app", "NotIn", ["web"])
    assert pdb(e_notin).selects(db) and not pdb(e_notin).selects(web)
    assert pdb(e_notin).selects(bare)  # missing key satisfies NotIn
    e_ex = MatchExpression("tier", "Exists")
    assert pdb(e_ex).selects(db) and not pdb(e_ex).selects(web)
    e_dne = MatchExpression("tier", "DoesNotExist")
    assert not pdb(e_dne).selects(db) and pdb(e_dne).selects(web)
    assert not pdb(MatchExpression("app", "Garbage")).selects(db)


def test_host_pdb_protects_victims():
    """A victim under an exhausted PodDisruptionBudget must never be
    evicted; an unprotected victim on another node is chosen instead,
    and when no candidate remains, no eviction happens at all."""
    from kubernetes_scheduler_tpu.host import NodeUtil, RecordingEvictor
    from kubernetes_scheduler_tpu.host.types import PodDisruptionBudget
    from tests.test_host import make_node, make_pod

    nodes = [make_node("n0", cpu=1000), make_node("n1", cpu=1000)]
    utils = {n.name: NodeUtil(cpu_pct=10, disk_io=5) for n in nodes}
    guarded = make_pod("guarded", cpu=900,
                       labels={"scv/priority": "1", "app": "db"})
    guarded.node_name = "n0"
    plain = make_pod("plain", cpu=900, labels={"scv/priority": "2"})
    plain.node_name = "n1"
    running = [guarded, plain]
    pdbs = [PodDisruptionBudget("db-pdb", match_labels={"app": "db"},
                                min_available=1)]
    ev = RecordingEvictor()
    s = _sched(nodes, utils, running, evictor=ev)
    s.list_pdbs = lambda: pdbs
    s.submit(make_pod("urgent", cpu=800, labels={"scv/priority": "9"}))
    m = s.run_cycle()
    # guarded (prio 1) would be the lexicographic-best victim, but its
    # budget allows 0 disruptions (1 pod, minAvailable 1) -> plain goes
    assert m.pods_preempted == 1
    assert ev.evictions[0].victim.name == "plain"

    # same cluster, BOTH victims budget-protected: nothing is evicted
    running2 = [guarded, plain]
    pdbs2 = pdbs + [PodDisruptionBudget("all-pdb", match_labels={},
                                        max_unavailable=0)]
    ev2 = RecordingEvictor()
    s2 = _sched(nodes, utils, running2, evictor=ev2)
    s2.list_pdbs = lambda: pdbs2
    s2.submit(make_pod("urgent2", cpu=800, labels={"scv/priority": "9"}))
    m2 = s2.run_cycle()
    assert m2.pods_preempted == 0 and not ev2.evictions


def test_host_pdb_budget_caps_evictions_across_proposals():
    """One remaining disruption in a shared budget: only one of two
    preemptors' proposals may evict this cycle; the proposal that would
    overdraw is skipped whole."""
    from kubernetes_scheduler_tpu.host import NodeUtil, RecordingEvictor
    from kubernetes_scheduler_tpu.host.types import PodDisruptionBudget
    from tests.test_host import make_node, make_pod

    nodes = [make_node("n0", cpu=1000), make_node("n1", cpu=1000)]
    utils = {n.name: NodeUtil(cpu_pct=10, disk_io=5) for n in nodes}
    v0 = make_pod("v0", cpu=900, labels={"scv/priority": "1", "app": "web"})
    v0.node_name = "n0"
    v1 = make_pod("v1", cpu=900, labels={"scv/priority": "1", "app": "web"})
    v1.node_name = "n1"
    running = [v0, v1]
    pdbs = [PodDisruptionBudget("web-pdb", match_labels={"app": "web"},
                                min_available=1)]  # 2 pods -> 1 allowed
    ev = RecordingEvictor()
    s = _sched(nodes, utils, running, evictor=ev)
    s.list_pdbs = lambda: pdbs
    s.submit(make_pod("u1", cpu=800, labels={"scv/priority": "9"}))
    s.submit(make_pod("u2", cpu=800, labels={"scv/priority": "8"}))
    m = s.run_cycle()
    assert m.pods_preempted == 1 and m.victims_evicted == 1
    assert len(ev.evictions) == 1


def test_host_pdb_status_not_overdrawn_across_cycles():
    """A server-computed status.disruptionsAllowed is stale while a
    victim is still terminating: the NEXT cycle must charge the pending
    eviction against it instead of spending the same budget twice
    (ADVICE r3 medium — the never-overdraw guarantee held only on the
    spec-math path)."""
    from kubernetes_scheduler_tpu.host import NodeUtil, RecordingEvictor
    from kubernetes_scheduler_tpu.host.types import PodDisruptionBudget
    from tests.test_host import make_node, make_pod

    nodes = [make_node("n0", cpu=1000), make_node("n1", cpu=1000)]
    utils = {n.name: NodeUtil(cpu_pct=10, disk_io=5) for n in nodes}
    v0 = make_pod("v0", cpu=900, labels={"scv/priority": "1", "app": "db"})
    v0.node_name = "n0"
    v1 = make_pod("v1", cpu=900, labels={"scv/priority": "1", "app": "db"})
    v1.node_name = "n1"
    running = [v0, v1]
    # server-computed status: exactly one disruption allowed, and (being
    # a snapshot) it stays 1 across our cycles
    pdbs = [PodDisruptionBudget("db-pdb", match_labels={"app": "db"},
                                disruptions_allowed=1)]
    ev = RecordingEvictor()
    s = _sched(nodes, utils, running, evictor=ev)
    s.list_pdbs = lambda: pdbs
    s.submit(make_pod("u1", cpu=800, labels={"scv/priority": "9"},
                      annotations={"diskIO": "2"}))
    m1 = s.run_cycle()
    assert m1.victims_evicted == 1 and len(ev.evictions) == 1

    # the victim is still terminating (stays in `running`); a second
    # preemptor must NOT spend the same stale budget on the other victim
    s.queue._clock = lambda: 1e9  # clear backoffs
    s.submit(make_pod("u2", cpu=800, labels={"scv/priority": "8"},
                      annotations={"diskIO": "2"}))
    m2 = s.run_cycle()
    assert m2.victims_evicted == 0, "stale status budget spent twice"
    assert len(ev.evictions) == 1


def test_host_taints_exclude_preemption_candidates():
    from kubernetes_scheduler_tpu.host import RecordingEvictor
    from kubernetes_scheduler_tpu.host.types import Taint
    from tests.test_host import make_pod

    nodes, utils, running = _cluster()
    nodes[0].taints = [Taint(key="dedicated", value="x", effect="NoSchedule")]
    ev = RecordingEvictor()
    s = _sched(nodes, utils, running, evictor=ev)
    s.submit(make_pod("urgent", cpu=800, labels={"scv/priority": "9"}))
    m = s.run_cycle()
    # only untainted n1 is a candidate; its victim is prio-2 low1
    assert m.pods_preempted == 1
    assert ev.evictions[0].victim.name == "low1"


def test_padded_and_masked_victims_ignored():
    free = np.array([[0.0]])
    tables = build_victim_tables(
        jnp.asarray([0, 0, -1], jnp.int32), jnp.asarray([1, 1, 0], jnp.int32),
        jnp.ones((3, 1), jnp.float32),
        jnp.asarray([True, False, True]),  # second masked out
        n_nodes=1, k_cap=4,
    )
    assert int((np.asarray(tables.vid) >= 0).sum()) == 1
    res = preempt_candidates(
        jnp.asarray([[2.0]], jnp.float32), jnp.asarray([9], jnp.int32),
        jnp.ones(1, bool), jnp.ones((1, 1), bool),
        jnp.asarray(free, jnp.float32), tables,
    )
    assert int(res.node[0]) == -1  # only 1 unit can be freed, need 2


# ---- RemovePod re-simulation (round-5: victims' effect on counts) --------


def _affinity_case(*, anti_sel=-1, aff_sel=-1, victim_matches_s0=True,
                   victim_anti_s0=False, free_units=0.0):
    """One node, one selector column, one victim: engine-level
    preempt_batch with domain counts reflecting the victim."""
    import jax.numpy as jnp

    from kubernetes_scheduler_tpu import engine as E

    snap = E.make_snapshot(
        allocatable=np.array([[4.0]], np.float32),
        requested=np.array([[4.0 - free_units]], np.float32),
        disk_io=np.array([5.0]), cpu_pct=np.array([10.0]),
        mem_pct=np.array([10.0]),
        domain_counts=np.array([[1.0 if victim_matches_s0 else 0.0]],
                               np.float32),
        avoid_counts=np.array([[1.0 if victim_anti_s0 else 0.0]], np.float32),
    )
    pods = E.make_pod_batch(
        request=np.array([[2.0]], np.float32),
        priority=np.array([9], np.int32),
        affinity_sel=np.array([[aff_sel]], np.int32),
        anti_affinity_sel=np.array([[anti_sel]], np.int32),
        pod_matches=np.array([[True]]),
    )
    from kubernetes_scheduler_tpu.ops.preempt import VictimArrays

    victims = VictimArrays(
        node=jnp.asarray([0], jnp.int32),
        prio=jnp.asarray([1], jnp.int32),
        req=jnp.asarray([[2.0]], jnp.float32),
        mask=jnp.ones(1, bool),
        start=jnp.zeros(1, jnp.int32),
        matches=jnp.asarray([[victim_matches_s0]]),
        anti=jnp.asarray([[victim_anti_s0]]),
    )
    return E.preempt_batch(snap, pods, victims, k_cap=2)


def test_eviction_satisfies_required_anti_affinity():
    """The preemptor's required ANTI-affinity is violated by the victim
    itself: static counts say the domain is occupied, but evicting the
    victim clears it — upstream's RemovePod accounting finds the
    candidate (the round-4 deviation rejected it)."""
    res = _affinity_case(anti_sel=0)
    assert int(res.node[0]) == 0
    assert int(res.n_victims[0]) == 1


def test_eviction_breaks_required_affinity():
    """The preemptor's required AFFINITY is satisfied ONLY by the victim
    whose eviction frees the capacity: the candidate must be rejected —
    evicting would strand the preemptor (bind-time re-check would fail)
    and waste the eviction."""
    res = _affinity_case(aff_sel=0)
    assert int(res.node[0]) == -1


def test_eviction_of_avoider_clears_reverse_anti():
    """The victim is an AVOIDER (its required anti term forbids pods
    matching s0); the preemptor matches s0. Statically the node is
    barred (reverse anti-affinity), but evicting the avoider clears
    it."""
    res = _affinity_case(victim_matches_s0=False, victim_anti_s0=True)
    assert int(res.node[0]) == 0
    assert int(res.n_victims[0]) == 1


def test_remaining_avoider_still_bars_candidate():
    """Two avoiders, only one evictable prefix member needed for
    capacity: the remaining avoider keeps the node barred, so the
    candidate needs BOTH victims (k=2), not one."""
    import jax.numpy as jnp

    from kubernetes_scheduler_tpu import engine as E
    from kubernetes_scheduler_tpu.ops.preempt import VictimArrays

    snap = E.make_snapshot(
        allocatable=np.array([[4.0]], np.float32),
        requested=np.array([[4.0]], np.float32),
        disk_io=np.array([5.0]), cpu_pct=np.array([10.0]),
        mem_pct=np.array([10.0]),
        avoid_counts=np.array([[2.0]], np.float32),
    )
    pods = E.make_pod_batch(
        request=np.array([[2.0]], np.float32),
        priority=np.array([9], np.int32),
        pod_matches=np.array([[True]]),
    )
    victims = VictimArrays(
        node=jnp.asarray([0, 0], jnp.int32),
        prio=jnp.asarray([1, 2], jnp.int32),
        req=jnp.asarray([[2.0], [1.0]], jnp.float32),
        mask=jnp.ones(2, bool),
        start=jnp.zeros(2, jnp.int32),
        matches=jnp.zeros((2, 1), bool),
        anti=jnp.ones((2, 1), bool),
    )
    res = E.preempt_batch(snap, pods, victims, k_cap=2)
    assert int(res.node[0]) == 0
    assert int(res.n_victims[0]) == 2  # capacity alone needed only 1


def test_eviction_relaxes_spread_skew():
    """Hard topology spread: placing on n0 (3 matching pods) violates
    maxSkew=1 against n1's domain (1 matching). Evicting two matching
    victims from n0 brings its count to 1 — skew 1 — so the candidate
    exists with k=2 even though capacity alone needs only one."""
    import jax.numpy as jnp

    from kubernetes_scheduler_tpu import engine as E
    from kubernetes_scheduler_tpu.ops.preempt import VictimArrays

    snap = E.make_snapshot(
        allocatable=np.array([[8.0], [2.0]], np.float32),
        requested=np.array([[8.0], [2.0]], np.float32),
        disk_io=np.array([5.0, 5.0]), cpu_pct=np.array([10.0, 10.0]),
        mem_pct=np.array([10.0, 10.0]),
        domain_counts=np.array([[3.0], [1.0]], np.float32),
    )
    pods = E.make_pod_batch(
        request=np.array([[2.0]], np.float32),
        priority=np.array([9], np.int32),
        spread_sel=np.array([[0]], np.int32),
        spread_max=np.array([[1]], np.int32),
        pod_matches=np.array([[True]]),
    )
    victims = VictimArrays(
        node=jnp.asarray([0, 0, 0], jnp.int32),
        prio=jnp.asarray([1, 2, 3], jnp.int32),
        req=jnp.asarray([[2.0], [1.0], [1.0]], jnp.float32),
        mask=jnp.ones(3, bool),
        start=jnp.zeros(3, jnp.int32),
        matches=jnp.ones((3, 1), bool),
        anti=jnp.zeros((3, 1), bool),
    )
    res = E.preempt_batch(snap, pods, victims, k_cap=3)
    assert int(res.node[0]) == 0
    assert int(res.n_victims[0]) == 2


def test_pdb_percentage_expected_count():
    """Percentage minAvailable resolves against the owning controller's
    replica count when resolvable (upstream disruption-controller
    semantics): 50% of a 10-replica set with 6 healthy allows exactly
    ONE eviction (6 - ceil(5)), where the current-count fallback would
    over-allow three."""
    from kubernetes_scheduler_tpu.host.types import PodDisruptionBudget

    pdb = PodDisruptionBudget("web", min_available="50%",
                              match_labels={"app": "web"})
    assert pdb.allowed(6, expected_count=10) == 1
    assert pdb.allowed(6) == 3  # documented controller-less fallback
    # maxUnavailable resolves against expected too (upstream: healthy -
    # (expected - maxUnavailable)): 30% of 10 with 6 healthy -> the 4
    # missing replicas already spend the budget
    pdb_mu = PodDisruptionBudget("web", max_unavailable="30%")
    assert pdb_mu.allowed(6, expected_count=10) == 0
    assert pdb_mu.allowed(10, expected_count=10) == 3
    assert pdb_mu.allowed(6) == 2  # fallback: 6 - (6 - ceil(1.8))
    # status always wins
    pdb2 = PodDisruptionBudget("web", min_available="50%",
                               disruptions_allowed=0)
    assert pdb2.allowed(6, expected_count=10) == 0


def test_host_preemption_caps_by_expected_count():
    """End-to-end: a 50%-of-10 budget with 6 healthy replicas lets the
    preemption pass evict at most ONE victim per cycle once the
    controller resolver reports the replica count."""
    from kubernetes_scheduler_tpu.host import NodeUtil, RecordingEvictor
    from kubernetes_scheduler_tpu.host.types import PodDisruptionBudget
    from tests.test_host import make_node, make_pod

    nodes = [make_node(f"n{i}", cpu=1000) for i in range(3)]
    utils = {n.name: NodeUtil(cpu_pct=10, disk_io=5) for n in nodes}
    running = []
    for i in range(6):
        v = make_pod(f"web-{i}", cpu=450,
                     labels={"scv/priority": "1", "app": "web"})
        v.node_name = f"n{i % 3}"
        v.owner = ("ReplicaSet", "web-rs")
        running.append(v)
    pdbs = [PodDisruptionBudget("web-pdb", match_labels={"app": "web"},
                                min_available="50%")]
    replicas = {("ReplicaSet", "default", "web-rs"): 10}

    ev = RecordingEvictor()
    s = _sched(nodes, utils, running, evictor=ev,
               initial_backoff_seconds=0.0, max_backoff_seconds=0.0,
               controller_replicas=lambda k, ns, n: replicas.get((k, ns, n)))
    s.list_pdbs = lambda: pdbs
    # two preemptors, each needing one eviction on separate nodes — the
    # budget (allowed=1) must cap the cycle at ONE victim
    for i in range(2):
        s.submit(make_pod(f"urgent-{i}", cpu=500,
                          labels={"scv/priority": "9"}))
    # two cycles: the first spends the whole budget (allowed = 6 - 5 =
    # 1); the second sees 5 healthy replicas -> allowed 0 -> no eviction
    m = s.run_cycle()
    m_second = s.run_cycle()
    assert m.victims_evicted + m_second.victims_evicted == 1, (m, m_second)

    # without the resolver the fallback math allows 3 -> both evict
    ev2 = RecordingEvictor()
    running2 = []
    for i in range(6):
        v = make_pod(f"web-{i}", cpu=450,
                     labels={"scv/priority": "1", "app": "web"})
        v.node_name = f"n{i % 3}"
        running2.append(v)
    s2 = _sched(nodes, utils, running2, evictor=ev2,
                initial_backoff_seconds=0.0, max_backoff_seconds=0.0)
    s2.list_pdbs = lambda: pdbs
    for i in range(2):
        s2.submit(make_pod(f"urgent-{i}", cpu=500,
                           labels={"scv/priority": "9"}))
    m2a = s2.run_cycle()
    m2b = s2.run_cycle()
    assert m2a.victims_evicted + m2b.victims_evicted == 2, (m2a, m2b)
