"""Gang co-scheduling (ops/gang.py + the host/bridge plumbing): the
all-or-nothing guarantee across every path.

The pinned contracts (PARITY.md):
- no binding for a partial gang ever reaches mark_scheduled — serial,
  pipelined, scalar-fallback, and bridge (capability-downgraded) paths;
- gang-off <-> no-gangs-in-traffic bindings are bit-identical (the gang
  machinery is invisible to ordinary traffic);
- serial <-> pipelined bindings are bit-identical under gang traffic,
  on either queue implementation;
- a deferred gang requeues atomically via restore_window (front of its
  priority class on the Python queue, back on the native heap) and
  re-pops as a unit;
- journals replay clean even when recorded against a gang-blind engine
  (the journaled node_idx is the backstop-masked vector).
"""

import numpy as np
import pytest

from kubernetes_scheduler_tpu.engine import (
    LocalEngine,
    make_pod_batch,
    make_snapshot,
    schedule_batch,
)
from kubernetes_scheduler_tpu.host.queue import (
    SchedulingQueue,
    break_gang,
    pod_gang,
)
from kubernetes_scheduler_tpu.host.scheduler import Scheduler
from kubernetes_scheduler_tpu.host.types import Container, Pod
from kubernetes_scheduler_tpu.ops.gang import (
    GANG_MASKED_BASE,
    decode_masked,
    gang_mask_assign,
    mask_partial_gangs_np,
)
from kubernetes_scheduler_tpu.sim.host_gen import gen_host_cluster
from kubernetes_scheduler_tpu.utils.config import FeatureGates, SchedulerConfig


def _cfg(**kw):
    base = dict(
        batch_window=64, min_device_work=1, adaptive_dispatch=False,
        normalizer="none",
    )
    base.update(kw)
    return SchedulerConfig(**base)


def _gang_pod(name, gang, size, *, cpu=100.0, ns="default"):
    return Pod(
        name=name,
        namespace=ns,
        labels={"scv/gang": gang, "scv/gang-size": str(size)},
        containers=[Container(requests={"cpu": cpu, "memory": 2**28})],
    )


def _plain_pod(name, *, cpu=100.0):
    return Pod(
        name=name,
        containers=[Container(requests={"cpu": cpu, "memory": 2**28})],
    )


def _scheduler(nodes, advisor, running, **cfg_kw):
    return Scheduler(
        _cfg(**cfg_kw),
        advisor=advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
    )


def _bindings(sched):
    return [(b.pod.name, b.node_name) for b in sched.binder.bindings]


# ---- pod_gang / labels ----------------------------------------------------


def test_pod_gang_parses_and_memoizes():
    pod = _gang_pod("a", "train", 3)
    assert pod_gang(pod) == ("default/train", 3)
    assert pod_gang(pod) == ("default/train", 3)  # memo hit
    break_gang(pod)
    assert pod_gang(pod) is None


def test_pod_gang_rejects_garbage_and_singletons():
    assert pod_gang(Pod(name="x", labels={"scv/gang": "g"})) is None
    assert pod_gang(
        Pod(name="y", labels={"scv/gang": "g", "scv/gang-size": "banana"})
    ) is None
    assert pod_gang(
        Pod(name="z", labels={"scv/gang": "g", "scv/gang-size": "1"})
    ) is None
    assert pod_gang(Pod(name="w")) is None


# ---- the device op --------------------------------------------------------


def test_gang_mask_assign_rescinds_partial_and_returns_capacity():
    alloc = np.array([[8.0, 100.0], [8.0, 100.0]], np.float32)
    snap = make_snapshot(
        alloc, np.zeros((2, 2), np.float32),
        np.zeros(2), np.zeros(2), np.zeros(2),
    )
    pods = make_pod_batch(
        request=np.full((3, 2), [8.0, 1.0], np.float32),
        gang_id=np.zeros(3, np.int32),
        gang_size=np.full(3, 3, np.int32),
    )
    res = schedule_batch(snap, pods, normalizer="none")
    idx = np.asarray(res.node_idx)
    # two members fit, the third cannot: ALL placements rescinded
    assert (idx >= 0).sum() == 0
    assert (idx <= GANG_MASKED_BASE).sum() == 2
    # sentinels decode to the would-have nodes
    assert sorted(decode_masked(idx[idx <= GANG_MASKED_BASE]).tolist()) == [0, 1]
    assert int(res.n_assigned) == 0
    # the rescinded members' capacity came back
    assert np.allclose(np.asarray(res.free_after)[:, 0], 8.0)


def test_gang_mask_assign_identity_without_gangs():
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    node_idx = jnp.asarray(
        rng.integers(-1, 4, 16).astype(np.int32)
    )
    req = jnp.asarray(rng.random((16, 3), np.float32))
    free = jnp.asarray(rng.random((4, 3), np.float32))
    out_idx, out_free, out_n = gang_mask_assign(
        jnp.full(16, -1, jnp.int32), jnp.zeros(16, jnp.int32),
        jnp.ones(16, bool), node_idx, req, free, jnp.asarray(7, jnp.int32),
    )
    assert np.array_equal(np.asarray(out_idx), np.asarray(node_idx))
    assert np.array_equal(np.asarray(out_free), np.asarray(free))
    assert int(out_n) == 7


def test_np_mirror_matches_device_op():
    rng = np.random.default_rng(3)
    p = 32
    gang_id = np.where(
        rng.random(p) < 0.6, rng.integers(0, 5, p), -1
    ).astype(np.int32)
    sizes = rng.integers(2, 6, 5)
    gang_size = np.where(
        gang_id >= 0, sizes[np.clip(gang_id, 0, 4)], 0
    ).astype(np.int32)
    node_idx = rng.integers(-1, 8, p).astype(np.int32)

    import jax.numpy as jnp

    dev_idx, _, _ = gang_mask_assign(
        jnp.asarray(gang_id), jnp.asarray(gang_size), jnp.ones(p, bool),
        jnp.asarray(node_idx), jnp.zeros((p, 2), jnp.float32),
        jnp.zeros((8, 2), jnp.float32), jnp.asarray(0, jnp.int32),
    )
    np_idx, newly = mask_partial_gangs_np(gang_id, gang_size, node_idx)
    assert np.array_equal(np.asarray(dev_idx), np_idx)
    assert newly == int((np_idx <= GANG_MASKED_BASE).sum())
    # idempotent: masking a masked vector changes nothing
    again, newly2 = mask_partial_gangs_np(gang_id, gang_size, np_idx)
    assert np.array_equal(again, np_idx) and newly2 == 0


# ---- host loop: all-or-nothing + deferral ---------------------------------


def test_complete_gang_binds_incomplete_defers_then_splits():
    nodes, advisor = gen_host_cluster(16, seed=0)
    running: list = []
    s = _scheduler(nodes, advisor, running, gang_max_defers=2)
    for i in range(3):
        s.submit(_gang_pod(f"g1-{i}", "a", 3))
    for i in range(2):
        s.submit(_gang_pod(f"g2-{i}", "b", 4))  # 2 of 4: never complete
    s.run_until_empty(max_cycles=16)
    names = [n for n, _ in _bindings(s)]
    assert sorted(n for n in names if n.startswith("g1-")) == [
        "g1-0", "g1-1", "g1-2",
    ]
    assert not any(n.startswith("g2-") for n in names)
    assert s.totals["gangs_admitted"] == 1
    # deferred twice, then the budget-exhausted resolution (also counted)
    assert s.totals["gangs_deferred"] == 3


def test_straggler_member_reunites_gang_within_defer_budget():
    nodes, advisor = gen_host_cluster(16, seed=0)
    running: list = []
    s = _scheduler(nodes, advisor, running, gang_max_defers=4)
    for i in range(2):
        s.submit(_gang_pod(f"m-{i}", "late", 3))
    m = s.run_cycle()
    assert m.gangs_deferred == 1 and m.pods_bound == 0
    # the straggler arrives; the gang re-pops as a unit and binds whole
    s.submit(_gang_pod("m-2", "late", 3))
    m2 = s.run_cycle()
    assert m2.gangs_admitted == 1 and m2.pods_bound == 3
    assert s.totals["pods_bound"] == 3


def test_drop_policy_keeps_gang_identity():
    nodes, advisor = gen_host_cluster(8, seed=0)
    running: list = []
    s = _scheduler(
        nodes, advisor, running,
        gang_max_defers=1, gang_defer_policy="drop",
    )
    pods = [_gang_pod(f"d-{i}", "keep", 3) for i in range(2)]
    for p in pods:
        s.submit(p)
    s.run_cycle()
    s.run_cycle()
    # budget exhausted -> backoff requeue, gang identity intact
    assert all(pod_gang(p) == ("default/keep", 3) for p in pods)
    assert s.totals["pods_bound"] == 0


def test_oversize_gang_splits_immediately():
    nodes, advisor = gen_host_cluster(8, seed=0)
    running: list = []
    s = _scheduler(nodes, advisor, running, batch_window=8)
    pods = [_gang_pod(f"o-{i}", "huge", 100) for i in range(4)]
    for p in pods:
        s.submit(p)
    m = s.run_cycle()
    assert m.gangs_deferred == 1
    assert all(pod_gang(p) is None for p in pods)


def test_unknown_gang_defer_policy_rejected():
    nodes, advisor = gen_host_cluster(4, seed=0)
    with pytest.raises(ValueError, match="gang_defer_policy"):
        _scheduler(nodes, advisor, [], gang_defer_policy="explode")


# ---- deferred-gang requeue ordering (restore_window) ----------------------


def test_deferred_gang_requeues_to_front_in_order():
    nodes, advisor = gen_host_cluster(16, seed=0)
    running: list = []
    s = _scheduler(nodes, advisor, running)
    assert isinstance(s.queue, SchedulingQueue) or True
    # incomplete gang first, then plain pods at the same priority
    gang = [_gang_pod(f"fg-{i}", "front", 3) for i in range(2)]
    for p in gang:
        s.submit(p)
    plain = [_plain_pod(f"fp-{i}") for i in range(3)]
    for p in plain:
        s.submit(p)
    m = s.run_cycle()
    # the gang deferred; the plain pods bound
    assert m.gangs_deferred == 1 and m.pods_bound == 3
    # restore_window contract: the gang re-pops FIRST, original order
    nxt = s.queue.pop_window(8)
    assert [p.name for p in nxt[:2]] == ["fg-0", "fg-1"]
    s.queue.restore_window(nxt)


# ---- parity pins ----------------------------------------------------------


def _drain(pipeline_depth, pods_fn, *, gang_scheduling=True, n_nodes=24):
    nodes, advisor = gen_host_cluster(n_nodes, seed=0)
    running: list = []
    s = _scheduler(
        nodes, advisor, running,
        pipeline_depth=pipeline_depth,
        gang_scheduling=gang_scheduling,
        # zero-delay retries so deferral/backoff traffic re-enters the
        # run deterministically (prefetching is disabled at zero backoff
        # exactly to keep serial/pipelined pops identical)
        initial_backoff_seconds=0.0,
    )
    for pod in pods_fn():
        s.submit(pod)
    out = s.run_until_empty(max_cycles=32)
    s.drain_pipeline()
    return s, out


def _mixed_traffic():
    pods = []
    for g in range(4):
        size = 2 + g % 3
        for i in range(size):
            pods.append(_gang_pod(f"mg{g}-{i}", f"mix-{g}", size))
    pods.extend(_plain_pod(f"mp-{i}") for i in range(12))
    # one forever-incomplete gang churning through deferral
    pods.extend(_gang_pod(f"short-{i}", "short", 5) for i in range(3))
    return pods


def test_gang_parity_serial_vs_pipelined():
    s0, _ = _drain(0, _mixed_traffic)
    s1, _ = _drain(1, _mixed_traffic)
    assert _bindings(s0) == _bindings(s1)
    assert s0.totals["gangs_admitted"] == s1.totals["gangs_admitted"] > 0
    assert s0.totals["fallback_cycles"] == s1.totals["fallback_cycles"] == 0


def test_gang_off_matches_no_gangs_in_traffic():
    def plain_traffic():
        return [_plain_pod(f"p-{i}") for i in range(24)]

    on, _ = _drain(0, plain_traffic, gang_scheduling=True)
    off, _ = _drain(0, plain_traffic, gang_scheduling=False)
    assert _bindings(on) == _bindings(off)
    assert on.totals["gangs_admitted"] == 0
    assert on.totals["gangs_deferred"] == 0


def test_scalar_fallback_never_binds_partial_gangs():
    nodes, advisor = gen_host_cluster(12, seed=0)
    running: list = []
    s = Scheduler(
        _cfg(feature_gates=FeatureGates(tpu_batch_score=False)),
        advisor=advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
    )
    for i in range(3):
        s.submit(_gang_pod(f"sg-{i}", "scalarband", 3))
    for i in range(4):
        s.submit(_plain_pod(f"sp-{i}"))
    m = s.run_cycle()
    assert m.used_fallback
    # the gang deferred whole (scalar cycles never bind gangs); plain
    # pods scheduled normally
    assert m.gangs_deferred == 1
    names = [n for n, _ in _bindings(s)]
    assert not any(n.startswith("sg-") for n in names)
    assert sum(n.startswith("sp-") for n in names) == 4


# ---- bridge: capability downgrade ----------------------------------------
# (the generic mid-stream-downgrade pin — probe/invalidate/re-learn for
# EVERY HealthReply bit, parametrized off the proto — lives in
# tests/test_resident.py::test_mid_stream_downgrade_relearns_every_bit;
# this test pins the gang-specific degrade behavior on top of it)


def test_gang_capability_downgrade_old_sidecar():
    """An old sidecar (no gang_scheduling capability): the client strips
    the gang tensors off the wire, the host's backstop enforces
    all-or-nothing, and bindings match the local (device-masked) run —
    degraded mode is invisible in the decisions."""
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from kubernetes_scheduler_tpu.bridge.client import RemoteEngine
    from kubernetes_scheduler_tpu.bridge.server import make_server

    server, port, service = make_server("127.0.0.1:0")
    service.gang_enabled = False  # impersonate the old build
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=120.0)
    try:
        assert client.supports_gangs() is False

        nodes, advisor = gen_host_cluster(12, seed=0)
        running: list = []
        s = Scheduler(
            _cfg(),
            advisor=advisor,
            list_nodes=lambda: nodes,
            list_running_pods=lambda: running,
            engine=client,
        )
        # a gang that fits + one that cannot (members > cluster fit is
        # not needed; missing member suffices to exercise the backstop)
        for i in range(3):
            s.submit(_gang_pod(f"ok-{i}", "fits", 3))
        for i in range(2):
            s.submit(_gang_pod(f"part-{i}", "short", 4))
        for i in range(4):
            s.submit(_plain_pod(f"pl-{i}"))
        s.run_until_empty(max_cycles=8)
        remote_bindings = _bindings(s)
        assert s.totals["fallback_cycles"] == 0
        assert s.totals["gangs_admitted"] == 1
        names = [n for n, _ in remote_bindings]
        assert not any(n.startswith("part-") for n in names)

        # the same traffic against the local (gang-capable) engine
        nodes2, advisor2 = gen_host_cluster(12, seed=0)
        running2: list = []
        s2 = Scheduler(
            _cfg(),
            advisor=advisor2,
            list_nodes=lambda: nodes2,
            list_running_pods=lambda: running2,
        )
        for i in range(3):
            s2.submit(_gang_pod(f"ok-{i}", "fits", 3))
        for i in range(2):
            s2.submit(_gang_pod(f"part-{i}", "short", 4))
        for i in range(4):
            s2.submit(_plain_pod(f"pl-{i}"))
        s2.run_until_empty(max_cycles=8)
        assert remote_bindings == _bindings(s2)
    finally:
        client.close()
        server.stop(grace=None)


def test_gang_capable_sidecar_masks_on_device():
    """A current sidecar advertises the capability, receives the gang
    tensors, and rescinds partial placements on ITS side (sentinels in
    the reply; the sidecar's gang_pods_masked_total counter moves)."""
    pytest.importorskip("grpc")
    from kubernetes_scheduler_tpu.bridge.client import RemoteEngine
    from kubernetes_scheduler_tpu.bridge.server import make_server

    server, port, service = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=120.0)
    try:
        assert client.supports_gangs() is True
        alloc = np.array([[8.0, 100.0], [8.0, 100.0]], np.float32)
        snap = make_snapshot(
            alloc, np.zeros((2, 2), np.float32),
            np.zeros(2), np.zeros(2), np.zeros(2),
        )
        pods = make_pod_batch(
            request=np.full((3, 2), [8.0, 1.0], np.float32),
            gang_id=np.zeros(3, np.int32),
            gang_size=np.full(3, 3, np.int32),
        )
        res = client.schedule_batch(snap, pods, normalizer="none")
        idx = np.asarray(res.node_idx)
        assert (idx >= 0).sum() == 0
        assert (idx <= GANG_MASKED_BASE).sum() == 2
        assert "gang_pods_masked_total 2" in service.render_metrics()
    finally:
        client.close()
        server.stop(grace=None)


def test_pipelined_prefetch_flushed_on_gang_deferral():
    """A gang that defers at RESOLVE time (complete in the window but
    unschedulable) while the pipelined driver holds a prefetched window:
    the prefetch is handed back behind the restored gang, so pop order —
    and therefore bindings — stay identical to the serial driver."""

    def traffic():
        pods = [_plain_pod(f"w1-{i}", cpu=100.0) for i in range(8)]
        # complete gang, but no node can hold any member: defers at
        # resolve until the budget splits it (members then individually
        # unschedulable, parked in backoff)
        pods.extend(_gang_pod(f"big-{i}", "toobig", 3, cpu=10**6) for i in range(3))
        pods.extend(_plain_pod(f"w2-{i}", cpu=100.0) for i in range(8))
        return pods

    def drain(depth):
        nodes, advisor = gen_host_cluster(8, seed=0)
        running: list = []
        s = _scheduler(
            nodes, advisor, running,
            batch_window=8, pipeline_depth=depth, gang_max_defers=2,
        )
        for pod in traffic():
            s.submit(pod)
        s.run_until_empty(max_cycles=12)
        s.drain_pipeline()
        return s

    s0, s1 = drain(0), drain(1)
    assert _bindings(s0) == _bindings(s1)
    assert s0.totals["gangs_deferred"] == s1.totals["gangs_deferred"] > 0
    assert s0.totals["gangs_admitted"] == s1.totals["gangs_admitted"] == 0
    names = [n for n, _ in _bindings(s1)]
    assert not any(n.startswith("big-") for n in names)
    assert sum(1 for n in names if n.startswith(("w1-", "w2-"))) == 16


# ---- review-round pins ----------------------------------------------------


def test_gang_off_ignores_gang_labels_entirely():
    """config.gang_scheduling=False: gang labels are IGNORED — the
    builder leaves the gang tensors at their no-gang defaults, members
    schedule as individuals, and no gang counter ever moves."""
    nodes, advisor = gen_host_cluster(16, seed=0)
    running: list = []
    s = _scheduler(nodes, advisor, running, gang_scheduling=False)
    for i in range(2):
        s.submit(_gang_pod(f"ig-{i}", "ignored", 4))  # 2 of 4 "members"
    for i in range(3):
        s.submit(_plain_pod(f"ip-{i}"))
    batch = s.builder.build_pod_batch(
        [_gang_pod("probe", "ignored", 4)]
    )
    assert (np.asarray(batch.gang_id) == -1).all()
    s.run_until_empty(max_cycles=8)
    names = [n for n, _ in _bindings(s)]
    # the would-be-partial gang binds as individuals: labels ignored
    assert sum(n.startswith("ig-") for n in names) == 2
    assert sum(n.startswith("ip-") for n in names) == 3
    assert s.totals["gangs_admitted"] == 0
    assert s.totals["gangs_deferred"] == 0
    assert s.totals["gang_pods_masked"] == 0


def test_gang_window_routes_device_under_adaptive_default():
    """Gang pods carry an scv/ label, so gang windows are never
    scalar-eligible: even with the adaptive dispatcher's huge cold-start
    threshold the cycle takes the engine path and the gang binds whole
    — it is never scalar-deferred into a forced split."""
    nodes, advisor = gen_host_cluster(16, seed=0)
    running: list = []
    s = _scheduler(
        nodes, advisor, running,
        min_device_work=1 << 20, adaptive_dispatch=True,
    )
    for i in range(3):
        s.submit(_gang_pod(f"dev-{i}", "small", 3))
    m = s.run_cycle()
    assert not m.used_fallback
    assert m.gangs_admitted == 1 and m.pods_bound == 3


def test_over_submitted_gang_admits_by_count_like_the_device_op():
    """More members in the window than the declared size: admission is
    assigned-count >= size (the device op's rule); the surplus member
    falls through to the ordinary requeue path, never a whole-gang
    deferral of valid placements."""
    nodes, advisor = gen_host_cluster(2, seed=0)
    # shrink capacity so exactly 2 of the 3 members fit
    for nd in nodes:
        nd.allocatable["cpu"] = 1000.0
        nd.allocatable["memory"] = 4 * 2**30
    running: list = []
    s = _scheduler(nodes, advisor, running)
    for i in range(3):
        s.submit(_gang_pod(f"ov-{i}", "over", 2, cpu=1000.0))
    m = s.run_cycle()
    assert m.gangs_admitted == 1, (m, _bindings(s))
    assert m.pods_bound == 2
    assert m.gangs_deferred == 0
    assert m.pods_unschedulable == 1  # the surplus member, individually


@pytest.mark.parametrize("native", [True, False])
def test_pipelined_parity_with_traffic_beyond_the_prefetch(native):
    """The review's divergence shape: a gang defers mid-drain while the
    pipelined driver holds a prefetched window AND more traffic waits
    behind it — pop order (and bindings) must still match serial on
    BOTH queue implementations (the native heap restores to the back of
    the priority class, the Python queue to the front; _defer_gang
    branches on RESTORES_TO_FRONT)."""

    def traffic():
        pods = [_plain_pod(f"a-{i}") for i in range(8)]
        pods.extend(
            _gang_pod(f"big-{i}", "nofit", 3, cpu=10**6) for i in range(3)
        )
        pods.extend(_plain_pod(f"b-{i}") for i in range(8))
        pods.extend(_plain_pod(f"c-{i}") for i in range(8))
        return pods

    def drain(depth):
        nodes, advisor = gen_host_cluster(8, seed=0)
        running: list = []
        s = _scheduler(
            nodes, advisor, running,
            batch_window=8, pipeline_depth=depth, gang_max_defers=2,
            feature_gates=FeatureGates(native_host=native),
        )
        if not native:
            assert s.queue.RESTORES_TO_FRONT is True
        for pod in traffic():
            s.submit(pod)
        s.run_until_empty(max_cycles=16)
        s.drain_pipeline()
        return s

    s0, s1 = drain(0), drain(1)
    assert _bindings(s0) == _bindings(s1)
    assert s0.totals["gangs_deferred"] == s1.totals["gangs_deferred"] > 0
    names = [n for n, _ in _bindings(s1)]
    assert sum(1 for n in names if n.startswith(("a-", "b-", "c-"))) == 24


def test_degraded_mode_journal_replays_clean(tmp_path):
    """Recording against a gang-blind sidecar: the journaled node_idx
    must be the MASKED vector (the host backstop's output), so a local
    gang-capable replay reproduces it bitwise — the replay-pinning
    guarantee holds in degraded mode too."""
    pytest.importorskip("grpc")
    from kubernetes_scheduler_tpu.bridge.client import RemoteEngine
    from kubernetes_scheduler_tpu.bridge.server import make_server
    from kubernetes_scheduler_tpu.trace.replay import replay_journal

    server, port, service = make_server("127.0.0.1:0")
    service.gang_enabled = False  # gang-blind: raw replies, host masks
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=120.0)
    journal = str(tmp_path / "degraded")
    try:
        nodes, advisor = gen_host_cluster(2, seed=0)
        for nd in nodes:
            nd.allocatable["cpu"] = 1000.0
        running: list = []
        s = Scheduler(
            _cfg(trace_path=journal, gang_max_defers=1),
            advisor=advisor,
            list_nodes=lambda: nodes,
            list_running_pods=lambda: running,
            engine=client,
        )
        # a gang with a partial device fit: the raw reply carries real
        # placements the backstop must rescind — exactly the records
        # that used to replay dirty
        for i in range(3):
            s.submit(_gang_pod(f"dg-{i}", "nofit", 3, cpu=1000.0))
        for i in range(2):
            s.submit(_plain_pod(f"dp-{i}", cpu=100.0))
        s.run_until_empty(max_cycles=6)
        assert s.totals["gangs_deferred"] > 0
        assert s.totals["gang_pods_masked"] > 0  # backstop rescinded
        s.recorder.close()
        report = replay_journal(journal)  # local, gang-capable engine
        assert report.replayed > 0
        assert report.binding_diffs == 0, report.to_dict()
    finally:
        client.close()
        server.stop(grace=None)


def test_deep_backlog_keeps_stride_aligned_gangs():
    """A gang fully inside one stacked-window stride rides the
    multi-window dispatch (no trim); only a straddling gang cuts the
    pop, and only from its first member on."""
    nodes, advisor = gen_host_cluster(16, seed=0)
    running: list = []
    s = _scheduler(
        nodes, advisor, running, batch_window=8, max_windows_per_cycle=4,
    )
    # stride 0: 5 plain + aligned gang of 3 (rows 5..7); stride 1: 8 plain
    for i in range(5):
        s.submit(_plain_pod(f"s0-{i}"))
    for i in range(3):
        s.submit(_gang_pod(f"al-{i}", "aligned", 3))
    for i in range(8):
        s.submit(_plain_pod(f"s1-{i}"))
    m = s.run_cycle()
    # one deep cycle took everything: the aligned gang bound in-stride
    assert m.pods_in == 16 and m.pods_bound == 16, m
    assert m.gangs_admitted == 1
    assert s.totals["gangs_deferred"] == 0

    # straddling gang: rows 6..8 cross the stride boundary -> the pop
    # cuts at the gang's first member; the suffix leads the next cycle
    for i in range(6):
        s.submit(_plain_pod(f"t0-{i}"))
    for i in range(3):
        s.submit(_gang_pod(f"st-{i}", "straddle", 3))
    for i in range(4):
        s.submit(_plain_pod(f"t1-{i}"))
    m2 = s.run_cycle()
    assert m2.pods_bound == 6, m2          # the clean prefix
    m3 = s.run_cycle()
    assert m3.gangs_admitted == 1          # gang re-popped whole
    assert m3.pods_bound == 7, m3          # gang + trailing plains
    assert s.totals["gangs_deferred"] == 0


def test_np_mirror_per_lane_sizes_match_device():
    """Members declaring INCONSISTENT gang sizes (malformed labels):
    the np mirror must still match the device op lane for lane."""
    import jax.numpy as jnp

    gang_id = np.array([0, 0, 0, -1], np.int32)
    gang_size = np.array([3, 2, 2, 0], np.int32)  # malformed: mixed
    node_idx = np.array([0, 1, -1, 2], np.int32)  # cnt(assigned)=2
    dev_idx, _, _ = gang_mask_assign(
        jnp.asarray(gang_id), jnp.asarray(gang_size),
        jnp.ones(4, bool), jnp.asarray(node_idx),
        jnp.zeros((4, 2), jnp.float32), jnp.zeros((4, 2), jnp.float32),
        jnp.asarray(0, jnp.int32),
    )
    np_idx, _ = mask_partial_gangs_np(gang_id, gang_size, node_idx)
    assert np.array_equal(np.asarray(dev_idx), np_idx)
    # lane 0 (declared 3 > cnt 2) masked; lanes 1-2 (declared 2) kept
    assert np_idx[0] <= GANG_MASKED_BASE and np_idx[1] == 1
