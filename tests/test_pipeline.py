"""Pipelined host loop (config.pipeline_depth=1): serial parity and
pipeline hazards.

The guarantee under test (PARITY.md): for the same arrival order, the
1-deep pipelined loop produces BIT-IDENTICAL bindings to the strictly
alternating serial loop — the prefetch/speculative machinery is a pure
latency optimization. The hazard tests pin the three correctness gates:
an informer event mid-flight discards the speculative state (no stale
snapshot is ever scored), an engine failure mid-flight drains the
pipeline and falls back to scalar exactly once, and the preemption pass
runs in the completion stage against real — never speculative —
capacity."""

import numpy as np

from kubernetes_scheduler_tpu.engine import LocalEngine, PendingSchedule
from kubernetes_scheduler_tpu.host import (
    Container,
    Node,
    NodeUtil,
    Pod,
    Scheduler,
    SchedulingQueue,
    StaticAdvisor,
)
from kubernetes_scheduler_tpu.host.scheduler import RecordingEvictor
from kubernetes_scheduler_tpu.sim.host_gen import gen_host_cluster, gen_host_pods
from kubernetes_scheduler_tpu.utils.config import SchedulerConfig


def make_cfg(**kw):
    kw.setdefault("batch_window", 32)
    kw.setdefault("max_windows_per_cycle", 1)
    kw.setdefault("min_device_work", 1)
    kw.setdefault("adaptive_dispatch", False)
    # longer than any test drain: mid-drain backoff expiry is the one
    # documented serial/pipelined divergence (a retry becomes ready
    # between the prefetch pop and the serial pop point), so the parity
    # suite pins the guarantee on its own terms — same arrival order,
    # no mid-drain requeue re-entry
    kw.setdefault("initial_backoff_seconds", 3600.0)
    kw.setdefault("max_backoff_seconds", 3600.0)
    return SchedulerConfig(**kw)


def drain(sched, running, max_cycles=64):
    """run_cycle loop that feeds binds back as running pods between
    cycles (the live informer's append), so the pipelined run exercises
    apply_assignment_deltas against the serial suffix scan."""
    seen = 0
    out = []
    for _ in range(max_cycles):
        if len(sched.queue) == 0 and sched._prefetched is None:
            break
        out.append(sched.run_cycle())
        for b in sched.binder.bindings[seen:]:
            running.append(b.pod)
        seen = len(sched.binder.bindings)
    return out


def run_workload(depth, *, constraints=False, n_nodes=48, n_pods=130, engine=None):
    nodes, advisor = gen_host_cluster(n_nodes, seed=0, constraints=constraints)
    running: list = []
    sched = Scheduler(
        make_cfg(pipeline_depth=depth),
        advisor=advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
        engine=engine,
    )
    for pod in gen_host_pods(n_pods, seed=1, constraints=constraints):
        sched.submit(pod)
    metrics = drain(sched, running)
    binds = [(b.pod.namespace, b.pod.name, b.node_name)
             for b in sched.binder.bindings]
    return binds, metrics, sched


def test_pipeline_parity_bitidentical_plain():
    b0, _, _ = run_workload(0)
    b1, m1, s1 = run_workload(1)
    assert b1 == b0 and len(b0) > 0
    # the pipeline actually engaged: overlap was measured and nothing
    # forced a speculative discard on a churn-free drain
    assert s1.totals["host_overlap_seconds"] > 0.0
    assert s1.totals["pipeline_flushes"] == 0
    assert not any(m.used_fallback for m in m1)


def test_pipeline_parity_bitidentical_constraint_churn():
    """The churn workload: taints/tolerations, zone (anti-)affinity,
    infeasible pods requeueing — bindings still bit-identical."""
    b0, m0, _ = run_workload(0, constraints=True)
    b1, m1, _ = run_workload(1, constraints=True)
    assert b1 == b0 and len(b0) > 0
    # same per-cycle shape too, not just the same final multiset
    assert [(m.pods_in, m.pods_bound) for m in m1] == [
        (m.pods_in, m.pods_bound) for m in m0
    ]


def test_pipeline_parity_depth_clamps():
    """Depths beyond 1 behave as 1 (documented clamp), not as a deeper
    speculative pipeline."""
    b0, _, _ = run_workload(0)
    b2, _, _ = run_workload(2)
    assert b2 == b0


def make_node(name, cpu=8000.0):
    return Node(
        name=name,
        allocatable={"cpu": cpu, "memory": 32.0 * 2**30, "pods": 110.0},
    )


def make_pod(name, cpu=500.0, **kw):
    return Pod(
        name=name,
        containers=[Container(requests={"cpu": cpu, "memory": 2**30})],
        **kw,
    )


def test_pipeline_informer_event_midflight_forces_rebuild():
    """A node added while the speculative next-window batch is already
    built: the layout fingerprint mismatch discards it (pipeline_flushes)
    and the serial rebuild resolves against the NEW node set — the pod
    pinned to the new node binds there instead of being scored against a
    stale snapshot (where its target would be an out-of-range index)."""
    nodes = [make_node(f"n{i}") for i in range(4)]
    advisor = StaticAdvisor({n.name: NodeUtil(cpu_pct=10.0) for n in nodes})
    running: list = []
    sched = Scheduler(
        make_cfg(pipeline_depth=1, batch_window=4),
        advisor=advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
    )
    for i in range(4):
        sched.submit(make_pod(f"plain-{i}"))
    sched.submit(make_pod("pinned", target_node="n-new"))
    m1 = sched.run_cycle()  # dispatches the plain window, prefetches "pinned"
    assert m1.pods_bound == 4
    assert sched._spec_batch is not None  # speculative batch in hand
    n_new = make_node("n-new")            # informer event mid-flight
    nodes.append(n_new)
    advisor.utils["n-new"] = NodeUtil(cpu_pct=10.0)
    sched.mirror.apply_node_event("ADDED", n_new)
    m2 = sched.run_cycle()
    assert m2.pipeline_flushes == 1
    assert m2.pods_bound == 1
    (bind,) = [b for b in sched.binder.bindings if b.pod.name == "pinned"]
    assert bind.node_name == "n-new"


class MidflightFailEngine:
    """Async surface whose in-flight handle dies on force for one call —
    the remote-sidecar outage shape (RPC dispatched, connection lost)."""

    def __init__(self, fail_call: int):
        self.inner = LocalEngine()
        self.calls = 0
        self.fail_call = fail_call

    def schedule_batch(self, snapshot, pods, **kw):
        return self.inner.schedule_batch(snapshot, pods, **kw)

    def schedule_batch_async(self, snapshot, pods, **kw):
        self.calls += 1
        if self.calls == self.fail_call:
            class _Dead:
                def result(self):
                    raise RuntimeError("injected mid-flight engine failure")

            return _Dead()
        return PendingSchedule(self.inner.schedule_batch(snapshot, pods, **kw))


def test_pipeline_engine_failure_midflight_falls_back_exactly_once():
    engine = MidflightFailEngine(fail_call=2)
    b1, m1, s1 = run_workload(1, engine=engine)
    fallbacks = [m for m in m1 if m.used_fallback]
    assert len(fallbacks) == 1
    # the failed cycle drained its speculative next-cycle state
    assert fallbacks[0].pipeline_flushes >= 1
    # the window was re-scheduled by the scalar path exactly once — no
    # pod lost, no pod double-bound
    names = [b[1] for b in b1]
    assert len(names) == len(set(names))
    b0, _, _ = run_workload(0)
    assert len(b1) == len(b0)
    # recovery: cycles after the failure went back to the engine path
    later = m1[m1.index(fallbacks[0]) + 1:]
    assert later and not any(m.used_fallback for m in later)


def run_preemption(depth):
    nodes = [make_node("n0", cpu=2000.0), make_node("n1", cpu=2000.0)]
    advisor = StaticAdvisor({n.name: NodeUtil(cpu_pct=10.0) for n in nodes})
    running = []
    for i, node in enumerate(nodes):
        victim = make_pod(f"victim-{i}", cpu=1800.0, priority=0)
        victim.node_name = node.name
        victim.start_time = 100.0 + i
        running.append(victim)
    evictor = RecordingEvictor()
    sched = Scheduler(
        make_cfg(pipeline_depth=depth, batch_window=4),
        advisor=advisor,
        evictor=evictor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
    )
    sched.submit(make_pod("preemptor", cpu=1800.0, priority=100))
    # one extra plain window behind the preemptor, so the preemption
    # cycle runs while the pipeline holds prefetched state
    sched.submit(make_pod("small", cpu=100.0, priority=0))
    drain(sched, running)
    return (
        [(e.victim.name, e.preemptor.name) for e in evictor.evictions],
        dict(sched._nominations),
        sched,
    )


def test_pipeline_preempt_parity_real_capacity():
    """The preemption pass under the pipelined driver selects the same
    victims as serial mode: it runs in the completion stage, after the
    engine result was forced and the cycle's binds applied — never
    against speculative capacity."""
    ev0, nom0, _ = run_preemption(0)
    ev1, nom1, _ = run_preemption(1)
    assert ev1 == ev0 and len(ev0) >= 1
    assert set(nom1) == set(nom0)


def test_pipeline_run_until_empty_dispatches_prefetched_tail():
    """run_until_empty's stop condition counts the prefetched window: a
    backlog whose last window sits in the prefetch buffer still drains
    fully."""
    nodes, advisor = gen_host_cluster(16, seed=0)
    sched = Scheduler(
        make_cfg(pipeline_depth=1, batch_window=8),
        advisor=advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: [],
    )
    pods = gen_host_pods(24, seed=3)
    for pod in pods:
        sched.submit(pod)
    sched.run_until_empty()
    assert len(sched.binder.bindings) == len(pods)


def test_drain_pipeline_restores_prefetched_window():
    nodes, advisor = gen_host_cluster(16, seed=0)
    sched = Scheduler(
        make_cfg(pipeline_depth=1, batch_window=8),
        advisor=advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: [],
    )
    for pod in gen_host_pods(16, seed=3):
        sched.submit(pod)
    sched.run_cycle()  # binds 8, prefetches the other 8
    assert sched._prefetched is not None
    assert len(sched.queue) == 0
    sched.drain_pipeline()
    assert sched._prefetched is None and len(sched.queue) == 8
    # and the restored pods still schedule
    sched.run_until_empty()
    assert len(sched.binder.bindings) == 16


def test_apply_assignment_deltas_matches_cold_rebuild():
    """The delta fold IS the suffix scan, vectorized: after folding a
    window's binds and appending those pods to the running list, the
    next build's `requested` matrix is bit-identical to a cold rebuild
    by a fresh builder — and a pod with hostPorts refuses the delta
    (the dense batch SETS port cells where the scan INCREMENTS, which
    would diverge on a duplicated port)."""
    from kubernetes_scheduler_tpu.host.snapshot import SnapshotBuilder

    nodes = [make_node(f"n{i}") for i in range(3)]
    utils = {n.name: NodeUtil(cpu_pct=10.0) for n in nodes}
    running = [make_pod("r0", cpu=200.0)]
    running[0].node_name = "n0"
    window = [make_pod("w0", cpu=300.0), make_pod("w1", cpu=400.0)]

    b = SnapshotBuilder()
    b.build_snapshot(nodes, utils, running, pending_pods=window)
    batch = b.build_pod_batch(window)
    req_rows = np.asarray(batch.request)[:2]
    assert b.apply_assignment_deltas(window, np.asarray([1, 2]), req_rows)
    for i, pod in enumerate(window):
        pod.node_name = f"n{i + 1}"
        running.append(pod)
    snap = b.build_snapshot(nodes, utils, running)

    cold = SnapshotBuilder().build_snapshot(nodes, utils, running)
    np.testing.assert_array_equal(
        np.asarray(snap.requested), np.asarray(cold.requested)
    )

    # hostPort-bearing binds take the rescan path
    porty = make_pod("ports", host_ports=[53, 53])
    b2 = SnapshotBuilder()
    b2.build_snapshot(nodes, utils, [], pending_pods=[porty])
    pb = b2.build_pod_batch([porty])
    assert not b2.apply_assignment_deltas(
        [porty], np.asarray([0]), np.asarray(pb.request)[:1]
    )


def test_apply_assignment_deltas_rejects_unanticipated_churn():
    """If the informer does NOT append exactly the folded pods (list
    rebuilt, extra pod interleaved), the next build distrusts the
    accumulator and recomputes from zeros — a stale delta is never
    served."""
    from kubernetes_scheduler_tpu.host.snapshot import SnapshotBuilder

    nodes = [make_node(f"n{i}") for i in range(3)]
    utils = {n.name: NodeUtil(cpu_pct=10.0) for n in nodes}
    running: list = []
    window = [make_pod("w0", cpu=300.0)]
    b = SnapshotBuilder()
    b.build_snapshot(nodes, utils, running, pending_pods=window)
    batch = b.build_pod_batch(window)
    assert b.apply_assignment_deltas(
        window, np.asarray([0]), np.asarray(batch.request)[:1]
    )
    # churn: an unrelated pod lands where the bound pod was anticipated
    stranger = make_pod("stranger", cpu=100.0)
    stranger.node_name = "n2"
    running.append(stranger)
    snap = b.build_snapshot(nodes, utils, running)
    cold = SnapshotBuilder().build_snapshot(nodes, utils, running)
    np.testing.assert_array_equal(
        np.asarray(snap.requested), np.asarray(cold.requested)
    )


def test_restore_window_preserves_pop_order():
    q = SchedulingQueue()
    a = make_pod("a", priority=5)
    b = make_pod("b", priority=5)
    c = make_pod("c", priority=9)
    for pod in (a, b, c):
        q.push(pod)
    window = q.pop_window(2)
    assert [p.name for p in window] == ["c", "a"]
    q.restore_window(window)
    d = make_pod("d", priority=9)
    q.push(d)
    # restored pods keep their relative order AND precede pods queued
    # since, at equal priority
    assert [p.name for p in q.pop_window(4)] == ["c", "d", "a", "b"]


def test_pipeline_over_sidecar_bridge():
    """The bridge path of the pipeline: RemoteEngine.schedule_batch_async
    keeps the ScheduleBatch RPC in flight on its worker thread while the
    host preps the next window — bindings identical to the local serial
    loop, no fallback cycles, overlap measured."""
    grpc = __import__("pytest").importorskip("grpc")  # noqa: F841
    from kubernetes_scheduler_tpu.bridge.client import RemoteEngine
    from kubernetes_scheduler_tpu.bridge.server import make_server

    server, port, _ = make_server("127.0.0.1:0")
    server.start()
    client = RemoteEngine(f"127.0.0.1:{port}", deadline_seconds=60.0)
    try:
        b_remote, m_remote, s_remote = run_workload(
            1, n_pods=48, engine=client
        )
    finally:
        client.close()
        server.stop(grace=None)
    b_local, _, _ = run_workload(0, n_pods=48)
    assert b_remote == b_local
    assert not any(m.used_fallback for m in m_remote)
    assert s_remote.totals["host_overlap_seconds"] > 0.0


def test_pipeline_counters_exported():
    """host_overlap_seconds / pipeline_flushes ride metrics_snapshot()
    and the Prometheus rendering (the overlap win is observable in
    production, not just in bench.py)."""
    from kubernetes_scheduler_tpu.host.observe import render_prometheus

    _, _, sched = run_workload(1, n_pods=40)
    window, totals = sched.metrics_snapshot()
    assert totals["host_overlap_seconds"] > 0.0
    assert "pipeline_flushes" in totals
    text = render_prometheus(window, totals)
    assert "yoda_tpu_pipeline_flushes_total" in text
    assert "yoda_tpu_host_overlap_seconds_total" in text
    # pre-totals callers (older exporters) still render
    text2 = render_prometheus(window, None)
    assert "yoda_tpu_pipeline_flushes_total" in text2
