"""Span analytics + SLO watchdog: golden `spans report`/`spans diff`
runs over synthetic span files (torn tails and host-only directories
included), the diff CLI's exit-code gate, post-hoc `trace replay
--spans` timelines, and the cycle_slo_ms watchdog in both drivers
(breach records, counter series, profiler self-arm, and on/off binding
parity)."""

import json
import os

import numpy as np
import pytest

from kubernetes_scheduler_tpu.trace.analyze import (
    AnalyzeError,
    build_report,
    diff_reports,
    load_report,
    perturb_spans,
)

# ---- synthetic span files --------------------------------------------------


def write_span_dir(path, cycles, process="host"):
    """One span file in the writer's crash-tolerant format: `[` header,
    one comma-terminated event per line, no closing bracket. `cycles`
    is a list of {trace_id, seq, path, cycle_ms, stages: {name: ms}}."""
    os.makedirs(path, exist_ok=True)
    events = [
        {
            "ph": "M", "name": "process_name", "pid": 1, "tid": 0,
            "args": {"name": process},
        }
    ]
    ts = 0.0
    for c in cycles:
        t = ts
        args = {"trace_id": c["trace_id"], "seq": c.get("seq", 0)}
        for name, dur_ms in c["stages"].items():
            events.append(
                {
                    "name": name, "ph": "X", "cat": process, "ts": t,
                    "dur": dur_ms * 1e3, "pid": 1, "tid": 0, "args": args,
                }
            )
            t += dur_ms * 1e3
        events.append(
            {
                "name": "cycle", "ph": "X", "cat": process, "ts": ts,
                "dur": c["cycle_ms"] * 1e3, "pid": 1, "tid": 0,
                "args": {**args, "path": c.get("path", "serial")},
            }
        )
        ts += c["cycle_ms"] * 1e3
    fp = os.path.join(path, "spans-00000000.trace.json")
    with open(fp, "w", encoding="utf-8") as f:
        f.write("[\n")
        for ev in events:
            f.write(json.dumps(ev, separators=(",", ":")) + ",\n")
    return fp


def golden_cycles(n=4, engine_ms=7.0):
    return [
        {
            "trace_id": i + 1,
            "seq": 10 + i,
            "path": "pipelined" if i % 2 else "serial",
            "cycle_ms": 2.0 + engine_ms + 1.0,
            "stages": {
                "queue_pop": 1.0,
                "snapshot_build": 1.0,
                "engine_step": engine_ms,
                "bind": 1.0,
            },
        }
        for i in range(n)
    ]


# ---- spans report ----------------------------------------------------------


def test_report_golden_attribution(tmp_path):
    d = str(tmp_path / "spans")
    write_span_dir(d, golden_cycles())
    rep = build_report(d)
    assert rep["cycles"] == 4
    assert rep["cycle_ms"]["p50_ms"] == 10.0
    # per-stage percentiles over known durations
    assert rep["stages"]["engine_step"]["p50_ms"] == 7.0
    assert rep["stages"]["queue_pop"]["p50_ms"] == 1.0
    # the budget table: stage totals / cycle total, residual as "other",
    # summing to 100 by construction
    att = rep["attribution_pct"]
    assert att["engine_step"] == 70.0
    assert att["queue_pop"] == 10.0
    assert att["snapshot_build"] == 10.0
    assert att["bind"] == 10.0
    assert att["other"] == 0.0
    assert abs(sum(att.values()) - 100.0) < 1e-6
    # keyed by path label and flight-recorder seq range
    assert rep["by_path"]["serial"]["count"] == 2
    assert rep["by_path"]["pipelined"]["count"] == 2
    assert rep["seq"] == {"first": 10, "last": 13, "cycles_with_seq": 4}


def test_report_crash_truncated_file(tmp_path):
    """A torn tail (crashed writer) costs at most the last line; the
    report covers everything before it."""
    d = str(tmp_path / "spans")
    fp = write_span_dir(d, golden_cycles(n=3))
    with open(fp, "a", encoding="utf-8") as f:
        f.write('{"name": "engine_step", "ph": "X", "ts"')
    rep = build_report(d)
    assert rep["cycles"] == 3
    assert rep["stages"]["engine_step"]["count"] == 3


def test_report_host_only_dir(tmp_path):
    """A local-engine run has no sidecar spans: the report carries only
    host stages and the attribution table still closes at 100."""
    d = str(tmp_path / "spans")
    write_span_dir(d, golden_cycles())
    rep = build_report(d)
    assert "device_step" not in rep["stages"]
    assert abs(sum(rep["attribution_pct"].values()) - 100.0) < 0.1


def test_report_merged_trace_and_saved_report(tmp_path):
    """`spans report` accepts a merged Chrome trace; `spans diff`
    accepts a saved report JSON (load_report passes it through)."""
    d = str(tmp_path / "spans")
    write_span_dir(d, golden_cycles())
    from kubernetes_scheduler_tpu.trace.spans import read_spans

    merged = tmp_path / "merged.trace.json"
    merged.write_text(json.dumps({"traceEvents": read_spans(d)}))
    rep = build_report(str(merged))
    assert rep["cycles"] == 4
    saved = tmp_path / "report.json"
    saved.write_text(json.dumps(rep))
    assert load_report(str(saved))["cycles"] == 4


def test_report_empty_inputs_fail_loudly(tmp_path):
    with pytest.raises(AnalyzeError):
        build_report(str(tmp_path / "nowhere"))
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(AnalyzeError):
        build_report(str(empty))


def test_report_cli_exit_codes(tmp_path, capsys):
    from kubernetes_scheduler_tpu.cli import main

    d = str(tmp_path / "spans")
    write_span_dir(d, golden_cycles())
    assert main(["spans", "report", d]) == 0
    rep = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert rep["cycles"] == 4
    assert main(["spans", "report", str(tmp_path / "missing")]) == 1


# ---- spans diff ------------------------------------------------------------


def test_diff_identical_is_clean(tmp_path):
    d = str(tmp_path / "spans")
    write_span_dir(d, golden_cycles())
    diff = diff_reports(build_report(d), build_report(d))
    assert diff["clean"] and diff["regressions"] == []
    assert any(r["stage"] == "cycle" for r in diff["compared"])


def test_diff_slowed_stage_trips_threshold(tmp_path):
    """The acceptance shape: a synthetically slowed stage exits dirty
    while the untouched stages stay clean."""
    base = str(tmp_path / "base")
    write_span_dir(base, golden_cycles())
    slow = str(tmp_path / "slow")
    touched = perturb_spans(base, slow, stage="engine_step", factor=2.0)
    assert touched == 4
    diff = diff_reports(build_report(base), build_report(slow))
    assert not diff["clean"]
    assert "engine_step" in diff["regressions"]
    assert "cycle" in diff["regressions"]  # the cycle stretched too
    assert "queue_pop" not in diff["regressions"]


def test_diff_min_ms_floor_absorbs_micro_jitter(tmp_path):
    """A 2x regression on a micro-stage under the absolute floor never
    fails the gate (sub-tick jitter must not fail builds)."""
    base = str(tmp_path / "base")
    write_span_dir(
        base,
        [
            {
                "trace_id": 1, "seq": 1, "cycle_ms": 1.0,
                "stages": {"queue_pop": 0.01, "engine_step": 0.9},
            }
        ],
    )
    slow = str(tmp_path / "slow")
    perturb_spans(base, slow, stage="queue_pop", factor=2.0)
    diff = diff_reports(
        build_report(base), build_report(slow), min_ms=0.05
    )
    assert diff["clean"], diff


def test_diff_per_stage_threshold_override(tmp_path):
    base = str(tmp_path / "base")
    write_span_dir(base, golden_cycles())
    slow = str(tmp_path / "slow")
    perturb_spans(base, slow, stage="bind", factor=1.2)  # +20%
    b, c = build_report(base), build_report(slow)
    assert diff_reports(b, c, threshold_pct=25.0)["clean"]
    tightened = diff_reports(
        b, c, threshold_pct=25.0, stage_thresholds={"bind": 10.0}
    )
    assert tightened["regressions"] == ["bind"]


def test_diff_surfaces_candidate_only_stages(tmp_path):
    """A stage only the candidate has (e.g. delta_derive when the
    resident variant is the candidate) is surfaced as new_stages, not
    silently invisible."""
    base = str(tmp_path / "base")
    write_span_dir(base, golden_cycles())
    cand_cycles = golden_cycles()
    for c in cand_cycles:
        c["stages"]["delta_derive"] = 0.5
    cand = str(tmp_path / "cand")
    write_span_dir(cand, cand_cycles)
    diff = diff_reports(build_report(base), build_report(cand))
    assert diff["new_stages"] == ["delta_derive"]
    # and the reverse direction lands in missing_stages
    rev = diff_reports(build_report(cand), build_report(base))
    assert rev["missing_stages"] == ["delta_derive"]


def test_diff_cli_gate(tmp_path, capsys):
    from kubernetes_scheduler_tpu.cli import main

    base = str(tmp_path / "base")
    write_span_dir(base, golden_cycles())
    slow = str(tmp_path / "slow")
    perturb_spans(base, slow, stage="engine_step", factor=2.0)
    assert main(["spans", "diff", base, base]) == 0
    capsys.readouterr()
    assert main(["spans", "diff", base, slow]) == 1
    out = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert "engine_step" in out["regressions"]
    # per-stage override through the flag syntax
    assert main(
        ["spans", "diff", base, slow, "--stage-threshold",
         "engine_step=1000", "--stage-threshold", "cycle=1000"]
    ) == 0
    capsys.readouterr()
    # malformed specs exit 2 with the structured error (no traceback)
    for bad in ("engine_step", "engine_step=ten", "=10"):
        assert main(
            ["spans", "diff", base, slow, "--stage-threshold", bad]
        ) == 2
        assert "want stage=pct" in json.loads(
            capsys.readouterr().out.splitlines()[-1]
        )["error"]


# ---- trace replay --spans (post-hoc attribution) ---------------------------


def _run_recorded(tmp_path, **cfg_kw):
    from kubernetes_scheduler_tpu.host.scheduler import Scheduler
    from kubernetes_scheduler_tpu.sim.host_gen import (
        gen_host_cluster,
        gen_host_pods,
    )
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    nodes, advisor = gen_host_cluster(12, seed=0)
    running: list = []
    cfg = SchedulerConfig(
        batch_window=16,
        max_windows_per_cycle=1,
        min_device_work=1,
        adaptive_dispatch=False,
        initial_backoff_seconds=3600.0,
        max_backoff_seconds=3600.0,
        **cfg_kw,
    )
    sched = Scheduler(
        cfg,
        advisor=advisor,
        list_nodes=lambda: nodes,
        list_running_pods=lambda: running,
    )
    for pod in gen_host_pods(48, seed=1):
        sched.submit(pod)
    sched.run_until_empty(max_cycles=16)
    if sched.recorder is not None:
        sched.recorder.close()
    if sched.spans is not None:
        sched.spans.close()
    return sched


def test_replay_spans_posthoc_timeline(tmp_path):
    """A journal recorded with telemetry OFF replays into a
    Perfetto-loadable timeline whose cycle-span count matches the
    journal's cycle count, every span carrying its source record's
    seq — the post-hoc attribution acceptance shape."""
    from kubernetes_scheduler_tpu.trace.recorder import read_journal
    from kubernetes_scheduler_tpu.trace.replay import replay_journal
    from kubernetes_scheduler_tpu.trace.spans import read_spans

    journal = str(tmp_path / "journal")
    _run_recorded(tmp_path, trace_path=journal)  # span_path NOT set
    span_dir = str(tmp_path / "replay-spans")
    report = replay_journal(journal, span_path=span_dir)
    assert report.binding_diffs == 0 and report.replayed > 0
    records = list(read_journal(journal))
    events = [ev for ev in read_spans(span_dir) if ev.get("ph") == "X"]
    cycles = [ev for ev in events if ev["name"] == "cycle"]
    assert len(cycles) == len(records)
    assert {ev["args"]["seq"] for ev in cycles} == {
        r["seq"] for r in records
    }
    stage_names = {ev["name"] for ev in events}
    assert {"reconstruct", "engine_step", "cycle"} <= stage_names
    # the re-emitted timeline feeds the analytics layer directly
    rep = build_report(span_dir)
    assert rep["cycles"] == len(records)
    assert "engine_step" in rep["attribution_pct"]
    # rows are rounded to 2 decimals, so the sum closes to ~100
    assert abs(sum(rep["attribution_pct"].values()) - 100.0) < 0.1
    # and the replay spans CLI round-trips with the same exit contract
    from kubernetes_scheduler_tpu.cli import main

    span_dir2 = str(tmp_path / "replay-spans-2")
    assert main(["trace", "replay", journal, "--spans", span_dir2]) == 0
    assert build_report(span_dir2)["cycles"] == len(records)


# ---- SLO watchdog ----------------------------------------------------------


@pytest.mark.parametrize("depth", [0, 1])
def test_watchdog_breach_records_and_counter(tmp_path, depth):
    sched = _run_recorded(
        tmp_path / f"d{depth}",
        pipeline_depth=depth,
        trace_path=str(tmp_path / f"d{depth}-journal"),
        span_path=str(tmp_path / f"d{depth}-spans"),
        cycle_slo_ms=1e-6,  # every non-empty cycle breaches
    )
    assert sched.slo_breaches > 0
    breach = sched.last_slo_breach
    assert breach["path"] == ("pipelined" if depth else "serial")
    # the two handles that FIND the cycle again: span trace id and
    # flight-recorder seq
    assert breach["trace_id"] is not None
    assert breach["seq"] is not None
    assert breach["cycle_ms"] > breach["slo_ms"]
    text = "\n".join(sched.ctr_slo.render())
    assert (
        f'yoda_tpu_slo_breaches_total{{path="{breach["path"]}"}} '
        f"{sched.slo_breaches}" in text
    )


def test_watchdog_off_by_default(tmp_path):
    sched = _run_recorded(tmp_path)
    assert sched.slo_breaches == 0
    assert sched.last_slo_breach is None
    assert "slo_breaches_total" in "\n".join(sched.ctr_slo.render())


def test_watchdog_self_arms_profiler_once_per_window():
    """A breach storm arms the profiler once per slo_profile_cycles
    window — not once per breach — through the engine's own
    arm_profile surface."""
    from kubernetes_scheduler_tpu.host.advisor import NodeUtil, StaticAdvisor
    from kubernetes_scheduler_tpu.host.scheduler import Scheduler
    from kubernetes_scheduler_tpu.host.types import Node, Pod
    from kubernetes_scheduler_tpu.utils.config import SchedulerConfig

    class StubEngine:
        """No-device engine: rejects every pod, records profile arms."""

        def __init__(self):
            self.arms = []

        def schedule_batch(self, snapshot, pods, **kw):
            import types

            p = np.asarray(pods.request).shape[0]
            return types.SimpleNamespace(node_idx=np.full(p, -1, np.int32))

        def arm_profile(self, cycles, out_dir=None):
            self.arms.append(int(cycles))
            return {"armed": int(cycles), "out_dir": out_dir or "/tmp"}

    engine = StubEngine()
    nodes = [Node(name="n0", allocatable={"cpu": 4000.0})]
    sched = Scheduler(
        SchedulerConfig(
            batch_window=4,
            max_windows_per_cycle=1,
            min_device_work=1,
            adaptive_dispatch=False,
            gang_scheduling=False,
            preemption=False,
            initial_backoff_seconds=0.0,
            cycle_slo_ms=1e-6,
            slo_profile_cycles=2,
        ),
        advisor=StaticAdvisor({"n0": NodeUtil()}),
        list_nodes=lambda: nodes,
        list_running_pods=lambda: [],
        engine=engine,
    )
    for _ in range(4):
        sched.submit(Pod(name="p", namespace="default"))
        sched.run_cycle()
    assert sched.slo_breaches == 4
    # cycle 1 arms (pending=2); cycle 2 drains the window (pending=1);
    # cycle 3 drains to 0 and re-arms; cycle 4 drains again
    assert engine.arms == [2, 2]


@pytest.mark.parametrize("depth", [0, 1])
def test_watchdog_parity_bindings_bitidentical(tmp_path, depth):
    """PARITY round 11: the watchdog only reads clocks — watchdog-on
    and watchdog-off runs bind identically in both drivers."""

    def run(slo):
        sub = tmp_path / f"slo{int(slo * 10)}-{depth}"
        sched = _run_recorded(
            sub, pipeline_depth=depth, cycle_slo_ms=slo,
            slo_profile_cycles=2,
        )
        return [
            (b.pod.namespace, b.pod.name, b.node_name)
            for b in sched.binder.bindings
        ]

    on = run(1e-6)
    off = run(0.0)
    assert on == off and on


def test_watchdog_parity_gang_mix_scenario_e2e(tmp_path):
    """The acceptance pin: watchdog-on vs watchdog-off journals of the
    gang-mix scenario hold bit-identical decisions (trace/inspect.diff
    compares path, window identity, and node_idx record by record)."""
    import dataclasses

    from kubernetes_scheduler_tpu.sim import scenarios
    from kubernetes_scheduler_tpu.trace import inspect as tinspect

    journals = {}
    for tag, slo in (("on", 1e-6), ("off", 0.0)):
        cfg = dataclasses.replace(
            scenarios.scenario_config(), cycle_slo_ms=slo
        )
        journals[tag] = str(tmp_path / tag)
        summary = scenarios.run(
            "gang-mix", n_nodes=24, seed=0,
            trace_path=journals[tag], config=cfg,
        )
        assert summary["pods_bound"] > 0
    report = tinspect.diff(journals["on"], journals["off"])
    assert report["differences"] == 0
    assert report["extra_records_a"] == report["extra_records_b"] == 0


def test_scenario_run_emits_spans(tmp_path):
    """`scenario run --spans`: adversarial programs produce attribution
    data — the span directory feeds `spans report` like any production
    run's."""
    from kubernetes_scheduler_tpu.sim import scenarios

    span_dir = str(tmp_path / "spans")
    summary = scenarios.run(
        "burst", n_nodes=16, seed=0, span_path=span_dir
    )
    assert summary["pods_bound"] > 0
    assert summary["spans"] == span_dir
    rep = build_report(span_dir)
    assert rep["cycles"] > 0
    assert "engine_step" in rep["attribution_pct"]


def test_sidecar_step_slo_counter():
    """The sidecar half of the watchdog: a device step over
    --step-slo-ms bumps slo_breaches_total{rpc} on its exporter."""
    from kubernetes_scheduler_tpu.bridge.server import EngineService

    svc = EngineService(step_slo_ms=0.5)
    svc._finish_call("schedule_batch", 0.0001, 7, 3, None)  # under budget
    svc._finish_call("schedule_batch", 0.9, 7, 3, None)     # breach
    body = svc.render_metrics()
    assert 'yoda_tpu_slo_breaches_total{rpc="schedule_batch"} 1' in body
