"""graftlint tests: every rule family proves it fires on its violating
fixture AND stays quiet on its clean fixture; waiver mechanics; the CLI
contract; and the capstone — the repo itself lints clean (what `make
lint` enforces)."""

import os

import pytest

from kubernetes_scheduler_tpu.analysis import run_lint
from kubernetes_scheduler_tpu.analysis.__main__ import main as lint_main
from kubernetes_scheduler_tpu.analysis.rules import RULES

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")


def lint_fixture(name, rule):
    return run_lint([os.path.join(FIXTURES, name)], rules=[rule])


def active(violations):
    return [v for v in violations if not v.waived]


# ---- one violating + one clean fixture per rule family --------------------


@pytest.mark.parametrize(
    "rule,violating,clean,min_hits",
    [
        ("jit-purity", "jit_purity_violation.py", "jit_purity_clean.py", 3),
        ("host-sync", "host_sync_violation.py", "host_sync_clean.py", 3),
        (
            "lock-discipline",
            "lock_discipline_violation.py",
            "lock_discipline_clean.py",
            1,
        ),
        (
            "wire-schema",
            "wire_schema_violation.py",
            "wire_schema_clean.py",
            4,
        ),
        (
            "wire-schema",
            "journal_schema_violation.py",
            "journal_schema_clean.py",
            6,
        ),
        ("dtype-shape", "dtype_shape_violation.py", "dtype_shape_clean.py", 3),
        ("timeout-hygiene", "timeout_violation.py", "timeout_clean.py", 5),
        (
            "pallas-vmem",
            "pallas_vmem_violation.py",
            "pallas_vmem_clean.py",
            4,
        ),
        (
            "metric-hygiene",
            "metric_hygiene_violation.py",
            "metric_hygiene_clean.py",
            8,
        ),
        (
            "sim-determinism",
            "sim_determinism_violation.py",
            "sim_determinism_clean.py",
            6,
        ),
        (
            "span-hygiene",
            "span_hygiene_violation.py",
            "span_hygiene_clean.py",
            5,
        ),
    ],
)
def test_rule_fires_and_stays_quiet(rule, violating, clean, min_hits):
    hits = active(lint_fixture(violating, rule))
    assert len(hits) >= min_hits, [v.format() for v in hits]
    assert all(v.rule == rule for v in hits)
    quiet = active(lint_fixture(clean, rule))
    assert quiet == [], [v.format() for v in quiet]


# ---- rule specifics -------------------------------------------------------


def test_jit_purity_flags_reachable_helper_only():
    vs = active(lint_fixture("jit_purity_violation.py", "jit-purity"))
    assert any("global" in v.message for v in vs)  # helper via call graph
    assert any("print" in v.message for v in vs)
    assert any("TRACE_LOG" in v.message for v in vs)
    # the clean fixture's host_only_reporting prints but is unreachable
    vs = active(lint_fixture("jit_purity_clean.py", "jit-purity"))
    assert vs == []


def test_host_sync_messages_name_the_sync():
    msgs = [
        v.message
        for v in active(lint_fixture("host_sync_violation.py", "host-sync"))
    ]
    assert any("block_until_ready" in m for m in msgs)
    assert any("np.asarray" in m for m in msgs)
    assert any(".item()" in m for m in msgs)


def test_lock_discipline_names_class_method_and_attr():
    (v,) = active(
        lint_fixture("lock_discipline_violation.py", "lock-discipline")
    )
    assert "SharedCache.drop" in v.message and "_store" in v.message


def test_wire_schema_catches_ctor_attr_and_unknown_message():
    msgs = [
        v.message
        for v in active(
            lint_fixture("wire_schema_violation.py", "wire-schema")
        )
    ]
    assert any("`bogus`" in m for m in msgs)        # ctor kwarg
    assert any("`nonexistent`" in m for m in msgs)  # annotated param attr
    assert any("`Missing`" in m for m in msgs)      # unknown message
    assert any("`status`" in m for m in msgs)       # assigned-var attr


def test_dtype_shape_allows_static_shape_branching():
    # the clean fixture branches on x.shape[0] — idiomatic, not flagged
    assert active(lint_fixture("dtype_shape_clean.py", "dtype-shape")) == []
    msgs = [
        v.message
        for v in active(
            lint_fixture("dtype_shape_violation.py", "dtype-shape")
        )
    ]
    assert any("float64 dtype" in m for m in msgs)
    assert any("astype" in m for m in msgs)
    assert any("any" in m for m in msgs)


def test_dtype_shape_flags_donated_buffer_reread():
    """The donate_argnums family (the resident-state apply_snapshot_delta
    signature): a leaf read after being donated is a violation; the
    idiomatic `x = f(x)` rebind — and reads before the donation — are
    clean."""
    hits = active(
        lint_fixture("dtype_shape_donate_violation.py", "dtype-shape")
    )
    assert len(hits) >= 2, [v.format() for v in hits]
    assert all("donated" in v.message for v in hits)
    assert all("apply_delta" in v.message for v in hits)
    quiet = active(
        lint_fixture("dtype_shape_donate_clean.py", "dtype-shape")
    )
    assert quiet == [], [v.format() for v in quiet]


def test_pallas_vmem_covers_all_three_families():
    """The rule family's three checks each fire — tiling (a block that
    cannot divide the lane-padded axis), the VMEM budget, reduced-
    precision accumulators, and host callbacks — and runtime-valued dims
    (the clean fixture's n_res) are skipped, not guessed."""
    msgs = [
        v.message
        for v in active(lint_fixture("pallas_vmem_violation.py", "pallas-vmem"))
    ]
    assert any("multiple of 128" in m for m in msgs)
    # BinOp-resolved dims (64 * 3) are checked too, in AND out specs —
    # the resolution the fused megakernel's stacked-row shapes go through
    assert sum("multiple of 128" in m for m in msgs) >= 3, msgs
    assert any("VMEM budget" in m for m in msgs)
    assert any("accumulate in f32" in m for m in msgs)
    assert any("host callback" in m for m in msgs)
    # the real fused kernel stays clean (what `make lint` enforces)
    real = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "kubernetes_scheduler_tpu", "ops", "pallas_fused.py",
    )
    assert active(run_lint([real], rules=["pallas-vmem"])) == []


def test_journal_schema_messages_name_the_drift():
    """Each journal-schema failure mode fires with a message naming the
    drift — and the REAL trace/schema.py lints clean (what `make lint`
    enforces for the journal contract)."""
    msgs = [
        v.message
        for v in active(
            lint_fixture("journal_schema_violation.py", "wire-schema")
        )
    ]
    assert any("tag 1 reused" in m for m in msgs)
    assert any("`seq` declared twice" in m for m in msgs)
    assert any("positive integer LITERAL" in m for m in msgs)
    assert any("unknown journal field kind" in m for m in msgs)
    assert any("kind must be a string LITERAL" in m for m in msgs)
    assert any("float64" in m for m in msgs)
    assert any("not a declared `tensors`-kind" in m for m in msgs)
    real = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "kubernetes_scheduler_tpu", "trace", "schema.py",
    )
    assert active(run_lint([real], rules=["wire-schema"])) == []


def test_metric_hygiene_covers_every_failure_mode():
    """Each metric-hygiene failure mode fires with a message naming the
    metric — and the REAL metric surfaces (host/observe.py's _HELP +
    SHIPPED_METRICS registry, the scheduler's and sidecar's labeled
    collectors) lint clean across the package (what `make lint`
    enforces)."""
    msgs = [
        v.message
        for v in active(
            lint_fixture("metric_hygiene_violation.py", "metric-hygiene")
        )
    ]
    assert any("`queue_depth` has no unit suffix" in m for m in msgs)
    assert any("empty HELP string" in m for m in msgs)
    assert any("`binds_total` declared twice" in m for m in msgs)
    assert any("must end in `_total`" in m for m in msgs)
    assert any("no (or an empty) help string" in m for m in msgs)
    assert any("no HELP entry in any *_HELP table" in m for m in msgs)
    assert any("no longer declared anywhere" in m for m in msgs)
    assert any("not registered in SHIPPED_METRICS" in m for m in msgs)
    assert active(run_lint(rules=["metric-hygiene"])) == []


def test_shipped_registry_matches_help_table():
    """The live registry covers every _HELP key (the lint checks the
    static surfaces; this pins the runtime tables to each other)."""
    from kubernetes_scheduler_tpu.host.observe import _HELP, SHIPPED_METRICS

    assert set(_HELP) <= set(SHIPPED_METRICS)


def test_span_hygiene_covers_every_failure_mode():
    """Each span-hygiene failure mode fires with a message naming the
    stage — and the REAL span surfaces (Scheduler._span call sites, the
    sidecar's SpanSet.add sites, the replay emitter) lint clean against
    observe.SHIPPED_SPANS across the package (what `make lint`
    enforces)."""
    msgs = [
        v.message
        for v in active(
            lint_fixture("span_hygiene_violation.py", "span-hygiene")
        )
    ]
    assert any("`mystery_stage` is not registered" in m for m in msgs)
    assert any("`orphan_stage` is not registered" in m for m in msgs)
    assert any("'Bind-Phase' is not lower_snake_case" in m for m in msgs)
    assert any("`cycle` registered twice" in m for m in msgs)
    assert any(
        "`removed_stage` is no longer emitted" in m for m in msgs
    )
    assert active(run_lint(rules=["span-hygiene"])) == []


def test_shipped_spans_cover_attribution_stages():
    """The analytics layer's attribution table only names registered
    stages (a table row over an unshipped name could never fill)."""
    from kubernetes_scheduler_tpu.host.observe import SHIPPED_SPANS
    from kubernetes_scheduler_tpu.trace.analyze import (
        ATTRIBUTION_STAGES,
        NON_ATTRIBUTED_STAGES,
    )

    assert set(ATTRIBUTION_STAGES) <= set(SHIPPED_SPANS)
    assert set(NON_ATTRIBUTED_STAGES) <= set(SHIPPED_SPANS)
    assert "cycle" in SHIPPED_SPANS


def test_real_schedule_proto_parses():
    from kubernetes_scheduler_tpu.analysis.rules.wire_schema import parse_proto

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    messages = parse_proto(
        os.path.join(
            root, "kubernetes_scheduler_tpu", "bridge", "schedule.proto"
        )
    )
    assert "session_id" in messages["ScheduleRequest"]
    assert "field_cache" in messages["HealthReply"]
    assert messages["HealthRequest"] == set()  # single-line empty message
    assert "same_as_last" in messages["Tensor"]


# ---- waiver mechanics -----------------------------------------------------


def test_waivers_inline_and_preceding_line():
    vs = run_lint(
        [os.path.join(FIXTURES, "waiver_fixture.py")],
        rules=["timeout-hygiene"],
    )
    waived = [v for v in vs if v.waived]
    unwaived = [v for v in vs if not v.waived]
    # both waiver placements took effect, with their reasons preserved
    assert len(waived) == 2
    assert all(v.waiver_reason for v in waived)
    # the reason-less waiver: its own bad-waiver violation AND the
    # underlying finding stays active; the wrong-rule waiver leaves the
    # timeout finding active too
    assert any(v.rule == "bad-waiver" for v in unwaived)
    assert (
        len([v for v in unwaived if v.rule == "timeout-hygiene"]) == 2
    ), [v.format() for v in vs]


def test_bad_waiver_cannot_waive_itself():
    vs = run_lint(
        [os.path.join(FIXTURES, "waiver_fixture.py")],
        rules=["timeout-hygiene"],
    )
    assert all(not v.waived for v in vs if v.rule == "bad-waiver")


# ---- runner / CLI contract ------------------------------------------------


def test_unknown_rule_rejected():
    with pytest.raises(ValueError, match="unknown lint rules"):
        run_lint(rules=["no-such-rule"])


def test_registry_has_all_six_families():
    assert {
        "jit-purity", "host-sync", "lock-discipline", "wire-schema",
        "dtype-shape", "timeout-hygiene", "sim-determinism",
    } <= set(RULES)


def test_sim_determinism_messages_name_the_fix():
    msgs = [
        v.message
        for v in active(
            lint_fixture("sim_determinism_violation.py", "sim-determinism")
        )
    ]
    assert any("default_rng(seed)" in m for m in msgs)
    assert any("GLOBAL RNG" in m for m in msgs)
    # unseeded default_rng gets its own targeted message
    assert any("unseeded default_rng()" in m for m in msgs)


def test_sim_determinism_real_simulators_clean():
    import glob

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    real = glob.glob(
        os.path.join(repo_root, "kubernetes_scheduler_tpu", "sim", "**", "*.py"),
        recursive=True,
    )
    assert real, "sim/ sources not found"
    assert active(run_lint(real, rules=["sim-determinism"])) == []


def test_lint_main_exit_codes(capsys):
    rc = lint_main(
        [os.path.join(FIXTURES, "timeout_violation.py"),
         "--rules", "timeout-hygiene"]
    )
    assert rc == 1
    out = capsys.readouterr().out
    assert "timeout-hygiene" in out
    rc = lint_main(
        [os.path.join(FIXTURES, "timeout_clean.py"),
         "--rules", "timeout-hygiene"]
    )
    assert rc == 0


def test_lint_main_json_format(capsys):
    import json

    rc = lint_main(
        [os.path.join(FIXTURES, "lock_discipline_violation.py"),
         "--rules", "lock-discipline", "--format", "json"]
    )
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload and payload[0]["rule"] == "lock-discipline"


# ---- the capstone: the repo itself lints clean ----------------------------


def test_repo_lints_clean():
    """`make lint` must exit 0: every genuine violation in the tree is
    either fixed or carries an inline justification. New unwaived
    findings fail HERE, in tier-1, before CI even reaches `make lint`."""
    vs = run_lint()
    bad = active(vs)
    assert bad == [], "\n".join(v.format() for v in bad)
    # the waivers that exist all carry their justifications
    assert all(v.waiver_reason for v in vs if v.waived)
